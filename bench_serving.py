"""Serving-path benchmark: the REAL stack under concurrent load.

bench.py times the raw fused decode loop — the engine's compute ceiling.
This benchmark answers the question that actually decides the north star
(BASELINE.md: >=2,000 tok/s/chip *serving* Qwen2.5-7B): what survives once
the scheduler, abort bookkeeping, numpy mirrors, queue handoffs, HTTP
framing, and SSE relay sit between the chip and the client?

Method:
- This process builds the production engine (w-int8 / kv-int8, b-slot
  continuous batching) + OpenAIServer, exactly as ``python -m
  arks_tpu.server`` would.
- A **separate client process** (stdlib-only, launched with ``python -S``
  so this image's jax-importing sitecustomize stays out of it) drives
  ``--clients`` closed-loop streaming completions plus low-rate TTFT
  probe threads.  Clients deliberately number slightly below the slot
  count so probes measure loaded-but-admittable TTFT (queueing for a free
  slot is a capacity question, not a latency one).
- Sustained throughput = delta of the engine's own
  ``generation_tokens_total`` over a timed window after warmup, read via
  the real ``/metrics`` endpoint — every counted token took the full
  serving path.  Client-side usage totals are kept as a cross-check.

Prints ONE JSON line.  Env knobs mirror bench.py (ARKS_BENCH_MODEL,
ARKS_BENCH_BATCH, ARKS_BENCH_CACHE_LEN, ARKS_BENCH_STEPS) plus
ARKS_BENCH_SERVE_SECONDS / _WARMUP / _MAX_TOKENS / _PROMPT_LEN /
_PROBE_PROMPT_LEN.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.request

BASELINE_TOK_S_CHIP = 2000.0


# ---------------------------------------------------------------------------
# Client mode (stdlib only — runs under ``python -S``)
# ---------------------------------------------------------------------------


def _shared_prefix(prefix_len: int) -> list:
    """The one fixed pseudo-system-prompt every client shares — seeded so
    the server-side primer and the client subprocess build the SAME ids."""
    import random
    return [random.Random(1234).randint(3, 200)
            for _ in range(max(prefix_len, 0))]


def _client_main(argv: list[str]) -> None:
    import argparse
    import http.client
    import random
    import threading

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=30.0)
    ap.add_argument("--max-tokens", type=int, default=256)
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--prefix-len", type=int, default=0)
    # Probe sizing: p99 claims need >= 100 TTFT observations per window
    # (r04 shipped a "p99" from 14 samples — i.e. the max).  Each probe
    # cycle costs ttft + interval, so at the saturated-regime TTFT (~2.5s
    # pre-deferral) 10 probes at 0.25s still clear ~100 per 30s window.
    ap.add_argument("--probes", type=int, default=10)
    ap.add_argument("--probe-prompt-len", type=int, default=512)
    ap.add_argument("--probe-interval", type=float, default=0.25)
    args = ap.parse_args(argv)

    stop_at = time.monotonic() + args.seconds
    lock = threading.Lock()
    usage_tokens = [0]
    completed = [0]
    errors = [0]
    error_samples: list[str] = []
    ttfts: list[tuple[float, float]] = []  # (t_sent_monotonic, ttft_s)

    def stream_once(conn, body: dict) -> tuple[int, float | None]:
        """POST a streaming completion; returns (completion_tokens from the
        usage frame, time-to-first-content-frame seconds)."""
        payload = json.dumps(body).encode()
        t0 = time.monotonic()
        conn.request("POST", "/v1/completions", body=payload,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            resp.read()
            raise RuntimeError(f"HTTP {resp.status}")
        first = None
        toks = 0
        buf = b""
        while True:
            chunk = resp.read1(65536)
            if not chunk:
                break
            buf += chunk
            while b"\n\n" in buf:
                frame, buf = buf.split(b"\n\n", 1)
                for line in frame.splitlines():
                    if not line.startswith(b"data: ") or line == b"data: [DONE]":
                        continue
                    obj = json.loads(line[6:])
                    if first is None and any(
                            c.get("text") for c in obj.get("choices", [])):
                        first = time.monotonic() - t0
                    u = obj.get("usage")
                    if u:
                        toks = int(u.get("completion_tokens", 0))
        return toks, first

    # Distinct random prompts defeat the prefix cache on purpose: the
    # default measures the no-reuse worst case.  --prefix-len > 0 prepends
    # a SHARED prefix (one fixed pseudo-system-prompt across every client)
    # so the paged engine's on-device prefix sharing is exercised — the
    # multi-turn / shared-system-prompt serving shape.
    shared_prefix = _shared_prefix(args.prefix_len)

    def make_prompt(n: int) -> list[int]:
        tail = [random.randint(3, 200) for _ in range(max(n - len(shared_prefix), 1))]
        return shared_prefix + tail

    def worker() -> None:
        conn = http.client.HTTPConnection(args.host, args.port, timeout=600)
        body = {"model": "bench", "stream": True,
                "stream_options": {"include_usage": True},
                "max_tokens": args.max_tokens, "temperature": 0.0,
                "ignore_eos": True}
        while time.monotonic() < stop_at:
            body["prompt"] = make_prompt(args.prompt_len)
            # Jittered lengths de-synchronize completion waves (all-equal
            # max_tokens would retire every slot at once and make the
            # admission burst periodic instead of steady-state).
            body["max_tokens"] = random.randint(
                max(args.max_tokens // 2, 1), args.max_tokens)
            try:
                toks, _ = stream_once(conn, body)
            except Exception as e:
                with lock:
                    errors[0] += 1
                    if len(error_samples) < 5:
                        error_samples.append(f"{type(e).__name__}: {e}")
                conn.close()
                conn = http.client.HTTPConnection(args.host, args.port,
                                                  timeout=600)
                continue
            with lock:
                usage_tokens[0] += toks
                completed[0] += 1
        conn.close()

    def probe() -> None:
        conn = http.client.HTTPConnection(args.host, args.port, timeout=600)
        body = {"model": "bench", "stream": True, "max_tokens": 2,
                "temperature": 0.0, "ignore_eos": True}
        while time.monotonic() < stop_at:
            body["prompt"] = make_prompt(args.probe_prompt_len)
            t_sent = time.monotonic()
            try:
                _, first = stream_once(conn, body)
            except Exception:
                conn.close()
                conn = http.client.HTTPConnection(args.host, args.port,
                                                  timeout=600)
                continue
            if first is not None:
                with lock:
                    ttfts.append((t_sent, first))
            time.sleep(args.probe_interval)
        conn.close()

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(args.clients)]
    threads += [threading.Thread(target=probe, daemon=True)
                for _ in range(args.probes)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.seconds + 600)
    print(json.dumps({
        "client_usage_tokens": usage_tokens[0],
        "completed_requests": completed[0],
        "errors": errors[0],
        "error_samples": error_samples,
        "wall_s": time.monotonic() - t_start,
        "ttfts": [(round(ts - t_start, 3), round(v, 4)) for ts, v in ttfts],
    }))


# ---------------------------------------------------------------------------
# Server mode (the benchmark itself)
# ---------------------------------------------------------------------------


def _scrape(port: int, names: tuple[str, ...]) -> dict[str, float]:
    """{metric-line-prefix: value} for every series whose name is listed
    (labeled series keyed as name{labels})."""
    out: dict[str, float] = {}
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
        for line in r.read().decode().splitlines():
            for name in names:
                if line.startswith(name + " ") or line.startswith(name + "{"):
                    key, val = line.rsplit(" ", 1)
                    out[key] = float(val)
    return out


def _series_sum(scraped: dict[str, float], name: str) -> float:
    """A family summed across label combinations (tier-labeled counters
    read as one number)."""
    return sum(v for k, v in scraped.items()
               if k == name or k.startswith(name + "{"))


# TTFT leg -> trace span name.  "first_decode" is derived (first_token
# instant minus prefill end) rather than a recorded span.
_TTFT_LEGS = (("queue", "queue"), ("guide", "park.guide"),
              ("restore", "park.restore"), ("model_wait", "park.model"),
              ("prefill", "prefill"))


def _ttft_decomposition(traces, since: float | None = None) -> dict:
    """Per-phase TTFT split from assembled trace timelines: where the
    time before the first token actually went.  Each leg is the summed
    duration of that span family within a trace (a request can park more
    than once); "first_decode" is the gap between the prefill's end and
    the first-token instant — the first decode dispatch's issue+resolve.
    Means are over the traces that HAVE the leg; ``n`` counts them."""
    import numpy as np

    legs: dict[str, list[float]] = {k: [] for k, _ in _TTFT_LEGS}
    legs["first_decode"] = []
    used = 0
    for t in traces:
        if since is not None and t["start"] < since:
            continue
        used += 1
        closed: dict[str, float] = {}
        first = prefill_end = None
        for s in t["spans"]:
            if s.get("component") not in (None, "engine"):
                continue
            if s["name"] == "first_token":
                first = s["start"]
            elif s.get("end") is not None:
                closed[s["name"]] = closed.get(s["name"], 0.0) \
                    + (s["end"] - s["start"])
                if s["name"] == "prefill":
                    prefill_end = max(prefill_end or 0.0, s["end"])
        for key, span_name in _TTFT_LEGS:
            if span_name in closed:
                legs[key].append(closed[span_name])
        if first is not None and prefill_end is not None:
            legs["first_decode"].append(max(0.0, first - prefill_end))
    out: dict = {"traces": used}
    for key, vals in legs.items():
        out[f"{key}_mean_ms"] = (
            round(float(np.mean(vals)) * 1e3, 3) if vals else None)
        out[f"{key}_n"] = len(vals)
    return out


def _run_moderate_phase(port: int, slots: int, seconds: float,
                        max_tokens: int, prompt_len: int, probe_len: int,
                        n_chips: int, names: tuple[str, ...],
                        prefix_len: int = 0, engine=None) -> dict:
    """Second load phase at clients ~= slots/4: the north star's
    "p50 TTFT < 200ms under RPM load" is a moderate-load contract — the
    saturation phase answers a different question (TTFT at 100% slot
    occupancy).  The measurement window starts AFTER a ramp sleep so
    tokens draining phase 1's saturated queue are not attributed to the
    moderate load."""
    import numpy as np

    ramp = 5.0
    mclients = max(slots // 4, 1)
    mtotal = ramp + seconds + 5
    print(f"# moderate phase: {mclients} clients", file=sys.stderr,
          flush=True)
    mproc = subprocess.Popen(
        [sys.executable, "-S", os.path.abspath(__file__), "--client",
         "--host", "127.0.0.1", "--port", str(port),
         "--clients", str(mclients), "--seconds", str(mtotal),
         "--max-tokens", str(max_tokens),
         "--prompt-len", str(prompt_len),
         "--probe-prompt-len", str(probe_len),
         "--probes", os.environ.get("ARKS_BENCH_SERVE_PROBES", "10"),
         "--probe-interval",
         os.environ.get("ARKS_BENCH_SERVE_PROBE_INTERVAL", "0.25"),
         "--prefix-len", str(prefix_len)],
        stdout=subprocess.PIPE, text=True)
    try:
        time.sleep(ramp)
        m0 = _scrape(port, names)
        tm0 = time.monotonic()
        time.sleep(seconds)
        m1 = _scrape(port, names)
        tm1 = time.monotonic()
        mout, _ = mproc.communicate(timeout=mtotal + 600)
    finally:
        if mproc.poll() is None:
            mproc.kill()
    mclient = json.loads(mout.strip().splitlines()[-1])
    # TTFT probes from the ramp window are dropped for the same reason
    # the token window starts after it.
    mttfts = [v for ts, v in mclient["ttfts"] if ts >= ramp]
    # Per-phase TTFT split from the server-side traces: the client
    # subprocess only sees the total, the trace store knows which leg
    # (queue / guide / restore / model_wait / prefill / first-decode)
    # the time went to.  Window-scoped via the monotonic clock — bench
    # and engine share a process.
    decomp = None
    if engine is not None and getattr(engine, "trace", None) is not None \
            and engine.trace.enabled:
        engine.trace.flush()
        decomp = _ttft_decomposition(engine.trace.store.all(), since=tm0)
    return {
        "serving_moderate_ttft_phases": decomp,
        "serving_moderate_clients": mclients,
        "serving_moderate_tok_s_chip": round(
            (m1.get("generation_tokens_total", 0.0)
             - m0.get("generation_tokens_total", 0.0))
            / (tm1 - tm0) / n_chips, 1),
        "serving_moderate_ttft_p50_ms": round(
            float(np.percentile(mttfts, 50)) * 1e3, 1) if mttfts else None,
        "serving_moderate_ttft_p99_ms": round(
            float(np.percentile(mttfts, 99)) * 1e3, 1) if mttfts else None,
        "serving_moderate_ttft_samples": len(mttfts),
    }


def _measure_recovery(engine, port: int) -> dict:
    """Fault-recovery probe: with a few live streams decoding, arm a
    one-shot injected decode fault (the engine's ARKS_FAULT_INJECT
    machinery, armed programmatically) and measure the fault-to-resumed
    window the engine reports (engine_recovery_seconds) plus client-side
    stream integrity — every stream must still finish completely."""
    import json as _json
    import threading as _threading
    import urllib.request as _urllib

    n = int(os.environ.get("ARKS_BENCH_RECOVERY_STREAMS", "4"))
    max_toks = int(os.environ.get("ARKS_BENCH_RECOVERY_MAX_TOKENS", "64"))
    results: list = []

    def stream(i: int) -> None:
        body = _json.dumps({
            "model": "bench", "prompt": [3 + i] * 16,
            "max_tokens": max_toks, "temperature": 0.0,
            "ignore_eos": True, "stream": True}).encode()
        req = _urllib.Request(
            f"http://127.0.0.1:{port}/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        try:
            finish = None
            with _urllib.urlopen(req, timeout=600) as r:
                for raw in r:
                    line = raw.decode().strip()
                    if not line.startswith("data: ") or line.endswith("[DONE]"):
                        continue
                    p = _json.loads(line[len("data: "):])
                    for c in p.get("choices", []):
                        finish = c.get("finish_reason") or finish
            results.append(finish)
        except Exception as e:  # recorded; the probe reports it
            results.append(f"{type(e).__name__}: {e}")

    threads = [_threading.Thread(target=stream, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 60
    while engine.num_running < n and time.monotonic() < deadline:
        time.sleep(0.05)
    # Kill the next decode dispatch; the engine quarantines nobody (first
    # fault, default retry budget) and token-replays every stream.
    engine._faults.arm("decode:1:runtime")
    for t in threads:
        t.join(timeout=600)
    hist = engine.metrics.engine_recovery_seconds
    with hist._lock:
        data = dict(hist._data)
    _counts, total, cnt = data.get((), ([], 0.0, 0))
    recovered = sum(
        engine.metrics.requests_recovered_total._values.values())
    return {
        "recovery_seconds": round(total / cnt, 4) if cnt else None,
        "recovery_events": cnt,
        "recovery_requests_recovered": int(recovered),
        "recovery_streams_completed": sum(1 for f in results
                                          if f == "length"),
        "recovery_streams_total": n,
    }


def run_serving_bench(model: str | None = None) -> dict:
    """Build the production engine+server, run the load, return results.
    Importable so bench.py can fold the numbers into its JSON line."""
    import numpy as np

    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config
    from arks_tpu.server import OpenAIServer

    model = model or os.environ.get("ARKS_BENCH_MODEL", "qwen2.5-7b")
    slots = int(os.environ.get("ARKS_BENCH_BATCH", "192"))
    cache_len = int(os.environ.get("ARKS_BENCH_CACHE_LEN", "1024"))
    steps = int(os.environ.get("ARKS_BENCH_STEPS", "32"))
    seconds = float(os.environ.get("ARKS_BENCH_SERVE_SECONDS", "30"))
    warmup = float(os.environ.get("ARKS_BENCH_SERVE_WARMUP", "25"))
    max_tokens = int(os.environ.get("ARKS_BENCH_SERVE_MAX_TOKENS", "256"))
    prompt_len = int(os.environ.get("ARKS_BENCH_SERVE_PROMPT_LEN", "128"))
    probe_len = int(os.environ.get("ARKS_BENCH_SERVE_PROBE_PROMPT_LEN", "512"))
    # Shared-prefix length across all client prompts (0 = worst case, no
    # reuse).  With the paged layout, hits skip the shared head's prefill
    # entirely (table pointers at already-resident pages).
    prefix_len = int(os.environ.get("ARKS_BENCH_SERVE_PREFIX_LEN", "0"))
    if prefix_len and prefix_len >= prompt_len:
        raise ValueError(
            f"ARKS_BENCH_SERVE_PREFIX_LEN={prefix_len} must be smaller "
            f"than the prompt length {prompt_len} (the prefix is part of "
            "the prompt, not an addition to it)")
    weight_dtype = os.environ.get("ARKS_BENCH_WEIGHT_DTYPE", "int8")
    # Clients sit just under the slot count: probes then measure loaded
    # TTFT (decode saturated) without conflating it with slot queueing.
    clients = int(os.environ.get(
        "ARKS_BENCH_SERVE_CLIENTS", str(max(slots - 8, 1))))

    import jax
    # Honor a late JAX_PLATFORMS (the sitecustomize-imported jax read the
    # platform at interpreter startup — see bench.py's module note).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    n_chips = max(len(jax.devices()), 1)

    cfg = get_config(model)
    # Spec ladder rung (ARKS_BENCH_DRAFT_MODEL=tiny-gqa etc.): the same
    # load through a spec-mixed engine, emitting spec_acceptance_rate +
    # spec_goodput_tok_s_chip alongside the plain numbers — the goodput
    # delta vs the no-draft rung is the speculation win under load.
    draft_model = os.environ.get("ARKS_BENCH_DRAFT_MODEL") or None
    draft_len = int(os.environ.get("ARKS_BENCH_DRAFT_LEN", "4"))
    ecfg = EngineConfig(
        model=model, num_slots=slots, max_cache_len=cache_len,
        steps_per_dispatch=steps, weight_dtype=weight_dtype,
        prefill_buckets=(128, 256, 512, 1024),
        draft_model=draft_model, draft_len=draft_len,
        tensor_parallel=n_chips if n_chips > 1 else None)
    engine = InferenceEngine(cfg, ecfg, ByteTokenizer())
    engine.start()
    server = OpenAIServer(engine, served_model_name="bench",
                          host="127.0.0.1", port=0)
    server.start(background=True)

    # Prime every compiled program the load will hit (prefill buckets for
    # both prompt lengths, every resolved admission-batch variant M, the
    # fused decode loop): remote TPU compiles are 20-40s each and must not
    # land inside the measurement window.
    import random as _random
    import threading as _threading

    def _one(plen, seed):
        rng = _random.Random(seed)
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/completions",
            data=json.dumps({"model": "bench",
                             "prompt": [rng.randint(3, 200)
                                        for _ in range(plen)],
                             "max_tokens": steps + 1, "temperature": 0.0,
                             "ignore_eos": True}).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=600).read()

    t_prime = time.monotonic()
    for plen in sorted({prompt_len, probe_len}):
        _one(plen, 0)
        print(f"# primed bucket {plen} at {time.monotonic()-t_prime:.0f}s",
              file=sys.stderr, flush=True)
    if prefix_len:
        # Two sequential shared-prefix prompts: the second takes the
        # prefix-HIT path (digest match -> chunked tail prefill), whose
        # jitted chunk/insert programs must not compile inside the
        # measured window.
        import random as _r
        pre = _shared_prefix(prefix_len)
        for seed in (51, 52):
            rng = _r.Random(seed)
            body = json.dumps({
                "model": "bench",
                "prompt": pre + [rng.randint(3, 200)
                                 for _ in range(prompt_len - prefix_len)],
                "max_tokens": steps + 1, "temperature": 0.0,
                "ignore_eos": True}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/v1/completions", data=body,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=600).read()
        print(f"# primed prefix path at {time.monotonic()-t_prime:.0f}s",
              file=sys.stderr, flush=True)
    # Prime every admission-batch variant the ENGINE resolved (the ladder
    # is env-tunable — a swept M=16 program must not compile inside the
    # measurement window).
    for burst in [s for s in engine._admit_sizes if s > 1]:
        ts = [_threading.Thread(target=_one, args=(prompt_len, 100 + i))
              for i in range(burst)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        print(f"# primed burst {burst} at {time.monotonic()-t_prime:.0f}s",
              file=sys.stderr, flush=True)

    total_s = warmup + seconds + 5
    proc = subprocess.Popen(
        [sys.executable, "-S", os.path.abspath(__file__), "--client",
         "--host", "127.0.0.1", "--port", str(server.port),
         "--clients", str(clients), "--seconds", str(total_s),
         "--max-tokens", str(max_tokens), "--prompt-len", str(prompt_len),
         "--probe-prompt-len", str(probe_len),
         "--probes", os.environ.get("ARKS_BENCH_SERVE_PROBES", "10"),
         "--probe-interval",
         os.environ.get("ARKS_BENCH_SERVE_PROBE_INTERVAL", "0.25"),
         "--prefix-len", str(prefix_len)],
        stdout=subprocess.PIPE, text=True)
    names = ("generation_tokens_total", "scheduler_seconds_total",
             "prefix_cache_hit_tokens_total",
             "decode_resolve_wait_seconds_total",
             "pipeline_depth_occupancy_sum",
             "pipeline_depth_occupancy_count",
             "spec_decode_proposed_tokens_total",
             "spec_decode_accepted_tokens_total")
    moderate = None
    try:
        t_launch = time.monotonic()
        print("# client launched; warming up", file=sys.stderr, flush=True)
        time.sleep(warmup)
        s0 = _scrape(server.port, names)
        t0 = time.monotonic()
        time.sleep(seconds)
        s1 = _scrape(server.port, names)
        t1 = time.monotonic()
        out, _ = proc.communicate(timeout=total_s + 600)
        # Second phase: MODERATE load (clients ~= slots/4).  The north
        # star's "p50 TTFT < 200ms under RPM load" is a moderate-load
        # contract — the saturation probe above answers a different
        # question (TTFT at 100% slot occupancy).  Skippable for quick
        # runs (ARKS_BENCH_SERVE_MODERATE=0).
        if os.environ.get("ARKS_BENCH_SERVE_MODERATE", "1") != "0":
            # Failure-isolated: a dead moderate phase must not discard the
            # saturation numbers already measured above.
            try:
                moderate = _run_moderate_phase(
                    server.port, slots, seconds, max_tokens, prompt_len,
                    probe_len, n_chips, names, prefix_len, engine=engine)
            except Exception as e:
                import traceback
                traceback.print_exc()
                moderate = {"serving_moderate_error": f"{type(e).__name__}: {e}"}
        # Third phase: fault-recovery probe (ARKS_BENCH_RECOVERY=0 skips).
        # Failure-isolated like the moderate phase.
        if os.environ.get("ARKS_BENCH_RECOVERY", "1") != "0":
            try:
                rec = _measure_recovery(engine, server.port)
                moderate = {**(moderate or {}), **rec}
                print(f"# recovery probe: {rec}", file=sys.stderr,
                      flush=True)
            except Exception as e:
                import traceback
                traceback.print_exc()
                moderate = {**(moderate or {}),
                            "recovery_error": f"{type(e).__name__}: {e}"}
    finally:
        if proc.poll() is None:
            proc.kill()
        server.stop()
        engine.stop()

    client = json.loads(out.strip().splitlines()[-1])
    window = (t0 - t_launch, t1 - t_launch)  # in client t_start coords (~)
    ttfts = [v for ts, v in client["ttfts"]
             if window[0] <= ts <= window[1]] or \
            [v for _, v in client["ttfts"]]
    c0 = s0.get("generation_tokens_total", 0.0)
    c1 = s1.get("generation_tokens_total", 0.0)
    tok_s_chip = (c1 - c0) / (t1 - t0) / n_chips
    # Scheduler phase split over the window: where the engine thread spent
    # its wall time (fractions of the window).
    phases = {}
    for key in s1:
        if key.startswith("scheduler_seconds_total"):
            phase = key.split('phase="')[-1].rstrip('"}')
            phases[phase] = round(
                (s1[key] - s0.get(key, 0.0)) / (t1 - t0), 3)
    # Pure device-stream wait fraction: trustworthy in overlap mode, where
    # the phase-seconds wall attribution can land waits in whichever phase
    # fetched first.  Split by mode: "pipelined" waits land a full
    # pipeline slot after issue (the device computed through them), so a
    # high pipelined fraction means the HOST is the bottleneck draining
    # results, while a high "sequential" fraction is the per-step stall
    # ARKS_PIPELINE_DEPTH exists to remove.
    dw_key = "decode_resolve_wait_seconds_total"
    resolve_wait = {}
    for key in s1:
        if key.startswith(dw_key):
            mode = (key.split('mode="')[-1].rstrip('"}')
                    if "mode=" in key else "total")
        else:
            continue
        resolve_wait[mode] = resolve_wait.get(mode, 0.0) + round(
            (s1[key] - s0.get(key, 0.0)) / (t1 - t0), 3)
    device_wait = round(sum(resolve_wait.values()), 3)
    # Mean in-flight dispatches after each pipelined issue over the
    # window: at ARKS_PIPELINE_DEPTH=N steady state this reads ~N; stuck
    # near 1 means the scheduler keeps falling off the pipelined path.
    occ_n = (s1.get("pipeline_depth_occupancy_count", 0.0)
             - s0.get("pipeline_depth_occupancy_count", 0.0))
    occ_sum = (s1.get("pipeline_depth_occupancy_sum", 0.0)
               - s0.get("pipeline_depth_occupancy_sum", 0.0))
    occupancy = round(occ_sum / occ_n, 3) if occ_n else None
    hit0 = _series_sum(s0, "prefix_cache_hit_tokens_total")
    hit1 = _series_sum(s1, "prefix_cache_hit_tokens_total")
    # Speculative decoding under LOAD: the window's draft acceptance rate
    # and the goodput it buys (emitted tokens/s/chip already counts every
    # accepted token — DeepServe's acceptance-rate-driven throughput
    # argument).  Only emitted on spec engines; a collapsing acceptance
    # rate here is the same signal docs/monitoring.md alerts on.
    spec = None
    prop = (s1.get("spec_decode_proposed_tokens_total", 0.0)
            - s0.get("spec_decode_proposed_tokens_total", 0.0))
    if prop > 0:
        acc = (s1.get("spec_decode_accepted_tokens_total", 0.0)
               - s0.get("spec_decode_accepted_tokens_total", 0.0))
        spec = {
            "spec_acceptance_rate": round(acc / prop, 3),
            "spec_proposed_tok_s": round(prop / (t1 - t0), 1),
            "spec_accepted_tok_s": round(acc / (t1 - t0), 1),
            # Goodput = emitted tokens/s/chip under load; with spec on,
            # the gap between this and a no-draft run of the same ladder
            # is the speculation win at the measured acceptance rate.
            "spec_goodput_tok_s_chip": round(tok_s_chip, 1),
        }
    return {
        # Which engine path produced these numbers (kv layout, decode
        # impl, overlap...) — the resolved config, not the requested one.
        "serving_engine_config": engine.resolved_config,
        "serving_prefix_len": prefix_len,
        "serving_prefix_hit_tok_s": round((hit1 - hit0) / (t1 - t0), 1),
        "serving_tok_s_chip": round(tok_s_chip, 1),
        "serving_vs_baseline": round(tok_s_chip / BASELINE_TOK_S_CHIP, 3),
        "serving_ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1e3, 1)
        if ttfts else None,
        "serving_ttft_p99_ms": round(float(np.percentile(ttfts, 99)) * 1e3, 1)
        if ttfts else None,
        "serving_clients": clients,
        "serving_window_s": round(t1 - t0, 1),
        "serving_completed_requests": client["completed_requests"],
        "serving_client_errors": client["errors"],
        "serving_error_samples": client.get("error_samples", []),
        "serving_prompt_len": prompt_len,
        "serving_max_tokens": max_tokens,
        "serving_probe_prompt_len": probe_len,
        "serving_ttft_samples": len(ttfts),
        "serving_phase_fractions": phases,
        "serving_device_wait_fraction": device_wait,
        "decode_resolve_wait_fraction": resolve_wait,
        "pipeline_depth_occupancy": occupancy,
        **(spec or {}),
        **(moderate or {}),
    }


def run_shared_prefix_bench() -> dict:
    """``--workload shared-prefix``: a common system prompt plus
    per-client multi-turn histories that GROW each turn — the serving
    shape the hierarchical prefix cache exists for.  The paged pool is
    configured with zero retention surplus so a client's history pages
    are evicted (and spilled to the host tier) while other clients run;
    its next turn then restores them instead of re-prefilling.

    Requests are driven sequentially through the engine API and each is
    classified by hit depth from the per-tier hit-token deltas:
    tier0 (device pages), tier1 (host-tier restore), miss.  Reports
    per-tier hit tokens and the TTFT split by class — the number that
    decides whether a restore actually beats a re-prefill.

    Env knobs: ARKS_BENCH_SP_MODEL (default tiny — the CPU-mechanics
    shape), ARKS_BENCH_SP_CLIENTS, ARKS_BENCH_SP_TURNS,
    ARKS_PREFIX_HOST_MB (the tier-1 budget under test)."""
    import random

    import numpy as np

    from arks_tpu.engine import (EngineConfig, InferenceEngine, Request,
                                 SamplingParams)
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config

    model = os.environ.get("ARKS_BENCH_SP_MODEL", "tiny")
    # Enough clients that the combined history working set OVERFLOWS the
    # pool (4 slots x 8 pages): later turns then find their history
    # evicted from the device index and restore it from the host tier.
    clients = int(os.environ.get("ARKS_BENCH_SP_CLIENTS", "10"))
    turns = int(os.environ.get("ARKS_BENCH_SP_TURNS", "4"))
    cfg = get_config(model)
    chunk = 16
    # prefix_cache_mb=0 and a 2-slot pool: no retention surplus, so the
    # combined client histories cannot stay device-resident — finished
    # histories are evicted (-> spilled) by later admissions, the
    # smallest pool that still decodes, i.e. the worst case tier 1 must
    # absorb.
    ecfg = EngineConfig(model=model, num_slots=2, max_cache_len=128,
                        prefill_buckets=(16, 32), steps_per_dispatch=4,
                        prefill_chunk=chunk, kv_layout="paged",
                        prefix_cache_mb=0)
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
    eng.start()

    rng = random.Random(42)
    vocab = cfg.vocab_size
    system = [rng.randrange(3, min(200, vocab)) for _ in range(2 * chunk)]
    histories = [list(system) for _ in range(clients)]
    rows = []

    def _measure(rid, prompt):
        d0 = eng.metrics.prefix_cache_hit_tokens_total.get(tier="device")
        h0 = eng.metrics.prefix_cache_hit_tokens_total.get(tier="host")
        req = Request(rid, prompt,
                      SamplingParams(max_tokens=4, temperature=0.0,
                                     ignore_eos=True))
        eng.add_request(req)
        toks, ttft = [], None
        while True:
            out = req.outputs.get(timeout=300)
            if out.ttft_s is not None and ttft is None:
                ttft = out.ttft_s
            toks.extend(out.token_ids)
            if out.finished:
                break
        ddev = eng.metrics.prefix_cache_hit_tokens_total.get(
            tier="device") - d0
        dhost = eng.metrics.prefix_cache_hit_tokens_total.get(
            tier="host") - h0
        return toks, ttft, ddev, dhost

    try:
        # Prime every compiled program the workload hits (mixed step,
        # admit/chunk, restore scatter stays cold — it compiles on the
        # first tier-1 hit below, which is why the FIRST restore is not
        # the number to read) so the TTFT split measures serving, not
        # jit compiles.
        _measure("sp-prime",
                 [rng.randrange(3, min(200, vocab)) for _ in range(44)])
        for turn in range(turns):
            for ci in range(clients):
                prompt = histories[ci] + [
                    rng.randrange(3, min(200, vocab))
                    for _ in range(chunk - 4)]
                rid = f"sp-{ci}-{turn}"
                toks, ttft, ddev, dhost = _measure(rid, prompt)
                depth = ("tier1" if dhost > 0
                         else "tier0" if ddev > 0 else "miss")
                rows.append({"rid": rid, "client": ci, "turn": turn,
                             "depth": depth,
                             "hit_dev": ddev, "hit_host": dhost,
                             "prompt_tokens": len(prompt),
                             "ttft_s": ttft})
                histories[ci] = prompt + toks
        # Cold misses at full warmth: never-seen prompts of tier-1-hit
        # length, so the miss TTFT is a compiled-path prefill number (the
        # apples-to-apples baseline a restore must beat).
        for i in range(max(clients // 2, 3)):
            plen = len(histories[i % clients]) if histories else 76
            prompt = [rng.randrange(3, min(200, vocab))
                      for _ in range(min(plen, 90))]
            rid = f"sp-cold-{i}"
            _, ttft, ddev, dhost = _measure(rid, prompt)
            depth = ("tier1" if dhost > 0
                     else "tier0" if ddev > 0 else "miss")
            rows.append({"rid": rid, "client": -1, "turn": -1,
                         "depth": depth,
                         "hit_dev": ddev, "hit_host": dhost,
                         "prompt_tokens": len(prompt), "ttft_s": ttft})
        # Per-phase TTFT split from the engine traces, keyed by hit-depth
        # class: shows WHERE each class's TTFT goes — a tier-1 hit should
        # trade prefill time for park.restore time, and the trade only
        # pays if restore+queue comes in under the miss row's prefill.
        traces_by_rid = {}
        if eng.trace.enabled:
            eng.trace.flush()
            traces_by_rid = {t["request_id"]: t
                             for t in eng.trace.store.all()}
    finally:
        eng.stop()

    def _ttfts(depth):
        return [r["ttft_s"] for r in rows
                if r["depth"] == depth and r["ttft_s"] is not None]

    out = {
        "workload": "shared-prefix",
        "sp_model": model, "sp_clients": clients, "sp_turns": turns,
        "sp_requests": len(rows),
        "sp_prefix_host_mb": eng.resolved_config["prefix_host_mb"],
        "sp_hit_tokens_tier0": sum(r["hit_dev"] for r in rows),
        "sp_hit_tokens_tier1": sum(r["hit_host"] for r in rows),
        "sp_spilled_blocks": int(
            eng.metrics.prefix_spill_blocks_total.total()),
        "sp_restored_blocks": int(
            eng.metrics.prefix_restore_blocks_total.total()),
        "sp_requests_by_depth": {
            d: sum(1 for r in rows if r["depth"] == d)
            for d in ("tier0", "tier1", "miss")},
    }
    for depth in ("tier0", "tier1", "miss"):
        ts = _ttfts(depth)
        out[f"sp_ttft_{depth}_mean_ms"] = (
            round(float(np.mean(ts)) * 1e3, 2) if ts else None)
        if traces_by_rid:
            out[f"sp_ttft_phases_{depth}"] = _ttft_decomposition(
                [traces_by_rid[r["rid"]] for r in rows
                 if r["depth"] == depth and r["rid"] in traces_by_rid])
    return out


def _sp_clients_workload(cfg, chunk, clients, extra):
    """Deterministic per-client prompts sharing a system prefix: the
    request sequence every persistence/peer rung replays verbatim."""
    import random
    rng = random.Random(42)
    lo, hi = 3, min(200, cfg.vocab_size)
    system = [rng.randrange(lo, hi) for _ in range(2 * chunk)]
    return [(f"c{ci}", system + [rng.randrange(lo, hi)
                                 for _ in range(extra)])
            for ci in range(clients)]


def _sp_engine_measure(eng, rid, prompt, peer_hint=None):
    """One request through a started engine; returns
    (token_ids, ttft_s, per-tier hit/query/chunk deltas)."""
    from arks_tpu.engine import Request, SamplingParams
    m = eng.metrics
    b = {"query": m.prefix_cache_query_tokens_total.total(),
         "chunk": m.mixed_chunk_tokens_total.total(),
         **{t: m.prefix_cache_hit_tokens_total.get(tier=t)
            for t in ("device", "host", "disk", "peer")}}
    req = Request(rid, prompt,
                  SamplingParams(max_tokens=4, temperature=0.0,
                                 ignore_eos=True), peer_hint=peer_hint)
    eng.add_request(req)
    toks, ttft = [], None
    while True:
        out = req.outputs.get(timeout=300)
        if out.ttft_s is not None and ttft is None:
            ttft = out.ttft_s
        toks.extend(out.token_ids)
        if out.finished:
            assert out.finish_reason == "length", (rid, out)
            break
    d = {"query": m.prefix_cache_query_tokens_total.total() - b["query"],
         "chunk": m.mixed_chunk_tokens_total.total() - b["chunk"],
         **{t: m.prefix_cache_hit_tokens_total.get(tier=t) - b[t]
            for t in ("device", "host", "disk", "peer")}}
    return toks, ttft, d


def run_shared_prefix_restart_bench() -> dict:
    """``--workload shared-prefix --restart``: the tier-2 persistence
    rung.  An engine with a disk tier warms per-client shared-prefix
    prompts, stops (the graceful stop flushes warm blocks to
    ARKS_PREFIX_DISK_DIR), and a SECOND engine boots on the same
    directory and replays the identical prompts.

    The acceptance surface: the relaunched engine re-prefills ZERO
    warm-prefix full-page tokens — every full page comes back through
    the disk fetch + tier-1 restore path (only the sub-page tail is
    chunk-prefilled), the generated streams are byte-identical across
    the restart, and the warm TTFT is reported against the relaunched
    engine's own cold-miss TTFT (the re-prefill it avoided)."""
    import tempfile

    import numpy as np

    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config

    model = os.environ.get("ARKS_BENCH_SP_MODEL", "tiny")
    clients = int(os.environ.get("ARKS_BENCH_SP_CLIENTS", "4"))
    chunk = 16
    cfg = get_config(model)
    ddir = tempfile.mkdtemp(prefix="arks-bench-restart-")
    saved = {k: os.environ.get(k) for k in
             ("ARKS_PREFIX_HOST_MB", "ARKS_PREFIX_DISK_MB",
              "ARKS_PREFIX_DISK_DIR")}
    os.environ["ARKS_PREFIX_HOST_MB"] = "64"
    os.environ["ARKS_PREFIX_DISK_MB"] = "64"
    os.environ["ARKS_PREFIX_DISK_DIR"] = ddir

    def _mk():
        ecfg = EngineConfig(model=model, num_slots=2, max_cache_len=128,
                            prefill_buckets=(16, 32), steps_per_dispatch=4,
                            prefill_chunk=chunk, kv_layout="paged",
                            prefix_cache_mb=0)
        eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
        eng.start()
        return eng

    # 76-token prompts: 4 full pages (restorable) + a 12-token tail.
    work = _sp_clients_workload(cfg, chunk, clients, extra=44 - chunk)
    try:
        eng = _mk()
        cold_rows, base_toks = [], {}
        try:
            for rid, prompt in work:
                toks, ttft, d = _sp_engine_measure(eng, rid, prompt)
                base_toks[rid] = toks
                cold_rows.append({"rid": rid, "ttft_s": ttft, **d})
        finally:
            eng.stop()  # graceful: flushes warm blocks into the store

        eng2 = _mk()
        warm_rows = []
        try:
            assert eng2._disk is not None and eng2._disk.num_blocks > 0, \
                "restart bench: the disk store came up empty"
            for rid, prompt in work:
                toks, ttft, d = _sp_engine_measure(eng2, rid, prompt)
                assert toks == base_toks[rid], \
                    f"stream diverged across the restart: {rid}"
                nfull = (len(prompt) - 1) // chunk
                reprefill = (d["query"] - d["device"] - d["host"]
                             - d["disk"] - d["peer"])
                assert reprefill == len(prompt) - nfull * chunk, (
                    "warm full-page tokens were re-prefilled after the "
                    f"restart: {rid} {d}")
                warm_rows.append({"rid": rid, "ttft_s": ttft,
                                  "reprefill": reprefill, **d})
            # Cold miss on the RELAUNCHED engine: the apples-to-apples
            # re-prefill TTFT the disk restore avoided.
            import random
            rng = random.Random(9)
            miss_rows = []
            for i in range(max(clients // 2, 2)):
                prompt = [rng.randrange(3, min(200, cfg.vocab_size))
                          for _ in range(len(work[0][1]))]
                _, ttft, d = _sp_engine_measure(eng2, f"miss-{i}", prompt)
                miss_rows.append({"ttft_s": ttft, **d})
        finally:
            eng2.stop()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def _mean_ms(rows, skip_first=False):
        ts = [r["ttft_s"] for r in rows if r["ttft_s"] is not None]
        if skip_first and len(ts) > 1:
            ts = ts[1:]  # first warm row pays the restore-scatter compile
        return round(float(np.mean(ts)) * 1e3, 2) if ts else None

    return {
        "workload": "shared-prefix-restart",
        "spr2_model": model, "spr2_clients": clients,
        "spr2_prompt_tokens": len(work[0][1]),
        "spr2_identical_streams": True,
        "spr2_disk_hit_tokens": sum(r["disk"] for r in warm_rows),
        "spr2_warm_reprefill_tokens": sum(r["reprefill"]
                                          for r in warm_rows),
        "spr2_cold_chunk_tokens": sum(r["chunk"] for r in cold_rows),
        "spr2_warm_chunk_tokens": sum(r["chunk"] for r in warm_rows),
        "spr2_ttft_cold_mean_ms": _mean_ms(cold_rows),
        "spr2_ttft_warm_mean_ms": _mean_ms(warm_rows, skip_first=True),
        "spr2_ttft_miss_mean_ms": _mean_ms(miss_rows),
    }


def run_shared_prefix_peer_restore_bench() -> dict:
    """``--workload shared-prefix --peer-restore``: the fleet-wide
    restore rung.  Replica A warms the shared-prefix prompts and (after
    churn spills them into its host tier) serves raw blocks from its
    OpenAI server's ``/v1/cache/blocks/{digest}``; replica B admits the
    identical prompts with a peer hint and restores A's blocks instead
    of re-prefilling; a hint-less control replica C re-prefills.

    Asserts B's streams are byte-identical to A's and C's, and that B
    chunk-prefills STRICTLY fewer tokens than C — the paper's
    fetch-beats-prefill premise, reported as TTFT + fetched-block
    numbers per side."""
    import numpy as np

    from arks_tpu.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.paged import chain_digests
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config
    from arks_tpu.server import OpenAIServer

    model = os.environ.get("ARKS_BENCH_SP_MODEL", "tiny")
    clients = int(os.environ.get("ARKS_BENCH_SP_CLIENTS", "4"))
    chunk = 16
    cfg = get_config(model)
    saved = {k: os.environ.get(k) for k in
             ("ARKS_PREFIX_HOST_MB", "ARKS_PREFIX_DISK_MB",
              "ARKS_PEER_FETCH")}
    os.environ["ARKS_PREFIX_HOST_MB"] = "64"
    os.environ.pop("ARKS_PREFIX_DISK_MB", None)

    def _mk(peer_fetch):
        os.environ["ARKS_PEER_FETCH"] = "1" if peer_fetch else "0"
        ecfg = EngineConfig(model=model, num_slots=2, max_cache_len=128,
                            prefill_buckets=(16, 32), steps_per_dispatch=4,
                            prefill_chunk=chunk, kv_layout="paged",
                            prefix_cache_mb=0)
        eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
        eng.start()
        return eng

    work = _sp_clients_workload(cfg, chunk, clients, extra=44 - chunk)
    digests = {rid: chain_digests(prompt, chunk,
                                  (len(prompt) - 1) // chunk)
               for rid, prompt in work}
    a = srv = b = c = None
    try:
        # --- replica A: warm, churn into the host tier, serve blocks.
        a = _mk(peer_fetch=False)
        base_toks = {}
        for rid, prompt in work:
            toks, _, _ = _sp_engine_measure(a, rid, prompt)
            base_toks[rid] = toks
        i = 0
        while (not all(a._host.has(d) for ds in digests.values()
                       for d in ds) and i < 40):
            _sp_engine_measure(a, f"churn-{i}", [(9 + i) % cfg.vocab_size] * 33)
            i += 1
        assert all(a._host.has(d) for ds in digests.values() for d in ds), \
            "churn never spilled the warm prompts into A's host tier"
        srv = OpenAIServer(a, served_model_name=model + "-bench",
                           host="127.0.0.1", port=0)
        srv.start(background=True)
        hint = f"127.0.0.1:{srv.port}"

        # --- control replica C: no hint, re-prefills everything.
        c = _mk(peer_fetch=False)
        ctrl_rows = []
        for rid, prompt in work:
            toks, ttft, d = _sp_engine_measure(c, rid, prompt)
            assert toks == base_toks[rid], f"control diverged: {rid}"
            ctrl_rows.append({"ttft_s": ttft, **d})

        # --- replica B: peer hint, fetches A's blocks instead.
        b = _mk(peer_fetch=True)
        peer_rows = []
        for rid, prompt in work:
            toks, ttft, d = _sp_engine_measure(b, rid, prompt,
                                               peer_hint=hint)
            assert toks == base_toks[rid], f"peer-restored diverged: {rid}"
            peer_rows.append({"ttft_s": ttft, **d})
        fetched = int(b.metrics.prefix_peer_fetch_blocks_total.get(
            source="peer"))
        assert fetched > 0, "the peer-restore rung never fetched a block"
        b_chunk = sum(r["chunk"] for r in peer_rows)
        c_chunk = sum(r["chunk"] for r in ctrl_rows)
        assert b_chunk < c_chunk, (
            "peer restore must chunk-prefill strictly fewer tokens than "
            f"the no-fetch control: {b_chunk} vs {c_chunk}")
        fs = b.metrics.prefix_peer_fetch_seconds._data.get(())
        fetch_mean_ms = (round(fs[1] / fs[2] * 1e3, 2)
                         if fs and fs[2] else None)
    finally:
        for x in (srv, b, c, a):
            if x is not None:
                x.stop()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def _mean_ms(rows):
        ts = [r["ttft_s"] for r in rows if r["ttft_s"] is not None]
        return round(float(np.mean(ts)) * 1e3, 2) if ts else None

    return {
        "workload": "shared-prefix-peer-restore",
        "spp_model": model, "spp_clients": clients,
        "spp_prompt_tokens": len(work[0][1]),
        "spp_identical_streams": True,
        "spp_peer_fetched_blocks": fetched,
        "spp_peer_hit_tokens": sum(r["peer"] for r in peer_rows),
        "spp_peer_chunk_tokens": b_chunk,
        "spp_control_chunk_tokens": c_chunk,
        "spp_peer_fetch_mean_ms": fetch_mean_ms,
        "spp_ttft_peer_mean_ms": _mean_ms(peer_rows),
        "spp_ttft_control_mean_ms": _mean_ms(ctrl_rows),
    }


def run_slo_tiers_bench() -> dict:
    """``--workload slo-tiers``: the preemptive-KV-swap acceptance bench
    (CPU mechanics).  A mixed load — long batch-tier decodes occupying
    every slot, latency-tier arrivals landing while the pool is full —
    runs twice on identical tiny engines: ARKS_PREEMPT=1 (latency
    arrivals seize slots by swapping batch decode state to host RAM) and
    ARKS_PREEMPT=0 (they wait for a batch stream to finish).  Asserts
    the two claims from the PR's acceptance criteria:

    - latency-tier TTFT p50 with preemption is STRICTLY below the
      preemption-off p50 under the same load;
    - every preempted-and-resumed batch stream is byte-identical to its
      unpreempted run (the swap is a pure schedule change).

    Env knobs: ARKS_BENCH_SLO_MODEL (default tiny), ARKS_BENCH_SLO_WAVES
    (latency-arrival waves, default 3), ARKS_PREFIX_HOST_MB (swap budget,
    default 64 here — 0 exercises the replay fallback instead)."""
    import numpy as np

    from arks_tpu.engine import (EngineConfig, InferenceEngine, Request,
                                 SamplingParams)
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config

    model = os.environ.get("ARKS_BENCH_SLO_MODEL", "tiny")
    waves = int(os.environ.get("ARKS_BENCH_SLO_WAVES", "3"))
    cfg = get_config(model)
    os.environ.setdefault("ARKS_PREFIX_HOST_MB", "64")
    os.environ["ARKS_SLO_TIERS"] = "latency:ttft_ms=300,batch:"
    os.environ["ARKS_MIXED_STEP"] = "auto"

    def _mk():
        eng = InferenceEngine(cfg, EngineConfig(
            model=model, num_slots=2, max_cache_len=128,
            prefill_buckets=(16, 32), steps_per_dispatch=2,
            prefill_chunk=16, kv_layout="paged", prefix_cache_mb=0),
            ByteTokenizer())
        return eng

    def _drive(eng, n=20000):
        for _ in range(n):
            eng.step(block_s=0.01)
            if eng.idle:
                return
        raise RuntimeError("slo-tiers workload did not drain")

    def _collect(req):
        toks, ttft, fin = [], None, None
        while True:
            out = req.outputs.get(timeout=300)
            if out.ttft_s is not None and ttft is None:
                ttft = out.ttft_s
            toks.extend(out.token_ids)
            if out.finished:
                fin = out
                break
        return toks, ttft, fin.finish_reason

    def _batch_req(rid, i):
        return Request(rid, [3 + i, 5, 7 + i], SamplingParams(
            max_tokens=48, temperature=0.9, top_p=0.9, top_k=40,
            seed=11 + i, ignore_eos=True, priority=1))

    def _lat_req(rid, i):
        return Request(rid, [9, 9, 9, 2 + i], SamplingParams(
            max_tokens=4, temperature=0.0, ignore_eos=True, priority=0))

    def _run_mode(preempt: bool) -> dict:
        os.environ["ARKS_PREEMPT"] = "1" if preempt else "0"
        eng = _mk()
        if preempt:
            # Prime the swap/resume compiled paths (gather/scatter/sampler
            # row jits) on a throwaway preempt cycle so the measured TTFTs
            # are serving numbers, not jit compiles.
            b = _batch_req("prime-b", 0)
            eng.add_request(b)
            for _ in range(10):
                eng.step(block_s=0.01)
            l = _lat_req("prime-l", 0)
            eng.add_request(l)
            _drive(eng)
            _collect(b), _collect(l)
        else:
            b = _batch_req("prime-b", 0)
            eng.add_request(b)
            _drive(eng)
            _collect(b)
        batch_streams: dict[str, list] = {}
        lat_ttfts: list[float] = []
        for w in range(waves):
            bts = [_batch_req(f"bt-{w}-{i}", i) for i in range(2)]
            for r in bts:
                eng.add_request(r)
            # Let both batch requests admit and decode a few tokens so
            # the pool is genuinely full when the latency wave lands.
            for _ in range(12):
                eng.step(block_s=0.01)
            lts = [_lat_req(f"lt-{w}-{i}", i) for i in range(2)]
            for r in lts:
                eng.add_request(r)
            _drive(eng)
            for r in bts:
                toks, _, reason = _collect(r)
                batch_streams[r.request_id] = [toks, reason]
            for r in lts:
                toks, ttft, reason = _collect(r)
                assert reason == "length", (r.request_id, reason)
                lat_ttfts.append(ttft)
        pre = eng.metrics.requests_preempted_total
        out = {
            "mode": eng.resolved_config.get("preempt", "off"),
            "lat_ttft_p50_ms": round(
                float(np.percentile(lat_ttfts, 50)) * 1e3, 2),
            "lat_ttft_p95_ms": round(
                float(np.percentile(lat_ttfts, 95)) * 1e3, 2),
            "preempted_total": int(sum(pre._values.values())),
            "batch_streams": batch_streams,
        }
        if preempt:
            # Histogram internals: {labels: (bucket_counts, sum, count)}.
            data = eng.metrics.preempt_swap_seconds._data.values()
            total = sum(t for _, t, _ in data)
            n = sum(c for _, _, c in data)
            out["preempt_swap_s_mean"] = round(total / n, 4) if n else None
        return out

    on = _run_mode(True)
    off = _run_mode(False)
    assert on["preempted_total"] > 0, \
        "preempt run never preempted — the workload is not exercising swap"
    assert on["batch_streams"] == off["batch_streams"], \
        "preempted batch streams diverged from the unpreempted run"
    assert on["lat_ttft_p50_ms"] < off["lat_ttft_p50_ms"], (
        f"preemption did not improve latency-tier TTFT p50: "
        f"{on['lat_ttft_p50_ms']}ms (on) vs {off['lat_ttft_p50_ms']}ms (off)")
    return {
        "workload": "slo-tiers",
        "slo_model": model, "slo_waves": waves,
        "slo_mode": on["mode"],
        "slo_prefix_host_mb": int(os.environ["ARKS_PREFIX_HOST_MB"]),
        "slo_preempted_total": on["preempted_total"],
        "slo_preempt_swap_s_mean": on.get("preempt_swap_s_mean"),
        "slo_batch_streams_identical": True,
        "lat_ttft_p50_preempt_ms": on["lat_ttft_p50_ms"],
        "lat_ttft_p50_off_ms": off["lat_ttft_p50_ms"],
        "lat_ttft_p95_preempt_ms": on["lat_ttft_p95_ms"],
        "lat_ttft_p95_off_ms": off["lat_ttft_p95_ms"],
    }


def run_long_context_bench() -> dict:
    """``--workload long-context``: the windowed-residency acceptance
    bench (CPU mechanics; the Pallas mixed path runs in interpret mode).
    One decode stream grows a context strictly larger than the device
    page pool; the windowed engine (ARKS_RESIDENCY_WINDOW_PAGES) spills
    cold pages to pinned host RAM and streams them back span-by-span
    each forward, issuing the H2D prefetch for span i+1 before the
    attend of span i is dispatched.  Asserts the rung's acceptance
    criteria:

    - the final context is strictly larger than the device page pool;
    - the windowed stream (token ids AND top-logprob floats) is
      byte-identical to a large-pool control engine at pipeline depth 2;
    - prefetch overlap is visible in the trace decomposition: residency
      prefetch spans land ahead of the attend that consumes them.

    Env knobs: ARKS_BENCH_LC_MODEL (default tiny), ARKS_BENCH_LC_WINDOW
    (resident pages per slot, default 6), ARKS_BENCH_LC_PROMPT (default
    40), ARKS_BENCH_LC_GEN (default 70), ARKS_BENCH_LC_DEPTH (pipeline
    depth, default 2)."""
    import queue as _queue

    from arks_tpu.engine import (EngineConfig, InferenceEngine, Request,
                                 SamplingParams)
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config

    model = os.environ.get("ARKS_BENCH_LC_MODEL", "tiny")
    window = int(os.environ.get("ARKS_BENCH_LC_WINDOW", "6"))
    prompt_len = int(os.environ.get("ARKS_BENCH_LC_PROMPT", "40"))
    gen = int(os.environ.get("ARKS_BENCH_LC_GEN", "70"))
    depth = int(os.environ.get("ARKS_BENCH_LC_DEPTH", "2"))
    cfg = get_config(model)
    os.environ["ARKS_MIXED_STEP"] = "1"
    os.environ["ARKS_ATTN_IMPL"] = "pallas"
    os.environ["ARKS_PIPELINE_DEPTH"] = str(depth)
    os.environ["ARKS_TRACE"] = "1"
    os.environ["ARKS_TRACE_RING"] = "65536"
    os.environ["ARKS_TRACE_SAMPLE"] = "1.0"

    def _mk(win):
        os.environ["ARKS_RESIDENCY_WINDOW_PAGES"] = str(win)
        eng = InferenceEngine(cfg, EngineConfig(
            model=model, num_slots=1, max_cache_len=256,
            prefill_buckets=(8, 16, 32), steps_per_dispatch=4,
            prefill_chunk=16, kv_layout="paged", prefix_cache_mb=0),
            ByteTokenizer())
        if depth:
            assert eng._pipe_warm_wait(300) == "ready"
        return eng

    def _run(eng):
        """Drive one long greedy+logprobs decode; stamp the wall time of
        every emitted token so tok/s splits at the engagement point."""
        r = Request("lc",
                    [(3 + i) % cfg.vocab_size for i in range(prompt_len)],
                    SamplingParams(max_tokens=gen, temperature=0.0,
                                   ignore_eos=True, logprobs=2))
        eng.add_request(r)
        ids, lps, stamps, fin = [], [], [], None
        for _ in range(50000):
            eng.step(block_s=0.01)
            while True:
                try:
                    out = r.outputs.get_nowait()
                except _queue.Empty:
                    break
                now = time.perf_counter()
                for t in out.token_ids:
                    ids.append(t)
                    stamps.append(now)
                if out.logprobs:
                    lps.extend(out.logprobs)
                if out.finished:
                    fin = out
            if fin is not None and eng.idle:
                break
        assert fin is not None, "long-context stream did not finish"
        return ids, lps, fin.finish_reason, stamps

    # -- windowed run -----------------------------------------------------
    eng = _mk(window)
    page = eng._page_size()
    pool_pages = eng._alloc.num_pages
    pool_tokens = pool_pages * page
    ids, lps, reason, stamps = _run(eng)
    final_ctx = prompt_len + len(ids)
    assert final_ctx > pool_tokens, (
        f"context {final_ctx} never outgrew the pool {pool_tokens} — "
        f"raise ARKS_BENCH_LC_GEN")
    spans = int(eng.metrics.residency_spans_total.total())
    prefetch_pages = int(
        eng.metrics.residency_prefetch_pages_total.total())
    assert spans > 0 and prefetch_pages > 0, (spans, prefetch_pages)

    # tok/s before vs after window engagement.  Engagement is
    # deterministic: the step whose context needs more pages than the
    # window flips the slot to windowed residency.
    max_pages = eng._max_pages
    from arks_tpu.engine.paged import pages_needed
    split = next((k for k in range(len(ids))
                  if pages_needed(prompt_len + k + 1, 1, page,
                                  max_pages) > window), len(ids))

    def _rate(ts):
        if len(ts) < 2 or ts[-1] <= ts[0]:
            return None
        return round((len(ts) - 1) / (ts[-1] - ts[0]), 2)

    # -- trace decomposition ---------------------------------------------
    # residency.prefetch / residency.attend B/E pairs carry the page span
    # [lo, hi) as arg.  A prefetch is "issued ahead" when the very next
    # attend dispatched after it targets a DIFFERENT span — i.e. the
    # scatter for span i+1 was already on the device stream before the
    # attend of span i ran, so it never serializes with its consumer.
    evs = [e for e in eng.trace.tail(65536)
           if e["name"] in ("residency.prefetch", "residency.attend")]
    decomp = {"residency.prefetch": [0, 0.0], "residency.attend": [0, 0.0]}
    open_b: dict = {}
    ahead = 0
    pending_prefetch = []  # (arg,) prefetches waiting for their next attend
    for e in evs:
        if e["ph"] == "B":
            open_b[e["name"]] = e
            if e["name"] == "residency.prefetch":
                pending_prefetch.append(e["arg"])
            else:
                ahead += sum(1 for a in pending_prefetch if a != e["arg"])
                pending_prefetch.clear()
        elif e["ph"] == "E" and e["name"] in open_b:
            b = open_b.pop(e["name"])
            d = decomp[e["name"]]
            d[0] += 1
            d[1] += e["t"] - b["t"]
    n_pre, t_pre = decomp["residency.prefetch"]
    n_att, t_att = decomp["residency.attend"]
    assert n_pre > 0 and n_att > 0, "residency trace events missing"
    assert ahead > 0, (
        "no prefetch landed ahead of its consuming attend — the overlap "
        "schedule regressed")

    # -- large-pool control (same traffic, full-width pool) ---------------
    ctl = _mk(0)
    ctl_pool = ctl._alloc.num_pages * ctl._page_size()
    assert ctl_pool >= final_ctx, "control pool too small to be a control"
    c_ids, c_lps, c_reason, _ = _run(ctl)
    assert (ids, lps, reason) == (c_ids, c_lps, c_reason), \
        "windowed stream diverged from the large-pool control"

    return {
        "workload": "long-context",
        "lc_model": model, "lc_window_pages": window,
        "lc_pipeline_depth": depth,
        "lc_pool_pages": pool_pages, "lc_pool_tokens": pool_tokens,
        "lc_final_context_tokens": final_ctx,
        "lc_finish_reason": reason,
        "lc_streams_identical": True,
        "lc_residency_spans_total": spans,
        "lc_residency_prefetch_pages_total": prefetch_pages,
        "lc_decode_toks_resident": _rate(stamps[:split]),
        "lc_decode_toks_windowed": _rate(stamps[split:]),
        "lc_trace_attend_spans": n_att,
        "lc_trace_attend_ms_total": round(t_att * 1e3, 2),
        "lc_trace_prefetch_events": n_pre,
        "lc_trace_prefetch_ms_total": round(t_pre * 1e3, 2),
        "lc_trace_prefetch_issued_ahead": ahead,
        "lc_trace_prefetch_ahead_frac": round(ahead / n_pre, 3),
    }


def run_multi_tenant_bench() -> dict:
    """``--workload multi-tenant``: the tenant-fair admission acceptance
    bench (CPU mechanics).  One aggressor tenant floods the engine with a
    sustained backlog of short streams while a victim tenant submits a
    steady serial trickle — the same SLO tier, so only the weighted-fair
    queue separates them.  Runs the contended phase twice (ARKS_FAIR=1
    and ARKS_FAIR=0) at pipeline depths 0 and 2, plus an unloaded victim
    baseline, and asserts the PR's acceptance criteria:

    - fairness ON keeps victim TTFT p50 within the gate
      ``ARKS_BENCH_MT_FACTOR x unloaded + ARKS_BENCH_MT_BUDGET_STEPS x
      mean contended dispatch`` at each depth.  The explicit dispatch
      budget absorbs the fixed few-step scheduling cost (slot wait +
      pipeline occupancy) that is microseconds on a real accelerator
      but swamps the tiny unloaded baseline on this CPU-mechanics
      bench; the 1.3x factor is the paper's acceptance ratio;
    - fairness OFF must VIOLATE that same gate AND sit strictly above
      the fair run — the flood buries the victim in the FIFO;
    - every surviving stream is byte-identical fairness on vs off (the
      fair queue is a pure admission reorder);
    - bounded-queue sheds carry a usable Retry-After (>= 1s);
    - metered usage is exact: every finished stream's accounting equals
      the tokens actually delivered (= max_tokens under ignore_eos).

    Env knobs: ARKS_BENCH_MT_WAVES (victim requests per phase, default
    12), ARKS_BENCH_MT_FLOOD (standing aggressor backlog, default 24),
    ARKS_BENCH_MT_FACTOR (victim p50 ratio vs unloaded, default 1.3),
    ARKS_BENCH_MT_BUDGET_STEPS (dispatch-interference budget, default
    6)."""
    import numpy as np

    from arks_tpu.engine import (EngineConfig, InferenceEngine, Request,
                                 SamplingParams)
    from arks_tpu.engine import fairqueue
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config

    waves = int(os.environ.get("ARKS_BENCH_MT_WAVES", "12"))
    flood = int(os.environ.get("ARKS_BENCH_MT_FLOOD", "24"))
    factor = float(os.environ.get("ARKS_BENCH_MT_FACTOR", "1.3"))
    budget_steps = int(os.environ.get("ARKS_BENCH_MT_BUDGET_STEPS", "6"))
    AGG, VIC = "bench/aggressor", "bench/victim"
    cfg = get_config("tiny")

    def _mk(depth: int):
        os.environ["ARKS_PIPELINE_DEPTH"] = str(depth)
        # Quantum sized to a handful of requests (costs here are 5-17
        # tokens): the default 512 would let one ring visit drain a whole
        # tenant backlog before rotating.
        os.environ["ARKS_FAIR_QUANTUM_TOKENS"] = "8"
        return InferenceEngine(cfg, EngineConfig(
            model="tiny", num_slots=4, max_cache_len=64,
            prefill_buckets=(16,), steps_per_dispatch=1,
            prefill_chunk=16, kv_layout="paged", prefix_cache_mb=0),
            ByteTokenizer())

    def _agg_req(rid, i):
        # Short streams: slots churn constantly, so a fair pick admits
        # the victim within a step or two of a slot freeing.
        return Request(rid, [3 + (i % 5), 5, 7], SamplingParams(
            max_tokens=1, temperature=0.9, top_p=0.9, seed=31 + i,
            ignore_eos=True), tenant=AGG)

    def _vic_req(rid, i):
        # A full prefill chunk: victim TTFT is prefill-dominated, so the
        # fair-on flood overhead (a step or two of slot wait) stays
        # within the 1.3x acceptance budget while the unfair FIFO still
        # degrades it by the whole backlog.
        return Request(rid, [9] * 14 + [2 + (i % 3)], SamplingParams(
            max_tokens=2, temperature=0.8, seed=77 + i,
            ignore_eos=True), tenant=VIC)

    def _collect(req):
        toks, ttft, fin = [], None, None
        while True:
            out = req.outputs.get(timeout=300)
            if out.ttft_s is not None and ttft is None:
                ttft = out.ttft_s
            toks.extend(out.token_ids)
            if out.finished:
                fin = out
                break
        return toks, ttft, fin

    def _prime(eng):
        # Warm every compiled path on a throwaway request so measured
        # TTFTs are serving numbers, not jit compiles.
        r = _vic_req("prime", 0)
        eng.add_request(r)
        while not eng.idle:
            eng.step(block_s=0.01)
        _collect(r)

    def _run_to_finish(eng, req, clock):
        """Step the engine until ``req`` finishes, draining its output
        queue as it goes (other requests' queues buffer — collected once
        the engine drains).  ``clock`` accumulates [steps, seconds] so
        the contended phase knows its own mean dispatch time."""
        toks, ttft, fin = [], None, None
        for _ in range(20000):
            while not req.outputs.empty():
                out = req.outputs.get()
                if out.ttft_s is not None and ttft is None:
                    ttft = out.ttft_s
                toks.extend(out.token_ids)
                if out.finished:
                    fin = out
            if fin is not None:
                return toks, ttft, fin
            t0 = time.monotonic()
            eng.step(block_s=0.01)
            clock[0] += 1
            clock[1] += time.monotonic() - t0
        raise RuntimeError("multi-tenant workload did not progress")

    def _unloaded(depth: int) -> float:
        eng = _mk(depth)
        _prime(eng)
        ttfts = []
        for i in range(waves):
            r = _vic_req(f"base-{i}", i)
            eng.add_request(r)
            while not eng.idle:
                eng.step(block_s=0.01)
            _, ttft, _ = _collect(r)
            ttfts.append(ttft)
        eng.stop()
        return float(np.percentile(ttfts, 50))

    def _contended(depth: int, fair: bool) -> dict:
        os.environ["ARKS_FAIR"] = "1" if fair else "0"
        eng = _mk(depth)
        _prime(eng)
        streams: dict[str, list] = {}
        agg_reqs = [_agg_req(f"agg-{i}", i) for i in range(flood)]
        n_agg = 0
        backlog: list = []
        for r in agg_reqs:
            eng.add_request(r)
            backlog.append(r)
            n_agg += 1
        # Let the flood fill every slot before the victim shows up.
        for _ in range(8):
            eng.step(block_s=0.01)
        ttfts, usage_exact, clock = [], True, [0, 0.0]
        for i in range(waves):
            # Top up the flood to a STANDING backlog >= flood before each
            # victim arrival — the unfair FIFO must have a real queue to
            # bury the victim behind.
            while eng.saturation()["queue_depth"] < flood:
                r = _agg_req(f"agg-{n_agg}", n_agg)
                eng.add_request(r)
                backlog.append(r)
                n_agg += 1
            v = _vic_req(f"vic-{i}", i)
            eng.add_request(v)
            toks, ttft, fin = _run_to_finish(eng, v, clock)
            ttfts.append(ttft)
            streams[v.request_id] = toks
            usage_exact &= (fin.num_generated_tokens == len(toks)
                            == v.params.max_tokens)
        while not eng.idle:
            eng.step(block_s=0.01)
        for r in backlog:
            toks, _, fin = _collect(r)
            streams[r.request_id] = toks
            usage_exact &= (fin.num_generated_tokens == len(toks)
                            == r.params.max_tokens)
        eng.stop()
        return {"ttft_p50_s": float(np.percentile(ttfts, 50)),
                "step_s": clock[1] / max(clock[0], 1),
                "streams": streams, "usage_exact": usage_exact}

    def _shed_probe() -> dict:
        # Bounded-queue rejection carries a drain-derived Retry-After.
        os.environ["ARKS_FAIR"] = "1"
        os.environ["ARKS_QUEUE_TENANT_MAX"] = "4"
        try:
            eng = _mk(0)
            sheds = []
            reqs = []
            for i in range(10):
                r = _agg_req(f"shed-{i}", i)
                try:
                    eng.add_request(r)
                    reqs.append(r)
                except fairqueue.QueueFullError as e:
                    sheds.append(e)
            assert sheds, "tenant cap 4 never shed a 10-request flood"
            assert all(e.retry_after >= 1 for e in sheds), \
                "shed without a usable Retry-After"
            assert all(e.scope == "tenant" for e in sheds)
            # The victim's lane is untouched by the aggressor's cap.
            v = _vic_req("shed-vic", 0)
            eng.add_request(v)
            while not eng.idle:
                eng.step(block_s=0.01)
            _collect(v)
            for r in reqs:
                _collect(r)
            eng.stop()
            return {"sheds": len(sheds),
                    "retry_after_s": sheds[0].retry_after}
        finally:
            del os.environ["ARKS_QUEUE_TENANT_MAX"]

    out = {"workload": "multi-tenant", "waves": waves, "flood": flood,
           "factor": factor}
    for depth in (0, 2):
        base = _unloaded(depth)
        on = _contended(depth, fair=True)
        off = _contended(depth, fair=False)
        assert on["usage_exact"] and off["usage_exact"], \
            "metered usage diverged from delivered tokens"
        # Byte-identity gate: every request served by BOTH arms must
        # stream the same bytes — the fair queue is a pure admission
        # reorder.  (The standing-backlog top-up mints however many
        # aggressors each arm's drain rate calls for, so the key sets
        # differ; victims are the fixed cohort and must be in both.)
        common = set(on["streams"]) & set(off["streams"])
        assert all(f"vic-{i}" in common for i in range(waves)), \
            f"depth {depth}: a victim stream is missing from one arm"
        diverged = [k for k in sorted(common)
                    if on["streams"][k] != off["streams"][k]]
        assert not diverged, (
            f"depth {depth}: streams diverged fairness on vs off "
            f"({diverged[:5]}) — the fair queue must be a pure "
            "admission reorder")
        # The fairness gate: victim p50 within factor x unloaded, plus an
        # explicit interference budget of a few contended dispatch times
        # (budget_steps x the phase's own mean step).  On accelerators a
        # dispatch is microseconds and the budget vanishes into the 1.3x;
        # on this CPU-mechanics bench the fixed few-dispatch scheduling
        # cost (slot wait + pipeline occupancy) would otherwise swamp the
        # tiny unloaded baseline.  The control arm must VIOLATE the same
        # gate — that is what "the flood buries the victim" means.
        gate = factor * base + budget_steps * on["step_s"]
        assert on["ttft_p50_s"] <= gate, (
            f"depth {depth}: victim TTFT p50 {on['ttft_p50_s'] * 1e3:.1f}ms "
            f"under flood exceeds the fairness gate {gate * 1e3:.1f}ms "
            f"({factor}x unloaded {base * 1e3:.1f}ms + {budget_steps} "
            f"dispatches) with fairness ON")
        assert off["ttft_p50_s"] > gate, (
            f"depth {depth}: fairness OFF still met the gate "
            f"({off['ttft_p50_s'] * 1e3:.1f}ms <= {gate * 1e3:.1f}ms) — "
            "the flood is not flooding")
        assert off["ttft_p50_s"] > on["ttft_p50_s"], (
            f"depth {depth}: fairness OFF did not degrade the victim "
            f"({off['ttft_p50_s'] * 1e3:.1f}ms vs "
            f"{on['ttft_p50_s'] * 1e3:.1f}ms)")
        out[f"d{depth}_unloaded_ttft_p50_ms"] = round(base * 1e3, 2)
        out[f"d{depth}_fair_ttft_p50_ms"] = round(
            on["ttft_p50_s"] * 1e3, 2)
        out[f"d{depth}_unfair_ttft_p50_ms"] = round(
            off["ttft_p50_s"] * 1e3, 2)
        out[f"d{depth}_gate_ms"] = round(gate * 1e3, 2)
        out[f"d{depth}_step_ms"] = round(on["step_s"] * 1e3, 3)
        out[f"d{depth}_streams_identical"] = True
    out.update(_shed_probe())
    os.environ.pop("ARKS_FAIR", None)
    return out


def run_shared_prefix_router_bench(n_backends: int) -> dict:
    """``--workload shared-prefix --backends N``: the multi-backend
    routing comparison.  N in-process engines (each behind a real
    OpenAIServer) sit behind a real Router in unified mode; the same
    multi-turn shared-prefix workload runs once per routing policy —

    - ``sketch``      cache_aware, sketch scoring on (the PR under test)
    - ``rendezvous``  cache_aware with ARKS_ROUTER_SKETCH=0 (prefix-key
                      rendezvous only, the pre-sketch behavior)
    - ``random``      round_robin

    — on a FRESH fleet each time, driving token-id prompts (token-domain
    scoring, no tokenizer in the router) with streamed responses.  TTFT
    is the first SSE content frame; re-prefilled tokens per policy =
    prefix-query tokens minus per-tier hit tokens, summed over backends.
    Asserts byte-identical generated streams per request across policies
    (any replica must serve the same bytes) and that sketch routing
    strictly beats random on BOTH aggregate TTFT and re-prefilled tokens.

    CPU mechanics: the tiny model keeps compile budgets flat; the
    numbers compare routing policies, not absolute hardware speed."""
    import random
    import urllib.request

    import numpy as np

    from arks_tpu.engine import (EngineConfig, InferenceEngine, Request,
                                 SamplingParams)
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config
    from arks_tpu.router import Discovery, Router
    from arks_tpu.server import OpenAIServer

    model = os.environ.get("ARKS_BENCH_SP_MODEL", "tiny")
    clients = int(os.environ.get("ARKS_BENCH_SP_CLIENTS", "8"))
    turns = int(os.environ.get("ARKS_BENCH_SP_TURNS", "3"))
    chunk = 16
    cfg = get_config(model)
    policies = (("sketch", "cache_aware", "1"),
                ("rendezvous", "cache_aware", "0"),
                ("random", "round_robin", "1"))

    def _workload():
        """The identical request sequence every policy replays: a shared
        system prefix, then per-client histories that each turn extend
        the PREVIOUS prompt (so its pages are reusable) plus fresh
        tokens.  Deterministic — byte-identity across policies depends
        on it."""
        rng = random.Random(42)
        lo, hi = 3, min(200, cfg.vocab_size)
        system = [rng.randrange(lo, hi) for _ in range(2 * chunk)]
        histories = [list(system) for _ in range(clients)]
        seq = []
        for turn in range(turns):
            # Shuffled arrival order: real traffic is not aligned to the
            # fleet size, and without this a round-robin counter can land
            # every client on the same backend each turn by arithmetic
            # accident (clients % n_backends == 0), faking affinity.
            for ci in rng.sample(range(clients), clients):
                prompt = histories[ci] + [rng.randrange(lo, hi)
                                          for _ in range(chunk)]
                seq.append((f"c{ci}-t{turn}", turn, prompt))
                histories[ci] = prompt
        return seq

    def _stream_one(port, rid, prompt):
        """POST through the router, streamed.  Returns (ttft_s, text)."""
        body = json.dumps({"model": model + "-bench", "prompt": prompt,
                           "max_tokens": 4, "temperature": 0,
                           "ignore_eos": True, "stream": True}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        t0 = time.monotonic()
        ttft, text = None, []
        with urllib.request.urlopen(req, timeout=300) as resp:
            for raw in resp:
                line = raw.decode("utf-8", "replace").strip()
                if not line.startswith("data: "):
                    continue
                payload = line[len("data: "):]
                if payload == "[DONE]":
                    break
                frame = json.loads(payload)
                piece = (frame.get("choices") or [{}])[0].get("text")
                if piece:
                    if ttft is None:
                        ttft = time.monotonic() - t0
                    text.append(piece)
        return ttft, "".join(text)

    def _run_policy(name, policy, sketch_flag):
        saved = {k: os.environ.get(k) for k in
                 ("ARKS_PREFIX_HOST_MB", "ARKS_ROUTER_SKETCH",
                  "ARKS_ROUTER_SKETCH_POLL_S", "ARKS_PREFILL_ADDRS",
                  "ARKS_DECODE_ADDRS")}
        engines, servers, router = [], [], None
        try:
            os.environ["ARKS_PREFIX_HOST_MB"] = "8"
            os.environ["ARKS_ROUTER_SKETCH"] = sketch_flag
            # The bench drives poll_once() itself between turns.
            os.environ["ARKS_ROUTER_SKETCH_POLL_S"] = "600"
            rngp = random.Random(7)
            for _ in range(n_backends):
                # prefix_cache_mb=1: a retention surplus, so a session's
                # history STAYS device-resident on its home backend — the
                # locality the routing policies are competing to exploit.
                ecfg = EngineConfig(model=model, num_slots=2,
                                    max_cache_len=128,
                                    prefill_buckets=(16, 32),
                                    steps_per_dispatch=4,
                                    prefill_chunk=chunk, kv_layout="paged",
                                    prefix_cache_mb=1)
                eng = InferenceEngine(cfg, ecfg, ByteTokenizer())
                eng.start()
                srv = OpenAIServer(eng, served_model_name=model + "-bench",
                                   host="127.0.0.1", port=0)
                srv.start(background=True)
                engines.append(eng)
                servers.append(srv)
                # Prime the compiled programs so TTFT measures serving.
                prime = Request("prime", [rngp.randrange(3, 200)
                                         for _ in range(44)],
                                SamplingParams(max_tokens=4, temperature=0.0,
                                               ignore_eos=True))
                eng.add_request(prime)
                while not prime.outputs.get(timeout=300).finished:
                    pass
            os.environ["ARKS_PREFILL_ADDRS"] = ""
            os.environ["ARKS_DECODE_ADDRS"] = ",".join(
                f"127.0.0.1:{s.port}" for s in servers)
            router = Router(Discovery(None), model + "-bench",
                            host="127.0.0.1", port=0, policy=policy,
                            unified=True)
            router.start(background=True)
            base = [{
                "query": e.metrics.prefix_cache_query_tokens_total.total(),
                "dev": e.metrics.prefix_cache_hit_tokens_total.get(
                    tier="device"),
                "host": e.metrics.prefix_cache_hit_tokens_total.get(
                    tier="host"),
            } for e in engines]
            ttfts, texts = [], {}
            last_turn = -1
            for rid, turn, prompt in _workload():
                if turn != last_turn:
                    if router.sketch_on:
                        router.sketches.poll_once()
                    last_turn = turn
                ttft, text = _stream_one(router.port, rid, prompt)
                ttfts.append(ttft)
                texts[rid] = text
            dev = sum(e.metrics.prefix_cache_hit_tokens_total.get(
                tier="device") - b["dev"] for e, b in zip(engines, base))
            host = sum(e.metrics.prefix_cache_hit_tokens_total.get(
                tier="host") - b["host"] for e, b in zip(engines, base))
            query = sum(
                e.metrics.prefix_cache_query_tokens_total.total() - b["query"]
                for e, b in zip(engines, base))
            decisions = {
                reason: int(router.metrics.route_decisions_total.get(
                    reason=reason))
                for reason in ("sketch_hit", "tie_fallback", "stale_sketch",
                               "no_key")}
            measured = [t for t in ttfts if t is not None]
            return {
                "texts": texts,
                "ttft_sum_ms": round(float(np.sum(measured)) * 1e3, 1),
                "ttft_mean_ms": round(float(np.mean(measured)) * 1e3, 2),
                "ttft_samples": len(measured),
                "hit_tokens_tier0": int(dev),
                "hit_tokens_tier1": int(host),
                "reprefill_tokens": int(query - dev - host),
                "route_decisions": decisions,
            }
        finally:
            if router is not None:
                router.stop()
            for s in servers:
                s.stop()
            for e in engines:
                e.stop()
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    results = {}
    for name, policy, sketch_flag in policies:
        results[name] = _run_policy(name, policy, sketch_flag)

    # Byte-identity: every request's generated stream is identical no
    # matter which replica (or policy) served it.
    ref = results["sketch"]["texts"]
    for name in ("rendezvous", "random"):
        other = results[name]["texts"]
        assert set(other) == set(ref)
        diff = [rid for rid in ref if other[rid] != ref[rid]]
        assert not diff, f"streams diverge between sketch and {name}: {diff}"
    summary = {name: {k: v for k, v in r.items() if k != "texts"}
               for name, r in results.items()}
    assert (results["sketch"]["reprefill_tokens"]
            < results["random"]["reprefill_tokens"]), (
        "sketch routing must strictly reduce re-prefilled tokens vs "
        f"random: {summary}")
    assert (results["sketch"]["ttft_sum_ms"]
            < results["random"]["ttft_sum_ms"]), (
        "sketch routing must strictly reduce aggregate TTFT vs random: "
        f"{summary}")

    out = {
        "workload": "shared-prefix-router",
        "spr_model": model, "spr_backends": n_backends,
        "spr_clients": clients, "spr_turns": turns,
        "spr_requests": clients * turns,
        "spr_identical_streams": True,
    }
    for name in results:
        for k, v in results[name].items():
            if k != "texts":
                out[f"spr_{name}_{k}"] = v
    return out


def run_multi_model_bench() -> dict:
    """``--workload multi-model``: two models on ONE engine process with
    bursty alternating traffic — the serverless-LLM shape the weight pool
    exists for.  The second model's first burst lands while the first
    model is mid-decode, so its weights stream against live pipelined
    decoding; the loader holds the load window open for
    ARKS_BENCH_MM_LOAD_FLOOR_S seconds (CPU-mechanics stand-in for a real
    multi-GB checkpoint read) and the engine's dispatch accounting proves
    the pipeline kept FULL depth for the whole window.  Later bursts
    alternate models and measure warm (context-cached) switches.

    Emits per-switch ``model_switch_seconds`` plus TTFT percentiles split
    by class: cold (weights had to load), switch (resident, context swap
    only), active (model already live).

    Env knobs: ARKS_BENCH_MM_MODEL (default tiny), ARKS_BENCH_MM_SECOND
    (default: a renamed copy of the first — same shapes, so the compile
    budget stays flat), ARKS_BENCH_MM_BURSTS, ARKS_BENCH_MM_BURST_REQS,
    ARKS_BENCH_MM_LOAD_FLOOR_S, ARKS_BENCH_MM_OVERLAP_TOKENS,
    ARKS_PIPELINE_DEPTH."""
    import dataclasses as _dc
    import random

    import numpy as np

    from arks_tpu.engine import (EngineConfig, InferenceEngine, Request,
                                 SamplingParams)
    from arks_tpu.engine.model_pool import ModelPool
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config

    model_a = os.environ.get("ARKS_BENCH_MM_MODEL", "tiny")
    model_b = os.environ.get("ARKS_BENCH_MM_SECOND", "")
    bursts = int(os.environ.get("ARKS_BENCH_MM_BURSTS", "5"))
    burst_n = int(os.environ.get("ARKS_BENCH_MM_BURST_REQS", "2"))
    load_floor = float(os.environ.get("ARKS_BENCH_MM_LOAD_FLOOR_S", "1.0"))
    overlap_tokens = int(os.environ.get("ARKS_BENCH_MM_OVERLAP_TOKENS", "192"))

    cfg = get_config(model_a)
    ecfg = EngineConfig(model=model_a, num_slots=burst_n, max_cache_len=256,
                        prefill_buckets=(16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, kv_layout="paged")
    pool = ModelPool()
    eng = InferenceEngine(cfg, ecfg, ByteTokenizer(), pool=pool)
    if model_b:
        eng.register_model(model_b)
        name_b = model_b
    else:
        cfg_b = _dc.replace(cfg, name=f"{model_a}-b")
        eng.register_model(cfg_b)
        name_b = cfg_b.name
    # Hold the load window open so the decode overlap is measurable on
    # CPU (a tiny random init is instant; a real sharded checkpoint read
    # is seconds — the engine mechanics under test are identical).
    entry = pool.entry(name_b)
    base_loader = entry.loader

    def _floored_loader():
        t_end = time.monotonic() + load_floor
        params = base_loader()
        while time.monotonic() < t_end:
            time.sleep(0.01)
        return params

    entry.loader = _floored_loader
    eng.start()

    rng = random.Random(7)
    vocab = cfg.vocab_size

    def _prompt(n=12):
        return [rng.randrange(3, min(200, vocab)) for _ in range(n)]

    def _submit(model, rid, max_tokens):
        req = Request(rid, _prompt(),
                      SamplingParams(max_tokens=max_tokens, temperature=0.0,
                                     ignore_eos=True),
                      model=None if model == model_a else model)
        t_submit = time.monotonic()
        eng.add_request(req)
        return req, t_submit

    def _drain(req, t_submit):
        ttft = None
        while True:
            out = req.outputs.get(timeout=600)
            if ttft is None and out.token_ids:
                # Engine ttft_s covers queue+park+switch time; fall back
                # to wall clock if a path ever omits it.
                ttft = out.ttft_s if out.ttft_s is not None \
                    else time.monotonic() - t_submit
            if out.finished:
                if out.finish_reason == "error":
                    raise RuntimeError(f"{req.request_id}: {out.error}")
                return ttft

    ttfts: dict[str, list[float]] = {"cold": [], "switch": [], "active": []}
    switches: list[dict] = []
    last_stats = None

    def _note_switch():
        nonlocal last_stats
        if eng.last_switch_stats is not None \
                and eng.last_switch_stats is not last_stats:
            last_stats = eng.last_switch_stats
            switches.append(dict(last_stats))

    try:
        # Prime every program AND the AOT pipe executables: the overlap
        # claim below is about steady-state pipelining, not compiles.
        _drain(*_submit(model_a, "mm-prime", 24))
        eng._pipe_warm_wait(600)

        # Burst 0 (model A, active) decodes long enough to span the load
        # window; model B's cold burst lands mid-decode so its weights
        # stream against live pipelined dispatches.
        b0 = [_submit(model_a, f"mm-a0-{i}", overlap_tokens)
              for i in range(burst_n)]
        time.sleep(0.15)  # let decode reach steady state
        bc = [_submit(name_b, f"mm-b0-{i}", 16) for i in range(burst_n)]
        for req, t0 in b0:
            ttfts["active"].append(_drain(req, t0))
        for req, t0 in bc:
            ttfts["cold"].append(_drain(req, t0))
        _note_switch()
        cold_switch = switches[0] if switches else None

        # Warm alternation: both models resident, every burst flips the
        # active model (saved-context swap, no compiles, no loads).
        current = name_b
        for b in range(1, bursts):
            current = model_a if current == name_b else name_b
            batch = [_submit(current, f"mm-w{b}-{i}", 16)
                     for i in range(burst_n)]
            for req, t0 in batch:
                ttfts["switch"].append(_drain(req, t0))
            _note_switch()
        # One repeat burst on the live model for the active baseline.
        batch = [_submit(current, f"mm-act-{i}", 16) for i in range(burst_n)]
        for req, t0 in batch:
            ttfts["active"].append(_drain(req, t0))
        _note_switch()
    finally:
        eng.stop()

    depth = eng._pipe_depth
    if cold_switch is not None and depth:
        # The acceptance gate: decode pipelining held FULL depth while the
        # second model's weights streamed (dispatch accounting, host-side).
        assert cold_switch["overlap_dispatches"] > 0, cold_switch
        assert cold_switch["overlap_max_depth"] == depth, (
            f"pipeline fell below full depth during the model switch: "
            f"{cold_switch} (want depth {depth})")

    def _pct(xs, q):
        return round(float(np.percentile(xs, q)) * 1e3, 2) if xs else None

    out = {
        "workload": "multi-model",
        "mm_models": [model_a, name_b],
        "mm_bursts": bursts, "mm_burst_reqs": burst_n,
        "mm_pipe_depth": depth,
        "mm_load_floor_s": load_floor,
        "mm_switch_count": len(switches),
        "mm_cold_starts_total": int(
            eng.metrics.model_cold_starts_total.total()),
        "model_switch_seconds": [round(s["seconds"], 4) for s in switches],
        "mm_cold_switch": cold_switch,
        "mm_warm_switch_seconds_mean": (
            round(float(np.mean([s["seconds"] for s in switches[1:]])), 4)
            if len(switches) > 1 else None),
    }
    for cls in ("cold", "switch", "active"):
        out[f"mm_ttft_{cls}_p50_ms"] = _pct(ttfts[cls], 50)
        out[f"mm_ttft_{cls}_p95_ms"] = _pct(ttfts[cls], 95)
    return out


def run_elastic_bench() -> dict:
    """``--workload elastic``: the elastic-parallelism acceptance bench
    (CPU mechanics).  Three phases, each asserting an acceptance claim
    from the PR in-bench:

    1. **Live resize mid-workload** — greedy streams decode on a tp1
       engine, a resize to tp2 posts mid-stream, and every surviving
       stream must be byte-identical to a never-resized run (greedy
       only: sampled streams are distribution-exact across a TP change,
       not byte-exact — psum reduction order).  Reports
       ``resize_to_first_token_s``: resize POST to the first token
       emitted at the new shape.
    2. **Streaming scale-from-zero + planned join** — replica B idles
       to zero behind a real OpenAIServer; a workload runs against the
       router (replica A only); B re-arms over POST /v1/elastic/resize
       and joins through Router.plan_join.  Asserts ZERO client-visible
       failures across the handoff and reports
       ``scale_from_zero_to_first_token_s``.
    3. **Autoscaler SLO-burn rescue** — a flood against A alone drives
       its per-tier SLO burn over the high-water mark; the signals-mode
       AutoscalerController scales the Application 1 -> 2 and its
       actuator re-arms + joins B inline.  Asserts the burn rate DROPS
       after the rescue (the loop closed).

    Env knobs: ARKS_BENCH_ELASTIC_MODEL (default tiny),
    ARKS_BENCH_ELASTIC_FLOOD (phase-3 client threads, default 8),
    ARKS_BENCH_ELASTIC_TTFT_MS (phase-3 tier target, default 600)."""
    import queue as queue_mod
    import threading
    import urllib.error

    from arks_tpu.engine import (EngineConfig, InferenceEngine, Request,
                                 SamplingParams)
    from arks_tpu.engine.tokenizer import ByteTokenizer
    from arks_tpu.models import get_config
    from arks_tpu.router import Discovery, Router
    from arks_tpu.server import OpenAIServer

    model = os.environ.get("ARKS_BENCH_ELASTIC_MODEL", "tiny")
    cfg = get_config(model)
    os.environ["ARKS_MIXED_STEP"] = "auto"
    os.environ.pop("ARKS_ELASTIC_IDLE_ZERO_S", None)

    def _mk(**kw):
        defaults = dict(model=model, num_slots=2, max_cache_len=128,
                        prefill_buckets=(16, 32), steps_per_dispatch=4,
                        prefill_chunk=16, kv_layout="paged")
        defaults.update(kw)
        return InferenceEngine(cfg, EngineConfig(**defaults),
                               ByteTokenizer())

    def _greedy(rid, prompt, max_tokens=16):
        return Request(rid, [int(x) % cfg.vocab_size for x in prompt],
                       SamplingParams(max_tokens=max_tokens,
                                      temperature=0.0, ignore_eos=True))

    def _collect(req):
        toks, fin = [], None
        while True:
            out = req.outputs.get(timeout=300)
            toks.extend(out.token_ids)
            if out.finished:
                fin = out
                break
        return toks, fin.finish_reason

    # ---- phase 1: live resize mid-workload ---------------------------

    def _phase_resize() -> dict:
        def _run(resize: bool):
            eng = _mk()
            reqs = [_greedy(f"r{i}", p) for i, p in
                    enumerate([[5, 6, 7], [9] * 5])]
            for r in reqs:
                eng.add_request(r)
            for _ in range(60):
                try:
                    eng.step(block_s=0.01)
                except Exception as e:  # noqa: BLE001
                    eng._recover_from_fault(e)
                if eng._slots:
                    break
            hold = t_post = None
            snap = t_first = None
            if resize:
                t_post = time.perf_counter()
                hold = eng.request_resize(tensor_parallel=2)
            for _ in range(4000):
                try:
                    eng.step(block_s=0.01)
                except Exception as e:  # noqa: BLE001
                    eng._recover_from_fault(e)
                if hold is not None and hold.outcome is not None:
                    if snap is None:
                        snap = [r.outputs.qsize() for r in reqs]
                    elif t_first is None and any(
                            r.outputs.qsize() > s
                            for r, s in zip(reqs, snap)):
                        t_first = time.perf_counter()
                if (eng._resize_req is None and not eng._swapped
                        and not eng._swap_pending and not eng._spills
                        and eng.num_running == 0 and eng._queue.empty()
                        and not eng._prefilling
                        and not eng._awaiting_restore
                        and eng.state == "serving"):
                    break
            outs = [_collect(r) for r in reqs]
            ttf = (t_first - t_post) if (t_first and t_post) else None
            return outs, eng, hold, ttf

        base, _, _, _ = _run(resize=False)
        got, eng, hold, ttf = _run(resize=True)
        assert hold.outcome == "ok", hold.error
        assert got == base, \
            "greedy streams diverged across the live resize"
        stats = eng.last_resize_stats
        assert stats["to"] == "tp2xdp1"
        return {
            "resize_streams_identical": True,
            "resize_from": stats["from"], "resize_to": stats["to"],
            "resize_seconds": round(stats["seconds"], 4),
            "resize_drain_seconds": round(stats["drain_seconds"], 4),
            "resize_swapped_streams": stats["swapped"],
            "resize_to_first_token_s": round(ttf, 4) if ttf else None,
        }

    # ---- shared HTTP plumbing for phases 2 and 3 ---------------------

    def _post_json(port, path, body, timeout=300):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.load(r)

    def _wait_disarmed(eng, timeout=60.0):
        deadline = time.monotonic() + timeout
        while eng.armed and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not eng.armed, "replica never scaled to zero"

    def _mk_replica(idle_zero=None, slots=2):
        if idle_zero is None:
            os.environ.pop("ARKS_ELASTIC_IDLE_ZERO_S", None)
        else:
            os.environ["ARKS_ELASTIC_IDLE_ZERO_S"] = str(idle_zero)
        eng = _mk(num_slots=slots)
        eng.start()
        srv = OpenAIServer(eng, served_model_name=model,
                           host="127.0.0.1", port=0)
        srv.start(background=True)
        os.environ.pop("ARKS_ELASTIC_IDLE_ZERO_S", None)
        return eng, srv

    def _mk_router(decode):
        os.environ["ARKS_PREFILL_ADDRS"] = ""
        os.environ["ARKS_DECODE_ADDRS"] = decode
        os.environ["ARKS_ROUTER_RETRY_BACKOFF_S"] = "0.01"
        os.environ["ARKS_ROUTER_SKETCH_POLL_S"] = "60"
        r = Router(Discovery(None), model, host="127.0.0.1", port=0,
                   policy="cache_aware", unified=True)
        r.start(background=True)
        return r

    class _Flood:
        """Closed-loop client threads against the router; every failure
        (non-2xx or raise) is recorded — the zero-5xx assertion."""

        def __init__(self, port, clients, max_tokens=8):
            self.port, self.clients = port, clients
            self.max_tokens = max_tokens
            self.failures: list = []
            self.completions = 0
            self._done = threading.Event()
            self._threads: list[threading.Thread] = []
            self._lock = threading.Lock()

        def _one(self, tid, n):
            body = json.dumps({
                "model": model, "prompt": [1 + tid, 2, 3, n % 97],
                "max_tokens": self.max_tokens, "temperature": 0,
                "ignore_eos": True}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{self.port}/v1/completions", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=300) as resp:
                    if resp.status != 200:
                        self.failures.append(resp.status)
                    else:
                        resp.read()
                        with self._lock:
                            self.completions += 1
            except Exception as e:  # noqa: BLE001
                self.failures.append(repr(e))

        def start(self):
            def loop(tid):
                n = 0
                while not self._done.is_set():
                    n += 1
                    self._one(tid, n)
            for tid in range(self.clients):
                t = threading.Thread(target=loop, args=(tid,), daemon=True)
                t.start()
                self._threads.append(t)

        def stop(self):
            self._done.set()
            for t in self._threads:
                t.join(timeout=60)

    # ---- phase 2: scale-from-zero + planned membership handoff -------

    def _phase_scale_from_zero() -> dict:
        a_eng, a_srv = _mk_replica()
        b_eng, b_srv = _mk_replica(idle_zero=0.05)
        r = _mk_router(f"127.0.0.1:{a_srv.port}")
        flood = _Flood(r.port, clients=2)
        try:
            _wait_disarmed(b_eng)
            flood.start()
            time.sleep(0.2)
            t0 = time.perf_counter()
            code, out = _post_json(b_srv.port, "/v1/elastic/resize",
                                   {"tensor_parallel": 1})
            assert code == 200 and out["status"] == "ok", out
            join = r.plan_join(f"127.0.0.1:{b_srv.port}")
            # First token at the re-armed replica, through the planned
            # membership (warm-up already compiled the programs).
            code, comp = _post_json(b_srv.port, "/v1/completions", {
                "model": model, "prompt": [4, 5, 6], "max_tokens": 1,
                "temperature": 0, "ignore_eos": True})
            t_first = time.perf_counter()
            assert code == 200
            time.sleep(0.3)   # post-join traffic crosses the handoff
        finally:
            flood.stop()
            r.stop()
            for srv, eng in ((a_srv, a_eng), (b_srv, b_eng)):
                srv.stop()
                eng.stop()
        assert not flood.failures, \
            f"client-visible failures across the handoff: {flood.failures[:5]}"
        assert flood.completions > 0
        return {
            "zero_handoff_failures": 0,
            "zero_handoff_completions": flood.completions,
            "scale_from_zero_to_first_token_s": round(t_first - t0, 4),
            "rearm_seconds": round(
                out["elastic"]["last_rearm"]["seconds"], 4),
            "join_seconds": round(join["seconds"], 4),
            "rearm_streamed": out["elastic"]["last_rearm"]["streamed"],
        }

    # ---- phase 3: autoscaler-closed SLO-burn rescue ------------------

    def _phase_autoscaler_rescue() -> dict:
        from arks_tpu.control import resources as res
        from arks_tpu.control.autoscaler import (AutoscalerController,
                                                 fleet_signals,
                                                 scrape_signals)
        from arks_tpu.control.store import Store

        # 600ms: the 8-client flood on one 2-slot replica queues TTFT
        # well past it (measured ~900ms mean on the CPU tiny engine);
        # split across two replicas it sits well under (~350ms).
        ttft_ms = os.environ.get("ARKS_BENCH_ELASTIC_TTFT_MS", "600")
        clients = int(os.environ.get("ARKS_BENCH_ELASTIC_FLOOD", "8"))
        os.environ["ARKS_SLO_TIERS"] = f"rt:ttft_ms={ttft_ms}"
        os.environ["ARKS_SLO_BURN_WINDOW_S"] = "3"
        try:
            a_eng, a_srv = _mk_replica()
            b_eng, b_srv = _mk_replica(idle_zero=0.05)
        finally:
            os.environ.pop("ARKS_SLO_TIERS", None)
            os.environ.pop("ARKS_SLO_BURN_WINDOW_S", None)
        a_addr = f"127.0.0.1:{a_srv.port}"
        b_addr = f"127.0.0.1:{b_srv.port}"
        r = _mk_router(a_addr)
        rescue_t: list[float] = []

        def actuator(app, desired, sig):
            t0 = time.perf_counter()
            code, out = _post_json(b_srv.port, "/v1/elastic/resize",
                                   {"tensor_parallel": 1})
            assert code == 200 and out["status"] == "ok", out
            r.plan_join(b_addr)
            rescue_t.append(time.perf_counter() - t0)

        store = Store()
        app = store.create(res.Application(name="fleet", spec={
            "replicas": 1, "servedModelName": model,
            "autoscale": {"minReplicas": 1, "maxReplicas": 2,
                          "scaleDownStabilizationSeconds": 3600},
        }))
        ctl = AutoscalerController(
            store, rate_source=lambda ns, m: 0.0,
            signals_source=lambda ns, m: fleet_signals([a_addr, b_addr]),
            actuator=actuator)
        flood = _Flood(r.port, clients=clients, max_tokens=24)
        try:
            _wait_disarmed(b_eng)
            flood.start()
            # The flood against A alone drives its burn over the mark.
            burn_before = 0.0
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                sig = scrape_signals(a_addr) or {}
                burn_before = max(burn_before, sig.get("burn", 0.0))
                if burn_before >= 1.0:
                    break
                time.sleep(0.2)
            assert burn_before >= 1.0, \
                f"flood never induced an SLO burn (peak {burn_before})"
            pre = fleet_signals([a_addr, b_addr])
            # One reconcile closes the loop: signal_high -> replicas 2,
            # actuator re-arms + joins B.
            ctl.reconcile(store.get(res.Application, "fleet"))
            app = store.get(res.Application, "fleet")
            assert app.spec["replicas"] == 2, app.status
            assert app.status["autoscale"]["reason"] == "signal_high"
            assert rescue_t, "the actuator never ran"
            assert b_eng.armed, "the rescue did not re-arm replica B"
            # The burn window (3s) rolls past the pre-rescue violations
            # while the flood now splits across two replicas.
            time.sleep(4.0)
            after = fleet_signals([a_addr, b_addr])
            burn_after = after["burn"]
        finally:
            flood.stop()
            r.stop()
            for srv, eng in ((a_srv, a_eng), (b_srv, b_eng)):
                srv.stop()
                eng.stop()
        assert not flood.failures, \
            f"client-visible failures during the rescue: {flood.failures[:5]}"
        assert burn_after < burn_before, (
            f"the scale-up did not drop the burn rate: "
            f"{burn_before} -> {burn_after}")
        return {
            "rescue_burn_before": round(burn_before, 3),
            "rescue_burn_after": round(burn_after, 3),
            "rescue_burn_dropped": True,
            "rescue_replicas": app.spec["replicas"],
            "rescue_actuation_s": round(rescue_t[0], 4),
            "rescue_disarmed_before": int(pre.get("disarmed", 0)),
            "rescue_ttft_target_ms": float(ttft_ms),
            "rescue_flood_clients": clients,
            "rescue_completions": flood.completions,
        }

    out = {"workload": "elastic", "elastic_model": model}
    out.update(_phase_resize())
    out.update(_phase_scale_from_zero())
    out.update(_phase_autoscaler_rescue())
    return out


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload",
                    choices=("default", "shared-prefix", "multi-model",
                             "slo-tiers", "multi-tenant", "long-context",
                             "elastic"),
                    default="default")
    ap.add_argument("--backends", type=int, default=1,
                    help="shared-prefix only: N>1 runs the multi-backend "
                         "routing comparison (N engines behind a real "
                         "Router; sketch vs rendezvous vs random)")
    ap.add_argument("--restart", action="store_true",
                    help="shared-prefix only: the tier-2 persistence rung "
                         "(stop + relaunch on the same disk store; zero "
                         "re-prefilled warm full-page tokens)")
    ap.add_argument("--peer-restore", action="store_true",
                    help="shared-prefix only: the fleet-wide restore rung "
                         "(replica B fetches replica A's blocks instead "
                         "of re-prefilling)")
    args, _ = ap.parse_known_args()
    if args.workload == "shared-prefix":
        if args.restart:
            print(json.dumps({"metric": "shared_prefix_restart",
                              **run_shared_prefix_restart_bench()}))
            return
        if args.peer_restore:
            print(json.dumps({"metric": "shared_prefix_peer_restore",
                              **run_shared_prefix_peer_restore_bench()}))
            return
        if args.backends > 1:
            print(json.dumps({"metric": "shared_prefix_router",
                              **run_shared_prefix_router_bench(
                                  args.backends)}))
            return
        print(json.dumps({"metric": "shared_prefix_serving",
                          **run_shared_prefix_bench()}))
        return
    if args.workload == "multi-model":
        print(json.dumps({"metric": "multi_model_serving",
                          **run_multi_model_bench()}))
        return
    if args.workload == "slo-tiers":
        print(json.dumps({"metric": "slo_tiers_serving",
                          **run_slo_tiers_bench()}))
        return
    if args.workload == "multi-tenant":
        print(json.dumps({"metric": "multi_tenant_serving",
                          **run_multi_tenant_bench()}))
        return
    if args.workload == "long-context":
        print(json.dumps({"metric": "long_context_serving",
                          **run_long_context_bench()}))
        return
    if args.workload == "elastic":
        print(json.dumps({"metric": "elastic_serving",
                          **run_elastic_bench()}))
        return
    print(json.dumps({
        "metric": "serving_throughput",
        "unit": "tok/s/chip",
        **run_serving_bench(),
    }))


if __name__ == "__main__":
    if "--client" in sys.argv:
        argv = [a for a in sys.argv[1:] if a != "--client"]
        _client_main(argv)
    else:
        main()
