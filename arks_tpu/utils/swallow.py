"""The sanctioned intentional-swallow marker for non-engine components.

The arkslint ``exceptions`` rule requires every broad handler
(``except Exception`` / bare ``except``) under ``arks_tpu/`` to re-raise,
route through the fault API, or log the exception with a traceback.  The
few handlers that *deliberately* discard an exception (capability
probes, best-effort error responses after a failure already in flight)
call this instead of silently passing — the same contract as
``arks_tpu.engine.faults.swallowed`` but importable without the engine
package (the router and gateway must stay JAX-free).
"""

from __future__ import annotations

import logging

_log = logging.getLogger("arks_tpu.swallowed")


def swallowed(site: str, exc: BaseException | None = None, *,
              warn: bool = False) -> None:
    """Record an intentionally swallowed exception.  ``warn=True`` for
    swallows that should be visible in default logs (supervision loops);
    the default DEBUG level suits per-request best-effort paths that
    would otherwise spam (client disconnects, probe failures)."""
    _log.log(logging.WARNING if warn else logging.DEBUG,
             "swallowed exception at %s: %s", site, exc, exc_info=exc)
