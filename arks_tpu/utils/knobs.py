"""The typed ``ARKS_*`` configuration-knob registry.

Every environment variable the runtime reads is declared here once, with
a type, default, one-line doc, and owning subsystem — and this module's
accessors are the ONLY sanctioned way to read one.  ``arkslint``
(``python -m arks_tpu.analysis``, rule ``knobs``) statically rejects raw
``os.environ``/``os.getenv`` reads of ``ARKS_*`` names anywhere else
under ``arks_tpu/``, and rejects accessor calls whose name is missing
from the registry — so a knob cannot exist without documentation, and
the generated ``docs/configuration.md`` table (``render_markdown()``)
is complete by construction.

Deliberately import-light (stdlib only): the router, gateway, and the
analyzer itself read knobs without dragging in JAX.

Reads are live (``os.environ`` at call time, no snapshot): tests and
launchers monkeypatch the environment and expect the next read to see
it.  Typed accessors raise ``ValueError`` naming the knob on a
malformed value — every call site used to hand-roll that message.
"""

from __future__ import annotations

import dataclasses
import os

__all__ = [
    "Knob", "REGISTRY", "is_registered", "raw", "get_str", "get_int",
    "get_float", "get_bool", "get_list", "push", "render_markdown",
]


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    type: str                       # str | int | float | bool | enum | list
    default: str | None             # raw (pre-parse) default; None = unset
    doc: str
    subsystem: str
    choices: tuple[str, ...] = ()   # for type == "enum"


REGISTRY: dict[str, Knob] = {}


def _k(name: str, type: str, default: str | None, doc: str, subsystem: str,
       choices: tuple[str, ...] = ()) -> None:
    if name in REGISTRY:
        raise ValueError(f"duplicate knob registration: {name}")
    REGISTRY[name] = Knob(name, type, default, doc, subsystem, choices)


# --------------------------------------------------------------- engine
_k("ARKS_FAULT_RETRIES", "int", "1",
   "Per-request fault retry budget before a culprit request is "
   "quarantined and failed alone.", "engine")
_k("ARKS_FAULT_INJECT", "str", None,
   "Chaos hook: comma-separated `phase:nth:kind` fault-injection specs "
   "(see engine/faults.py).", "engine")
_k("ARKS_FAULT_HANG_S", "float", "3600",
   "Sleep length of an injected `hang` fault (the watchdog-escalation "
   "fixture).", "engine")
_k("ARKS_DISPATCH_DEADLINE_S", "float", "0",
   "Watchdog deadline for a wedged device dispatch; past it the engine "
   "flips readiness and exits 70. 0 disables; must exceed the worst "
   "in-step jit compile.", "engine")
_k("ARKS_OVERLAP_DECODE", "enum", "auto",
   "Overlapped (async-dispatch) decode: auto = on where the platform "
   "supports it.", "engine", ("auto", "0", "1"))
_k("ARKS_PIPELINE_DEPTH", "int", "2",
   "In-flight dispatch depth of the pipelined decode loop; 0 falls back "
   "to the unpipelined step.", "engine")
_k("ARKS_MIXED_STEP", "enum", "auto",
   "Single mixed prefill+decode dispatch per step: auto = on where "
   "supported.", "engine", ("auto", "0", "1"))
_k("ARKS_SAMPLER_FUSE", "enum", "1",
   "Fuse sampler prep into steady-state depth-0 decode dispatches (the "
   "pipelined program with immediate resolve: zero host-side prep "
   "arrays between attention and sampling).  Kill switch; gated off "
   "automatically around prefill, transient overrides, speculative "
   "drafts and oversized stop sets.", "engine", ("0", "1"))
_k("ARKS_RESIDENCY_WINDOW_PAGES", "int", "0",
   "Windowed-residency attention: device-page budget per slot for "
   "contexts larger than the device pool — cold pages spill to the "
   "host tier and stream back through a staging window while the "
   "kernel attends span-by-span with carried softmax state.  0 "
   "disables (out-of-pool contexts are rejected as before).  Requires "
   "the Pallas ragged mixed path.", "engine")
_k("ARKS_MIXED_CHUNK_TOKENS", "int", None,
   "Prefill-token budget of one mixed dispatch (defaults to the chunked-"
   "prefill chunk size; clamped to max_cache_len).", "engine")
_k("ARKS_ADMIT_BATCH_SIZES", "list", "8,4,2,1",
   "Descending jit-bucket sizes for fused admission dispatches.",
   "engine")
_k("ARKS_PAD_HEAD_DIM", "bool", "1",
   "Lane-pad stored KV head dim to 128 so d<128 models ride the Pallas "
   "decode kernels; 0 opts out.", "engine")
_k("ARKS_PREFIX_HOST_MB", "int", "256",
   "Host-RAM byte budget (MiB) of the tier-1 prefix KV cache; 0 "
   "disables the host tier.", "engine")
_k("ARKS_PREFIX_DISK_MB", "int", "0",
   "Local-disk byte budget (MiB) of the tier-2 prefix KV block store, "
   "fed from tier-1 LRU evictions; 0 disables the disk tier.", "engine")
_k("ARKS_PREFIX_DISK_DIR", "str", None,
   "Directory for the tier-2 prefix block store; epoch-stamped so warm "
   "prefixes survive engine restarts on the same pool layout.  Unset "
   "with ARKS_PREFIX_DISK_MB>0 uses <tmpdir>/arks-prefix-disk.",
   "engine")
_k("ARKS_PEER_FETCH", "bool", "0",
   "Fetch missing prefix KV blocks from peer replicas (router "
   "X-Arks-Peer-Hint or ARKS_PEER_ADDRS) over GET /v1/cache/blocks/"
   "{digest} instead of re-prefilling.", "engine")
_k("ARKS_PEER_FETCH_TIMEOUT_S", "float", "5",
   "Per-request HTTP timeout for one peer block fetch; a timeout falls "
   "back to chunked re-prefill of the uncovered tail.", "engine")
_k("ARKS_PEER_ADDRS", "list", None,
   "Static comma-separated peer base addresses (host:port) probed for "
   "prefix blocks when no router peer hint accompanies the request.",
   "engine")
_k("ARKS_PREEMPT", "bool", "0",
   "Enable preemptive KV swap: latency-tier arrivals seize running "
   "low-tier slots by spilling their decode state to host RAM.",
   "engine")
_k("ARKS_PREEMPT_MAX_INFLIGHT", "int", "1",
   "Max concurrent preemption swap-outs in flight.", "engine")
_k("ARKS_PREEMPT_COOLDOWN_S", "float", "2",
   "Minimum spacing between preemptions of the same slot.", "engine")
_k("ARKS_QUEUE_AGING_S", "float", "0",
   "Queue-aging half-life for tier promotion of starved requests; 0 "
   "disables aging.", "engine")
_k("ARKS_FAIR", "bool", "1",
   "Tenant-fair admission: weighted deficit round-robin across tenants "
   "within each SLO tier. 0 reverts to the flat priority heap (the "
   "bench control arm).", "engine")
_k("ARKS_FAIR_QUANTUM_TOKENS", "int", "512",
   "Token credit (prompt + max_tokens cost units) each tenant earns per "
   "fair-queue round-robin visit.", "engine")
_k("ARKS_FAIR_WEIGHTS", "str", None,
   "Per-tenant fair-share weights as `namespace/user:weight,...`; "
   "unlisted tenants weigh 1. Shared by the engine's WDRR admission "
   "and the gateway's edge shedding.", "engine")
_k("ARKS_QUEUE_MAX", "int", "0",
   "Admission-queue depth cap across all tiers/tenants; a put past it "
   "is shed with 503 + drain-rate Retry-After. 0 = unbounded.",
   "engine")
_k("ARKS_QUEUE_TENANT_MAX", "int", "0",
   "Per-tenant admission-queue depth cap; a put past it is shed with "
   "429 + Retry-After while other tenants keep admitting. 0 = "
   "unbounded.", "engine")
_k("ARKS_SHED_DEADLINE", "float", "0",
   "Deadline-aware shedding factor: a popped request whose queue wait "
   "exceeds factor x its tier's ttft_ms budget is rejected before "
   "prefill (shed_deadline -> 503 + Retry-After). 0 = off.", "engine")
_k("ARKS_TENANT_LABEL_MAX", "int", "32",
   "Metric-label cardinality bound for tenant labels: the first N "
   "distinct tenants keep their id, later ones share the `other` "
   "bucket.", "engine")
_k("ARKS_SLO_TIERS", "str", None,
   "The SLO tier ladder, best tier first (see arks_tpu/slo.py for the "
   "spec grammar). Unset = no tiers.", "engine")
_k("ARKS_MODEL_SWITCH_POLICY", "enum", "drain",
   "Multi-model switch policy: drain (switch at empty) or timeslice "
   "(round-robin on a quantum).", "engine", ("drain", "timeslice"))
_k("ARKS_MODEL_SWITCH_QUANTUM_S", "float", "5",
   "Timeslice quantum for the timeslice switch policy.", "engine")
_k("ARKS_MODEL_POOL_HBM_MB", "int", "0",
   "HBM budget (MiB) for pooled model weights; LRU-evicts idle unpinned "
   "models. 0/unset = unlimited.", "engine")
_k("ARKS_GUIDE_MAX", "int", "8",
   "Max resident compiled guides (guided-decoding DFA tables).",
   "engine")
_k("ARKS_GUIDE_ROWS", "int", "4096",
   "Max total DFA rows across resident guides.", "engine")
_k("ARKS_GUIDE_CLASSES", "int", "2048",
   "Max token-equivalence classes per guide.", "engine")
_k("ARKS_GUIDE_COMPILE_WORKERS", "int", "2",
   "Guide-compilation worker-thread pool size.", "engine")
_k("ARKS_JSON_DEPTH", "int", "3",
   "Max nesting depth of the JSON-schema guide compiler.", "engine")

# ------------------------------------------------------------ multihost
_k("ARKS_COORDINATOR_ADDRESS", "str", None,
   "Leader pod address (host:port) for jax.distributed multi-host "
   "init; unset = single host.", "multihost")
_k("ARKS_PROCESS_ID", "int", "0",
   "Worker index within the gang (0 = leader; only the leader serves "
   "HTTP).", "multihost")
_k("ARKS_NUM_PROCESSES", "int", "1", "Gang size.", "multihost")
_k("ARKS_NUM_SLICES", "int", "1",
   "Slice count of a multi-slice topology (the k8s renderer passes it; "
   "an explicit --num-slices flag wins).", "multihost")
_k("ARKS_DISPATCH_ADDRESS", "str", None,
   "Explicit gang-dispatch channel address; defaults to the coordinator "
   "host on a derived port.", "multihost")
_k("ARKS_GANG_SECRET", "str", "arks-gang",
   "Shared secret authenticating gang dispatch/heartbeat peers.",
   "multihost")
_k("ARKS_GANG_HB_INTERVAL", "float", "2",
   "Follower heartbeat interval (seconds).", "multihost")
_k("ARKS_GANG_STALE_S", "float", "15",
   "Follower heartbeat age past which the leader reports the gang "
   "degraded.", "multihost")
_k("ARKS_GANG_WEDGE_FATAL_S", "float", "120",
   "Leader exits after a follower channel has been wedged this long so "
   "the gang driver restarts the gang.", "multihost")

# --------------------------------------------------------------- server
_k("ARKS_DRAIN_TIMEOUT", "float", "20",
   "SIGTERM grace: finish in-flight requests up to this many seconds "
   "before exiting.", "server")
_k("ARKS_TOOL_PARSER", "enum", "auto",
   "Tool-call parser dialect for /v1/chat/completions tools.", "server",
   ("auto", "hermes", "llama3", "mistral", "qwen"))

# -------------------------------------------------------------- kernels
_k("ARKS_ATTN_IMPL", "enum", "auto",
   "Decode attention implementation.", "kernels",
   ("auto", "pallas", "xla"))
_k("ARKS_ATTN_BLOCK_S", "int", "256",
   "Sequence block of the Pallas decode attention grid.", "kernels")
_k("ARKS_ATTN_BLOCK_B", "int", "16",
   "Batch block of the Pallas decode attention grid.", "kernels")
_k("ARKS_MIXED_GRID", "enum", "ragged",
   "Mixed-attention grid mode: ragged work-list or dense fallback.",
   "kernels", ("ragged", "dense"))
_k("ARKS_MOE_KERNEL", "enum", "auto",
   "MoE grouped-matmul implementation (auto resolves to the xla "
   "ragged_dot path until the Pallas kernel wins on hardware).",
   "kernels", ("auto", "pallas", "xla"))
_k("ARKS_KERNEL_TUNE", "enum", "cached",
   "Kernel autotune mode: off = built-in defaults, cached = use the "
   "persisted table, sweep = retune and persist.", "kernels",
   ("off", "cached", "sweep"))
_k("ARKS_KERNEL_TUNE_CACHE", "str", None,
   "Autotune table path; defaults to ARKS_MODEL_DIR/kernel_tune.json, "
   "else ~/.cache/arks_tpu/kernel_tune.json.", "kernels")
_k("ARKS_MODEL_DIR", "str", None,
   "Model checkpoint directory (also anchors the autotune table).",
   "kernels")
_k("ARKS_INT4_GROUP", "int", "128",
   "int4 weight-quantization group size along the contraction dim.",
   "kernels")

# -------------------------------------------------------------- gateway
_k("ARKS_NATIVE", "bool", "1",
   "Use the native (compiled) gateway hot-path helpers when available; "
   "0 forces the pure-Python fallback.", "gateway")
_k("ARKS_NATIVE_LIB", "str", None,
   "Path to a prebuilt native helper .so (skips the on-demand build).",
   "gateway")
_k("ARKS_GW_COLD_START_WAIT_S", "float", "10",
   "How long gateway admission holds a request for a cold-starting "
   "model before 503ing.", "gateway")
_k("ARKS_GW_SHED_INFLIGHT", "int", "0",
   "Gateway edge-shedding trigger: once this many proxied requests are "
   "in flight, new arrivals from the most-over-share tenant "
   "(in-flight/weight, per ARKS_FAIR_WEIGHTS) get 429 + Retry-After at "
   "the edge. 0 = off.", "gateway")
_k("ARKS_GW_DISCONNECT_DRAIN_S", "float", "10",
   "After a streaming client disconnects mid-relay, keep draining the "
   "backend response (feeding the usage scanner) for up to this long "
   "so the stream's tokens are still metered exactly.", "gateway")

# --------------------------------------------------------------- router
_k("ARKS_PREFILL_ADDRS", "list", None,
   "Static prefill backend addresses (comma-separated host:port).",
   "router")
_k("ARKS_DECODE_ADDRS", "list", None,
   "Static decode backend addresses (comma-separated host:port).",
   "router")
_k("ARKS_ROUTER_UNIFIED", "bool", "0",
   "Treat every backend as both prefill and decode (single-tier "
   "routing).", "router")
_k("ARKS_ROUTER_RETRY_BACKOFF_S", "float", "0.05",
   "Backoff between failover attempts to the next backend candidate.",
   "router")
_k("ARKS_ROUTER_SKETCH", "bool", "1",
   "Cache-aware routing from backend prefix-digest sketches; 0 falls "
   "back to rendezvous/least-loaded only.", "router")
_k("ARKS_ROUTER_SKETCH_POLL_S", "float", "2.0",
   "Sketch poll interval per decode backend.", "router")
_k("ARKS_ROUTER_SKETCH_STALE_S", "float", "10",
   "Sketch age past which a backend's sketch is ignored for scoring.",
   "router")
_k("ARKS_ROUTER_SKETCH_T0_WEIGHT", "float", "1.0",
   "Extra score weight of a tier-0 (device) block over a host-tier "
   "block.", "router")
_k("ARKS_ROUTER_SKETCH_DISK_WEIGHT", "float", "0.5",
   "Score weight of a tier-2 (disk) block relative to a host-tier "
   "block; disk hits restore slower than RAM but still beat "
   "re-prefill.", "router")
_k("ARKS_ROUTER_SKETCH_MAX_BLOCKS", "int", "64",
   "Max prompt prefix blocks hashed per routing decision.", "router")
_k("ARKS_ROUTER_SKETCH_CHARS", "int", "256",
   "Prompt characters per prefix block digest.", "router")
_k("ARKS_ROUTER_SKETCH_BITS", "int", "16384",
   "Bloom filter width (bits) of the exported sketch.", "router")
_k("ARKS_ROUTER_SKETCH_HASHES", "int", "4",
   "Bloom filter hash count.", "router")
_k("ARKS_ROUTER_SKETCH_TOPK", "int", "128",
   "Top-K exact digests exported alongside the bloom filter.", "router")
_k("ARKS_ROUTER_SKETCH_LINKS", "int", "4096",
   "Max parent->child digest links kept in the sketch chain index.",
   "router")

# -------------------------------------------------------------- elastic
_k("ARKS_ELASTIC_COOLDOWN_S", "float", "30",
   "Minimum seconds between autoscaler-driven elastic actions on one "
   "application (scale-up-from-zero is exempt).", "elastic")
_k("ARKS_ELASTIC_BURN_HI", "float", "1.0",
   "SLO burn rate above which the signals-mode autoscaler scales up "
   "even when RPM alone would not.", "elastic")
_k("ARKS_ELASTIC_BURN_LO", "float", "0.25",
   "SLO burn rate below which (together with ARKS_ELASTIC_SAT_LO) "
   "signals-mode scale-down becomes eligible.", "elastic")
_k("ARKS_ELASTIC_SAT_HI", "float", "0.9",
   "Admission saturation above which the signals-mode autoscaler "
   "scales up.", "elastic")
_k("ARKS_ELASTIC_SAT_LO", "float", "0.3",
   "Admission saturation below which (together with "
   "ARKS_ELASTIC_BURN_LO) signals-mode scale-down becomes eligible.",
   "elastic")
_k("ARKS_ELASTIC_IDLE_ZERO_S", "float", "0",
   "Idle seconds after which a fully drained engine scales itself to "
   "zero (drops params + device KV, keeps host/disk prefix tiers); "
   "0 = never.", "elastic")
_k("ARKS_ELASTIC_WARMUP", "bool", "1",
   "Issue a self-enqueued warm-up request after a live resize or a "
   "scale-from-zero re-arm, before external traffic hits the new "
   "shape.", "elastic")
_k("ARKS_ELASTIC_JOIN_TIMEOUT_S", "float", "10",
   "Seconds the router's planned membership handoff waits for a "
   "joining backend's /readiness to go green before giving up.",
   "elastic")
_k("ARKS_SLO_BURN_WINDOW_S", "float", "60",
   "Rolling window (seconds) over which the engine computes per-tier "
   "SLO burn rates for /readiness and the signals-mode autoscaler.",
   "elastic")
_k("ARKS_SLO_ERROR_BUDGET", "float", "0.1",
   "Allowed fraction of requests missing their tier's ttft_ms target; "
   "burn rate = observed violation fraction / this budget (1.0 = "
   "burning exactly at budget).", "elastic")

# ------------------------------------------------------------------ obs
_k("ARKS_TRACE", "bool", "1",
   "Request tracing (span timelines, flight recorder); 0 disables.",
   "obs")
_k("ARKS_TRACE_RING", "int", "8192",
   "Per-thread trace event ring capacity.", "obs")
_k("ARKS_TRACE_SAMPLE", "float", "1.0",
   "Fraction of requests traced.", "obs")
_k("ARKS_TRACE_TAIL", "int", "256",
   "Flight-recorder tail length (events kept past a finished span).",
   "obs")
_k("ARKS_TRACE_FLUSH_S", "float", "0.2",
   "Trace assembly flush interval.", "obs")
_k("ARKS_TRACE_MAX", "int", "256",
   "Finished traces retained in the in-memory store.", "obs")
_k("ARKS_PROF_AUTO_ARM", "float", "0",
   "Auto-open a profiler window when a step exceeds this multiple of "
   "the trailing median step time; 0 = off.", "obs")
_k("ARKS_PROF_WINDOW_S", "float", "5",
   "Auto-armed profiler window length.", "obs")
_k("ARKS_PROF_DIR", "str", "/tmp/arks-prof",
   "Profiler trace output directory.", "obs")

# -------------------------------------------------------------- control
_k("ARKS_CONVERT_ORBAX", "bool", "0",
   "Convert downloaded safetensors to an Orbax sharded checkpoint after "
   "fetch.", "control")
_k("ARKS_SCRIPTS_IMAGE", "str", "arks-tpu/engine:latest",
   "Model-download worker image.", "control")
_k("ARKS_RUNTIME_DEFAULT_VLLM_IMAGE", "str", None,
   "Default vllm runtime image override.", "control")
_k("ARKS_RUNTIME_DEFAULT_SGLANG_IMAGE", "str", None,
   "Default sglang runtime image override.", "control")
_k("ARKS_RUNTIME_DEFAULT_DYNAMO_IMAGE", "str", None,
   "Default dynamo runtime image override.", "control")
_k("ARKS_RUNTIME_DEFAULT_JAX_IMAGE", "str", None,
   "Default native jax runtime image override.", "control")
_k("ARKS_GANG_LEADER_ADDRESS", "str", None,
   "Exported into GPU runtime containers as the distributed init "
   "address (not read in-process).", "control")
_k("ARKS_GANG_SIZE", "str", None,
   "Exported into runtime containers as the gang size (not read "
   "in-process).", "control")
_k("ARKS_GANG_WORKER_INDEX", "str", None,
   "Exported into runtime containers as the worker rank (not read "
   "in-process).", "control")

# ---------------------------------------------------------------- bench
_k("ARKS_BENCH_PROBE_DEADLINE_S", "float", "0",
   "Deadline of the persistent accelerator-availability prober run by "
   "bench.py; 0 = single immediate probe.", "bench")
_k("ARKS_BENCH_DRAFT_MODEL", "str", None,
   "Draft model path/name enabling the speculative-decoding bench "
   "ladder.", "bench")


# ------------------------------------------------------------ accessors

def is_registered(name: str) -> bool:
    return name in REGISTRY


def _knob(name: str) -> Knob:
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name} is not a registered knob — declare it in "
            "arks_tpu/utils/knobs.py (arkslint rule `knobs` enforces "
            "this)") from None


def raw(name: str, fallback: str | None = None) -> str | None:
    """The raw string value: environment, else the registry default,
    else ``fallback`` (for knobs whose default is computed at the call
    site).  Empty-string env values count as set."""
    knob = _knob(name)
    v = os.environ.get(name)
    if v is not None:
        return v
    if knob.default is not None:
        return knob.default
    return fallback


def get_str(name: str, fallback: str | None = None) -> str | None:
    v = raw(name, fallback)
    knob = REGISTRY[name]
    if v is not None and knob.type == "enum" and knob.choices \
            and v not in knob.choices:
        raise ValueError(
            f"{name}={v!r}: expected one of {'|'.join(knob.choices)}")
    return v


def get_int(name: str, fallback: int | None = None) -> int | None:
    v = raw(name)
    if v is None or v == "":
        return fallback
    try:
        return int(v)
    except ValueError:
        raise ValueError(f"{name}={v!r}: expected an integer") from None


def get_float(name: str, fallback: float | None = None) -> float | None:
    v = raw(name)
    if v is None or v == "":
        return fallback
    try:
        return float(v)
    except ValueError:
        raise ValueError(f"{name}={v!r}: expected a number") from None


def get_bool(name: str, fallback: bool = False) -> bool:
    """Bool knobs: "0"/"false"/"" (and unset without a default) are
    False, anything else is True — matching every historical call site
    (`!= "0"`, `== "1"`, `not in ("", "0", "false")`)."""
    v = raw(name)
    if v is None:
        return fallback
    return v.strip().lower() not in ("", "0", "false")


def get_list(name: str, sep: str = ",") -> list[str]:
    v = raw(name)
    if not v:
        return []
    return [part.strip() for part in v.split(sep) if part.strip()]


def push(name: str, value: str) -> None:
    """Write a knob into the process environment (launchers forwarding
    CLI flags to the engine/watchdog, which read knobs at start).  Keeps
    writes registry-checked too."""
    _knob(name)
    os.environ[name] = str(value)


# ------------------------------------------------------- doc generation

def render_markdown() -> str:
    """The `docs/configuration.md` knob table — generated, never hand
    edited (tests assert the file matches this output)."""
    out = [
        "# Configuration knobs",
        "",
        "Every `ARKS_*` environment variable the runtime reads, generated "
        "from the typed registry in `arks_tpu/utils/knobs.py` "
        "(`python -m arks_tpu.analysis --gen-knob-docs`).  Raw "
        "`os.environ` reads of `ARKS_*` names are rejected by arkslint "
        "(rule `knobs`), so this table is complete by construction.",
        "",
    ]
    subsystems: dict[str, list[Knob]] = {}
    for knob in REGISTRY.values():
        subsystems.setdefault(knob.subsystem, []).append(knob)
    for subsystem in sorted(subsystems):
        out.append(f"## {subsystem}")
        out.append("")
        out.append("| Name | Type | Default | Description |")
        out.append("|---|---|---|---|")
        for knob in sorted(subsystems[subsystem], key=lambda k: k.name):
            typ = knob.type
            if knob.type == "enum" and knob.choices:
                typ = "enum: " + " \\| ".join(knob.choices)
            default = "(unset)" if knob.default is None else \
                f"`{knob.default}`"
            doc = knob.doc.replace("|", "\\|")
            out.append(f"| `{knob.name}` | {typ} | {default} | {doc} |")
        out.append("")
    return "\n".join(out) + ""
