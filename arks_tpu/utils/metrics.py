"""Minimal Prometheus text-format metrics registry.

Used by both the serving engine (normalized runtime metric names the
reference's ServiceMonitor expects — /root/reference/config/prometheus/
monitor-runtime.yaml:13-44 normalizes vLLM/SGLang names; we emit the
normalized names directly) and the gateway data plane (same metric families
as /root/reference/pkg/gateway/metrics/metrics.go:24-132).

Thread-safe; no external deps.
"""

from __future__ import annotations

import threading
from bisect import bisect_left


def _escape_label_value(v: str) -> str:
    # Prometheus text exposition format: label values escape backslash,
    # double-quote, and line-feed — in that order (backslash first, or
    # the other escapes get double-escaped).
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    return repr(v) if isinstance(v, float) and not v.is_integer() else str(int(v))


class _Metric:
    def __init__(self, name: str, help_: str, typ: str):
        self.name, self.help, self.type = name, help_, typ
        self._lock = threading.Lock()

    def header(self) -> list[str]:
        return [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.type}"]


class Counter(_Metric):
    def __init__(self, name: str, help_: str = ""):
        super().__init__(name, help_, "counter")
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, **labels: str) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def total(self) -> float:
        """Sum over every label combination (e.g. a tier-labeled family
        read as one number — what an unlabeled scrape used to return)."""
        with self._lock:
            return sum(self._values.values())

    def collect(self) -> list[str]:
        with self._lock:
            items = list(self._values.items())
        out = self.header()
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(v)}")
        return out


class Gauge(_Metric):
    def __init__(self, name: str, help_: str = ""):
        super().__init__(name, help_, "gauge")
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, **labels: str) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def total(self) -> float:
        """Sum over every label combination (see Counter.total)."""
        with self._lock:
            return sum(self._values.values())

    def collect(self) -> list[str]:
        with self._lock:
            items = list(self._values.items())
        out = self.header()
        for key, v in items:
            out.append(f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(v)}")
        return out


class Histogram(_Metric):
    def __init__(self, name: str, help_: str = "", buckets: list[float] | None = None):
        super().__init__(name, help_, "histogram")
        self.buckets = sorted(buckets or [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60])
        self._data: dict[tuple, tuple[list[int], float, int]] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            if key not in self._data:
                self._data[key] = ([0] * len(self.buckets), 0.0, 0)
            counts, total, n = self._data[key]
            i = bisect_left(self.buckets, value)
            for j in range(i, len(self.buckets)):
                counts[j] += 1
            self._data[key] = (counts, total + value, n + 1)

    def collect(self) -> list[str]:
        with self._lock:
            items = [(k, (list(c), t, n)) for k, (c, t, n) in self._data.items()]
        out = self.header()
        for key, (counts, total, n) in items:
            base = dict(key)
            for b, c in zip(self.buckets, counts):
                out.append(f"{self.name}_bucket{_fmt_labels({**base, 'le': _fmt_value(float(b))})} {c}")
            out.append(f"{self.name}_bucket{_fmt_labels({**base, 'le': '+Inf'})} {n}")
            out.append(f"{self.name}_sum{_fmt_labels(base)} {_fmt_value(total)}")
            out.append(f"{self.name}_count{_fmt_labels(base)} {n}")
        return out


class Registry:
    def __init__(self) -> None:
        self._metrics: list[_Metric] = []
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._register(Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._register(Gauge(name, help_))

    def histogram(self, name: str, help_: str = "", buckets: list[float] | None = None) -> Histogram:
        return self._register(Histogram(name, help_, buckets))

    def _register(self, m):
        with self._lock:
            for existing in self._metrics:
                if existing.name == m.name:
                    raise ValueError(
                        f"metric family {m.name!r} registered twice")
            self._metrics.append(m)
        return m

    def families(self) -> list[_Metric]:
        """Snapshot of registered metric families (for conformance tests)."""
        with self._lock:
            return list(self._metrics)

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics)
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.collect())
        return "\n".join(lines) + "\n"
