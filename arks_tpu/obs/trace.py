"""Per-request span timelines from the engine's lifecycle seams.

The design splits the work by thread so the scheduler never pays for
observability:

- **Hot path** (engine step loop, ``Tracer.evt``): append one small tuple
  into a per-thread overwrite ring — one slot write plus an index
  increment, no locks, no allocation beyond the record tuple, no
  serialization.  The slot is written *before* the index advances, so a
  concurrent reader under the GIL only ever sees complete records.
- **Off thread** (the collector, ``Tracer.flush``): drain the rings with
  per-ring cursors, pair begin/end markers into spans, fold in upstream
  (gateway/router) spans carried on the request's ``TraceCtx``, decide
  retention, and file the finished timeline in the bounded
  :class:`TraceStore`.

Retention is **tail-based**: traces that faulted, were quarantined, were
preempted, or violated their SLO tier target are always kept; the rest
are sampled at ``ARKS_TRACE_SAMPLE`` (default 1.0).  ``ARKS_TRACE=0``
disables event recording entirely — token streams are byte-identical
either way (the tracer records, it never schedules).

The same rings double as a **flight recorder**: :meth:`Tracer.tail`
returns the last-N events across every thread, which the watchdog's
wedged-dispatch dump and the fault-recovery path attach to their
diagnostics so a dead process ships its own timeline.
"""

from __future__ import annotations

import collections
import json
import os
import random
import threading
import time

from arks_tpu.utils import knobs
from arks_tpu.utils.swallow import swallowed

TRACEPARENT_HEADER = "traceparent"
SPANS_HEADER = "x-arks-trace-spans"

# Span names that flag a trace for unconditional retention.
_FLAG_NAMES = {
    "fault": "faulted",
    "quarantined": "quarantined",
    "park.preempt": "preempted",
    "slo_violation": "slo_violation",
    "replay": "faulted",
}

# Engine-scope (rid-less) span names attached to overlapping request
# traces; everything else engine-scope (phase.* markers) is export-only.
_ATTACH_NAMES = ("pipe", "spill", "recover")

# Events that end a request's timeline.  ``finish`` fires in
# ``_finish``; ``quarantined`` requests fail outside the slot machinery
# and never reach ``_finish``.
_TERMINAL = ("finish", "quarantined")


def _hexid(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class TraceCtx:
    """W3C trace context for one hop, plus upstream component spans.

    ``upstream`` carries the spans completed by earlier hops (gateway
    admit, router pick) as a list of dicts with a ``component`` key —
    they were serialized into the ``x-arks-trace-spans`` header because
    those processes keep no store of their own; the engine-side trace is
    the single assembly point.
    """

    __slots__ = ("trace_id", "span_id", "parent_id", "flags", "upstream")

    def __init__(self, trace_id: str | None = None, span_id: str | None = None,
                 parent_id: str | None = None, flags: str = "01",
                 upstream: list | None = None) -> None:
        self.trace_id = trace_id or _hexid(16)
        self.span_id = span_id or _hexid(8)
        self.parent_id = parent_id
        self.flags = flags
        self.upstream = upstream or []

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags}"

    def child(self) -> "TraceCtx":
        """A new span id under the same trace (the next hop's context)."""
        return TraceCtx(trace_id=self.trace_id, parent_id=self.span_id,
                        flags=self.flags, upstream=list(self.upstream))

    @classmethod
    def parse(cls, header: str | None) -> "TraceCtx | None":
        """Parse a ``traceparent`` header; None if absent or malformed."""
        if not header:
            return None
        parts = header.strip().split("-")
        if len(parts) != 4:
            return None
        ver, tid, sid, flags = parts
        if len(ver) != 2 or len(tid) != 32 or len(sid) != 16 or len(flags) != 2:
            return None
        try:
            int(tid, 16), int(sid, 16), int(flags, 16)
        except ValueError:
            return None
        if tid == "0" * 32 or sid == "0" * 16:
            return None
        return cls(trace_id=tid, parent_id=sid, flags=flags)

    @classmethod
    def from_headers(cls, headers) -> "TraceCtx":
        """Build the context for this hop from incoming HTTP headers:
        continue the propagated trace (minting this hop's span id) or
        mint a fresh root; fold in the upstream-spans header."""
        ctx = cls.parse(headers.get(TRACEPARENT_HEADER))
        if ctx is None:
            ctx = cls()
        raw = headers.get(SPANS_HEADER)
        if raw:
            try:
                spans = json.loads(raw)
                if isinstance(spans, list):
                    ctx.upstream = [s for s in spans if isinstance(s, dict)]
            except ValueError:
                pass
        return ctx


def spans_header(spans: list[dict]) -> str:
    """Serialize completed upstream spans for the forward header."""
    return json.dumps(spans, separators=(",", ":"))


class _Ring:
    """Per-thread overwrite ring.  Append is slot-write-then-index-bump —
    safe against the off-thread reader under the GIL without a lock."""

    __slots__ = ("buf", "cap", "idx", "seen", "tname")

    def __init__(self, cap: int) -> None:
        self.buf: list = [None] * cap
        self.cap = cap
        self.idx = 0        # writer position (monotonic)
        self.seen = 0       # collector cursor
        self.tname = threading.current_thread().name


class Tracer:
    """Event recording + off-thread trace assembly for one engine."""

    def __init__(self, enabled: bool | None = None) -> None:
        if enabled is None:
            enabled = knobs.get_bool("ARKS_TRACE")
        self.enabled = enabled
        self.ring_cap = knobs.get_int("ARKS_TRACE_RING")
        self.sample = knobs.get_float("ARKS_TRACE_SAMPLE")
        self.tail_n = knobs.get_int("ARKS_TRACE_TAIL")
        self.flush_s = knobs.get_float("ARKS_TRACE_FLUSH_S")
        self.store = TraceStore(knobs.get_int("ARKS_TRACE_MAX"))
        self._tl = threading.local()
        self._rings: list[_Ring] = []
        self._lock = threading.Lock()          # ring creation + meta only
        self._flush_lock = threading.Lock()    # collector/flush exclusion
        self._meta: dict[str, dict] = {}       # rid -> ctx/tier/tail
        self._pending: dict[str, list] = {}    # rid -> drained records
        self._done: list[str] = []             # rids with a terminal event
        self._open_eng: dict[str, list] = {}   # engine-scope B/E pairing
        self._engine_spans: collections.deque = collections.deque(maxlen=2048)
        self._phase_spans: collections.deque = collections.deque(maxlen=2048)
        self._thread: threading.Thread | None = None
        self._stopping = threading.Event()

    # ---- hot path -------------------------------------------------------

    def evt(self, rid, name, ph="I", arg=None):
        """Record one event.  ``rid`` is the request id ("" / None for
        engine-scope events); ``ph`` is "B"/"E"/"I" (begin/end/instant).
        This is the ONLY tracer entry point the step loop may call."""
        if not self.enabled:
            return
        try:
            ring = self._tl.ring
        except AttributeError:
            ring = self._new_ring()
        i = ring.idx
        ring.buf[i % ring.cap] = (time.monotonic(), rid, name, ph, arg)
        ring.idx = i + 1

    def _new_ring(self) -> _Ring:
        ring = _Ring(self.ring_cap)
        with self._lock:
            self._rings.append(ring)
        self._tl.ring = ring
        return ring

    # ---- registration (server threads / slow paths) ---------------------

    def register(self, rid: str, ctx: TraceCtx | None = None,
                 tier: str | None = None) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._meta[rid] = {"ctx": ctx, "tier": tier, "tail": None}

    def attach_tail(self, rid: str, tail: list) -> None:
        """Pin the flight-recorder tail onto a request's eventual trace
        (fault recovery calls this for every culprit/quarantined rid)."""
        if not self.enabled:
            return
        with self._lock:
            self._meta.setdefault(
                rid, {"ctx": None, "tier": None, "tail": None})["tail"] = tail

    def live_ids(self, limit: int = 8) -> str:
        """Compact 'rid=trace_id' list of registered in-flight requests —
        stamped into profiler annotations while a window is active."""
        with self._lock:
            items = list(self._meta.items())[:limit]
        return ",".join(
            f"{rid}={m['ctx'].trace_id}" if m.get("ctx") else rid
            for rid, m in items)

    # ---- flight recorder ------------------------------------------------

    def tail(self, n: int | None = None) -> list[dict]:
        """Last-N events across every thread ring, oldest first."""
        if not self.enabled:
            return []
        n = n or self.tail_n
        with self._lock:
            rings = list(self._rings)
        recs = []
        for ring in rings:
            idx = ring.idx
            for i in range(max(0, idx - ring.cap), idx):
                r = ring.buf[i % ring.cap]
                if r is not None:
                    recs.append((r, ring.tname))
        recs.sort(key=lambda p: p[0][0])
        return [{"t": round(r[0], 6), "rid": r[1], "name": r[2],
                 "ph": r[3], "arg": _plain(r[4]), "thread": tn}
                for r, tn in recs[-n:]]

    # ---- collector ------------------------------------------------------

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._stopping.clear()
        self._thread = threading.Thread(
            target=self._loop, name="trace-collect", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        t, self._thread = self._thread, None
        if t is not None:
            self._stopping.set()
            t.join(timeout=5)
        if self.enabled:
            self.flush()

    def _loop(self) -> None:
        while not self._stopping.wait(self.flush_s):
            try:
                self.flush()
            except Exception as e:
                # Keep the flusher thread alive, but a failed flush means
                # trace loss — surface it.
                swallowed("trace.flush", e, warn=True)

    def flush(self) -> None:
        """Drain the rings and assemble every finished trace.  Safe from
        any non-step-loop thread; also the synchronous entry the HTTP
        endpoints and the fault path use."""
        if not self.enabled:
            return
        with self._flush_lock:
            self._drain()
            self._assemble_done()
            self._gc_pending()

    _PENDING_CAP = 4096

    def _gc_pending(self) -> None:
        """Aborted/errored requests can end without a terminal event;
        drop the stalest pending timelines rather than grow forever."""
        excess = len(self._pending) - self._PENDING_CAP
        if excess <= 0:
            return
        stale = sorted(self._pending,
                       key=lambda r: self._pending[r][-1][0])[:excess]
        with self._lock:
            for rid in stale:
                self._pending.pop(rid, None)
                self._meta.pop(rid, None)

    def _drain(self) -> None:
        with self._lock:
            rings = list(self._rings)
        for ring in rings:
            idx = ring.idx
            for i in range(max(ring.seen, idx - ring.cap), idx):
                rec = ring.buf[i % ring.cap]
                if rec is None:
                    continue
                t, rid, name, ph, arg = rec
                if not rid:
                    self._fold_engine(t, name, ph, arg)
                    continue
                self._pending.setdefault(rid, []).append(rec)
                if name in _TERMINAL:
                    self._done.append(rid)
            ring.seen = idx

    def _fold_engine(self, t, name, ph, arg) -> None:
        if ph == "B":
            self._open_eng.setdefault(name, []).append((t, arg))
            return
        if ph == "E" and self._open_eng.get(name):
            t0, a0 = self._open_eng[name].pop(0)
            span = {"name": name, "start": t0, "end": t,
                    "arg": arg if arg is not None else a0}
        else:
            span = {"name": name, "start": t, "end": t, "arg": arg}
        if name in _ATTACH_NAMES:
            self._engine_spans.append(span)
        else:
            self._phase_spans.append(span)

    def _assemble_done(self) -> None:
        done, self._done = self._done, []
        for rid in done:
            events = self._pending.pop(rid, None)
            if events is None:
                continue
            with self._lock:
                meta = self._meta.pop(rid, None) or {}
            trace = self._assemble(
                rid, sorted(events, key=lambda e: e[0]), meta)
            keep = bool(trace["flags"]) or random.random() < self.sample
            if keep:
                self.store.add(trace)

    def _assemble(self, rid: str, events: list, meta: dict) -> dict:
        spans: list[dict] = []
        open_: dict[str, list] = {}
        flags: set[str] = set()
        for t, _rid, name, ph, arg in events:
            flag = _FLAG_NAMES.get(name)
            if flag:
                flags.add(flag)
            if ph == "B":
                open_.setdefault(name, []).append((t, arg))
            elif ph == "E":
                if open_.get(name):
                    t0, a0 = open_[name].pop(0)
                    spans.append({"name": name, "component": "engine",
                                  "start": t0, "end": t,
                                  "arg": _plain(arg if arg is not None else a0)})
                else:
                    spans.append({"name": name, "component": "engine",
                                  "start": t, "end": t, "arg": _plain(arg)})
            else:
                spans.append({"name": name, "component": "engine",
                              "start": t, "end": t, "arg": _plain(arg)})
        for name, rest in open_.items():
            for t0, a0 in rest:    # parked at fault/abort: open span
                spans.append({"name": name, "component": "engine",
                              "start": t0, "end": None, "arg": _plain(a0)})
        t_lo = events[0][0]
        t_hi = max(e[0] for e in events)
        for sp in self._engine_spans:
            if sp["end"] is not None and sp["end"] >= t_lo \
                    and sp["start"] <= t_hi:
                spans.append({"component": "engine", **sp,
                              "arg": _plain(sp["arg"])})
        ctx: TraceCtx | None = meta.get("ctx")
        if ctx is not None:
            for up in ctx.upstream:
                spans.append({"component": "upstream", **up})
        spans.sort(key=lambda s: s["start"])
        return {
            "trace_id": ctx.trace_id if ctx else _hexid(16),
            "span_id": ctx.span_id if ctx else _hexid(8),
            "parent_id": ctx.parent_id if ctx else None,
            "request_id": rid,
            "tier": meta.get("tier"),
            "flags": sorted(flags),
            "start": t_lo,
            "end": t_hi,
            "spans": spans,
            "flight_tail": meta.get("tail"),
        }

    def phase_spans(self) -> list[dict]:
        """Recent engine-scope scheduler-phase spans (export only)."""
        return list(self._phase_spans)


def _plain(arg):
    """Coerce an event payload to something JSON-serializable."""
    if arg is None or isinstance(arg, (str, int, float, bool)):
        return arg
    if isinstance(arg, (list, tuple)):
        return [_plain(a) for a in arg]
    return str(arg)


class TraceStore:
    """Bounded in-proc store of finished traces with tail-based eviction:
    when full, the oldest *unflagged* trace goes first — faulted,
    quarantined, preempted, and SLO-violating timelines outlive the
    sampled bulk."""

    def __init__(self, cap: int) -> None:
        self.cap = max(cap, 1)
        self._lock = threading.Lock()
        self._by_trace: collections.OrderedDict[str, dict] = \
            collections.OrderedDict()
        self._by_rid: dict[str, str] = {}

    def add(self, trace: dict) -> None:
        with self._lock:
            tid = trace["trace_id"]
            self._by_trace[tid] = trace
            self._by_rid[trace["request_id"]] = tid
            while len(self._by_trace) > self.cap:
                victim = next(
                    (k for k, v in self._by_trace.items() if not v["flags"]),
                    next(iter(self._by_trace)))
                gone = self._by_trace.pop(victim)
                self._by_rid.pop(gone["request_id"], None)

    def get(self, key: str) -> dict | None:
        with self._lock:
            tid = self._by_rid.get(key, key)
            return self._by_trace.get(tid)

    def all(self) -> list[dict]:
        with self._lock:
            return list(self._by_trace.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_trace)
