"""Chrome trace-event (Perfetto-loadable) export of assembled traces.

``chrome_trace`` renders the TraceStore's finished timelines plus the
engine-scope scheduler-phase spans as a Chrome trace-event JSON object —
open it at https://ui.perfetto.dev or chrome://tracing.  Layout: one
"process" per component (gateway / router / engine), one "thread" per
request, so a request's spans line up on one row and cross-component
hops read left to right under a single trace id.
"""

from __future__ import annotations

_PIDS = {"gateway": 1, "router": 2, "upstream": 2, "engine": 3}
_ENGINE_LOOP_TID = 0


def _component_pid(span: dict) -> int:
    return _PIDS.get(span.get("component", "engine"), 3)


def chrome_trace(traces: list[dict], phase_spans: list[dict] = ()) -> dict:
    """Render finished traces (and optional engine phase spans) as a
    Chrome trace-event object: ``{"traceEvents": [...]}``."""
    events: list[dict] = []
    tids: dict[str, int] = {}
    for pid_name, pid in (("gateway", 1), ("router", 2), ("engine", 3)):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": pid_name}})
    events.append({"name": "thread_name", "ph": "M", "pid": 3,
                   "tid": _ENGINE_LOOP_TID, "args": {"name": "engine-loop"}})

    for tr in traces:
        rid = tr["request_id"]
        tid = tids.setdefault(rid, len(tids) + 1)
        for pid in (1, 2, 3):
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": rid}})
        args = {"request_id": rid, "trace_id": tr["trace_id"],
                "flags": tr["flags"]}
        if tr.get("tier"):
            args["tier"] = tr["tier"]
        for sp in tr["spans"]:
            start = sp.get("start")
            if start is None:
                continue
            end = sp.get("end")
            ev = {"name": sp["name"], "ph": "X",
                  "ts": round(start * 1e6, 1),
                  "dur": round(((end if end is not None else start) - start)
                               * 1e6, 1),
                  "pid": _component_pid(sp), "tid": tid,
                  "cat": sp.get("component", "engine"), "args": dict(args)}
            if sp.get("arg") is not None:
                ev["args"]["arg"] = sp["arg"]
            if end is None:
                ev["args"]["open"] = True
            events.append(ev)

    for sp in phase_spans:
        if sp.get("start") is None:
            continue
        end = sp.get("end") if sp.get("end") is not None else sp["start"]
        events.append({"name": sp["name"], "ph": "X",
                       "ts": round(sp["start"] * 1e6, 1),
                       "dur": round((end - sp["start"]) * 1e6, 1),
                       "pid": 3, "tid": _ENGINE_LOOP_TID, "cat": "phase",
                       "args": ({"arg": sp["arg"]}
                                if sp.get("arg") is not None else {})})

    return {"traceEvents": events, "displayTimeUnit": "ms"}
