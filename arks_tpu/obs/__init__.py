"""Observability: per-request span timelines, flight recorder, Perfetto
export, on-demand JAX profiler windows, and log correlation.

Submodules:

- ``trace``    — W3C traceparent context, the lock-light per-thread event
  rings the engine step loop appends to, off-thread trace assembly, and
  the bounded tail-retention ``TraceStore``.
- ``perfetto`` — Chrome trace-event (Perfetto-loadable) export.
- ``profiler`` — ``jax.profiler`` windows (HTTP-armed or auto-armed on a
  step-time spike).
- ``logctx``   — contextvar-backed logging filter stamping
  ``request_id``/``trace_id`` into log records.
"""
