"""On-demand ``jax.profiler`` windows.

Armed two ways:

- **HTTP**: ``POST /v1/profiler/start`` / ``POST /v1/profiler/stop`` on
  the serving port — writes a profiler trace dir an operator can open in
  TensorBoard / Perfetto.
- **Auto-arm**: when a step's wall time jumps past
  ``ARKS_PROF_AUTO_ARM`` × the trailing median step time (default 0 =
  off), a window of ``ARKS_PROF_WINDOW_S`` seconds opens by itself — the
  profile of the anomaly, captured while it is still happening.

While a window is active the engine run loop wraps each step in a
``jax.profiler.TraceAnnotation`` carrying the live request/trace ids, so
device timelines correlate back to the span timelines in the TraceStore.
All hooks are called from the run loop (not the guarded hot-path
functions) and early-return to a couple of float compares when idle.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import os
import threading
import time

from arks_tpu.utils import knobs
from arks_tpu.utils.swallow import swallowed

log = logging.getLogger("arks_tpu.profiler")


class ProfilerWindows:
    def __init__(self, base_dir: str | None = None) -> None:
        self.base_dir = base_dir or knobs.get_str("ARKS_PROF_DIR")
        self.auto_mult = knobs.get_float("ARKS_PROF_AUTO_ARM",
                                         fallback=0.0)
        self.window_s = knobs.get_float("ARKS_PROF_WINDOW_S")
        self.active = False
        self.dir: str | None = None
        self.auto_armed_total = 0
        self._lock = threading.Lock()
        self._auto_end: float | None = None
        self._steps: collections.deque = collections.deque(maxlen=128)

    def start(self, logdir: str | None = None) -> dict:
        """Open a profiler window.  Returns {"ok", "dir"} or an error."""
        with self._lock:
            if self.active:
                return {"ok": False, "error": "already_active",
                        "dir": self.dir}
            d = logdir or os.path.join(
                self.base_dir, time.strftime("%Y%m%d-%H%M%S"))
            try:
                os.makedirs(d, exist_ok=True)
                import jax
                jax.profiler.start_trace(d)
            except Exception as e:
                log.debug("profiler start failed", exc_info=True)
                return {"ok": False, "error": f"{type(e).__name__}: {e}"}
            self.dir = d
            self.active = True
            return {"ok": True, "dir": d}

    def stop(self) -> dict:
        with self._lock:
            if not self.active:
                return {"ok": False, "error": "not_active"}
            self.active = False
            self._auto_end = None
            d, self.dir = self.dir, None
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as e:
                log.debug("profiler stop failed", exc_info=True)
                return {"ok": False, "error": f"{type(e).__name__}: {e}",
                        "dir": d}
            return {"ok": True, "dir": d}

    def on_step(self, dur_s: float) -> None:
        """Run-loop hook: feed one step's wall time.  Closes an expired
        auto window; opens one when the step time spikes past
        ``auto_mult`` × the trailing median."""
        if self.active:
            if self._auto_end is not None and time.monotonic() > self._auto_end:
                self.stop()
            return
        if self.auto_mult <= 0:
            return
        steps = self._steps
        steps.append(dur_s)
        if len(steps) < 32:
            return
        ordered = sorted(steps)
        med = ordered[len(ordered) // 2]
        if med > 0 and dur_s > self.auto_mult * med:
            r = self.start()
            if r.get("ok"):
                self._auto_end = time.monotonic() + self.window_s
                self.auto_armed_total += 1

    def annotate(self, name: str, ids: str = ""):
        """A ``jax.profiler.TraceAnnotation`` stamping the live span ids
        into the device timeline; a null context if jax is unavailable."""
        try:
            import jax
            label = f"{name}[{ids}]" if ids else name
            return jax.profiler.TraceAnnotation(label)
        except Exception as e:
            # No jax (pure-I/O process) → annotations are a no-op.
            swallowed("profiler.annotate", e)
            return contextlib.nullcontext()
