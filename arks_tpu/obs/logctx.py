"""Log correlation: stamp ``request_id``/``trace_id`` into log records.

A ``contextvars``-backed :class:`ContextFilter` sets ``record.request_id``
and ``record.trace_id`` on every record (``"-"`` when unbound) and, when
bound, appends a ``[rid=... trace=...]`` suffix to the message so
grep-by-request works with ANY formatter — no handler reconfiguration
required.  Server/router/gateway request threads bind around each
request; the engine binds in ``add_request`` and per-survivor in the
recovery path.  Format documented in docs/runbook.md.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging

request_id_var: contextvars.ContextVar = contextvars.ContextVar(
    "arks_request_id", default=None)
trace_id_var: contextvars.ContextVar = contextvars.ContextVar(
    "arks_trace_id", default=None)


class ContextFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        rid = request_id_var.get()
        tid = trace_id_var.get()
        record.request_id = rid or "-"
        record.trace_id = tid or "-"
        if rid or tid:
            # The suffix is literal text with no %-directives, so it is
            # safe to append before the formatter applies record.args.
            suffix = f" [rid={rid or '-'} trace={tid or '-'}]"
            msg = str(record.msg)
            if not msg.endswith(suffix):
                record.msg = msg + suffix
        return True


def install(logger: logging.Logger) -> None:
    """Attach the filter once (idempotent)."""
    if not any(isinstance(f, ContextFilter) for f in logger.filters):
        logger.addFilter(ContextFilter())


@contextlib.contextmanager
def bound(request_id: str | None = None, trace_id: str | None = None):
    """Bind ids for the current thread/context for the duration."""
    toks = []
    if request_id is not None:
        toks.append((request_id_var, request_id_var.set(request_id)))
    if trace_id is not None:
        toks.append((trace_id_var, trace_id_var.set(trace_id)))
    try:
        yield
    finally:
        for var, tok in toks:
            var.reset(tok)
