"""Prefix-digest sketches for cache-aware routing.

The engine's prefix reuse is keyed by chained content digests
(tier 0 = the paged allocator's on-device index, tier 1 = the host-RAM
spill tier).  Placement is only as good as the router's knowledge of
WHERE those digests live, so each decode backend exports a compact,
versioned summary of its resident digest chains — a bloom filter plus an
exact top-K of the most recently registered entries, per tier — via
``GET /v1/cache/sketch``.  The router polls the sketches and scores
candidate backends by *expected hit depth*: walk the request's digest
chain against each sketch and prefer the backend whose caches cover the
deepest prefix (tier-0 weighted — a device hit is free, a host hit costs
one H2D restore).

Two digest domains, because the router must stay tokenizer-free:

- **token**: requests whose ``prompt`` is a token-id list hash through
  the SAME chain as the engine (``iter_chain_digests``), so the router
  probes the engine's exact keys.
- **text**: text requests hash fixed char blocks of the canonical prompt
  text (``iter_text_digests``).  The server — which sees both the text
  and its token ids — records the text-block -> token-block alignment in
  a bounded ledger (``SketchExporter.link``); at build time a text digest
  is advertised as resident in a tier iff its aligned token digest is.
  Alignment rounds the required token depth UP, so a text-domain hit
  claim never overstates the token coverage behind it.

This module is imported by the router (pure I/O, no jax) and by the
engine — it must stay free of jax and of ``arks_tpu.engine`` imports.
"""

from __future__ import annotations

import base64
import hashlib
import os
import threading
import time
from collections import OrderedDict

import numpy as np

from arks_tpu.utils import knobs


# ---------------------------------------------------------------------------
# Chain digests — THE one hash-chaining implementation.  engine.paged
# re-exports these (the allocator's prefix index, the host tier and the
# disagg publish path all key through here); the router imports them
# directly so its token-domain probes hit the engine's exact keys.
# ---------------------------------------------------------------------------

def iter_chain_digests(ids, page: int):
    """Lazily yield chained content digests: digest j covers
    ids[: (j+1)*page].  Lazy yielding lets a matcher stop hashing at the
    first missing block instead of digesting a whole long prompt on what
    may be a first-block miss."""
    h = hashlib.sha1()
    arr = np.asarray(ids, np.int32)
    for j in range(len(arr) // page):
        h.update(arr[j * page:(j + 1) * page].tobytes())
        yield h.digest()


def chain_digests(ids, page: int, nblocks: int) -> list[bytes]:
    """First ``nblocks`` chained digests as a list (see iter_chain_digests)."""
    out = []
    for j, d in enumerate(iter_chain_digests(ids, page)):
        if j >= nblocks:
            break
        out.append(d)
    return out


def iter_text_digests(text: str, chars: int):
    """Text-domain chain: digest j covers text[: (j+1)*chars] (full char
    blocks only — a partial tail block can't anchor reuse)."""
    h = hashlib.sha1()
    # Block on CHARACTERS (stable across the router and server seeing the
    # same str), then hash the utf-8 bytes of each block.
    for j in range(len(text) // chars):
        h.update(text[j * chars:(j + 1) * chars].encode("utf-8",
                                                        "surrogatepass"))
        yield h.digest()


def canonical_prompt_text(obj) -> str | None:
    """The FULL prompt text of a parsed request body, extracted with the
    router's prefix-key scanning rules (content-part text joined, scan
    stops at the first unknown content shape so later turns never leak
    into the key).  The router's rendezvous key is a fixed-size prefix of
    this; the text-domain digest chain covers all of it.  None when the
    body carries no usable text (token-id prompts, image-only parts)."""
    if not isinstance(obj, dict):
        return None
    if isinstance(obj.get("messages"), list):
        parts = []
        for m in obj["messages"]:
            c = m.get("content") if isinstance(m, dict) else None
            if isinstance(c, list):
                c = "".join(t for p in c
                            if isinstance(p, dict) and p.get("type") == "text"
                            for t in (p.get("text"),) if isinstance(t, str))
                if not c:
                    break
            if not isinstance(c, str):
                break
            parts.append(c)
        text = "\x00".join(parts)
    elif isinstance(obj.get("prompt"), str):
        text = obj["prompt"]
    else:
        return None
    return text or None


# ---------------------------------------------------------------------------
# Bloom filter
# ---------------------------------------------------------------------------

class BloomSketch:
    """Fixed-size bloom filter over 20-byte digests.  The k bit indices
    are carved deterministically from the digest itself (4-byte
    big-endian words, extended by rehashing when k words outrun one
    digest) — no per-process salt, so an exported filter probes
    identically on any peer."""

    def __init__(self, m_bits: int, k: int, bits: bytes | None = None,
                 n: int = 0):
        if m_bits <= 0 or k <= 0:
            raise ValueError("m_bits and k must be positive")
        self.m = m_bits
        self.k = k
        self.n = n
        nbytes = (m_bits + 7) // 8
        self.bits = bytearray(bits) if bits is not None else bytearray(nbytes)
        if len(self.bits) != nbytes:
            raise ValueError("bloom bit-array size mismatch")

    def _indices(self, digest: bytes) -> list[int]:
        out: list[int] = []
        h, ctr = digest, 0
        while len(out) < self.k:
            for off in range(0, len(h) - 3, 4):
                if len(out) == self.k:
                    break
                out.append(int.from_bytes(h[off:off + 4], "big") % self.m)
            ctr += 1
            h = hashlib.sha1(digest + bytes([ctr & 0xFF])).digest()
        return out

    def add(self, digest: bytes) -> None:
        for i in self._indices(digest):
            self.bits[i >> 3] |= 1 << (i & 7)
        self.n += 1

    def __contains__(self, digest: bytes) -> bool:
        return all(self.bits[i >> 3] & (1 << (i & 7))
                   for i in self._indices(digest))

    def to_payload(self) -> dict:
        return {"m": self.m, "k": self.k, "n": self.n,
                "b64": base64.b64encode(bytes(self.bits)).decode()}

    @classmethod
    def from_payload(cls, p: dict) -> "BloomSketch":
        return cls(int(p["m"]), int(p["k"]),
                   bits=base64.b64decode(p["b64"]), n=int(p.get("n", 0)))


def _top_key(digest: bytes) -> str:
    """Exact-membership key for the top-K list: 8 bytes of the digest as
    hex — short enough to keep the payload compact, long enough that a
    collision is rarer than the bloom's false positives."""
    return digest[:8].hex()


# ---------------------------------------------------------------------------
# Engine side: build + export
# ---------------------------------------------------------------------------

class SketchExporter:
    """Per-engine sketch builder.  Holds the boot/reset epoch, the
    text->token alignment ledger, and a build cache keyed by the tier
    membership versions — a /v1/cache/sketch poll between membership
    changes returns the cached payload without re-walking anything.

    Thread-safety: built and linked from server threads, epoch-bumped
    from the engine thread; one lock guards the ledger and cache.  The
    engine thread itself never calls in here — membership reaches the
    builder through the allocator/host-tier snapshots the CALLER passes,
    keeping this class off the dispatch hot path entirely.
    """

    def __init__(self, page_tokens: int):
        self.page = page_tokens
        self.text_chars = knobs.get_int("ARKS_ROUTER_SKETCH_CHARS")
        self.m_bits = knobs.get_int("ARKS_ROUTER_SKETCH_BITS")
        self.k_hashes = knobs.get_int("ARKS_ROUTER_SKETCH_HASHES")
        self.top_k = knobs.get_int("ARKS_ROUTER_SKETCH_TOPK")
        self.max_links = knobs.get_int("ARKS_ROUTER_SKETCH_LINKS")
        if min(self.text_chars, self.m_bits, self.k_hashes, self.top_k,
               self.max_links) <= 0:
            raise ValueError("ARKS_ROUTER_SKETCH_* knobs must be positive")
        self._boot = os.urandom(4).hex()
        self._resets = 0
        self._reset_reason: str | None = None
        self._builds = 0
        self._lock = threading.Lock()
        # text digest -> aligned token digest, LRU order (oldest first).
        self._links: "OrderedDict[bytes, bytes]" = OrderedDict()
        self._links_version = 0
        self._cache: tuple | None = None  # (key, payload)

    @property
    def epoch(self) -> str:
        return f"{self._boot}.{self._resets}"

    def bump_epoch(self, reason: str | None = None) -> None:
        """Reset/restart marker: the next exported sketch carries a new
        epoch, and pollers drop their pre-reset copy immediately (a fresh
        cache must not keep winning on stale membership).  ``reason``
        ("resize", "rearm", ...) rides the next payloads' meta so a
        router operator can tell an elastic epoch roll from a crash."""
        with self._lock:
            self._resets += 1
            self._cache = None
            self._reset_reason = reason
            # The ledger maps text to token digests, not to residency —
            # it survives the reset like the host tier does.

    # -- text -> token alignment ledger --------------------------------

    def link(self, text: str | None, ids) -> None:
        """Record the text-block -> token-block alignment for one request
        (server threads, off the engine hot path).  Each full text block
        maps to the token chain digest at the depth that PROVABLY covers
        it: required token count rounded up to the next page boundary, so
        advertising the text digest never claims more token coverage than
        the tier actually holds."""
        if not text:
            return
        nchars, ntok = len(text), len(ids)
        ntok_blocks = ntok // self.page
        if nchars < self.text_chars or ntok_blocks == 0:
            return
        tok_digests = chain_digests(ids, self.page, ntok_blocks)
        pairs: list[tuple[bytes, bytes]] = []
        for j, td in enumerate(iter_text_digests(text, self.text_chars)):
            need_tokens = -(-((j + 1) * self.text_chars * ntok) // nchars)
            need_blocks = max(-(-need_tokens // self.page), 1)
            if need_blocks > ntok_blocks:
                break
            pairs.append((td, tok_digests[need_blocks - 1]))
        if not pairs:
            return
        with self._lock:
            changed = False
            for td, kd in pairs:
                if self._links.get(td) != kd:
                    changed = True
                self._links[td] = kd
                self._links.move_to_end(td)
            while len(self._links) > self.max_links:
                self._links.popitem(last=False)
                changed = True
            if changed:
                self._links_version += 1
                self._cache = None

    # -- build ---------------------------------------------------------

    def _tier_payload(self, members: list[bytes],
                      links: list[tuple[bytes, bytes]]) -> dict:
        bloom = BloomSketch(self.m_bits, self.k_hashes)
        for d in members:
            bloom.add(d)
        mset = set(members)
        covered = [td for td, kd in links if kd in mset]
        tbloom = BloomSketch(self.m_bits, self.k_hashes)
        for td in covered:
            tbloom.add(td)
        return {
            "count": len(members),
            # Most recently registered first — the exact-membership tier
            # of the summary.
            "top": [_top_key(d) for d in members[-self.top_k:]][::-1],
            "bloom": bloom.to_payload(),
            "text_count": len(covered),
            "text_top": [_top_key(t) for t in covered[-self.top_k:]][::-1],
            "text_bloom": tbloom.to_payload(),
        }

    def build(self, device: list[bytes], device_key, host: list[bytes],
              host_key, disk: list[bytes] | None = None, disk_key=-1,
              hit_tokens: dict | None = None,
              query_tokens: float = 0, extra: dict | None = None) -> dict:
        """The export payload for the given tier membership snapshots
        (oldest-first digest lists + an opaque version key per tier;
        ``disk`` is the optional tier-2 membership — peers use it to
        advertise restart-surviving blocks they can serve over
        /v1/cache/blocks).  Cached until a membership version, the link
        ledger, or the epoch changes; ``hit_tokens``/``query_tokens``
        ride every response uncached (they are cheap counters, and the
        actual-hit side of the router's expected-vs-actual accounting
        must not lag)."""
        with self._lock:
            key = (self._resets, device_key, host_key, disk_key,
                   self._links_version)
            if self._cache is not None and self._cache[0] == key:
                payload = self._cache[1]
            else:
                links = list(self._links.items())
                self._builds += 1
                tiers = {"device": self._tier_payload(device, links),
                         "host": self._tier_payload(host, links)}
                if disk:
                    tiers["disk"] = self._tier_payload(disk, links)
                payload = {
                    "enabled": True,
                    "epoch": self.epoch,
                    "epoch_reason": self._reset_reason,
                    "version": self._builds,
                    "built_unix": time.time(),
                    "page_tokens": self.page,
                    "text_chars": self.text_chars,
                    "tiers": tiers,
                }
                self._cache = (key, payload)
        out = dict(payload)
        out["hit_tokens"] = dict(hit_tokens or {})
        out["query_tokens"] = query_tokens
        if extra:
            out.update(extra)
        return out


# ---------------------------------------------------------------------------
# Router side: parse + score
# ---------------------------------------------------------------------------

class _TierView:
    def __init__(self, tier: dict, text: bool):
        pre = "text_" if text else ""
        self._top = set(tier.get(pre + "top") or [])
        b = tier.get(pre + "bloom")
        self._bloom = BloomSketch.from_payload(b) if b else None
        self.count = int(tier.get(pre + "count" if text else "count", 0))

    def contains(self, digest: bytes) -> bool:
        if _top_key(digest) in self._top:
            return True
        return self._bloom is not None and digest in self._bloom


class BackendSketch:
    """One backend's parsed sketch, as the router scores against it."""

    def __init__(self, payload: dict):
        self.enabled = bool(payload.get("enabled"))
        self.epoch = str(payload.get("epoch", ""))
        self.version = int(payload.get("version", 0))
        self.page_tokens = int(payload.get("page_tokens", 0) or 0)
        self.text_chars = int(payload.get("text_chars", 0) or 0)
        self.hit_tokens = {k: float(v) for k, v in
                           (payload.get("hit_tokens") or {}).items()}
        self.query_tokens = float(payload.get("query_tokens", 0) or 0)
        tiers = payload.get("tiers") or {}
        self._views = {}
        for tier in ("device", "host", "disk"):
            # "disk" is absent from pre-tier-2 backends' payloads; the
            # empty view then simply never extends a chain's coverage.
            t = tiers.get(tier) or {}
            self._views[(tier, "token")] = _TierView(t, text=False)
            self._views[(tier, "text")] = _TierView(t, text=True)

    @classmethod
    def from_payload(cls, payload: dict) -> "BackendSketch":
        return cls(payload)

    def score_chain(self, digests: list[bytes],
                    domain: str = "token") -> tuple[int, int, int]:
        """Expected hit depth for one request chain: the initial
        consecutive run resident in tier 0 (device), then the
        consecutive continuation resident in tier 1 (host), then the
        continuation resident in tier 2 (disk).  Returns
        (device_blocks, host_blocks, disk_blocks) — deterministic for a
        given sketch and chain."""
        dev_view = self._views[("device", domain)]
        host_view = self._views[("host", domain)]
        disk_view = self._views[("disk", domain)]
        dev = 0
        n = len(digests)
        while dev < n and dev_view.contains(digests[dev]):
            dev += 1
        host = 0
        while dev + host < n and host_view.contains(digests[dev + host]):
            host += 1
        disk = 0
        while (dev + host + disk < n
               and disk_view.contains(digests[dev + host + disk])):
            disk += 1
        return dev, host, disk
