"""Rule ``knobs`` — the typed registry is the only ``ARKS_*`` reader.

257 raw env reads across the tree meant no single place knew the full
configuration surface, and defaults silently disagreed between call
sites and docs.  ``arks_tpu/utils/knobs.py`` is now the one sanctioned
reader; this rule enforces it statically:

- ``raw-env-read``      ``os.environ.get/[]/setdefault`` / ``os.getenv``
                        of an ``ARKS_*`` name outside the registry
                        module (f-string reads with an ``ARKS_`` prefix
                        included);
- ``raw-env-write``     ``os.environ[...] = `` of an ``ARKS_*`` name —
                        use ``knobs.push`` so writes stay
                        registry-checked;
- ``unregistered-knob`` a knobs accessor called with a literal name the
                        registry doesn't declare;
- ``dynamic-knob-name`` WARN: an accessor called with a computed name
                        (the registry can't vouch statically — keep the
                        candidate names registered);
- ``unused-knob``       WARN: a registered name that appears nowhere
                        else in the package (stale registry entry).

The registered set is extracted from the registry module's AST (the
``_k("NAME", ...)`` declarations) — the analyzer never imports the code
it checks.
"""

from __future__ import annotations

import ast

from arks_tpu.analysis import Finding, SourceTree
from arks_tpu.analysis import queries as q

RULE = "knobs"

REGISTRY_PATH = "arks_tpu/utils/knobs.py"
ACCESSORS = {"raw", "get_str", "get_int", "get_float", "get_bool",
             "get_list", "push", "is_registered"}
# Knobs read by out-of-package surfaces only (bench.py, launch scripts)
# or exported into runtime containers: exempt from the unused-knob scan.
EXTERNAL_OK = {"ARKS_BENCH_PROBE_DEADLINE_S", "ARKS_BENCH_DRAFT_MODEL",
               "ARKS_GANG_LEADER_ADDRESS", "ARKS_GANG_SIZE",
               "ARKS_GANG_WORKER_INDEX",
               # read through a computed name (workloads.
               # default_runtime_image's f-string) — the dynamic-knob-name
               # warn at that site is the audit trail
               "ARKS_RUNTIME_DEFAULT_VLLM_IMAGE",
               "ARKS_RUNTIME_DEFAULT_SGLANG_IMAGE",
               "ARKS_RUNTIME_DEFAULT_DYNAMO_IMAGE",
               "ARKS_RUNTIME_DEFAULT_JAX_IMAGE"}


def registered_names(tree: SourceTree) -> set[str]:
    if REGISTRY_PATH not in tree.files:
        return set()
    names: set[str] = set()
    for node in ast.walk(tree.tree(REGISTRY_PATH)):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "_k" and node.args
                and isinstance(node.args[0], ast.Constant)):
            names.add(node.args[0].value)
    return names


def _is_environ(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os")


def _arks_literal(node: ast.AST) -> str | None:
    """The ARKS_* name of a Constant or ARKS_-prefixed f-string arg."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith("ARKS_"):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if isinstance(head, ast.Constant) \
                and str(head.value).startswith("ARKS_"):
            return ast.unparse(node)
    return None


def _module_consts(mod: ast.Module) -> dict[str, str]:
    """Module-level ``NAME = "literal"`` string bindings — an accessor
    called with such a name (slo's ``ENV_VAR`` style) resolves statically
    and doesn't trip the dynamic-name warn."""
    out: dict[str, str] = {}
    for stmt in mod.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, ast.Constant) \
                and isinstance(stmt.value.value, str):
            out[stmt.targets[0].id] = stmt.value.value
    return out


def check(tree: SourceTree) -> list[Finding]:
    findings: list[Finding] = []
    registered = registered_names(tree)
    referenced: set[str] = set()

    for path in tree.paths():
        mod = tree.tree(path)
        consts = _module_consts(mod)
        if path == REGISTRY_PATH:
            # the registry's own declarations don't count as references
            # (else unused-knob could never fire)
            continue
        referenced |= {s for s in q.string_constants(mod)
                       if s.startswith("ARKS_")}
        for node in ast.walk(mod):
            # raw reads: os.environ.get / os.getenv / os.environ[...]
            if isinstance(node, ast.Call):
                f = node.func
                name = None
                if isinstance(f, ast.Attribute) and node.args:
                    if (_is_environ(f.value)
                            and f.attr in ("get", "setdefault",
                                           "pop")) \
                            or (f.attr == "getenv"
                                and isinstance(f.value, ast.Name)
                                and f.value.id == "os"):
                        name = _arks_literal(node.args[0])
                if name:
                    fn = q.enclosing_function(mod, node.lineno)
                    findings.append(Finding(
                        RULE, "raw-env-read", path, node.lineno, fn,
                        "raw ARKS_* env read — go through "
                        "arks_tpu.utils.knobs (the typed registry)",
                        detail=name))
                # accessor calls
                target = None
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "knobs" and f.attr in ACCESSORS:
                    target = f.attr
                elif isinstance(f, ast.Name) and f.id in ACCESSORS \
                        and f.id not in ("raw", "push", "is_registered"):
                    # direct `from ... import get_int` style
                    target = f.id
                if target and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name) and arg.id in consts:
                        # named module constant → resolved statically
                        arg = ast.Constant(value=consts[arg.id])
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str):
                        if arg.value.startswith("ARKS_") \
                                and arg.value not in registered:
                            fn = q.enclosing_function(mod, node.lineno)
                            findings.append(Finding(
                                RULE, "unregistered-knob", path,
                                node.lineno, fn,
                                "knob not declared in the registry — add "
                                "it to arks_tpu/utils/knobs.py with type/"
                                "default/doc/subsystem",
                                detail=arg.value))
                    elif not isinstance(arg, ast.Constant):
                        fn = q.enclosing_function(mod, node.lineno)
                        findings.append(Finding(
                            RULE, "dynamic-knob-name", path, node.lineno,
                            fn,
                            "knob name computed at runtime — the registry "
                            "can't vouch statically; keep every candidate "
                            "registered", detail=ast.unparse(arg),
                            severity="warn"))
            elif isinstance(node, ast.Subscript) and _is_environ(
                    node.value):
                name = _arks_literal(node.slice)
                if name:
                    fn = q.enclosing_function(mod, node.lineno)
                    check_name = ("raw-env-read"
                                  if isinstance(node.ctx, ast.Load)
                                  else "raw-env-write")
                    verb = ("read" if isinstance(node.ctx, ast.Load)
                            else "write (use knobs.push)")
                    findings.append(Finding(
                        RULE, check_name, path, node.lineno, fn,
                        f"raw ARKS_* env {verb} — go through "
                        "arks_tpu.utils.knobs", detail=name))

    for name in sorted(registered - referenced - EXTERNAL_OK):
        findings.append(Finding(
            RULE, "unused-knob", REGISTRY_PATH, 1, "<registry>",
            "registered knob is referenced nowhere in the package — "
            "stale entry?", detail=name, severity="warn"))
    return findings
