"""Rule ``hotpath`` — zero-host-sync purity of the issue-side hot path.

The pipelined scheduler's contract: one dispatch goes OUT per issue-side
call, nothing comes back.  Instead of a hand-curated function allowlist
(the old ``HOT_PATH_FUNCTIONS`` tuple in tests/test_hotpath_guard.py,
which every PR had to remember to extend), this rule propagates the
contract over the call graph from the scheduler roots — every function
transitively reachable from ``step`` / ``_step_pipelined`` on the issue
side is checked automatically, so a new helper cannot dodge the guard by
not being listed.

Sanctioned boundaries (excluded from propagation, each with its own
contract):

- ``_resolve_*`` / ``_pipe_resolve_*`` / ``_finish_resume`` — the host-
  sync tails where blocking fetches BELONG;
- ``_warm_autotune`` — the pre-first-dispatch warm-up, the one place
  allowed to call ``autotune.ensure/sweep``;
- ``_disk_write_loop`` / ``_fetch_loop`` — the tier-2 spill writer and
  prefix-fetch worker THREADS (reached via their Thread-target
  registration): file and peer-HTTP IO is their whole job, so the
  issue-side purity contract stops at the thread hand-off queue;
- ``_residency_step`` — the windowed-residency forward: engagement
  spills, span-chained attends, and the sampler tail resolve
  synchronously by contract (a windowed slot's context does not fit the
  device, so its step IS a host-sync round trip).  Its prefetch issue
  helpers re-enter the checked set as explicit ROOTS instead.

(``_switch_to`` is deliberately NOT a boundary even though its stall is
sanctioned — it runs only after ``_drained_for_switch()`` — because its
subtree (``_init_model_state``) is where hot-path callbacks like
``on_evict -> _note_evicted`` are registered; cutting it off would blind
the graph to them.  Its one intentional finding, the warm-autotune call,
carries a baseline entry instead.)

Checks per reachable function:

- ``blocking-fetch``   np.asarray / device_get / .block_until_ready /
                       .item outside the sync tails;
- ``autotune-sweep``   a compile-and-time sweep reachable from the step
                       loop (``autotune.sweep`` / ``autotune.ensure`` /
                       ``_warm_autotune``);
- ``trace-access``     tracer use other than ``self.trace.evt`` /
                       ``.enabled`` (trace assembly leaking onto the
                       issue path);
- ``serialization``    time.sleep / json or pickle (de)serialization;
- ``lock-with``        WARN: ``with <...lock/mutex...>`` — brief host
                       mutexes are idiomatic here, but every new one
                       should be seen in review;
- ``lock-acquire``     explicit ``.acquire()`` (unbounded block).

Plus three surface contracts the old guard carried: ``trace-evt-impl``
(``Tracer.evt`` / ``_Ring`` stay lock- and serialization-free),
``sketch-import`` (``prefix_sketch`` stays importable without jax or the
engine), and ``contract`` (roots and sanctioned sync tails still exist
under their expected names).
"""

from __future__ import annotations

import ast
import re

from arks_tpu.analysis import Finding, SourceTree
from arks_tpu.analysis import queries as q
from arks_tpu.analysis.callgraph import CallGraph

RULE = "hotpath"

ENGINE = "arks_tpu/engine/engine.py"
ENGINE_CLASS = "InferenceEngine"

# Scheduler roots: the two step entry points, the sketch-export surface
# (server threads, same non-blocking contract), and the weight-streaming
# scatter path (H2D puts overlapped with live decode).
ROOTS = (
    (ENGINE, ENGINE_CLASS, "step"),
    (ENGINE, ENGINE_CLASS, "_step_pipelined"),
    (ENGINE, ENGINE_CLASS, "cache_sketch"),
    (ENGINE, ENGINE_CLASS, "note_prompt_text"),
    ("arks_tpu/models/weights.py", None, "stream_params_to_device"),
    # Tenant-fair admission: the WDRR pick/put/aging path runs inside the
    # scheduler's admission slice every step — same no-serialization /
    # no-sleep / no-blocking-fetch contract as the step roots.  (Appended
    # AFTER the legacy entries: step_reachable slices ROOTS[:2].)
    ("arks_tpu/engine/fairqueue.py", "FairQueue", "get_nowait"),
    ("arks_tpu/engine/fairqueue.py", "FairQueue", "put"),
    ("arks_tpu/engine/fairqueue.py", "FairQueue", "head_prio"),
    ("arks_tpu/engine/fairqueue.py", "FairQueue", "age_tick"),
    # Fleet prefix KV (tier 2): the spill hand-off and fetch park run in
    # the scheduler's step slice (file IO lives on the writer/fetch
    # threads — only the queue hand-off is issue-side); block_for_export
    # serves peer GETs from server threads under the same non-blocking
    # contract as cache_sketch; the disk tier's admission probe is a
    # pure in-memory index walk.
    (ENGINE, ENGINE_CLASS, "_drain_disk_spills"),
    (ENGINE, ENGINE_CLASS, "_issue_fetch"),
    (ENGINE, ENGINE_CLASS, "block_for_export"),
    ("arks_tpu/engine/prefix_cache.py", "DiskPrefixTier", "match_digests"),
    # Windowed residency (contexts larger than the device pool): the
    # prefetch ISSUE helpers — staging-half H2D scatter and span-table
    # assembly — run between attend dispatches inside the residency
    # forward; if they ever block on the device, the span-(i+1) prefetch
    # stops overlapping the attend of span i that hides it.  The forward
    # itself resolves logits synchronously by contract, so
    # _residency_step is a sanctioned sync tail (BOUNDARY_RE below),
    # like the _resolve_* family.
    ("arks_tpu/engine/residency.py", "ResidencyManager", "_ensure_staged"),
    ("arks_tpu/engine/residency.py", "ResidencyManager", "_span_tables"),
    # Depth-0 sampler fusion: the fused step's issue half dispatches the
    # whole token step (forward + sample) in one call and must stay free
    # of blocking fetches — the host sync belongs to its
    # _pipe_resolve_one tail alone.
    (ENGINE, ENGINE_CLASS, "_step_fused"),
    # Elastic resize: the reshard plan builds per-leaf device_put calls
    # from live params at the drained boundary — issue-side by design
    # (survivors are parked on host; a blocking fetch here stretches the
    # drain window every in-flight stream is waiting out).  The warm-up
    # issue helper runs right after the rebuild on the scheduler thread,
    # before traffic returns — same no-sleep / no-serialization budget.
    ("arks_tpu/models/weights.py", None, "reshard_params_to_mesh"),
    (ENGINE, ENGINE_CLASS, "_issue_warmup_request"),
)

BOUNDARY_RE = re.compile(
    r"^(_resolve_|_pipe_resolve_)"
    r"|^(_finish_resume|_warm_autotune|_disk_write_loop|_fetch_loop"
    r"|_residency_step)$")

# The sanctioned host-sync tails the boundary regex exists FOR: if these
# disappear wholesale the guard is checking a fiction.
EXPECTED_TAILS = (
    "_resolve_decode", "_resolve_mixed", "_resolve_spec_mixed",
    "_pipe_resolve_one", "_resolve_admit_batch", "_resolve_spills",
    "_resolve_restores", "_resolve_preempt_swaps", "_finish_resume",
    "_resolve_fetches", "_disk_write_loop", "_fetch_loop",
    "_residency_step",
)

SERIAL_CALLS = {"json.dumps", "json.loads", "pickle.dumps",
                "pickle.loads", "pickle.dump", "pickle.load",
                "time.sleep", "marshal.dumps", "marshal.loads"}

_LOCKISH = re.compile(r"lock|mutex|condition|semaphore", re.I)


def step_reachable(graph: CallGraph) -> set[str]:
    """Issue-side reachable set from the two scheduler step roots only
    (the acceptance-test surface: must cover the legacy tuple)."""
    roots = [graph.find(*r) for r in ROOTS[:2]]
    return graph.reachable([r for r in roots if r],
                           stop=lambda fn: bool(BOUNDARY_RE.match(fn.name)))


def _function_findings(fn, findings: list[Finding]) -> None:
    path, qual = fn.path, (f"{fn.cls}.{fn.name}" if fn.cls else fn.name)
    for hit, arg, lineno in q.blocking_fetches(fn.node):
        findings.append(Finding(
            RULE, "blocking-fetch", path, lineno, qual,
            "blocking device fetch on the issue-side hot path (move it "
            "into a _resolve_* tail or add a reviewed baseline entry)",
            detail=f"{hit}({arg})"))
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            f = node.func
            recv = ast.unparse(f.value)
            full = f"{recv}.{f.attr}"
            if f.attr in ("sweep", "ensure") \
                    and recv.split(".")[-1] == "autotune":
                findings.append(Finding(
                    RULE, "autotune-sweep", path, node.lineno, qual,
                    "autotune sweep reachable from the step loop (only "
                    "_warm_autotune may compile-and-time candidates)",
                    detail=full))
            elif f.attr == "_warm_autotune":
                findings.append(Finding(
                    RULE, "autotune-sweep", path, node.lineno, qual,
                    "warm-up sweep called from the step loop",
                    detail=full))
            elif full in SERIAL_CALLS:
                findings.append(Finding(
                    RULE, "serialization", path, node.lineno, qual,
                    "serialization/sleep on the issue-side hot path",
                    detail=full))
            elif f.attr == "acquire" and _LOCKISH.search(recv):
                # only lock-like receivers: pool/guide refcount
                # .acquire() is bookkeeping, not an unbounded block
                findings.append(Finding(
                    RULE, "lock-acquire", path, node.lineno, qual,
                    "explicit lock acquire on the issue-side hot path",
                    detail=full))
        if isinstance(node, ast.Attribute):
            v = node.value
            if (isinstance(v, ast.Attribute) and v.attr == "trace"
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self"
                    and node.attr not in ("evt", "enabled")):
                findings.append(Finding(
                    RULE, "trace-access", path, node.lineno, qual,
                    "non-evt tracer access on the issue-side hot path "
                    "(trace assembly belongs off-thread)",
                    detail=f"self.trace.{node.attr}"))
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = ast.unparse(item.context_expr)
                if _LOCKISH.search(expr):
                    findings.append(Finding(
                        RULE, "lock-with", path, node.lineno, qual,
                        "lock held on the issue-side hot path (keep the "
                        "critical section bounded and host-only)",
                        detail=expr, severity="warn"))


def _trace_evt_impl(tree: SourceTree, findings: list[Finding]) -> None:
    path = "arks_tpu/obs/trace.py"
    if path not in tree.files:
        return
    mod = tree.tree(path)
    classes = {n.name: n for n in mod.body if isinstance(n, ast.ClassDef)}
    scopes = []
    tracer = classes.get("Tracer")
    if tracer is not None:
        evt = q.func_defs(tracer).get("evt")
        if evt is None:
            findings.append(Finding(
                RULE, "contract", path, tracer.lineno, "Tracer",
                "Tracer.evt disappeared — the step loop's only sanctioned "
                "tracing entry"))
        else:
            scopes.append(("Tracer.evt", evt))
    if "_Ring" in classes:
        scopes.append(("_Ring", classes["_Ring"]))
    for scope_name, scope in scopes:
        allowed = set()
        for node in ast.walk(scope):
            if isinstance(node, ast.ExceptHandler):
                # the sanctioned first-call-per-thread ring creation
                for sub in ast.walk(node):
                    allowed.add(id(sub))
        for node in ast.walk(scope):
            if id(node) in allowed:
                continue
            bad = None
            if isinstance(node, (ast.With, ast.AsyncWith)):
                bad = "with-block (lock?)"
            elif isinstance(node, ast.Attribute) and node.attr in (
                    "acquire", "Lock", "RLock", "sleep", "dumps", "loads",
                    "flush", "join"):
                bad = f".{node.attr}"
            elif isinstance(node, ast.Name) and node.id in ("json",
                                                            "pickle"):
                bad = node.id
            if bad:
                findings.append(Finding(
                    RULE, "trace-evt-impl", path, node.lineno, scope_name,
                    "lock/serialization on the event-record path",
                    detail=bad))


def _sketch_import(tree: SourceTree, findings: list[Finding]) -> None:
    path = "arks_tpu/prefix_sketch.py"
    if path not in tree.files:
        return
    for name, lineno in q.module_imports(tree.tree(path)):
        if name.startswith("jax") or name.startswith("arks_tpu.engine"):
            findings.append(Finding(
                RULE, "sketch-import", path, lineno, "<module>",
                "prefix_sketch must stay importable by the pure-I/O "
                "router process (no jax, no engine)", detail=name))


def check(tree: SourceTree) -> list[Finding]:
    findings: list[Finding] = []
    graph = CallGraph(tree)

    missing_roots = [r for r in ROOTS
                     if r[0] in tree.files and graph.find(*r) is None]
    for path, cls, name in missing_roots:
        findings.append(Finding(
            RULE, "contract", path, 1, f"{cls}.{name}" if cls else name,
            "hot-path root renamed/removed — re-anchor the rule's ROOTS"))

    if ENGINE in tree.files:
        engine_cls = q.class_def(tree.tree(ENGINE), ENGINE_CLASS)
        methods = q.func_defs(engine_cls) if engine_cls else {}
        for tail in EXPECTED_TAILS:
            if tail not in methods:
                findings.append(Finding(
                    RULE, "contract", ENGINE, 1,
                    f"{ENGINE_CLASS}.{tail}",
                    "sanctioned host-sync tail renamed/removed — the "
                    "issue-side guard is only meaningful while the sync "
                    "tails exist"))

    roots = [nid for nid in (graph.find(*r) for r in ROOTS) if nid]
    reach = graph.reachable(
        roots, stop=lambda fn: bool(BOUNDARY_RE.match(fn.name)))
    for nid in sorted(reach):
        _function_findings(graph.nodes[nid], findings)

    _trace_evt_impl(tree, findings)
    _sketch_import(tree, findings)
    return findings
