"""Rule ``tracepurity`` — no host state inside traced functions.

A function handed to ``jax.jit`` / ``pjit`` / ``shard_map`` / a Pallas
kernel executes its Python body only at TRACE time.  A ``time.time()``
or ``np.random`` call inside one doesn't do what it looks like — it
bakes a trace-time constant into the compiled program — and an
``os.environ`` read there makes compilation depend on ambient process
state, the compile-variant hazard the compile-budget test only catches
after the fact.  This rule finds traced functions statically (decorator
forms, ``jax.jit(f)`` call forms, ``pl.pallas_call(kernel)`` /
``partial(kernel, ...)`` kernel references) and rejects:

- wall-clock reads (``time.time/monotonic/perf_counter/time_ns``) and
  sleeps;
- host RNG (``np.random.*``, ``random.*`` — device randomness goes
  through ``jax.random`` with threaded keys);
- env/file reads (``os.environ`` / ``os.getenv`` / ``open()`` /
  ``os.urandom``) — including knob reads: read the knob OUTSIDE and
  close over the value.
"""

from __future__ import annotations

import ast

from arks_tpu.analysis import Finding, SourceTree
from arks_tpu.analysis import queries as q

RULE = "tracepurity"

TIME_ATTRS = {"time", "monotonic", "perf_counter", "time_ns", "sleep",
              "monotonic_ns", "perf_counter_ns"}
TRACE_ENTRY = {"jax.jit", "jit", "pjit", "jax.pjit", "pl.pallas_call",
               "pallas_call", "shard_map", "jax.experimental.pjit"}


def _decorator_traced(dec: ast.AST) -> bool:
    s = ast.unparse(dec)
    base = s.split("(")[0]
    if base in TRACE_ENTRY or base.endswith(".pallas_call") \
            or base.endswith(".pjit") or base == "jax.jit":
        return True
    # partial(jax.jit, ...) / functools.partial(jit, static_argnums=...)
    return base.endswith("partial") and any(
        t in s for t in ("jax.jit", "jit,", "jit)", "pallas_call"))


def _call_targets(call: ast.Call) -> list[str]:
    """Local function names referenced as the traced target of a
    jit/pallas_call invocation: bare names, ``partial(name, ...)``, and
    ``self.name`` / ``cls.name`` attribute references."""
    out: list[str] = []
    args = list(call.args)
    for kw in call.keywords or []:
        if kw.arg in ("fun", "f", "kernel"):
            args.insert(0, kw.value)
    if not args:
        return out
    a = args[0]
    if isinstance(a, ast.Name):
        out.append(a.id)
    elif isinstance(a, ast.Attribute):
        out.append(a.attr)
    elif isinstance(a, ast.Call):
        base = ast.unparse(a.func)
        if base.endswith("partial") and a.args:
            inner = a.args[0]
            if isinstance(inner, ast.Name):
                out.append(inner.id)
            elif isinstance(inner, ast.Attribute):
                out.append(inner.attr)
    return out


def traced_functions(mod: ast.Module) -> dict[str, ast.AST]:
    """name -> FunctionDef for every function the module hands to a
    trace entry point (any nesting level)."""
    all_funcs: dict[str, ast.AST] = {}
    for node in ast.walk(mod):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            all_funcs.setdefault(node.name, node)
    traced: dict[str, ast.AST] = {}
    for node in ast.walk(mod):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_decorator_traced(d) for d in node.decorator_list):
                traced[node.name] = node
        elif isinstance(node, ast.Call):
            base = ast.unparse(node.func).split("(")[0]
            if base in TRACE_ENTRY or base.endswith(".pallas_call") \
                    or base.endswith(".pjit"):
                for name in _call_targets(node):
                    if name in all_funcs:
                        traced[name] = all_funcs[name]
    return traced


def _impurities(fn: ast.AST):
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute):
            s = ast.unparse(node)
            if isinstance(node.value, ast.Name) \
                    and node.value.id == "time" \
                    and node.attr in TIME_ATTRS:
                yield "wall-clock", s, node.lineno
            elif s.startswith(("np.random", "numpy.random")):
                yield "host-rng", s, node.lineno
            elif isinstance(node.value, ast.Name) \
                    and node.value.id == "random":
                yield "host-rng", s, node.lineno
            elif s in ("os.environ", "os.getenv", "os.urandom"):
                yield "host-state", s, node.lineno
        elif isinstance(node, ast.Call) and isinstance(node.func,
                                                       ast.Name):
            if node.func.id == "open":
                yield "host-state", "open()", node.lineno


def check(tree: SourceTree) -> list[Finding]:
    findings: list[Finding] = []
    for path in tree.paths():
        mod = tree.tree(path)
        for name, fn in sorted(traced_functions(mod).items()):
            for kind, what, lineno in _impurities(fn):
                findings.append(Finding(
                    RULE, kind, path, lineno, name,
                    f"{what} inside a jit/Pallas-traced function — runs "
                    "at trace time, not step time (compile-variant / "
                    "nondeterminism hazard); hoist it out and close over "
                    "the value", detail=what))
    return findings
