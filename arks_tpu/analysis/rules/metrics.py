"""Rule ``metrics`` — static metric-family census.

Prometheus conventions, checked at the registration call sites
(``registry.counter/gauge/histogram("name", ...)`` with a literal
name) across every component — not just the three registries the old
conformance test happened to instantiate:

- ``name-convention``   snake_case family names; counters end in
                        ``_total``; nothing else does;
- ``duplicate-family``  the same family name registered in two different
                        components (scrape-time collision when both land
                        on one exposition endpoint);
- ``dynamic-metric-name``  WARN: a non-literal family name — invisible
                        to this census and to grep.
"""

from __future__ import annotations

import ast
import re

from arks_tpu.analysis import Finding, SourceTree

RULE = "metrics"

NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
KINDS = ("counter", "gauge", "histogram")


def registrations(tree: SourceTree):
    """(path, scope, kind, name|None, lineno) for each registration call
    site; ``scope`` is the enclosing top-level class/function (the
    component owning the family)."""
    out = []
    for path in tree.paths():
        mod = tree.tree(path)

        def visit(node, scope, path=path):
            for child in ast.iter_child_nodes(node):
                s = scope
                if isinstance(child, (ast.ClassDef, ast.FunctionDef,
                                      ast.AsyncFunctionDef)) and not scope:
                    s = child.name
                if isinstance(child, ast.Call) \
                        and isinstance(child.func, ast.Attribute) \
                        and child.func.attr in KINDS:
                    name = None
                    if child.args and isinstance(child.args[0],
                                                 ast.Constant) \
                            and isinstance(child.args[0].value, str):
                        name = child.args[0].value
                    out.append((path, scope or "<module>",
                                child.func.attr, name, child.lineno))
                visit(child, s)

        visit(mod, "")
    return out


def check(tree: SourceTree) -> list[Finding]:
    findings: list[Finding] = []
    seen: dict[str, tuple[str, str]] = {}
    for path, scope, kind, name, lineno in registrations(tree):
        if name is None:
            findings.append(Finding(
                RULE, "dynamic-metric-name", path, lineno, scope,
                "metric family name computed at runtime — invisible to "
                "the census", severity="warn"))
            continue
        if not NAME_RE.match(name):
            findings.append(Finding(
                RULE, "name-convention", path, lineno, scope,
                "metric family name must be snake_case "
                "([a-z][a-z0-9_]*)", detail=name))
        if kind == "counter" and not name.endswith("_total"):
            findings.append(Finding(
                RULE, "name-convention", path, lineno, scope,
                "counter family must end in _total", detail=name))
        elif kind != "counter" and name.endswith("_total"):
            findings.append(Finding(
                RULE, "name-convention", path, lineno, scope,
                f"{kind} family must not end in _total", detail=name))
        prev = seen.get(name)
        if prev is not None and prev != (path, scope):
            findings.append(Finding(
                RULE, "duplicate-family", path, lineno, scope,
                f"family already registered by {prev[1]} ({prev[0]}) — "
                "two components exporting one family collide at scrape "
                "time", detail=name))
        seen.setdefault(name, (path, scope))
    return findings
