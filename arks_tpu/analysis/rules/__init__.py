"""Rule registry: name -> callable(SourceTree) -> list[Finding]."""

from __future__ import annotations

from arks_tpu.analysis.rules.exceptions import check as _exceptions
from arks_tpu.analysis.rules.hotpath import check as _hotpath
from arks_tpu.analysis.rules.knobs import check as _knobs
from arks_tpu.analysis.rules.metrics import check as _metrics
from arks_tpu.analysis.rules.tracepurity import check as _tracepurity

RULES = {
    "hotpath": _hotpath,
    "exceptions": _exceptions,
    "knobs": _knobs,
    "tracepurity": _tracepurity,
    "metrics": _metrics,
}
