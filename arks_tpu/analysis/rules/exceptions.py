"""Rule ``exceptions`` — no silent swallows, repo-wide.

The engine's fault-isolation contract (engine/faults.py) lives or dies
on faults being VISIBLE; the same failure mode — an ``except Exception``
that eats an error on a path tests rarely exercise — strands requests in
the gateway, hides poisoned state in the router, and wedges reconcile
loops in the control plane just as silently.  Every broad handler
(``except Exception`` / bare ``except``) under ``arks_tpu/`` must:

- re-raise (a ``raise`` anywhere in the handler), or
- route through the fault API — ``faults.swallowed`` /
  ``utils.swallow.swallowed`` / ``StepFault`` / ``classify`` /
  ``_recover_from_fault`` / ``os._exit`` —, or
- OUTSIDE ``arks_tpu/engine/``: log the exception with a traceback
  (``log.exception(...)`` or any ``exc_info=`` logging call) — the
  observable-swallow route supervision loops need, or
- carry a reviewed suppression in the baseline file.

``arks_tpu/engine/`` keeps the stricter legacy contract (no plain
log-and-continue): a swallowed engine exception defeats quarantine
accounting even when logged.  Narrow handlers are exempt — naming the
exception class is already a reviewed decision.
"""

from __future__ import annotations

import ast

from arks_tpu.analysis import Finding, SourceTree
from arks_tpu.analysis import queries as q

RULE = "exceptions"

FAULT_API = frozenset({
    "swallowed",            # faults.swallowed / utils.swallow.swallowed
    "StepFault",            # re-raise as an attributed fault
    "classify",             # building a StepFault's kind
    "_recover_from_fault",  # the recovery entry point itself
    "_exit",                # os._exit — the escalation ladder's last rung
})

STRICT_PREFIX = "arks_tpu/engine/"


def check(tree: SourceTree) -> list[Finding]:
    findings: list[Finding] = []
    for path in tree.paths():
        mod = tree.tree(path)
        strict = path.startswith(STRICT_PREFIX)
        for node in ast.walk(mod):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not q.is_broad_handler(node):
                continue
            if q.routes_fault(node, FAULT_API):
                continue
            if not strict and q.logs_with_traceback(node):
                continue
            fn = q.enclosing_function(mod, node.lineno)
            routes = ("re-raise or route through the fault API "
                      "(swallowed/StepFault)" if strict else
                      "re-raise, call swallowed(), or log with "
                      "exc_info/log.exception")
            findings.append(Finding(
                RULE, "broad-swallow", path, node.lineno, fn,
                f"broad exception handler swallows silently — {routes}, "
                "or justify a baseline entry",
                detail=f"except in {fn}"))
    return findings
