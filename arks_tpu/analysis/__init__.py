"""arkslint — call-graph-aware static analysis for the arks-tpu engine.

The engine's load-bearing invariants (zero-host-sync issue path, visible
faults, registered configuration knobs, trace-pure jitted functions,
metric naming) used to live in three hand-grown AST guard tests, each
gated on a hand-maintained function allowlist that every PR had to
remember to extend.  This package makes them machine-checked repo-wide:

- ``hotpath``      hot-path purity propagated over the call graph from
                   the scheduler roots — no hand-listed helper names.
- ``exceptions``   broad-exception discipline for every module under
                   ``arks_tpu/`` (engine keeps its stricter contract).
- ``knobs``        every ``ARKS_*`` env read goes through the typed
                   registry (``arks_tpu/utils/knobs.py``).
- ``tracepurity``  no wall-clock / RNG / host-state reads inside
                   functions handed to ``jax.jit`` / Pallas.
- ``metrics``      static metric-family census (naming conventions, no
                   duplicate families across components).

Pure AST over the source tree: the analyzer imports neither JAX nor the
modules it checks, so it runs anywhere in well under a second.  CLI:
``python -m arks_tpu.analysis --all`` (or ``tools/arkslint``); reviewed
suppressions live in ``tools/arkslint-baseline.json``.  See
``docs/runbook.md`` ("Reading arkslint output").
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib

__all__ = ["Finding", "SourceTree", "run_rules", "repo_root"]


@dataclasses.dataclass
class Finding:
    """One rule violation.

    ``key()`` is deliberately line-independent (rule, path, qualname,
    detail) so baseline suppressions survive unrelated edits to the same
    file; ``check`` names the sub-check within a rule so thin test
    wrappers can filter.
    """

    rule: str
    check: str
    path: str
    line: int
    qualname: str
    message: str
    detail: str = ""
    severity: str = "error"          # "error" | "warn"

    def key(self) -> tuple:
        return (self.rule, self.path, self.qualname,
                self.detail or self.check)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        what = f" [{self.detail}]" if self.detail else ""
        return (f"{loc}: {self.severity}[{self.rule}/{self.check}] "
                f"{self.qualname}: {self.message}{what}")


class SourceTree:
    """The parsed source universe: repo-relative path -> AST.

    Built from disk (``SourceTree.load``) for the real repo, or from an
    in-memory ``{path: source}`` dict for rule fixture tests — rules see
    no difference.
    """

    def __init__(self, files: dict[str, str]):
        self.files = dict(files)
        self._asts: dict[str, ast.Module] = {}

    @classmethod
    def load(cls, root: str | pathlib.Path,
             package: str = "arks_tpu") -> "SourceTree":
        root = pathlib.Path(root)
        files: dict[str, str] = {}
        for p in sorted((root / package).rglob("*.py")):
            if "__pycache__" in p.parts:
                continue
            files[p.relative_to(root).as_posix()] = p.read_text()
        if not files:
            raise FileNotFoundError(f"no {package}/**/*.py under {root}")
        return cls(files)

    def paths(self) -> list[str]:
        return sorted(self.files)

    def tree(self, path: str) -> ast.Module:
        if path not in self._asts:
            self._asts[path] = ast.parse(self.files[path], filename=path)
        return self._asts[path]

    def module_path(self, dotted: str) -> str | None:
        """Resolve a dotted module name to a path in this tree
        (``arks_tpu.ops.autotune`` -> ``arks_tpu/ops/autotune.py``)."""
        base = dotted.replace(".", "/")
        for cand in (base + ".py", base + "/__init__.py"):
            if cand in self.files:
                return cand
        return None


def repo_root() -> pathlib.Path:
    """The repo root: the directory holding the ``arks_tpu`` package."""
    return pathlib.Path(__file__).resolve().parents[2]


def run_rules(tree: SourceTree, rule_names=None) -> list[Finding]:
    """Run the named rules (all by default) and return raw findings,
    unsuppressed — baseline filtering is the caller's (CLI / test
    wrapper) concern."""
    from arks_tpu.analysis.rules import RULES
    findings: list[Finding] = []
    for name in (rule_names or sorted(RULES)):
        try:
            rule = RULES[name]
        except KeyError:
            raise KeyError(
                f"unknown rule {name!r} (have: {', '.join(sorted(RULES))})"
            ) from None
        findings.extend(rule(tree))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.check))
    return findings
