"""Shared AST queries: the vocabulary the rules (and the thin guard-test
wrappers in ``tests/``) are built from.  Everything here is pure
``ast`` — no imports of the code under analysis."""

from __future__ import annotations

import ast

# Host-blocking device fetches: the calls that turn an async dispatch
# into a synchronous host stall.
BLOCKING_ATTRS = {"block_until_ready", "item"}


def class_def(mod: ast.Module, name: str) -> ast.ClassDef | None:
    for node in mod.body:
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


def func_defs(scope: ast.AST) -> dict[str, ast.AST]:
    """Immediate function/async-function children of a module or class."""
    return {n.name: n for n in scope.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def blocking_fetches(func_node: ast.AST):
    """(kind, arg, lineno) for each blocking device fetch in the
    function: np.asarray / *.device_get / .block_until_ready / .item —
    skipping literal host containers, which are host data by
    construction."""
    out = []
    for node in ast.walk(func_node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute):
            continue
        hit = None
        if (f.attr == "asarray" and isinstance(f.value, ast.Name)
                and f.value.id == "np"):
            hit = "np.asarray"
        elif f.attr == "device_get":
            hit = "device_get"
        elif f.attr in BLOCKING_ATTRS:
            hit = f.attr
        if hit is None:
            continue
        if node.args and isinstance(node.args[0],
                                    (ast.List, ast.ListComp, ast.Tuple,
                                     ast.GeneratorExp, ast.Constant)):
            continue
        arg = ast.unparse(node.args[0]) if node.args else ""
        out.append((hit, arg, node.lineno))
    return out


def is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return any(n in ("Exception", "BaseException") for n in names)


def routes_fault(handler: ast.ExceptHandler, api_names: frozenset) -> bool:
    """True if the handler re-raises or calls one of ``api_names``."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name in api_names:
                return True
    return False


def logs_with_traceback(handler: ast.ExceptHandler) -> bool:
    """True if the handler logs the exception observably: a
    ``*.exception(...)`` call, or any call carrying an ``exc_info=``
    keyword (``log.warning(..., exc_info=True)``)."""
    for node in ast.walk(handler):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "exception":
            return True
        if any(kw.arg == "exc_info" for kw in node.keywords or []):
            return True
    return False


def enclosing_function(mod: ast.Module, lineno: int) -> str:
    """Qualname-ish (Class.method / func / <module>) of the innermost
    function containing ``lineno``."""
    best = "<module>"
    best_line = 0

    def visit(node, prefix):
        nonlocal best, best_line
        for child in ast.iter_child_nodes(node):
            name = prefix
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                name = f"{prefix}.{child.name}" if prefix else child.name
                if (not isinstance(child, ast.ClassDef)
                        and child.lineno <= lineno
                        and child.lineno > best_line
                        and lineno <= getattr(child, "end_lineno",
                                              lineno)):
                    best, best_line = name, child.lineno
            visit(child, name)

    visit(mod, "")
    return best


def module_imports(mod: ast.Module):
    """Dotted module names imported anywhere in the module."""
    out = []
    for node in ast.walk(mod):
        if isinstance(node, ast.Import):
            out.extend((a.name, node.lineno) for a in node.names)
        elif isinstance(node, ast.ImportFrom) and node.module:
            out.append((node.module, node.lineno))
    return out


def string_constants(mod: ast.Module) -> set[str]:
    return {n.value for n in ast.walk(mod)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}
