"""A call-graph builder tuned for this codebase's dispatch patterns.

Nodes are functions, addressed ``path::Class.method`` or ``path::func``.
Edges cover the ways the engine actually composes its hot path:

- ``self.method(...)`` calls AND bare ``self.method`` references (the
  scheduler passes methods as callbacks — ``on_evict=self._note_evicted``
  must pull ``_note_evicted`` into the reachable set);
- bare-name calls/references to functions of the same module;
- ``alias.func(...)`` where ``alias`` is an imported ``arks_tpu`` module
  (``from arks_tpu.ops import paged_attention as pa; pa.mixed_grid_plan``),
  and names bound by ``from arks_tpu.x import f`` — so reachability flows
  from ``_issue_mixed`` through ``ops.paged_attention.mixed_grid_plan``
  into ``ops.autotune.lookup`` with zero configuration.

Deliberately NOT handled (would need type inference): calls through
instance attributes of *other* objects (``self.pool.load(...)``) — those
cross a thread boundary in this engine anyway, which is exactly where
the zero-host-sync contract changes hands.
"""

from __future__ import annotations

import ast
import dataclasses

from arks_tpu.analysis import SourceTree


@dataclasses.dataclass
class FuncNode:
    qualname: str                 # "arks_tpu/engine/engine.py::C.m"
    path: str
    cls: str | None
    name: str
    node: ast.AST                 # FunctionDef | AsyncFunctionDef


def node_id(path: str, cls: str | None, name: str) -> str:
    return f"{path}::{cls}.{name}" if cls else f"{path}::{name}"


class CallGraph:
    def __init__(self, tree: SourceTree):
        self.tree = tree
        self.nodes: dict[str, FuncNode] = {}
        # per (path, cls) method tables and per-path module-level tables
        self._methods: dict[tuple[str, str], dict[str, str]] = {}
        self._mod_funcs: dict[str, dict[str, str]] = {}
        # per-path import maps: alias -> module path; name -> func node id
        self._mod_alias: dict[str, dict[str, str]] = {}
        self._name_imports: dict[str, dict[str, tuple[str, str]]] = {}
        for path in tree.paths():
            self._index_module(path)
        self.edges: dict[str, set[str]] = {}
        for nid in self.nodes:
            self.edges[nid] = self._edges_of(nid)

    # ---------------------------------------------------------- indexing

    def _index_module(self, path: str) -> None:
        mod = self.tree.tree(path)
        self._mod_funcs[path] = {}
        self._mod_alias[path] = {}
        self._name_imports[path] = {}
        for stmt in mod.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nid = node_id(path, None, stmt.name)
                self.nodes[nid] = FuncNode(nid, path, None, stmt.name, stmt)
                self._mod_funcs[path][stmt.name] = nid
            elif isinstance(stmt, ast.ClassDef):
                table: dict[str, str] = {}
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        nid = node_id(path, stmt.name, sub.name)
                        self.nodes[nid] = FuncNode(nid, path, stmt.name,
                                                   sub.name, sub)
                        table[sub.name] = nid
                self._methods[(path, stmt.name)] = table
        # imports (module level only — local imports inside functions are
        # also walked so `from arks_tpu.x import f` in a function resolves)
        for stmt in ast.walk(mod):
            if isinstance(stmt, ast.Import):
                for a in stmt.names:
                    target = self.tree.module_path(a.name)
                    if target:
                        alias = a.asname or a.name.split(".")[0]
                        if a.asname or "." not in a.name:
                            self._mod_alias[path][alias] = target
            elif isinstance(stmt, ast.ImportFrom) and stmt.module:
                for a in stmt.names:
                    sub = self.tree.module_path(f"{stmt.module}.{a.name}")
                    if sub:
                        self._mod_alias[path][a.asname or a.name] = sub
                        continue
                    target = self.tree.module_path(stmt.module)
                    if target:
                        self._name_imports[path][a.asname or a.name] = (
                            target, a.name)

    # ------------------------------------------------------------- edges

    def _edges_of(self, nid: str) -> set[str]:
        fn = self.nodes[nid]
        path = fn.path
        out: set[str] = set()
        methods = self._methods.get((path, fn.cls), {}) if fn.cls else {}
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Attribute):
                v = node.value
                # self.X — call or callback reference
                if isinstance(v, ast.Name) and v.id == "self" \
                        and node.attr in methods:
                    out.add(methods[node.attr])
                # alias.X — imported arks_tpu module
                elif isinstance(v, ast.Name) \
                        and v.id in self._mod_alias[path]:
                    target = self._mod_alias[path][v.id]
                    tfuncs = self._mod_funcs.get(target, {})
                    if node.attr in tfuncs:
                        out.add(tfuncs[node.attr])
            elif isinstance(node, ast.Name):
                if node.id in self._mod_funcs[path] \
                        and node.id != fn.name:
                    out.add(self._mod_funcs[path][node.id])
                elif node.id in self._name_imports[path]:
                    target, name = self._name_imports[path][node.id]
                    tfuncs = self._mod_funcs.get(target, {})
                    if name in tfuncs:
                        out.add(tfuncs[name])
        out.discard(nid)
        return out

    # ------------------------------------------------------ reachability

    def find(self, path: str, cls: str | None, name: str) -> str | None:
        nid = node_id(path, cls, name)
        return nid if nid in self.nodes else None

    def reachable(self, roots, stop=None) -> set[str]:
        """Transitive closure from ``roots`` (node ids), never expanding
        THROUGH a node for which ``stop(FuncNode)`` is true — boundary
        nodes are excluded from the result entirely (they are sanctioned
        surfaces with their own contract, e.g. ``_resolve_*`` sync
        tails)."""
        seen: set[str] = set()
        stack = [r for r in roots if r in self.nodes]
        while stack:
            nid = stack.pop()
            if nid in seen:
                continue
            fn = self.nodes[nid]
            if stop is not None and stop(fn) and nid not in roots:
                continue
            seen.add(nid)
            stack.extend(self.edges.get(nid, ()))
        return seen
