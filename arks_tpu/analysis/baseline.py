"""Reviewed-suppression baseline for arkslint.

A suppression is a reviewed decision, not an escape hatch: every entry
carries a one-line ``reason`` and matches findings by the same
line-number-independent key findings use (rule, path, qualname, detail)
— so it survives unrelated edits but goes STALE (an error, like the old
guard tests' ``test_allowed_entries_still_exist``) the moment the code
it justified moves or is fixed.  The file is capped at
``MAX_SUPPRESSIONS`` entries; past that, fix the code instead.
"""

from __future__ import annotations

import json
import pathlib

from arks_tpu.analysis import Finding

DEFAULT_PATH = "tools/arkslint-baseline.json"
MAX_SUPPRESSIONS = 20


class Baseline:
    def __init__(self, entries: list[dict], path: str | None = None):
        self.entries = entries
        self.path = path
        for e in entries:
            missing = {"rule", "path", "qualname", "detail", "reason"} \
                - set(e)
            if missing:
                raise ValueError(
                    f"baseline entry {e!r} missing fields: {sorted(missing)}")

    @classmethod
    def load(cls, path: str | pathlib.Path) -> "Baseline":
        p = pathlib.Path(path)
        if not p.exists():
            return cls([], str(path))
        data = json.loads(p.read_text())
        return cls(data.get("suppressions", []), str(path))

    def _keys(self) -> dict[tuple, dict]:
        return {(e["rule"], e["path"], e["qualname"], e["detail"]): e
                for e in self.entries}

    def apply(self, findings: list[Finding]):
        """Split findings into (active, suppressed) and return the list
        of stale entries that matched nothing."""
        keys = self._keys()
        active: list[Finding] = []
        suppressed: list[Finding] = []
        used: set[tuple] = set()
        for f in findings:
            k = f.key()
            if k in keys:
                suppressed.append(f)
                used.add(k)
            else:
                active.append(f)
        stale = [e for k, e in keys.items() if k not in used]
        return active, suppressed, stale

    def save(self) -> None:
        assert self.path is not None
        body = json.dumps({"version": 1, "suppressions": self.entries},
                          indent=2, sort_keys=False)
        pathlib.Path(self.path).write_text(body + "\n")

    @classmethod
    def from_findings(cls, findings: list[Finding],
                      path: str) -> "Baseline":
        entries = []
        seen: set[tuple] = set()
        for f in findings:
            if f.severity != "error" or f.key() in seen:
                continue
            seen.add(f.key())
            entries.append({
                "rule": f.rule, "path": f.path, "qualname": f.qualname,
                "detail": f.detail or f.check,
                "reason": "TODO: one-line justification (review before "
                          "committing)",
            })
        return cls(entries, path)
