"""arkslint CLI.

    python -m arks_tpu.analysis --all                 # every rule
    python -m arks_tpu.analysis --rules hotpath,knobs
    python -m arks_tpu.analysis --all --json          # machine output
    python -m arks_tpu.analysis --all --write-baseline  # seed suppressions
    python -m arks_tpu.analysis --gen-knob-docs       # docs/configuration.md

Exit codes: 0 clean (no unsuppressed errors, no stale suppressions),
1 findings, 2 usage error.  Warnings never affect the exit code unless
``--strict-warn``.  Pure AST — no JAX, no imports of the code under
analysis — so it is safe (and fast) as a pre-commit hook; see
``tools/arkslint``.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

from arks_tpu.analysis import SourceTree, repo_root, run_rules
from arks_tpu.analysis.baseline import (
    DEFAULT_PATH, MAX_SUPPRESSIONS, Baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m arks_tpu.analysis",
        description="arkslint: call-graph-aware static analysis over the "
                    "arks_tpu tree")
    ap.add_argument("--all", action="store_true",
                    help="run every rule (default when --rules is absent)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset "
                         "(hotpath,exceptions,knobs,tracepurity,metrics)")
    ap.add_argument("--root", default=None,
                    help="repo root holding arks_tpu/ (default: "
                         "auto-detected from this install)")
    ap.add_argument("--baseline", default=None,
                    help=f"suppression file (default {DEFAULT_PATH} under "
                         "the root; 'none' disables)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current error findings as the baseline "
                         "(review and fill in reasons before committing)")
    ap.add_argument("--strict-warn", action="store_true",
                    help="warnings also fail the run")
    ap.add_argument("--gen-knob-docs", action="store_true",
                    help="regenerate docs/configuration.md from the knob "
                         "registry and exit")
    args = ap.parse_args(argv)

    root = pathlib.Path(args.root) if args.root else repo_root()

    if args.gen_knob_docs:
        from arks_tpu.utils import knobs
        out = root / "docs" / "configuration.md"
        out.write_text(knobs.render_markdown())
        print(f"wrote {out}")
        return 0

    rule_names = None
    if args.rules:
        rule_names = [r.strip() for r in args.rules.split(",") if r.strip()]
    t0 = time.monotonic()
    try:
        tree = SourceTree.load(root)
        findings = run_rules(tree, rule_names)
    except KeyError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    baseline_path = None if args.baseline == "none" else (
        pathlib.Path(args.baseline) if args.baseline
        else root / DEFAULT_PATH)

    if args.write_baseline:
        if baseline_path is None:
            print("error: --write-baseline with --baseline none",
                  file=sys.stderr)
            return 2
        bl = Baseline.from_findings(findings, str(baseline_path))
        bl.save()
        print(f"wrote {len(bl.entries)} suppressions to {baseline_path}")
        return 0

    baseline = Baseline.load(baseline_path) if baseline_path else \
        Baseline([], None)
    # a rule-subset run can only vouch for its own rules' entries —
    # entries for unselected rules are out of scope, not stale
    if rule_names is not None:
        baseline.entries = [e for e in baseline.entries
                            if e["rule"] in rule_names]
    active, suppressed, stale = baseline.apply(findings)
    errors = [f for f in active if f.severity == "error"]
    warns = [f for f in active if f.severity == "warn"]
    elapsed = time.monotonic() - t0

    over_budget = len(baseline.entries) > MAX_SUPPRESSIONS

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in active],
            "suppressed": [f.to_dict() for f in suppressed],
            "stale_suppressions": stale,
            "counts": {"errors": len(errors), "warnings": len(warns),
                       "suppressed": len(suppressed),
                       "stale": len(stale),
                       "baseline_entries": len(baseline.entries)},
            "elapsed_s": round(elapsed, 3),
        }, indent=2))
    else:
        for f in active:
            print(f.render())
        for e in stale:
            print(f"{e['path']}: error[baseline/stale] {e['qualname']}: "
                  f"suppression matches nothing — the justified code "
                  f"moved or was fixed; delete the entry [{e['detail']}]")
        if over_budget:
            print(f"error[baseline/budget]: {len(baseline.entries)} "
                  f"suppressions > cap of {MAX_SUPPRESSIONS} — fix code "
                  "instead of suppressing")
        print(f"arkslint: {len(errors)} error(s), {len(warns)} "
              f"warning(s), {len(suppressed)} suppressed, "
              f"{len(stale)} stale suppression(s) "
              f"[{elapsed*1000:.0f} ms]")

    failed = bool(errors) or bool(stale) or over_budget \
        or (args.strict_warn and warns)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
