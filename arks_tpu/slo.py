"""SLO tiers: named latency classes mapped onto the engine priority scale.

The engine's admission queue has always ordered by the integer
``SamplingParams.priority`` (lower = sooner); since the preemptive-swap
work that integer is real QoS — a queued lower value can seize a running
slot.  This module gives the integers NAMES and TARGETS so the gateway,
the router, the OpenAI front-end and the metrics pipeline all speak the
same tier vocabulary:

- ``ARKS_SLO_TIERS`` declares the ladder, best tier first, e.g.::

      latency:ttft_ms=300;tpot_ms=50,interactive:ttft_ms=1500,batch:

  Each comma-separated entry is ``name[:key=val[;key=val...]]``.  Tier
  index == engine priority (``latency`` above is priority 0, ``batch``
  priority 2).  Known target keys: ``ttft_ms``, ``tpot_ms`` — surfaced
  for dashboards/alerting (docs/monitoring.md); unknown keys are
  rejected so a typo'd SLO does not silently vanish.
- The gateway accepts an ``x-arks-tier`` header, validates it against
  the ladder (unknown tier -> 400) and forwards it; the OpenAI server
  maps it to ``params.priority`` (header wins over a body ``priority``).
- ``tier_of(priority)`` is the metric label everywhere
  (``ttft_seconds{tier=...}`` etc.); priorities past the end of the
  ladder clamp to the last (worst) tier, and with no ladder configured
  every request labels as ``"default"``.

With ``ARKS_SLO_TIERS`` unset nothing changes: no tiers exist, tier
headers are rejected, and body priorities pass through untouched.
"""

from __future__ import annotations

import dataclasses

from arks_tpu.utils import knobs

ENV_VAR = "ARKS_SLO_TIERS"
DEFAULT_TIER = "default"

_TARGET_KEYS = ("ttft_ms", "tpot_ms")


@dataclasses.dataclass(frozen=True)
class Tier:
    """One rung of the SLO ladder: a name, its engine priority (= ladder
    index), and optional latency targets in milliseconds."""
    name: str
    priority: int
    ttft_ms: float | None = None
    tpot_ms: float | None = None


class SloTiers:
    """An ordered tier ladder (best first).  Empty = tiers disabled."""

    def __init__(self, tiers: tuple[Tier, ...] = ()) -> None:
        self.tiers = tiers
        self._by_name = {t.name: t for t in tiers}

    def __bool__(self) -> bool:
        return bool(self.tiers)

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(t.name for t in self.tiers)

    def get(self, name: str) -> Tier | None:
        return self._by_name.get(name)

    def priority_of(self, name: str) -> int | None:
        """Engine priority for a tier name (None = unknown tier)."""
        t = self._by_name.get(name)
        return None if t is None else t.priority

    def tier_of(self, priority: int) -> str:
        """Metric label for an engine priority.  Priorities are clamped
        into the ladder (replayers run at priority - 2**20; overly-batch
        requests clamp to the worst tier); no ladder -> "default"."""
        if not self.tiers:
            return DEFAULT_TIER
        idx = min(max(int(priority), 0), len(self.tiers) - 1)
        return self.tiers[idx].name


def parse_tiers(spec: str) -> SloTiers:
    """Parse an ``ARKS_SLO_TIERS`` value.  Raises ValueError on malformed
    entries, duplicate names, or unknown target keys."""
    tiers: list[Tier] = []
    seen: set[str] = set()
    for i, entry in enumerate(s for s in spec.split(",") if s.strip()):
        name, _, rest = entry.strip().partition(":")
        name = name.strip()
        if not name or not name.replace("-", "").replace("_", "").isalnum():
            raise ValueError(f"{ENV_VAR}: bad tier name in entry {entry!r}")
        if name in seen:
            raise ValueError(f"{ENV_VAR}: duplicate tier {name!r}")
        seen.add(name)
        targets: dict[str, float] = {}
        for kv in (s for s in rest.split(";") if s.strip()):
            key, sep, val = kv.partition("=")
            key = key.strip()
            if not sep or key not in _TARGET_KEYS:
                raise ValueError(
                    f"{ENV_VAR}: unknown target {kv!r} in tier {name!r} "
                    f"(known: {', '.join(_TARGET_KEYS)})")
            try:
                targets[key] = float(val)
            except ValueError:
                raise ValueError(
                    f"{ENV_VAR}: non-numeric target {kv!r} in tier "
                    f"{name!r}") from None
            if targets[key] <= 0:
                raise ValueError(
                    f"{ENV_VAR}: target {kv!r} in tier {name!r} must be "
                    "positive")
        tiers.append(Tier(name=name, priority=i, **targets))
    return SloTiers(tuple(tiers))


def from_env() -> SloTiers:
    """The process-wide ladder from ``ARKS_SLO_TIERS`` (empty when
    unset)."""
    spec = knobs.get_str(ENV_VAR, fallback="") or ""
    return parse_tiers(spec) if spec.strip() else SloTiers()
