"""Router entrypoint: python -m arks_tpu.router --port ... --discovery-file ...

The reference router command line is generated at
/root/reference/internal/controller/
arksdisaggregatedapplication_controller.go:1630-1670; this is its
TPU-native stand-in (no jax import — the router is pure I/O).
"""

from __future__ import annotations

import argparse
import logging


def main() -> None:
    p = argparse.ArgumentParser("arks_tpu.router")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--served-model-name", default="")
    p.add_argument("--discovery-file", default=None,
                   help="JSON {prefill: [addr], decode: [addr]}; falls back "
                        "to ARKS_PREFILL_ADDRS/ARKS_DECODE_ADDRS env")
    p.add_argument("--policy", default="cache_aware",
                   choices=("round_robin", "cache_aware"),
                   help="cache_aware pins shared prompt prefixes to one "
                        "backend so engine prefix caches hit (reference "
                        "router default)")
    args = p.parse_args()

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    from arks_tpu.router import Discovery, Router

    router = Router(Discovery(args.discovery_file), args.served_model_name,
                    host=args.host, port=args.port, policy=args.policy)
    router.start(background=False)


if __name__ == "__main__":
    main()
