"""Router entrypoint: python -m arks_tpu.router --port ... --discovery-file ...

The reference router command line is generated at
/root/reference/internal/controller/
arksdisaggregatedapplication_controller.go:1630-1670; this is its
TPU-native stand-in (no jax import — the router is pure I/O).
"""

from __future__ import annotations

import argparse
import logging


def main() -> None:
    p = argparse.ArgumentParser("arks_tpu.router")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--served-model-name", default="")
    p.add_argument("--discovery-file", default=None,
                   help="JSON {prefill: [addr], decode: [addr]}; falls back "
                        "to ARKS_PREFILL_ADDRS/ARKS_DECODE_ADDRS env")
    p.add_argument("--service-discovery", action="store_true",
                   help="discover prefill/decode pods from the Kubernetes "
                        "API by label selector (the reference router's "
                        "--service-discovery mode) instead of a file")
    p.add_argument("--namespace", default=None,
                   help="pod namespace for --service-discovery (default: "
                        "the pod's own namespace)")
    p.add_argument("--application", default=None,
                   help="arks.ai/application label value to select")
    p.add_argument("--backend-port", type=int, default=8080,
                   help="fallback port when a pod declares no containerPort")
    p.add_argument("--discovery-interval", type=float, default=2.0)
    p.add_argument("--kube-api", default=None,
                   help="apiserver base URL (default: in-cluster config)")
    p.add_argument("--policy", default="cache_aware",
                   choices=("round_robin", "cache_aware"),
                   help="cache_aware scores backends by expected prefix "
                        "hit depth against their exported cache sketches "
                        "(ARKS_ROUTER_SKETCH_* knobs; ARKS_ROUTER_SKETCH=0 "
                        "falls back to rendezvous-only), pinning shared "
                        "prompt prefixes to the backend that actually "
                        "holds them (reference router default)")
    p.add_argument("--unified", action="store_true",
                   help="backends are plain OpenAI servers (no prefill/"
                        "decode split): route over the decode list only "
                        "and forward to the ordinary completion paths "
                        "(also ARKS_ROUTER_UNIFIED=1)")
    args = p.parse_args()

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    from arks_tpu.router import Discovery, KubeDiscovery, Router

    if args.service_discovery:
        from arks_tpu.control.k8s_client import KubeApi

        api = (KubeApi(args.kube_api) if args.kube_api
               else KubeApi.in_cluster())
        namespace = args.namespace or KubeApi.namespace_in_cluster()
        if not args.application:
            p.error("--service-discovery requires --application")
        discovery = KubeDiscovery(api, namespace, args.application,
                                  backend_port=args.backend_port,
                                  interval_s=args.discovery_interval)
    else:
        discovery = Discovery(args.discovery_file)

    router = Router(discovery, args.served_model_name,
                    host=args.host, port=args.port, policy=args.policy,
                    unified=args.unified)
    router.start(background=False)


if __name__ == "__main__":
    main()
