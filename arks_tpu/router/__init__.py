"""Disaggregated-serving router.

The reference deploys ``sglang_router.launch_router --pd-disaggregation
--service-discovery --prefill-selector ... --decode-selector ...``
(/root/reference/internal/controller/
arksdisaggregatedapplication_controller.go:1630-1670).  This is the native
equivalent: an OpenAI-surface HTTP server that, per request, picks one
prefill and one decode backend and forwards the request to the decode server
with the chosen prefill address in the ``X-Arks-Prefill-Addr`` header; the
decode server pulls the KV directly from the prefill server (one KV hop —
the router never carries KV bytes).

Service discovery: a JSON file ``{"prefill": ["host:port"...],
"decode": [...]}`` re-read on mtime change.  Locally the controller
maintains the file; on k8s it is a projected ConfigMap the controller
updates — the moral equivalent of the reference router's label-selector
pod discovery.

Routing policies (the reference router's ``--policy`` flag, default
``cache_aware`` in its generated command line):

- ``round_robin``: rotate over ready backends.
- ``cache_aware``: prefer the backend whose prefix caches ACTUALLY hold
  the request's prefix.  Decode backends export a prefix-digest sketch
  (``GET /v1/cache/sketch`` — a versioned bloom/top-K summary of the
  chain digests resident in tier 0 and tier 1, see
  arks_tpu.prefix_sketch); an async poller keeps a per-backend copy, and
  ``_pick`` scores candidates by *expected hit depth*: walk the
  request's digest chain against each sketch — tokenize-free, in the
  token domain for pre-tokenized prompts and the text domain otherwise —
  and take the deepest hit, tier-0 weighted.  Fallback ladder when
  sketches are stale/absent or scores tie: least-loaded, then
  rendezvous-hashing the prompt *prefix* (which also keeps remapping
  minimal when backends come and go — only the moved backend's keys
  reshuffle).  ``ARKS_ROUTER_SKETCH=0`` turns scoring off entirely
  (rendezvous-only, the pre-sketch behavior).
"""

from __future__ import annotations

import hashlib
import http.client
import itertools
import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from arks_tpu import prefix_sketch as sketch_mod
from arks_tpu import tenancy
from arks_tpu.gateway.metrics import RouterMetrics
from arks_tpu.obs import logctx
from arks_tpu.obs import trace as trace_mod
from arks_tpu.utils import knobs
from arks_tpu.utils.swallow import swallowed

log = logging.getLogger("arks_tpu.router")
logctx.install(log)

# Trace propagation rides the same switch the engine tracer uses; the
# router keeps no span store of its own — its completed spans travel in
# the x-arks-trace-spans header and assemble engine-side.
_TRACE_ON = knobs.get_bool("ARKS_TRACE")

HDR_PREFILL_ADDR = "X-Arks-Prefill-Addr"
HDR_TIER = "x-arks-tier"   # SLO tier (arks_tpu.slo), forwarded verbatim
# Fleet prefix cache: the decode backend the router's sketches say holds
# the request's warm prefix DEEPEST.  Forwarded whenever it differs from
# the backend actually chosen (load/ties/failover can route elsewhere) —
# the engine's peer fetch (ARKS_PEER_FETCH) then pulls the blocks from
# this peer instead of re-prefilling.
HDR_PEER_HINT = "X-Arks-Peer-Hint"


class Discovery:
    """mtime-cached backend lists from a discovery file (+ env fallback).

    A programmatic overlay (``add``/``remove``) sits ON TOP of the file/
    env lists: planned membership changes (Router.plan_join / plan_leave,
    the elastic scale-up handoff) take effect immediately and survive file
    reloads — the controller's discovery file catching up later is a
    no-op, not a flap.  ``remove`` also MASKS a file-listed backend, so a
    planned leave can run ahead of the file update."""

    def __init__(self, path: str | None):
        self.path = path
        self._mtime = 0.0
        self._lock = threading.Lock()
        self._prefill: list[str] = _env_addrs("ARKS_PREFILL_ADDRS")
        self._decode: list[str] = _env_addrs("ARKS_DECODE_ADDRS")
        self._extra: dict[str, list[str]] = {"prefill": [], "decode": []}
        self._masked: dict[str, set[str]] = {"prefill": set(),
                                             "decode": set()}

    def add(self, role: str, addr: str) -> None:
        """Admit ``addr`` to ``role`` ahead of the discovery file."""
        if role not in ("prefill", "decode"):
            raise ValueError(f"unknown backend role {role!r}")
        with self._lock:
            self._masked[role].discard(addr)
            if addr not in self._extra[role]:
                self._extra[role].append(addr)

    def remove(self, role: str, addr: str) -> None:
        """Withdraw ``addr`` from ``role`` (and mask it if file-listed)."""
        if role not in ("prefill", "decode"):
            raise ValueError(f"unknown backend role {role!r}")
        with self._lock:
            if addr in self._extra[role]:
                self._extra[role].remove(addr)
            self._masked[role].add(addr)

    def backends(self) -> tuple[list[str], list[str]]:
        if self.path and os.path.exists(self.path):
            try:
                mtime = os.path.getmtime(self.path)
                with self._lock:
                    if mtime != self._mtime:
                        with open(self.path) as f:
                            data = json.load(f)
                        self._prefill = list(data.get("prefill", []))
                        self._decode = list(data.get("decode", []))
                        self._mtime = mtime
            except (OSError, ValueError, json.JSONDecodeError):
                log.warning("bad discovery file %s", self.path, exc_info=True)
        with self._lock:
            out = []
            for role, base in (("prefill", self._prefill),
                               ("decode", self._decode)):
                merged = [a for a in base if a not in self._masked[role]]
                merged += [a for a in self._extra[role] if a not in merged]
                out.append(merged)
            return out[0], out[1]


def _env_addrs(name: str) -> list[str]:
    return knobs.get_list(name)


class KubeDiscovery:
    """Label-selector pod discovery against the Kubernetes API — the native
    counterpart of the reference router's ``--service-discovery
    --prefill-selector/--decode-selector`` mode
    (/root/reference/internal/controller/
    arksdisaggregatedapplication_controller.go:1630-1670).

    Lists pods labeled ``arks.ai/application=<app>`` with
    ``arks.ai/component`` prefill/decode, keeps READY ones (worker
    processes of a gang return 503 on /readiness, so only leaders are
    Ready — exactly the addresses that serve), and addresses them as
    ``podIP:containerPort`` (the port named ``http`` — k8s_export's serving
    port name — else a single unambiguous declared port; falls back to
    ``backend_port``).  Results are cached for ``interval_s`` — the same
    poll cadence the live operator uses; env fallback
    (ARKS_PREFILL_ADDRS/ARKS_DECODE_ADDRS) covers bootstrap windows."""

    def __init__(self, api, namespace: str, application: str,
                 backend_port: int = 8080, interval_s: float = 2.0):
        self.api = api
        self.namespace = namespace
        self.application = application
        self.backend_port = backend_port
        self.interval = interval_s
        self._lock = threading.Lock()
        self._at = 0.0
        self._prefill: list[str] = _env_addrs("ARKS_PREFILL_ADDRS")
        self._decode: list[str] = _env_addrs("ARKS_DECODE_ADDRS")

    @staticmethod
    def _ready(pod: dict) -> bool:
        if pod.get("status", {}).get("phase") != "Running":
            return False
        for c in pod.get("status", {}).get("conditions", []):
            if c.get("type") == "Ready":
                return c.get("status") == "True"
        return False

    def _addr(self, pod: dict) -> str | None:
        ip = pod.get("status", {}).get("podIP")
        if not ip:
            return None
        # Prefer the port NAMED "http" (the name k8s_export assigns to the
        # serving port): a pod whose first declared port is a metrics port,
        # or with a sidecar ordered first, must not silently hijack routing.
        # A single unnamed declared port is unambiguous and honored; any
        # other ambiguity falls back to backend_port.
        declared = [p for c in pod.get("spec", {}).get("containers", [])
                    for p in (c.get("ports") or []) if p.get("containerPort")]
        for p in declared:
            if p.get("name") == "http":
                return f"{ip}:{p['containerPort']}"
        if len(declared) == 1 and not declared[0].get("name"):
            # Unnamed single port: unambiguous.  A single NAMED non-http
            # port (e.g. only a metrics port declared) is not a serving
            # port — fall through to backend_port.
            return f"{ip}:{declared[0]['containerPort']}"
        return f"{ip}:{self.backend_port}"

    def _refresh(self) -> None:
        roles: dict[str, list[str]] = {"prefill": [], "decode": []}
        for pod in self.api.list("v1", "pods", self.namespace):
            labels = pod.get("metadata", {}).get("labels", {})
            if labels.get("arks.ai/application") != self.application:
                continue
            role = labels.get("arks.ai/component")
            if role not in roles or not self._ready(pod):
                continue
            addr = self._addr(pod)
            if addr:
                roles[role].append(addr)
        # Keep env fallback while a tier has no discovered pods yet.
        # (Swap under the lock: backends() reads these concurrently.)
        with self._lock:
            if roles["prefill"]:
                self._prefill = sorted(roles["prefill"])
            if roles["decode"]:
                self._decode = sorted(roles["decode"])

    def backends(self) -> tuple[list[str], list[str]]:
        # The API list happens OUTSIDE the lock and only one thread does it
        # (the _at timestamp claims the refresh): a slow apiserver degrades
        # to a stale backend set, never to every request blocking on the
        # discovery lock.
        now = time.monotonic()
        refresh = False
        with self._lock:
            if now - self._at >= self.interval:
                self._at = now  # claim (and back off a full interval on error)
                refresh = True
        if refresh:
            try:
                self._refresh()
            except Exception:
                log.warning("pod discovery failed; keeping last set",
                            exc_info=True)
        with self._lock:
            return list(self._prefill), list(self._decode)


# Prompt-prefix window the cache_aware policy keys on.  Long enough to
# separate distinct system prompts, short enough that divergent tails (the
# user turn) don't defeat the affinity.
_PREFIX_KEY_CHARS = 512


def _prefix_key(body: bytes) -> bytes | None:
    """Locality key: the first _PREFIX_KEY_CHARS of the prompt text."""
    try:
        obj = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return None
    return _prefix_key_obj(obj)


def _prefix_key_obj(obj) -> bytes | None:
    """Locality key from a parsed body.  Text extraction (content-part
    joining, stop-at-unknown-shape so later turns never leak into the
    key) lives in prefix_sketch.canonical_prompt_text — the SAME scan the
    sketch's text-domain digests use, so the rendezvous key and the
    scoring chain always agree on what "the prompt text" is.  Prompts
    with no usable text get no key (round-robin — never pin them all to
    one backend via a shared empty key), EXCEPT pre-tokenized token-id
    prompts, which key on their leading id window."""
    if not isinstance(obj, dict):
        return None
    text = sketch_mod.canonical_prompt_text(obj)
    if text:
        return text[:_PREFIX_KEY_CHARS].encode("utf-8", "surrogatepass")
    ids = _token_prompt(obj)
    if ids:
        return json.dumps(ids[:64]).encode()
    return None


def _token_prompt(obj) -> list | None:
    """The request's pre-tokenized prompt ids, or None.  These score in
    the token domain — the engine's exact chain digests — with no
    tokenizer anywhere near the router."""
    p = obj.get("prompt") if isinstance(obj, dict) else None
    if (isinstance(p, list) and p
            and all(isinstance(t, int) and not isinstance(t, bool)
                    for t in p)):
        return p
    return None


def _rendezvous(key: bytes, backends: list[str]) -> str:
    """Highest-random-weight choice: stable per key, minimal remap on
    backend churn."""
    return max(backends,
               key=lambda b: hashlib.sha1(key + b"\x00" + b.encode()).digest())


class _SketchPoller:
    """Per-backend prefix-digest sketch cache, refreshed by one
    background thread off the request path (requests only ever read the
    last accepted copy — a slow backend degrades to a stale sketch and
    the fallback ladder, never to requests blocking on a poll).

    Epoch discipline: a backend that restarts or fault-resets comes back
    with a new epoch; the poller replaces its copy wholesale on every
    successful fetch (counting epoch changes), and the forward path's
    connection errors invalidate eagerly — a dead backend's pre-restart
    sketch must not keep winning placement until the poll interval
    catches up."""

    def __init__(self, router: "Router", interval_s: float, stale_s: float):
        self.router = router
        self.interval = interval_s
        self.stale = stale_s
        self._lock = threading.Lock()
        self._state: dict[str, dict] = {}   # addr -> {"sketch", "at"}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="router-sketch", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception:
                log.warning("sketch poll failed", exc_info=True)

    def poll_once(self) -> None:
        """One refresh round over the current decode set (also the test/
        bench entry point — deterministic, no thread required)."""
        _, decode = self.router.discovery.backends()
        m = self.router.metrics
        now = time.monotonic()
        for addr in decode:
            payload = self._fetch(addr)
            if payload is None:
                # Unreachable or malformed: keep the last accepted copy
                # until the staleness deadline retires it in get().
                continue
            bs = sketch_mod.BackendSketch.from_payload(payload)
            with self._lock:
                prev = self._state.get(addr)
                if not bs.enabled:
                    self._state[addr] = {"sketch": None, "at": now}
                    continue
                if (prev is not None and prev["sketch"] is not None
                        and prev["sketch"].epoch != bs.epoch):
                    # Backend restarted/reset between polls: the old
                    # sketch described a cache that no longer exists.
                    m.sketch_epoch_drops_total.inc(backend=addr)
                self._state[addr] = {"sketch": bs, "at": now}
            for tier, v in bs.hit_tokens.items():
                m.backend_hit_tokens.set(v, backend=addr, tier=tier)
        with self._lock:
            for addr in list(self._state):
                if addr not in decode:
                    del self._state[addr]
            ages = {a: max(0.0, now - st["at"])
                    for a, st in self._state.items()}
        for addr, age in ages.items():
            m.sketch_age.set(age, backend=addr)

    def _fetch(self, addr: str) -> dict | None:
        host, _, port = addr.partition(":")
        try:
            conn = http.client.HTTPConnection(host, int(port or 80),
                                              timeout=2.0)
            try:
                conn.request("GET", "/v1/cache/sketch")
                resp = conn.getresponse()
                data = resp.read()
                if resp.status != 200:
                    return None
                obj = json.loads(data)
                return obj if isinstance(obj, dict) else None
            finally:
                conn.close()
        except (OSError, http.client.HTTPException, ValueError):
            return None

    def get(self, addr: str) -> "sketch_mod.BackendSketch | None":
        """The backend's sketch if fresh; None when absent, disabled, or
        past the ARKS_ROUTER_SKETCH_STALE_S deadline."""
        with self._lock:
            st = self._state.get(addr)
            if st is None or st["sketch"] is None:
                return None
            if time.monotonic() - st["at"] > self.stale:
                return None
            return st["sketch"]

    def invalidate(self, addr: str) -> None:
        with self._lock:
            self._state.pop(addr, None)

    def prime(self, addr: str) -> bool:
        """Seed a joining backend's sketch BEFORE it enters routing (the
        planned-membership handoff).  A prime is the backend's first
        observation, so it NEVER counts as an epoch drop — the drop
        counter stays reserved for restarts/resizes of an already-known
        backend.  Returns True when a sketch (enabled or not) was
        fetched and stored."""
        payload = self._fetch(addr)
        if payload is None:
            return False
        bs = sketch_mod.BackendSketch.from_payload(payload)
        with self._lock:
            self._state[addr] = {
                "sketch": bs if bs.enabled else None,
                "at": time.monotonic()}
        return True


class Router:
    def __init__(self, discovery: Discovery, served_model_name: str,
                 host: str = "0.0.0.0", port: int = 8080,
                 policy: str = "cache_aware", unified: bool = False):
        if policy not in ("round_robin", "cache_aware"):
            raise ValueError(f"unknown policy {policy!r}")
        self.discovery = discovery
        self.served_model_name = served_model_name
        self.host, self.port = host, port
        self.policy = policy
        # Unified mode: backends are plain OpenAI servers (no prefill/
        # decode split) — only the decode list is consulted, and requests
        # forward to the ordinary path with no prefill header.
        self.unified = unified or knobs.get_bool("ARKS_ROUTER_UNIFIED")
        self._rr = itertools.count()
        self._httpd: ThreadingHTTPServer | None = None
        self.metrics = RouterMetrics()
        self.registry = self.metrics.registry
        self.requests_total = self.metrics.requests_total
        self.backends_gauge = self.metrics.backends
        self.retries_total = self.metrics.retries_total
        # Sketch scoring (cache_aware only; ARKS_ROUTER_SKETCH=0 restores
        # the rendezvous-only behavior).
        self.sketch_on = (policy == "cache_aware"
                          and knobs.get_bool("ARKS_ROUTER_SKETCH"))
        self._t0_weight = knobs.get_float("ARKS_ROUTER_SKETCH_T0_WEIGHT")
        self._disk_weight = knobs.get_float("ARKS_ROUTER_SKETCH_DISK_WEIGHT")
        self._max_blocks = knobs.get_int("ARKS_ROUTER_SKETCH_MAX_BLOCKS")
        poll_s = knobs.get_float("ARKS_ROUTER_SKETCH_POLL_S")
        stale_s = knobs.get_float("ARKS_ROUTER_SKETCH_STALE_S")
        self.sketches = _SketchPoller(self, poll_s, stale_s)
        # In-flight forwards per decode backend (least-loaded fallback).
        self._load_lock = threading.Lock()
        self._inflight: dict[str, int] = {}

    # ------------------------------------------------------------------

    def start(self, background: bool = True) -> None:
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _json(self, code: int, payload: dict) -> None:
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _error(self, code: int, message: str) -> None:
                self._json(code, {"error": {"message": message, "code": code}})

            def do_GET(self):
                if self.path == "/v1/models":
                    self._json(200, {"object": "list", "data": [
                        {"id": router.served_model_name, "object": "model",
                         "created": int(time.time()), "owned_by": "arks-tpu"}]})
                elif self.path == "/metrics":
                    text = router.registry.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(text)))
                    self.end_headers()
                    self.wfile.write(text)
                elif self.path in ("/healthz", "/health"):
                    self._json(200, {"status": "ok"})
                elif self.path == "/readiness":
                    pre, dec = router.discovery.backends()
                    if dec and (pre or router.unified):
                        self._json(200, {"status": "ready"})
                    else:
                        self._error(503, "no prefill/decode backends yet")
                else:
                    self._error(404, f"no route {self.path}")

            def do_POST(self):
                if self.path not in ("/v1/chat/completions", "/v1/completions"):
                    return self._error(404, f"no route {self.path}")
                router._route(self)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_port
        if self.sketch_on:
            self.sketches.start()
        if background:
            threading.Thread(target=self._httpd.serve_forever, name="router",
                             daemon=True).start()
        else:
            self._httpd.serve_forever()

    def stop(self) -> None:
        self.sketches.stop()
        if self._httpd:
            self._httpd.shutdown()

    # ---- planned membership (elastic scale-up/down handoff) ----------

    def plan_join(self, addr: str, role: str = "decode",
                  timeout_s: float | None = None) -> dict:
        """Admit a (re-)armed backend through a PLANNED handoff: gate on
        its /readiness (a scaled-to-zero replica 503s until re-armed and
        warm-up has been issued), prime its sketch drop-free, and only
        then add it to routing — the joining replica never sees traffic
        before it can serve, so a mid-workload join produces zero 5xx.
        Returns join stats; raises TimeoutError when the backend never
        went ready within ARKS_ELASTIC_JOIN_TIMEOUT_S."""
        if timeout_s is None:
            timeout_s = knobs.get_float("ARKS_ELASTIC_JOIN_TIMEOUT_S")
        add = getattr(self.discovery, "add", None)
        if add is None:
            raise TypeError(
                f"discovery {type(self.discovery).__name__} does not "
                "support programmatic membership (plan_join needs "
                "Discovery.add)")
        t0 = time.monotonic()
        polls = 0
        deadline = t0 + max(timeout_s, 0.0)
        while True:
            polls += 1
            if self._backend_ready(addr):
                break
            if time.monotonic() >= deadline:
                self.metrics.planned_membership_total.inc(
                    op="join", outcome="timeout")
                raise TimeoutError(
                    f"backend {addr} not ready after {timeout_s:.1f}s "
                    "(ARKS_ELASTIC_JOIN_TIMEOUT_S)")
            time.sleep(min(0.05, max(deadline - time.monotonic(), 0.0)))
        primed = False
        if self.sketch_on and role == "decode":
            primed = self.sketches.prime(addr)
        add(role, addr)
        dt = time.monotonic() - t0
        self.metrics.planned_membership_total.inc(op="join", outcome="ok")
        self.metrics.join_seconds.set(dt, backend=addr)
        log.info("planned join: %s role=%s ready after %d poll(s) in "
                 "%.3fs (sketch primed=%s)", addr, role, polls, dt, primed)
        return {"addr": addr, "role": role, "seconds": dt,
                "ready_polls": polls, "sketch_primed": primed}

    def plan_leave(self, addr: str, role: str = "decode") -> dict:
        """Withdraw a backend from routing (scale-down / maintenance):
        remove it from membership and drop its sketch so placement stops
        crediting a cache that is about to disappear.  In-flight streams
        on the leaving backend finish naturally — the router only stops
        sending NEW work."""
        remove = getattr(self.discovery, "remove", None)
        if remove is None:
            raise TypeError(
                f"discovery {type(self.discovery).__name__} does not "
                "support programmatic membership (plan_leave needs "
                "Discovery.remove)")
        remove(role, addr)
        self.sketches.invalidate(addr)
        self.metrics.planned_membership_total.inc(op="leave", outcome="ok")
        log.info("planned leave: %s role=%s", addr, role)
        return {"addr": addr, "role": role}

    def _backend_ready(self, addr: str) -> bool:
        host, _, port = addr.partition(":")
        try:
            conn = http.client.HTTPConnection(host, int(port or 80),
                                              timeout=2.0)
            try:
                conn.request("GET", "/readiness")
                resp = conn.getresponse()
                resp.read()
                return resp.status == 200
            finally:
                conn.close()
        except (OSError, http.client.HTTPException, ValueError):
            return False

    # ------------------------------------------------------------------

    def _route(self, h) -> None:
        status = 500
        started = [False]  # response headers already sent to the client
        # Always drain the body first: an early error response with the body
        # unread desyncs HTTP/1.1 keep-alive connections.
        body = h.rfile.read(int(h.headers.get("Content-Length", 0)))
        # Continue the gateway-propagated trace (or root one for direct
        # clients); the pick span completes here and travels downstream in
        # the spans header — the engine's store is the assembly point.
        ctx = (trace_mod.TraceCtx.from_headers(h.headers)
               if _TRACE_ON else None)
        try:
            with logctx.bound(trace_id=ctx.trace_id if ctx else None):
                prefill, decode = self.discovery.backends()
                if self.unified:
                    # Unified deployments list their backends under
                    # "decode" (or only set ARKS_DECODE_ADDRS); there is
                    # no prefill tier to pick.
                    prefill = []
                self.backends_gauge.set(len(prefill), role="prefill")
                self.backends_gauge.set(len(decode), role="decode")
                if not decode or (not prefill and not self.unified):
                    status = 503
                    return h._error(503, "no ready prefill/decode backends")
                t0 = time.monotonic()
                hint_out: list = []
                p, candidates = self._pick(body, prefill, decode,
                                           hint_out=hint_out)
                if ctx is not None:
                    ctx.upstream.append({
                        "component": "router", "name": "router.pick",
                        "start": t0, "end": time.monotonic(),
                        "arg": candidates[0]})
                status = self._forward_failover(
                    h, body, p, candidates[0], candidates, started,
                    ctx=ctx, peer_hint=(hint_out[0] if hint_out else None))
        except (BrokenPipeError, ConnectionResetError):
            status = 499
        except Exception as e:
            log.exception("router failure")
            if started[0]:
                # Headers (and possibly chunks) already went out: a second
                # response would corrupt the stream — just drop the
                # connection so the client sees a clean truncation.
                h.close_connection = True
            else:
                try:
                    h._error(500, f"router error: {e}")
                except Exception as e2:
                    # Client hung up before the error response went out.
                    swallowed("router.error-response", e2)
        finally:
            self.requests_total.inc(status=str(status))

    def _pick(self, body: bytes, prefill: list[str],
              decode: list[str], hint_out: list | None = None
              ) -> tuple[str, tuple[str, ...]]:
        """(prefill addr, decode candidates in preference order).  The
        failover path walks the decode tuple in exactly this order, so
        sketch scoring shapes the retry sequence too — while the failover
        semantics themselves (when to move on, backoff, Retry-After) stay
        untouched.  Unified mode returns "" for prefill.  ``hint_out``
        (when given) receives the peer-hint backend: the one whose
        sketch covers the request deepest, for the X-Arks-Peer-Hint
        header when routing lands elsewhere."""
        if self.policy == "cache_aware":
            try:
                obj = json.loads(body)
            except (ValueError, UnicodeDecodeError):
                obj = None
            key = _prefix_key_obj(obj)
            if key is not None:
                p = _rendezvous(key, prefill) if prefill else ""
                return p, tuple(self._order_decode(obj, key, decode,
                                                   hint_out))
            if self.sketch_on:
                self.metrics.route_decisions_total.inc(reason="no_key")
        n = next(self._rr)
        p = prefill[n % len(prefill)] if prefill else ""
        i = n % len(decode)
        return p, tuple(decode[i:] + decode[:i])

    def _order_decode(self, obj, key: bytes, decode: list[str],
                      hint_out: list | None = None) -> list[str]:
        """Decode candidates by expected prefix hit depth, deepest first.

        Scoring walks the request's digest chain against each backend's
        sketch (token domain for pre-tokenized prompts — the engine's
        exact keys — else the text domain fed by the server's alignment
        ledger) and weights tier-0 blocks by 1 + ARKS_ROUTER_SKETCH_T0_
        WEIGHT over tier-1 blocks (a device hit is free; a host hit costs
        one H2D restore); tier-2 (disk) blocks weigh ARKS_ROUTER_SKETCH_
        DISK_WEIGHT — a disk hit costs a file read plus the restore, but
        still beats re-prefill.  Fallback ladder: no fresh sketch
        anywhere -> rendezvous (reason stale_sketch); tied scores,
        including the all-zero case -> least in-flight, then rendezvous
        among the still tied (tie_fallback); a unique deepest hit wins
        (sketch_hit).  ``hint_out`` receives the deepest-covering
        backend regardless of who wins routing — ties and load can send
        the request elsewhere, and the peer hint is how the warm blocks
        still get used (engine-side ARKS_PEER_FETCH)."""
        def rz(b: str) -> bytes:
            return hashlib.sha1(key + b"\x00" + b.encode()).digest()

        if not self.sketch_on:
            return sorted(decode, key=rz, reverse=True)
        m = self.metrics
        ids = _token_prompt(obj)
        text = None if ids is not None else sketch_mod.canonical_prompt_text(
            obj)
        scores: dict[str, tuple[int, int]] = {}
        chains: dict[tuple, list[bytes]] = {}
        saw_sketch = False
        for b in decode:
            bs = self.sketches.get(b)
            if bs is None:
                continue
            saw_sketch = True
            if ids is not None and bs.page_tokens > 0:
                domain, block = "token", bs.page_tokens
                if (domain, block) not in chains:
                    nb = min(len(ids) // block, self._max_blocks)
                    chains[(domain, block)] = sketch_mod.chain_digests(
                        ids, block, nb)
            elif text is not None and bs.text_chars > 0:
                domain, block = "text", bs.text_chars
                if (domain, block) not in chains:
                    digs: list[bytes] = []
                    for d in sketch_mod.iter_text_digests(text, block):
                        digs.append(d)
                        if len(digs) >= self._max_blocks:
                            break
                    chains[(domain, block)] = digs
            else:
                continue
            chain = chains[(domain, block)]
            if chain:
                scores[b] = bs.score_chain(chain, domain)
        if not saw_sketch:
            m.route_decisions_total.inc(reason="stale_sketch")
            return sorted(decode, key=rz, reverse=True)
        w = self._t0_weight
        dw = self._disk_weight

        def val(b: str) -> float:
            dev, host, disk = scores.get(b, (0, 0, 0))
            return dev * (1.0 + w) + host + disk * dw

        if hint_out is not None and scores:
            deepest = max(scores, key=lambda b: (sum(scores[b]), rz(b)))
            if sum(scores[deepest]) > 0:
                hint_out.append(deepest)
        best = max(val(b) for b in decode)
        tied = [b for b in decode if val(b) == best]
        if best > 0 and len(tied) == 1:
            chosen = tied[0]
            m.route_decisions_total.inc(reason="sketch_hit")
            dev, host, disk = scores[chosen]
            if dev:
                m.expected_hit_blocks_total.inc(dev, backend=chosen,
                                                tier="device")
            if host:
                m.expected_hit_blocks_total.inc(host, backend=chosen,
                                                tier="host")
            if disk:
                m.expected_hit_blocks_total.inc(disk, backend=chosen,
                                                tier="disk")
        else:
            with self._load_lock:
                load = {b: self._inflight.get(b, 0) for b in tied}
            least = min(load.values())
            quiet = [b for b in tied if load[b] == least]
            chosen = max(quiet, key=rz)
            m.route_decisions_total.inc(reason="tie_fallback")
        rest = sorted((b for b in decode if b != chosen),
                      key=lambda b: (val(b), rz(b)), reverse=True)
        return [chosen] + rest

    def _forward_failover(self, h, body: bytes, prefill_addr: str,
                          decode_addr: str, decode: list[str],
                          started: list[bool], ctx=None,
                          peer_hint: str | None = None) -> int:
        """Backend failover: the picked decode backend first, then every
        other ready one, retried for ONE bounded backoff round — a request
        moves to the next backend on a connection error or a 503
        (draining/recovering replica) IFF no response bytes have been
        streamed to the client yet.  When every backend 503s, the largest
        Retry-After the backends offered passes through so clients back
        off the amount the slowest replica asked for."""
        candidates = [decode_addr] + [b for b in decode if b != decode_addr]
        backoff = knobs.get_float("ARKS_ROUTER_RETRY_BACKOFF_S")
        retry_after: str | None = None
        last_err: Exception | None = None
        for attempt in range(2):
            if attempt:
                time.sleep(backoff)  # one bounded backoff round, then give up
            for cand in candidates:
                try:
                    with self._load_lock:
                        self._inflight[cand] = self._inflight.get(cand, 0) + 1
                    try:
                        status, ra = self._forward(h, body, prefill_addr,
                                                   cand, started, ctx=ctx,
                                                   peer_hint=peer_hint)
                    finally:
                        with self._load_lock:
                            self._inflight[cand] -= 1
                except (OSError, http.client.HTTPException) as e:
                    # The backend may have restarted: its sketch is no
                    # longer evidence of cache residency — drop it now
                    # instead of waiting out the staleness deadline.
                    self.sketches.invalidate(cand)
                    if started[0]:
                        # Bytes already reached the client: a retry would
                        # splice two streams — surface the truncation.
                        raise
                    last_err = e
                    self.retries_total.inc(reason="connect_error")
                    log.warning("decode backend %s unreachable (%s); "
                                "trying next", cand, e)
                    continue
                if status is None:
                    # 503 captured before any relay: replica draining or
                    # recovering — another backend may accept.
                    retry_after = ra or retry_after
                    self.retries_total.inc(reason="backend_503")
                    continue
                return status
        data = json.dumps({"error": {
            "message": ("no decode backend accepted the request"
                        + (f" (last error: {last_err})" if last_err else "")),
            "code": 503}}).encode()
        h.send_response(503)
        if retry_after:
            h.send_header("Retry-After", retry_after)
        h.send_header("Content-Type", "application/json")
        h.send_header("Content-Length", str(len(data)))
        h.end_headers()
        h.wfile.write(data)
        return 503

    def _forward(self, h, body: bytes, prefill_addr: str, decode_addr: str,
                 started: list[bool], ctx=None, peer_hint: str | None = None
                 ) -> tuple[int | None, str | None]:
        """Forward to one decode backend.  Returns (status, None) after
        relaying, or (None, retry_after) for a 503 swallowed BEFORE any
        byte reached the client (the failover input).  Raises OSError /
        http.client.HTTPException on connection failure."""
        if self.unified:
            path = h.path
            headers = {"Content-Type": "application/json"}
        else:
            path = "/v1/disagg" + h.path[len("/v1"):]
            headers = {"Content-Type": "application/json",
                       HDR_PREFILL_ADDR: prefill_addr}
        # SLO tier rides through to the decode backend (arks_tpu.slo):
        # the OpenAI server maps it onto the engine priority scale, where
        # preemptive swap / queue aging act on it.  The gateway-minted
        # tenant identity rides along the same way — the engine's
        # weighted-fair admission keys on it.
        tier = h.headers.get(HDR_TIER)
        if tier:
            headers[HDR_TIER] = tier
        if peer_hint and peer_hint != decode_addr:
            # Only when routing landed AWAY from the deepest-covering
            # replica: fetching from yourself is a no-op.
            headers[HDR_PEER_HINT] = peer_hint
        tenant = h.headers.get(tenancy.HDR_TENANT)
        if tenant:
            headers[tenancy.HDR_TENANT] = tenant
        if ctx is not None:
            # Each attempt gets its own span id under the same trace id
            # (a retry is a new hop); the accumulated upstream spans ride
            # along for the engine-side assembly.
            fwd = ctx.child()
            headers[trace_mod.TRACEPARENT_HEADER] = fwd.traceparent()
            if fwd.upstream:
                headers[trace_mod.SPANS_HEADER] = trace_mod.spans_header(
                    fwd.upstream)
        host, _, port = decode_addr.partition(":")
        conn = http.client.HTTPConnection(host, int(port or 80), timeout=300)
        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            if resp.status == 503:
                resp.read()  # drain for keep-alive hygiene
                return None, resp.headers.get("Retry-After")
            started[0] = True
            h.send_response(resp.status)
            ctype = resp.headers.get("Content-Type", "application/json")
            h.send_header("Content-Type", ctype)
            # Backpressure metadata must survive the relay: the backend's
            # Retry-After (queue_full / shed_deadline / pool-exhausted
            # 429s and 503s), the saturated tier, the shed tenant, and
            # the queue-saturation signal all reach the gateway/client
            # unchanged — stripping them here would turn precise backoff
            # into blind retry storms.
            for bh in ("Retry-After", HDR_TIER, tenancy.HDR_TENANT,
                       tenancy.HDR_SATURATION):
                bv = resp.headers.get(bh)
                if bv:
                    h.send_header(bh, bv)
            clen = resp.headers.get("Content-Length")
            if clen is not None:
                h.send_header("Content-Length", clen)
                h.end_headers()
                h.wfile.write(resp.read())
            else:
                h.send_header("Transfer-Encoding", "chunked")
                h.end_headers()
                while True:
                    chunk = resp.read1(65536)
                    if not chunk:
                        break
                    h.wfile.write(f"{len(chunk):x}\r\n".encode() + chunk
                                  + b"\r\n")
                    h.wfile.flush()
                h.wfile.write(b"0\r\n\r\n")
                h.wfile.flush()
            return resp.status, None
        finally:
            conn.close()
