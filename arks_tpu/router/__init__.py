"""Disaggregated-serving router.

The reference deploys ``sglang_router.launch_router --pd-disaggregation
--service-discovery --prefill-selector ... --decode-selector ...``
(/root/reference/internal/controller/
arksdisaggregatedapplication_controller.go:1630-1670).  This is the native
equivalent: an OpenAI-surface HTTP server that, per request, picks one
prefill and one decode backend and forwards the request to the decode server
with the chosen prefill address in the ``X-Arks-Prefill-Addr`` header; the
decode server pulls the KV directly from the prefill server (one KV hop —
the router never carries KV bytes).

Service discovery: a JSON file ``{"prefill": ["host:port"...],
"decode": [...]}`` re-read on mtime change.  Locally the controller
maintains the file; on k8s it is a projected ConfigMap the controller
updates — the moral equivalent of the reference router's label-selector
pod discovery.

Routing policies (the reference router's ``--policy`` flag, default
``cache_aware`` in its generated command line):

- ``round_robin``: rotate over ready backends.
- ``cache_aware``: rendezvous-hash the request's prompt *prefix* (system
  prompt / few-shot preamble) to a backend, so requests sharing a prefix
  land on the same prefill AND decode engines — whose prefix KV caches
  (arks_tpu.engine.prefix_cache) then serve the shared blocks without
  recompute.  Rendezvous hashing keeps remapping minimal when backends
  come and go (only the moved backend's keys reshuffle).
"""

from __future__ import annotations

import hashlib
import http.client
import itertools
import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from arks_tpu.utils import metrics as prom

log = logging.getLogger("arks_tpu.router")

HDR_PREFILL_ADDR = "X-Arks-Prefill-Addr"


class Discovery:
    """mtime-cached backend lists from a discovery file (+ env fallback)."""

    def __init__(self, path: str | None):
        self.path = path
        self._mtime = 0.0
        self._lock = threading.Lock()
        self._prefill: list[str] = _env_addrs("ARKS_PREFILL_ADDRS")
        self._decode: list[str] = _env_addrs("ARKS_DECODE_ADDRS")

    def backends(self) -> tuple[list[str], list[str]]:
        if self.path and os.path.exists(self.path):
            try:
                mtime = os.path.getmtime(self.path)
                with self._lock:
                    if mtime != self._mtime:
                        with open(self.path) as f:
                            data = json.load(f)
                        self._prefill = list(data.get("prefill", []))
                        self._decode = list(data.get("decode", []))
                        self._mtime = mtime
            except (OSError, ValueError, json.JSONDecodeError):
                log.warning("bad discovery file %s", self.path, exc_info=True)
        with self._lock:
            return list(self._prefill), list(self._decode)


def _env_addrs(name: str) -> list[str]:
    v = os.environ.get(name, "")
    return [a for a in v.split(",") if a]


class KubeDiscovery:
    """Label-selector pod discovery against the Kubernetes API — the native
    counterpart of the reference router's ``--service-discovery
    --prefill-selector/--decode-selector`` mode
    (/root/reference/internal/controller/
    arksdisaggregatedapplication_controller.go:1630-1670).

    Lists pods labeled ``arks.ai/application=<app>`` with
    ``arks.ai/component`` prefill/decode, keeps READY ones (worker
    processes of a gang return 503 on /readiness, so only leaders are
    Ready — exactly the addresses that serve), and addresses them as
    ``podIP:containerPort`` (the port named ``http`` — k8s_export's serving
    port name — else a single unambiguous declared port; falls back to
    ``backend_port``).  Results are cached for ``interval_s`` — the same
    poll cadence the live operator uses; env fallback
    (ARKS_PREFILL_ADDRS/ARKS_DECODE_ADDRS) covers bootstrap windows."""

    def __init__(self, api, namespace: str, application: str,
                 backend_port: int = 8080, interval_s: float = 2.0):
        self.api = api
        self.namespace = namespace
        self.application = application
        self.backend_port = backend_port
        self.interval = interval_s
        self._lock = threading.Lock()
        self._at = 0.0
        self._prefill: list[str] = _env_addrs("ARKS_PREFILL_ADDRS")
        self._decode: list[str] = _env_addrs("ARKS_DECODE_ADDRS")

    @staticmethod
    def _ready(pod: dict) -> bool:
        if pod.get("status", {}).get("phase") != "Running":
            return False
        for c in pod.get("status", {}).get("conditions", []):
            if c.get("type") == "Ready":
                return c.get("status") == "True"
        return False

    def _addr(self, pod: dict) -> str | None:
        ip = pod.get("status", {}).get("podIP")
        if not ip:
            return None
        # Prefer the port NAMED "http" (the name k8s_export assigns to the
        # serving port): a pod whose first declared port is a metrics port,
        # or with a sidecar ordered first, must not silently hijack routing.
        # A single unnamed declared port is unambiguous and honored; any
        # other ambiguity falls back to backend_port.
        declared = [p for c in pod.get("spec", {}).get("containers", [])
                    for p in (c.get("ports") or []) if p.get("containerPort")]
        for p in declared:
            if p.get("name") == "http":
                return f"{ip}:{p['containerPort']}"
        if len(declared) == 1 and not declared[0].get("name"):
            # Unnamed single port: unambiguous.  A single NAMED non-http
            # port (e.g. only a metrics port declared) is not a serving
            # port — fall through to backend_port.
            return f"{ip}:{declared[0]['containerPort']}"
        return f"{ip}:{self.backend_port}"

    def _refresh(self) -> None:
        roles: dict[str, list[str]] = {"prefill": [], "decode": []}
        for pod in self.api.list("v1", "pods", self.namespace):
            labels = pod.get("metadata", {}).get("labels", {})
            if labels.get("arks.ai/application") != self.application:
                continue
            role = labels.get("arks.ai/component")
            if role not in roles or not self._ready(pod):
                continue
            addr = self._addr(pod)
            if addr:
                roles[role].append(addr)
        # Keep env fallback while a tier has no discovered pods yet.
        # (Swap under the lock: backends() reads these concurrently.)
        with self._lock:
            if roles["prefill"]:
                self._prefill = sorted(roles["prefill"])
            if roles["decode"]:
                self._decode = sorted(roles["decode"])

    def backends(self) -> tuple[list[str], list[str]]:
        # The API list happens OUTSIDE the lock and only one thread does it
        # (the _at timestamp claims the refresh): a slow apiserver degrades
        # to a stale backend set, never to every request blocking on the
        # discovery lock.
        now = time.monotonic()
        refresh = False
        with self._lock:
            if now - self._at >= self.interval:
                self._at = now  # claim (and back off a full interval on error)
                refresh = True
        if refresh:
            try:
                self._refresh()
            except Exception:
                log.warning("pod discovery failed; keeping last set",
                            exc_info=True)
        with self._lock:
            return list(self._prefill), list(self._decode)


# Prompt-prefix window the cache_aware policy keys on.  Long enough to
# separate distinct system prompts, short enough that divergent tails (the
# user turn) don't defeat the affinity.
_PREFIX_KEY_CHARS = 512


def _prefix_key(body: bytes) -> bytes | None:
    """Locality key: the first _PREFIX_KEY_CHARS of the prompt text."""
    try:
        obj = json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(obj, dict):
        return None
    if isinstance(obj.get("messages"), list):
        parts = []
        total = 0
        for m in obj["messages"]:
            c = m.get("content") if isinstance(m, dict) else None
            if isinstance(c, list):
                # OpenAI content parts: serialize the text parts so
                # part-based requests key on their REAL prefix instead of
                # skipping ahead to a later turn's text (which would pin
                # different prefixes to one backend).
                c = "".join(t for p in c
                            if isinstance(p, dict) and p.get("type") == "text"
                            for t in (p.get("text"),) if isinstance(t, str))
                if not c:
                    # No usable text (image-only parts): same rule as any
                    # other unknown shape — never key on later turns.
                    break
            if not isinstance(c, str):
                # Unknown content shape: stop scanning — keying on LATER
                # turns would defeat the prefix-affinity intent.
                break
            parts.append(c)
            total += len(c)
            if total >= _PREFIX_KEY_CHARS:
                break
        text = "\x00".join(parts)
    elif isinstance(obj.get("prompt"), str):
        text = obj["prompt"]
    else:
        return None
    if not text:
        # Prompts with no usable text (empty, or content parts carrying no
        # text) get no key — round-robin, don't pin them all to one backend
        # via a shared empty key.
        return None
    return text[:_PREFIX_KEY_CHARS].encode("utf-8", "surrogatepass")


def _rendezvous(key: bytes, backends: list[str]) -> str:
    """Highest-random-weight choice: stable per key, minimal remap on
    backend churn."""
    return max(backends,
               key=lambda b: hashlib.sha1(key + b"\x00" + b.encode()).digest())


class Router:
    def __init__(self, discovery: Discovery, served_model_name: str,
                 host: str = "0.0.0.0", port: int = 8080,
                 policy: str = "cache_aware"):
        if policy not in ("round_robin", "cache_aware"):
            raise ValueError(f"unknown policy {policy!r}")
        self.discovery = discovery
        self.served_model_name = served_model_name
        self.host, self.port = host, port
        self.policy = policy
        self._rr = itertools.count()
        self._httpd: ThreadingHTTPServer | None = None
        self.registry = prom.Registry()
        self.requests_total = self.registry.counter(
            "router_requests_total", "Routed requests")
        self.backends_gauge = self.registry.gauge(
            "router_backends", "Known backends")
        self.retries_total = self.registry.counter(
            "router_retries_total",
            "Requests retried on another backend (by reason)")

    # ------------------------------------------------------------------

    def start(self, background: bool = True) -> None:
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _json(self, code: int, payload: dict) -> None:
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _error(self, code: int, message: str) -> None:
                self._json(code, {"error": {"message": message, "code": code}})

            def do_GET(self):
                if self.path == "/v1/models":
                    self._json(200, {"object": "list", "data": [
                        {"id": router.served_model_name, "object": "model",
                         "created": int(time.time()), "owned_by": "arks-tpu"}]})
                elif self.path == "/metrics":
                    text = router.registry.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(text)))
                    self.end_headers()
                    self.wfile.write(text)
                elif self.path in ("/healthz", "/health"):
                    self._json(200, {"status": "ok"})
                elif self.path == "/readiness":
                    pre, dec = router.discovery.backends()
                    if pre and dec:
                        self._json(200, {"status": "ready"})
                    else:
                        self._error(503, "no prefill/decode backends yet")
                else:
                    self._error(404, f"no route {self.path}")

            def do_POST(self):
                if self.path not in ("/v1/chat/completions", "/v1/completions"):
                    return self._error(404, f"no route {self.path}")
                router._route(self)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_port
        if background:
            threading.Thread(target=self._httpd.serve_forever, name="router",
                             daemon=True).start()
        else:
            self._httpd.serve_forever()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()

    # ------------------------------------------------------------------

    def _route(self, h) -> None:
        status = 500
        started = [False]  # response headers already sent to the client
        # Always drain the body first: an early error response with the body
        # unread desyncs HTTP/1.1 keep-alive connections.
        body = h.rfile.read(int(h.headers.get("Content-Length", 0)))
        try:
            prefill, decode = self.discovery.backends()
            self.backends_gauge.set(len(prefill), role="prefill")
            self.backends_gauge.set(len(decode), role="decode")
            if not prefill or not decode:
                status = 503
                return h._error(503, "no ready prefill/decode backends")
            p, d = self._pick(body, prefill, decode)
            status = self._forward_failover(h, body, p, d, decode, started)
        except (BrokenPipeError, ConnectionResetError):
            status = 499
        except Exception as e:
            log.exception("router failure")
            if started[0]:
                # Headers (and possibly chunks) already went out: a second
                # response would corrupt the stream — just drop the
                # connection so the client sees a clean truncation.
                h.close_connection = True
            else:
                try:
                    h._error(500, f"router error: {e}")
                except Exception:
                    pass
        finally:
            self.requests_total.inc(status=str(status))

    def _pick(self, body: bytes, prefill: list[str],
              decode: list[str]) -> tuple[str, str]:
        if self.policy == "cache_aware":
            key = _prefix_key(body)
            if key is not None:
                return _rendezvous(key, prefill), _rendezvous(key, decode)
        n = next(self._rr)
        return prefill[n % len(prefill)], decode[n % len(decode)]

    def _forward_failover(self, h, body: bytes, prefill_addr: str,
                          decode_addr: str, decode: list[str],
                          started: list[bool]) -> int:
        """Backend failover: the picked decode backend first, then every
        other ready one, retried for ONE bounded backoff round — a request
        moves to the next backend on a connection error or a 503
        (draining/recovering replica) IFF no response bytes have been
        streamed to the client yet.  When every backend 503s, the largest
        Retry-After the backends offered passes through so clients back
        off the amount the slowest replica asked for."""
        candidates = [decode_addr] + [b for b in decode if b != decode_addr]
        backoff = float(os.environ.get("ARKS_ROUTER_RETRY_BACKOFF_S", "0.05"))
        retry_after: str | None = None
        last_err: Exception | None = None
        for attempt in range(2):
            if attempt:
                time.sleep(backoff)  # one bounded backoff round, then give up
            for cand in candidates:
                try:
                    status, ra = self._forward(h, body, prefill_addr, cand,
                                               started)
                except (OSError, http.client.HTTPException) as e:
                    if started[0]:
                        # Bytes already reached the client: a retry would
                        # splice two streams — surface the truncation.
                        raise
                    last_err = e
                    self.retries_total.inc(reason="connect_error")
                    log.warning("decode backend %s unreachable (%s); "
                                "trying next", cand, e)
                    continue
                if status is None:
                    # 503 captured before any relay: replica draining or
                    # recovering — another backend may accept.
                    retry_after = ra or retry_after
                    self.retries_total.inc(reason="backend_503")
                    continue
                return status
        data = json.dumps({"error": {
            "message": ("no decode backend accepted the request"
                        + (f" (last error: {last_err})" if last_err else "")),
            "code": 503}}).encode()
        h.send_response(503)
        if retry_after:
            h.send_header("Retry-After", retry_after)
        h.send_header("Content-Type", "application/json")
        h.send_header("Content-Length", str(len(data)))
        h.end_headers()
        h.wfile.write(data)
        return 503

    def _forward(self, h, body: bytes, prefill_addr: str, decode_addr: str,
                 started: list[bool]) -> tuple[int | None, str | None]:
        """Forward to one decode backend.  Returns (status, None) after
        relaying, or (None, retry_after) for a 503 swallowed BEFORE any
        byte reached the client (the failover input).  Raises OSError /
        http.client.HTTPException on connection failure."""
        path = "/v1/disagg" + h.path[len("/v1"):]
        host, _, port = decode_addr.partition(":")
        conn = http.client.HTTPConnection(host, int(port or 80), timeout=300)
        try:
            conn.request("POST", path, body=body, headers={
                "Content-Type": "application/json",
                HDR_PREFILL_ADDR: prefill_addr,
            })
            resp = conn.getresponse()
            if resp.status == 503:
                resp.read()  # drain for keep-alive hygiene
                return None, resp.headers.get("Retry-After")
            started[0] = True
            h.send_response(resp.status)
            ctype = resp.headers.get("Content-Type", "application/json")
            h.send_header("Content-Type", ctype)
            clen = resp.headers.get("Content-Length")
            if clen is not None:
                h.send_header("Content-Length", clen)
                h.end_headers()
                h.wfile.write(resp.read())
            else:
                h.send_header("Transfer-Encoding", "chunked")
                h.end_headers()
                while True:
                    chunk = resp.read1(65536)
                    if not chunk:
                        break
                    h.wfile.write(f"{len(chunk):x}\r\n".encode() + chunk
                                  + b"\r\n")
                    h.wfile.flush()
                h.wfile.write(b"0\r\n\r\n")
                h.wfile.flush()
            return resp.status, None
        finally:
            conn.close()
