"""Gang workloads: spec generation + drivers.

The reference delegates gang semantics to external operators (LWS v0.7.0 /
RBGS — SURVEY.md §1) and only generates their specs.  Here GangSet is a
first-class resource with pluggable drivers:

- FakeGangDriver — test double; readiness is script-controlled (the "fake
  gang-status driver" the reference lacks, SURVEY.md §4).
- LocalProcessDriver — real subprocesses on this host (single-node demo and
  e2e tests): spawns the leader command per replica group, readiness-probes
  its HTTP port, restarts the whole group on exit (the LWS
  RecreateGroupOnPodRestart semantic, arksapplication_controller.go:581-584).

Env contract injected into every member (the LWS env contract translated —
reference :560-569):
  ARKS_GANG_LEADER_ADDRESS, ARKS_GANG_SIZE, ARKS_GANG_WORKER_INDEX
and for the jax runtime the serving entrypoint's rendezvous vars
(ARKS_COORDINATOR_ADDRESS / ARKS_NUM_PROCESSES / ARKS_PROCESS_ID).
"""

from __future__ import annotations

import logging
import os
import shlex
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Protocol

from arks_tpu.control.resources import GangSet

log = logging.getLogger("arks_tpu.workloads")


class GangDriver(Protocol):
    def ensure(self, gs: GangSet) -> None: ...
    def status(self, gs: GangSet) -> dict: ...
    def teardown(self, gs: GangSet) -> None: ...


def stable_hash(obj) -> str:
    """Short deterministic content hash for revision stamps (shared by the
    gang drivers and the k8s renderer so 'outdated' means the same thing
    everywhere)."""
    import hashlib
    import json

    return hashlib.sha256(
        json.dumps(obj, sort_keys=True, default=str).encode()
    ).hexdigest()[:12]


def spec_hash(gs: GangSet) -> str:
    """Hash of the spec fields that require a group restart to apply.

    Stamped onto every launched group; a mismatch marks the group outdated
    for the rolling update.  ``replicas`` is deliberately excluded — scaling
    must not restart existing groups."""
    return stable_hash({k: gs.spec.get(k)
                        for k in ("size", "leader", "worker", "ports", "runtime")})


def pick_rolling_restart(hashes: dict[int, str], want_hash: str,
                         ready: dict[int, bool]) -> int | None:
    """maxUnavailable=1 / maxSurge=0 rolling update (the reference's RBGS
    RollingUpdate strategy, arksapplication_controller.go:867-874).

    Unready outdated groups roll first — restarting a group that serves no
    traffic cannot reduce availability, and without this a revision that
    hangs (alive but never ready) would wedge the corrective rollout
    forever.  A READY outdated group only rolls when every other group is
    ready, so the endpoint's backend list never goes empty mid-rollout and
    a stuck new revision halts the rollout instead of cascading."""
    outdated = sorted(i for i, h in hashes.items() if h != want_hash)
    if not outdated:
        return None
    for i in outdated:
        if not ready.get(i, False):
            return i
    cand = outdated[0]
    if all(ready.get(i, False) for i in hashes if i != cand):
        return cand
    return None


# ---------------------------------------------------------------------------
# Fake driver (tests)
# ---------------------------------------------------------------------------


class FakeGangDriver:
    """Marks each group Running after ``ready_after`` ensure() calls (0 =
    ready from the first ensure); tests can fail groups explicitly.  Applies
    the same rolling-update semantics as the real drivers (spec-hash stamp,
    one restart at a time gated on the others' readiness) and records each
    rolling restart in ``restarts`` for assertions."""

    def __init__(self, ready_after: int = 0):
        self.ready_after = ready_after
        # gs.key -> index -> {"hash": str, "ensures": int}
        self._groups: dict[tuple, dict[int, dict]] = {}
        self._failed: set[tuple] = set()
        self.torn_down: list[tuple] = []
        self.restarts: list[tuple] = []  # (gs.key, index) rolling restarts

    def fail_group(self, gs_key: tuple, index: int) -> None:
        self._failed.add((gs_key, index))

    def recover_group(self, gs_key: tuple, index: int) -> None:
        self._failed.discard((gs_key, index))

    def _is_ready(self, key: tuple, index: int, g: dict) -> bool:
        return (key, index) not in self._failed and g["ensures"] > self.ready_after

    def ensure(self, gs: GangSet) -> None:
        want = spec_hash(gs)
        groups = self._groups.setdefault(gs.key, {})
        replicas = gs.spec.get("replicas", 1)
        for idx in range(replicas):
            groups.setdefault(idx, {"hash": want, "ensures": 0})
        for idx in [i for i in groups if i >= replicas]:
            del groups[idx]
        for g in groups.values():
            g["ensures"] += 1
        ready = {i: self._is_ready(gs.key, i, g) for i, g in groups.items()}
        cand = pick_rolling_restart(
            {i: g["hash"] for i, g in groups.items()}, want, ready)
        if cand is not None:
            groups[cand] = {"hash": want, "ensures": 0}
            self.restarts.append((gs.key, cand))

    def status(self, gs: GangSet) -> dict:
        replicas = gs.spec.get("replicas", 1)
        groups = self._groups.get(gs.key, {})
        out = []
        for i in range(replicas):
            g = groups.get(i)
            if (gs.key, i) in self._failed:
                phase = "Failed"
            elif g is not None and g["ensures"] > self.ready_after:
                phase = "Running"
            else:
                phase = "Pending"
            out.append({"index": i, "phase": phase,
                        "leaderAddr": f"fake-{gs.name}-{i}:8080"})
        ready = sum(1 for g in out if g["phase"] == "Running")
        return {"replicas": replicas, "readyReplicas": ready, "groups": out}

    def teardown(self, gs: GangSet) -> None:
        self.torn_down.append(gs.key)
        self._groups.pop(gs.key, None)


# ---------------------------------------------------------------------------
# Local process driver (single-node demo / e2e)
# ---------------------------------------------------------------------------


class _Group:
    def __init__(self, proc: subprocess.Popen, port: int, spec_hash: str):
        self.proc = proc
        self.port = port
        self.spec_hash = spec_hash  # revision stamp for rolling updates
        self.started = time.monotonic()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class LocalProcessDriver:
    """Runs each replica group's leader as a local subprocess.

    size > 1 gangs still launch only the leader here (one host); multi-host
    members come from the k8s deployment path (arks_tpu.control.k8s_export).
    """

    def __init__(self, log_dir: str = "/tmp/arks-tpu-logs"):
        import atexit

        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._groups: dict[tuple, dict[int, _Group]] = {}
        self._lock = threading.Lock()
        # Unlike k8s pods (which rightly outlive their operator), local
        # subprocesses must die with this process or they leak.
        atexit.register(self.teardown_all)

    def teardown_all(self) -> None:
        with self._lock:
            groups = [g for d in self._groups.values() for g in d.values()]
            self._groups.clear()
        for g in groups:
            self._stop_group(g)

    def ensure(self, gs: GangSet) -> None:
        want = spec_hash(gs)
        with self._lock:
            groups = self._groups.setdefault(gs.key, {})
            replicas = gs.spec.get("replicas", 1)
            # Reap dead groups → restart whole group (RecreateGroupOnPodRestart).
            # Relaunches pick up the CURRENT spec, so a crashed outdated
            # group rolls forward for free.
            for idx, g in list(groups.items()):
                if g.proc.poll() is not None:
                    log.warning("gang %s group %d exited rc=%s; restarting",
                                gs.name, idx, g.proc.returncode)
                    del groups[idx]
            for idx in range(replicas):
                if idx in groups:
                    continue
                groups[idx] = self._launch(gs, idx)
            # Scale down.
            for idx in [i for i in groups if i >= replicas]:
                self._stop_group(groups.pop(idx))
            # Rolling update: restart at most ONE outdated group per ensure,
            # gated on every other group being ready (maxUnavailable=1).
            # Probe only when a rollout is actually pending — probing every
            # group (2s timeout each) under the driver lock on every ensure
            # would stall status() and every other gang's reconcile.
            hashes = {i: g.spec_hash for i, g in groups.items()}
            if all(h == want for h in hashes.values()):
                return
            ready = {i: self._probe(g.port) for i, g in groups.items()}
            cand = pick_rolling_restart(hashes, want, ready)
            if cand is not None:
                log.info("gang %s/%s group %d: rolling restart to revision %s",
                         gs.namespace, gs.name, cand, want)
                self._stop_group(groups.pop(cand))
                groups[cand] = self._launch(gs, cand)

    def _launch(self, gs: GangSet, index: int) -> _Group:
        revision = spec_hash(gs)
        port = _free_port()
        cmd = list(gs.spec["leader"]["command"])
        cmd = [c.replace("$(PORT)", str(port)) for c in cmd]
        env = dict(os.environ)
        env.update(gs.spec["leader"].get("env", {}))
        env.update({
            "ARKS_GANG_LEADER_ADDRESS": f"127.0.0.1:{port}",
            "ARKS_GANG_SIZE": str(gs.spec.get("size", 1)),
            "ARKS_GANG_WORKER_INDEX": "0",
        })
        logf = open(os.path.join(
            self.log_dir, f"{gs.namespace}-{gs.name}-{index}.log"), "ab")
        log.info("gang %s/%s group %d: %s (port %d)",
                 gs.namespace, gs.name, index, shlex.join(cmd), port)
        proc = subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf)
        return _Group(proc, port, revision)

    def status(self, gs: GangSet) -> dict:
        with self._lock:
            groups = dict(self._groups.get(gs.key, {}))
        replicas = gs.spec.get("replicas", 1)
        out = []
        for i in range(replicas):
            g = groups.get(i)
            if g is None or g.proc.poll() is not None:
                out.append({"index": i, "phase": "Pending", "leaderAddr": ""})
                continue
            phase = "Running" if self._probe(g.port) else "Starting"
            out.append({"index": i, "phase": phase,
                        "leaderAddr": f"127.0.0.1:{g.port}"})
        ready = sum(1 for g in out if g["phase"] == "Running")
        return {"replicas": replicas, "readyReplicas": ready, "groups": out}

    def _probe(self, port: int) -> bool:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readiness", timeout=2) as r:
                return r.status == 200
        except Exception:
            return False

    def _stop_group(self, g: _Group) -> None:
        if g.proc.poll() is None:
            g.proc.terminate()
            try:
                g.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                g.proc.kill()

    def teardown(self, gs: GangSet) -> None:
        with self._lock:
            groups = self._groups.pop(gs.key, {})
        for g in groups.values():
            self._stop_group(g)


# ---------------------------------------------------------------------------
# Runtime command generation (the generateLeaderCommand analogue,
# reference arksapplication_controller.go:941-1014)
# ---------------------------------------------------------------------------


def jax_serve_command(model_arg: str, served_model_name: str, port_token: str,
                      tensor_parallel: int, size: int, common_args: list[str],
                      model_path: str | None = None,
                      platform: str | None = None) -> list[str]:
    cmd = [sys.executable, "-m", "arks_tpu.server",
           "--model", model_arg,
           "--served-model-name", served_model_name,
           "--port", port_token,
           "--tensor-parallel-size", str(tensor_parallel)]
    if model_path:
        cmd += ["--model-path", model_path]
    if platform:
        cmd += ["--platform", platform]
    cmd += list(common_args)
    return cmd


def gpu_runtime_command(runtime: str, model_path: str, served_model_name: str,
                        tensor_parallel: int, size: int,
                        common_args: list[str]) -> list[str]:
    """Command lines for the GPU runtimes the reference launches, kept for
    mixed-fleet parity (semantics per arksapplication_controller.go:941-1014;
    these run in their own container images, never on this host)."""
    if runtime == "vllm":
        return (["python3", "-m", "vllm.entrypoints.openai.api_server",
                 "--host", "0.0.0.0", "--port", "8080",
                 "--model", model_path,
                 "--served-model-name", served_model_name,
                 "--tensor-parallel-size", str(tensor_parallel)]
                + list(common_args))
    if runtime == "sglang":
        return (["python3", "-m", "sglang.launch_server",
                 "--host", "0.0.0.0", "--port", "8080",
                 "--model-path", model_path,
                 "--served-model-name", served_model_name,
                 "--tp", str(tensor_parallel),
                 "--dist-init-addr", "$(ARKS_GANG_LEADER_ADDRESS)",
                 "--nnodes", str(size),
                 "--node-rank", "$(ARKS_GANG_WORKER_INDEX)",
                 "--enable-metrics"]
                + list(common_args))
    if runtime == "dynamo":
        return (["dynamo", "run", "in=http", f"out=dyn://{served_model_name}",
                 "--model-path", model_path] + list(common_args))
    raise ValueError(f"unknown runtime {runtime!r}")
