"""Gang workloads: spec generation + drivers.

The reference delegates gang semantics to external operators (LWS v0.7.0 /
RBGS — SURVEY.md §1) and only generates their specs.  Here GangSet is a
first-class resource with pluggable drivers:

- FakeGangDriver — test double; readiness is script-controlled (the "fake
  gang-status driver" the reference lacks, SURVEY.md §4).
- LocalProcessDriver — real subprocesses on this host (single-node demo and
  e2e tests): spawns the leader command per replica group, readiness-probes
  its HTTP port, restarts the whole group on exit (the LWS
  RecreateGroupOnPodRestart semantic, arksapplication_controller.go:581-584).

Env contract injected into every member (the LWS env contract translated —
reference :560-569):
  ARKS_GANG_LEADER_ADDRESS, ARKS_GANG_SIZE, ARKS_GANG_WORKER_INDEX
and for the jax runtime the serving entrypoint's rendezvous vars
(ARKS_COORDINATOR_ADDRESS / ARKS_NUM_PROCESSES / ARKS_PROCESS_ID).
"""

from __future__ import annotations

import logging
import os
import shlex
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Protocol

from arks_tpu.control.resources import GangSet
from arks_tpu.utils import knobs
from arks_tpu.utils.swallow import swallowed

log = logging.getLogger("arks_tpu.workloads")


class GangDriver(Protocol):
    def ensure(self, gs: GangSet) -> None: ...
    def status(self, gs: GangSet) -> dict: ...
    def teardown(self, gs: GangSet) -> None: ...


def stable_hash(obj) -> str:
    """Short deterministic content hash for revision stamps (shared by the
    gang drivers and the k8s renderer so 'outdated' means the same thing
    everywhere)."""
    import hashlib
    import json

    return hashlib.sha256(
        json.dumps(obj, sort_keys=True, default=str).encode()
    ).hexdigest()[:12]


def spec_hash(gs: GangSet) -> str:
    """Hash of the spec fields that require a group restart to apply.

    Stamped onto every launched group; a mismatch marks the group outdated
    for the rolling update.  ``replicas`` is deliberately excluded — scaling
    must not restart existing groups."""
    return stable_hash({k: gs.spec.get(k)
                        for k in ("size", "leader", "worker", "ports",
                                  "runtime", "image", "accelerator",
                                  "modelPvc")})


def pick_rolling_restart(hashes: dict[int, str], want_hash: str,
                         ready: dict[int, bool]) -> int | None:
    """maxUnavailable=1 / maxSurge=0 rolling update (the reference's RBGS
    RollingUpdate strategy, arksapplication_controller.go:867-874).

    Unready outdated groups roll first — restarting a group that serves no
    traffic cannot reduce availability, and without this a revision that
    hangs (alive but never ready) would wedge the corrective rollout
    forever.  A READY outdated group only rolls when every other group is
    ready, so the endpoint's backend list never goes empty mid-rollout and
    a stuck new revision halts the rollout instead of cascading."""
    outdated = sorted(i for i, h in hashes.items() if h != want_hash)
    if not outdated:
        return None
    for i in outdated:
        if not ready.get(i, False):
            return i
    cand = outdated[0]
    if all(ready.get(i, False) for i in hashes if i != cand):
        return cand
    return None


# ---------------------------------------------------------------------------
# Fake driver (tests)
# ---------------------------------------------------------------------------


class FakeGangDriver:
    """Marks each group Running after ``ready_after`` ensure() calls (0 =
    ready from the first ensure); tests can fail groups explicitly.  Applies
    the same rolling-update semantics as the real drivers (spec-hash stamp,
    one restart at a time gated on the others' readiness) and records each
    rolling restart in ``restarts`` for assertions."""

    def __init__(self, ready_after: int = 0):
        self.ready_after = ready_after
        # gs.key -> index -> {"hash": str, "ensures": int}
        self._groups: dict[tuple, dict[int, dict]] = {}
        self._failed: set[tuple] = set()
        self.torn_down: list[tuple] = []
        self.restarts: list[tuple] = []  # (gs.key, index) rolling restarts

    def fail_group(self, gs_key: tuple, index: int) -> None:
        self._failed.add((gs_key, index))

    def recover_group(self, gs_key: tuple, index: int) -> None:
        self._failed.discard((gs_key, index))

    def _is_ready(self, key: tuple, index: int, g: dict) -> bool:
        return (key, index) not in self._failed and g["ensures"] > self.ready_after

    def ensure(self, gs: GangSet) -> None:
        want = spec_hash(gs)
        groups = self._groups.setdefault(gs.key, {})
        replicas = gs.spec.get("replicas", 1)
        for idx in range(replicas):
            groups.setdefault(idx, {"hash": want, "ensures": 0})
        for idx in [i for i in groups if i >= replicas]:
            del groups[idx]
        for g in groups.values():
            g["ensures"] += 1
        ready = {i: self._is_ready(gs.key, i, g) for i, g in groups.items()}
        cand = pick_rolling_restart(
            {i: g["hash"] for i, g in groups.items()}, want, ready)
        if cand is not None:
            groups[cand] = {"hash": want, "ensures": 0}
            self.restarts.append((gs.key, cand))

    def status(self, gs: GangSet) -> dict:
        replicas = gs.spec.get("replicas", 1)
        groups = self._groups.get(gs.key, {})
        out = []
        for i in range(replicas):
            g = groups.get(i)
            if (gs.key, i) in self._failed:
                phase = "Failed"
            elif g is not None and g["ensures"] > self.ready_after:
                phase = "Running"
            else:
                phase = "Pending"
            out.append({"index": i, "phase": phase,
                        "leaderAddr": f"fake-{gs.name}-{i}:8080"})
        ready = sum(1 for g in out if g["phase"] == "Running")
        return {"replicas": replicas, "readyReplicas": ready, "groups": out}

    def teardown(self, gs: GangSet) -> None:
        self.torn_down.append(gs.key)
        self._groups.pop(gs.key, None)


# ---------------------------------------------------------------------------
# Local process driver (single-node demo / e2e)
# ---------------------------------------------------------------------------


class _Group:
    """One replica group: the leader plus ``size - 1`` worker processes.

    ``procs[0]`` is the leader (serves HTTP on ``port``); gang semantics
    are all-or-nothing — any member dying recreates the whole group."""

    def __init__(self, procs: list[subprocess.Popen], port: int,
                 spec_hash: str):
        self.procs = procs
        self.port = port
        self.spec_hash = spec_hash  # revision stamp for rolling updates
        self.started = time.monotonic()

    @property
    def proc(self) -> subprocess.Popen:  # leader, for probes/logs
        return self.procs[0]

    def poll_any_dead(self):
        """Returncode of the first dead member, else None."""
        for p in self.procs:
            rc = p.poll()
            if rc is not None:
                return rc
        return None


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class LocalProcessDriver:
    """Runs each replica group as local subprocesses — ALL ``size`` members,
    leader + workers, wired with the jax.distributed rendezvous env
    (ARKS_COORDINATOR_ADDRESS / ARKS_NUM_PROCESSES / ARKS_PROCESS_ID), so a
    size-N gang runs a real N-process distributed engine on one machine.
    The k8s deployment path (arks_tpu.control.k8s_export) renders the same
    contract across hosts."""

    def __init__(self, log_dir: str = "/tmp/arks-tpu-logs"):
        import atexit

        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._groups: dict[tuple, dict[int, _Group]] = {}
        self._lock = threading.Lock()
        # Unlike k8s pods (which rightly outlive their operator), local
        # subprocesses must die with this process or they leak.
        atexit.register(self.teardown_all)

    def teardown_all(self) -> None:
        with self._lock:
            groups = [g for d in self._groups.values() for g in d.values()]
            self._groups.clear()
        for g in groups:
            self._stop_group(g)

    def ensure(self, gs: GangSet) -> None:
        want = spec_hash(gs)
        # Groups to finish stopping OUTSIDE the lock: waiting for a member
        # stuck in a native collective (up to 10s each) must not block
        # status() and every other gang's reconcile.
        to_reap: list[_Group] = []
        with self._lock:
            groups = self._groups.setdefault(gs.key, {})
            replicas = gs.spec.get("replicas", 1)
            # Reap groups with ANY dead member → restart whole group
            # (RecreateGroupOnPodRestart).  Relaunches pick up the CURRENT
            # spec, so a crashed outdated group rolls forward for free.
            for idx, g in list(groups.items()):
                rc = g.poll_any_dead()
                if rc is not None:
                    log.warning("gang %s group %d member exited rc=%s; "
                                "restarting group", gs.name, idx, rc)
                    self._signal_stop(g)
                    to_reap.append(g)
                    del groups[idx]
            for idx in range(replicas):
                if idx in groups:
                    continue
                groups[idx] = self._launch(gs, idx)
            # Scale down.
            for idx in [i for i in groups if i >= replicas]:
                g = groups.pop(idx)
                self._signal_stop(g)
                to_reap.append(g)
            # Rolling update: restart at most ONE outdated group per ensure,
            # gated on every other group being ready (maxUnavailable=1).
            # Probe only when a rollout is actually pending — probing every
            # group (2s timeout each) under the driver lock on every ensure
            # would stall status() and every other gang's reconcile.
            hashes = {i: g.spec_hash for i, g in groups.items()}
            rolling = not all(h == want for h in hashes.values())
            if rolling:
                ready = {i: self._probe(g.port) for i, g in groups.items()}
                cand = pick_rolling_restart(hashes, want, ready)
                if cand is not None:
                    log.info("gang %s/%s group %d: rolling restart to "
                             "revision %s", gs.namespace, gs.name, cand, want)
                    g = groups.pop(cand)
                    self._signal_stop(g)
                    to_reap.append(g)
                    groups[cand] = self._launch(gs, cand)
        for g in to_reap:
            self._reap_stop(g)

    def _launch(self, gs: GangSet, index: int) -> _Group:
        import secrets

        revision = spec_hash(gs)
        size = gs.spec.get("size", 1)
        leader_port = _free_port()
        coord_port = _free_port() if size > 1 else 0
        # Explicitly allocated (not derived from coord_port) — derived ports
        # collide with other allocations on a shared host.
        dispatch_port = _free_port() if size > 1 else 0
        gang_secret = secrets.token_hex(16)
        procs: list[subprocess.Popen] = []
        for member in range(size):
            role = "leader" if member == 0 else "worker"
            spec = gs.spec.get(role) or gs.spec["leader"]
            port = leader_port if member == 0 else _free_port()
            cmd = [c.replace("$(PORT)", str(port)) for c in spec["command"]]
            env = dict(os.environ)
            env.update(spec.get("env", {}))
            env.update({
                "ARKS_GANG_LEADER_ADDRESS": f"127.0.0.1:{leader_port}",
                "ARKS_GANG_SIZE": str(size),
                "ARKS_GANG_WORKER_INDEX": str(member),
                # Fit the graceful drain inside THIS driver's 10s
                # SIGTERM->SIGKILL window.  Env-default only: an explicit
                # --drain-timeout flag wins, and K8s-rendered pods (30s
                # grace) keep the server's own 20s default.
                "ARKS_DRAIN_TIMEOUT": env.get("ARKS_DRAIN_TIMEOUT", "8"),
            })
            if size > 1:
                # jax.distributed rendezvous (the LWS env contract
                # translated — reference :560-569) + the authenticated
                # dispatch channel (arks_tpu.engine.multihost).
                env.update({
                    "ARKS_COORDINATOR_ADDRESS": f"127.0.0.1:{coord_port}",
                    "ARKS_NUM_PROCESSES": str(size),
                    "ARKS_PROCESS_ID": str(member),
                    "ARKS_DISPATCH_ADDRESS": f"127.0.0.1:{dispatch_port}",
                    "ARKS_GANG_SECRET": gang_secret,
                })
            logf = open(os.path.join(
                self.log_dir,
                f"{gs.namespace}-{gs.name}-{index}-{member}.log"), "ab")
            log.info("gang %s/%s group %d member %d: %s (port %d)",
                     gs.namespace, gs.name, index, member,
                     shlex.join(cmd), port)
            procs.append(subprocess.Popen(cmd, env=env, stdout=logf,
                                          stderr=logf))
        return _Group(procs, leader_port, revision)

    def status(self, gs: GangSet) -> dict:
        with self._lock:
            groups = dict(self._groups.get(gs.key, {}))
        replicas = gs.spec.get("replicas", 1)
        out = []
        for i in range(replicas):
            g = groups.get(i)
            if g is None or g.poll_any_dead() is not None:
                out.append({"index": i, "phase": "Pending", "leaderAddr": ""})
                continue
            phase = "Running" if self._probe(g.port) else "Starting"
            out.append({"index": i, "phase": phase,
                        "leaderAddr": f"127.0.0.1:{g.port}"})
        ready = sum(1 for g in out if g["phase"] == "Running")
        return {"replicas": replicas, "readyReplicas": ready, "groups": out}

    def _probe(self, port: int) -> bool:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readiness", timeout=2) as r:
                return r.status == 200
        except Exception as e:
            # A failed probe IS the signal (not-ready); expected while a
            # member is still booting.
            swallowed("workloads.readiness-probe", e)
            return False

    def _signal_stop(self, g: _Group) -> None:
        """Fast half of a group stop: deliver SIGTERM to every member."""
        for p in g.procs:
            if p.poll() is None:
                p.terminate()

    def _reap_stop(self, g: _Group) -> None:
        """Slow half: wait for exits, escalate to SIGKILL.  Call WITHOUT the
        driver lock — a member wedged in a native collective ignores
        SIGTERM until the call returns."""
        for p in g.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass

    def _stop_group(self, g: _Group) -> None:
        self._signal_stop(g)
        self._reap_stop(g)

    def teardown(self, gs: GangSet) -> None:
        with self._lock:
            groups = self._groups.pop(gs.key, {})
        for g in groups.values():
            self._stop_group(g)


# ---------------------------------------------------------------------------
# Runtime command generation (the generateLeaderCommand analogue,
# reference arksapplication_controller.go:941-1014)
# ---------------------------------------------------------------------------


def jax_serve_command(model_arg: str, served_model_name: str, port_token: str,
                      tensor_parallel: int, size: int, common_args: list[str],
                      model_path: str | None = None,
                      platform: str | None = None,
                      context_parallel: int = 1,
                      num_slices: int = 1) -> list[str]:
    cmd = [sys.executable, "-m", "arks_tpu.server",
           "--model", model_arg,
           "--served-model-name", served_model_name,
           "--port", port_token,
           "--tensor-parallel-size", str(tensor_parallel)]
    if context_parallel > 1:
        cmd += ["--context-parallel-size", str(context_parallel)]
    if num_slices > 1:
        cmd += ["--num-slices", str(num_slices)]
    if model_path:
        cmd += ["--model-path", model_path]
    if platform:
        cmd += ["--platform", platform]
    cmd += list(common_args)
    return cmd


def default_runtime_image(runtime: str) -> str:
    """Per-runtime default image with env escape hatches — same contract
    as the reference (ARKS_RUNTIME_DEFAULT_{VLLM,SGLANG,DYNAMO}_IMAGE,
    arksapplication_controller.go:907-939), extended with the native jax
    runtime's ARKS_RUNTIME_DEFAULT_JAX_IMAGE.  Spec.runtimeImage always
    wins; env beats the built-in default."""
    name = f"ARKS_RUNTIME_DEFAULT_{runtime.upper()}_IMAGE"
    env = knobs.get_str(name) if knobs.is_registered(name) else None
    if env:
        return env
    return {
        "vllm": "vllm/vllm-openai:v0.8.2",
        "sglang": "lmsysorg/sglang:v0.4.5-cu124",
        "dynamo": "scitixai/k8s/dynamo:vllm",
    }.get(runtime, "arks-tpu/engine:latest")


def default_scripts_image() -> str:
    """Model-download worker image (ARKS_SCRIPTS_IMAGE escape hatch —
    arksmodel_controller.go:369-375)."""
    return knobs.get_str("ARKS_SCRIPTS_IMAGE")


def gpu_runtime_command(runtime: str, model_path: str, served_model_name: str,
                        tensor_parallel: int, size: int,
                        common_args: list[str]) -> list[str]:
    """Command lines for the GPU runtimes the reference launches, kept for
    mixed-fleet parity (semantics per arksapplication_controller.go:941-1014;
    these run in their own container images, never on this host)."""
    if runtime == "vllm":
        return (["python3", "-m", "vllm.entrypoints.openai.api_server",
                 "--host", "0.0.0.0", "--port", "8080",
                 "--model", model_path,
                 "--served-model-name", served_model_name,
                 "--tensor-parallel-size", str(tensor_parallel)]
                + list(common_args))
    if runtime == "sglang":
        return (["python3", "-m", "sglang.launch_server",
                 "--host", "0.0.0.0", "--port", "8080",
                 "--model-path", model_path,
                 "--served-model-name", served_model_name,
                 "--tp", str(tensor_parallel),
                 "--dist-init-addr", "$(ARKS_GANG_LEADER_ADDRESS)",
                 "--nnodes", str(size),
                 "--node-rank", "$(ARKS_GANG_WORKER_INDEX)",
                 "--enable-metrics"]
                + list(common_args))
    if runtime == "dynamo":
        return (["dynamo", "run", "in=http", f"out=dyn://{served_model_name}",
                 "--model-path", model_path] + list(common_args))
    raise ValueError(f"unknown runtime {runtime!r}")
