"""Minimal Kubernetes API client + in-process fake.

The reference operator is a controller-runtime process against a live
apiserver (/root/reference/cmd/main.go:255-301).  The TPU-native operator's
live mode (arks_tpu.control.live) needs the same — but this image has no
kubernetes python package, and the k8s API is plain REST+JSON, so a small
dependency-free client suffices: CRUD + merge-patch + status subresource
over HTTPS with bearer-token auth (in-cluster service account or explicit
flags).

``FakeKubeApi`` implements the same surface over an in-memory dict with the
apiserver behaviors the operator depends on (resourceVersion bumps,
finalizer-gated deletion, status subresource isolation) and records every
mutation — the envtest analogue for this repo's test tiers (SURVEY.md §4).
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

log = logging.getLogger("arks_tpu.control.k8s")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"{status}: {message}")
        self.status = status


def _gv_path(gv: str) -> str:
    # "v1" -> /api/v1 ; "apps/v1" | "arks.ai/v1" -> /apis/<group>/<version>
    return f"/api/{gv}" if "/" not in gv else f"/apis/{gv}"


class KubeApi:
    """REST client over one apiserver.

    Paths are built from (group_version, plural, namespace, name); payloads
    are plain dicts in wire form.  PATCH uses merge-patch, which is how the
    controllers avoid resourceVersion conflicts on status updates.
    """

    def __init__(self, base_url: str, token: str | None = None,
                 ca_file: str | None = None, verify: bool = True,
                 timeout_s: float = 15.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout_s = timeout_s
        if ca_file:
            ctx = ssl.create_default_context(cafile=ca_file)
        elif verify:
            ctx = ssl.create_default_context()
        else:
            ctx = ssl._create_unverified_context()
        self._ctx = ctx

    @classmethod
    def in_cluster(cls) -> "KubeApi":
        """Service-account config, like client-go's rest.InClusterConfig."""
        with open(os.path.join(SA_DIR, "token")) as f:
            token = f.read().strip()
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        return cls(f"https://{host}:{port}", token=token,
                   ca_file=os.path.join(SA_DIR, "ca.crt"))

    @staticmethod
    def namespace_in_cluster() -> str:
        try:
            with open(os.path.join(SA_DIR, "namespace")) as f:
                return f.read().strip()
        except OSError:
            return "default"

    # -- wire ----------------------------------------------------------

    def _request(self, method: str, path: str, body: dict | None = None,
                 content_type: str = "application/json"):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.base_url + path, data=data,
                                     method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s,
                                        context=self._ctx) as r:
                payload = r.read()
                return json.loads(payload) if payload else None
        except urllib.error.HTTPError as e:
            raise ApiError(e.code, e.read().decode(errors="replace")[:500])

    def _obj_path(self, gv: str, plural: str, namespace: str | None,
                  name: str | None = None, subresource: str | None = None) -> str:
        path = _gv_path(gv)
        if namespace:
            path += f"/namespaces/{namespace}"
        path += f"/{plural}"
        if name:
            path += f"/{name}"
        if subresource:
            path += f"/{subresource}"
        return path

    # -- auth ----------------------------------------------------------

    def token_review(self, token: str) -> bool:
        """authentication.k8s.io/v1 TokenReview: is this bearer token a
        valid cluster identity?  The operator's metrics endpoint gates on
        this — the authn half of the reference manager's
        WithAuthenticationAndAuthorization filter (cmd/main.go:157-169)."""
        try:
            out = self._request(
                "POST",
                self._obj_path("authentication.k8s.io/v1", "tokenreviews",
                               None),
                {"apiVersion": "authentication.k8s.io/v1",
                 "kind": "TokenReview", "spec": {"token": token}})
        except (ApiError, OSError):
            return False  # fail CLOSED: unverifiable = unauthenticated
        return bool((out or {}).get("status", {}).get("authenticated"))

    # -- resource ops --------------------------------------------------

    def list(self, gv: str, plural: str, namespace: str | None = None) -> list[dict]:
        out = self._request("GET", self._obj_path(gv, plural, namespace))
        return out.get("items", []) if out else []

    def get(self, gv: str, plural: str, namespace: str, name: str) -> dict | None:
        try:
            return self._request("GET", self._obj_path(gv, plural, namespace, name))
        except ApiError as e:
            if e.status == 404:
                return None
            raise

    def create(self, gv: str, plural: str, namespace: str, obj: dict) -> dict:
        return self._request("POST", self._obj_path(gv, plural, namespace), obj)

    def patch(self, gv: str, plural: str, namespace: str, name: str,
              patch: dict, subresource: str | None = None) -> dict:
        return self._request(
            "PATCH", self._obj_path(gv, plural, namespace, name, subresource),
            patch, content_type="application/merge-patch+json")

    def replace(self, gv: str, plural: str, namespace: str, name: str,
                obj: dict) -> dict:
        """PUT — full replacement (merge-patch cannot remove keys).  The
        object must carry the current metadata.resourceVersion."""
        return self._request("PUT", self._obj_path(gv, plural, namespace, name),
                             obj)

    def delete(self, gv: str, plural: str, namespace: str, name: str) -> None:
        try:
            self._request("DELETE", self._obj_path(gv, plural, namespace, name))
        except ApiError as e:
            if e.status != 404:
                raise

    def watch(self, gv: str, plural: str, namespace: str | None = None,
              since_rv: int = 0, timeout_s: float = 30.0):
        """Stream watch events ({'type', 'object'} dicts) from
        ``?watch=1`` until the server closes the window (apiserver
        timeoutSeconds semantics).  410 = resourceVersion too old, caller
        must relist."""
        path = self._obj_path(gv, plural, namespace)
        qs = urllib.parse.urlencode({
            "watch": "1", "resourceVersion": str(since_rv),
            "timeoutSeconds": str(int(timeout_s)),
        })
        req = urllib.request.Request(self.base_url + path + "?" + qs)
        req.add_header("Accept", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=timeout_s + 10,
                                        context=self._ctx) as r:
                for line in r:
                    line = line.strip()
                    if line:
                        yield json.loads(line)
        except urllib.error.HTTPError as e:
            raise ApiError(e.code, e.read().decode(errors="replace")[:500])


# ---------------------------------------------------------------------------
# Fake apiserver (tests + local dry runs)
# ---------------------------------------------------------------------------


def _merge(base, patch):
    """RFC 7386 merge-patch."""
    if not isinstance(patch, dict) or not isinstance(base, dict):
        return patch
    out = dict(base)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = _merge(out.get(k), v)
    return out


class FakeKubeApi:
    """In-memory KubeApi with the apiserver behaviors controllers rely on:
    resourceVersion bumps on every write, finalizer-gated deletion
    (deletionTimestamp until finalizers empty), and a status subresource
    that only touches .status.  Records (verb, path) tuples in ``actions``.
    """

    _EVENT_WINDOW = 4096  # watch history; older resourceVersions get 410

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        # (gv, plural, namespace, name) -> obj dict
        self._objs: dict[tuple, dict] = {}
        self._rv = 0
        # Watch event log: (rv, type, key, obj snapshot), bounded window.
        self._events: list[tuple[int, str, tuple, dict]] = []
        self.actions: list[tuple[str, str]] = []
        # TokenReview double: bearer tokens token_review() accepts.
        self.valid_tokens: set[str] = set()

    def token_review(self, token: str) -> bool:
        return token in self.valid_tokens

    def _key(self, gv, plural, namespace, name):
        return (gv, plural, namespace or "", name)

    def _bump(self, obj: dict) -> None:
        self._rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)

    def _emit_event(self, typ: str, key: tuple, obj: dict) -> None:
        """Record a watch event (caller holds the lock, obj already
        bumped — DELETED events carry the last seen object)."""
        self._events.append((self._rv, typ, key, json.loads(json.dumps(obj))))
        if len(self._events) > self._EVENT_WINDOW:
            del self._events[: len(self._events) - self._EVENT_WINDOW]
        self._cond.notify_all()

    def _record(self, verb, gv, plural, namespace, name=""):
        self.actions.append((verb, f"{gv}/{plural}/{namespace or ''}/{name}"))

    def list(self, gv, plural, namespace=None) -> list[dict]:
        with self._lock:
            self._record("list", gv, plural, namespace)
            return [json.loads(json.dumps(o)) for (g, p, ns, _), o
                    in sorted(self._objs.items())
                    if g == gv and p == plural
                    and (namespace is None or ns == namespace)]

    def watch(self, gv, plural, namespace=None, since_rv=0,
              timeout_s: float = 30.0):
        """Yield {'type', 'object'} events newer than ``since_rv`` until
        ``timeout_s`` passes with nothing new (apiserver watch semantics;
        the caller reopens with the last seen resourceVersion).  Raises
        410 when ``since_rv`` predates the retained window — the caller
        must relist."""
        with self._lock:
            self._record("watch", gv, plural, namespace)
        last = int(since_rv)
        deadline = time.monotonic() + timeout_s
        while True:
            with self._cond:
                if (last and self._events
                        and last < self._events[0][0] - 1):
                    raise ApiError(410, "resourceVersion too old")
                batch = [
                    (rv, typ, obj) for rv, typ, (g, p, ns, _), obj
                    in self._events
                    if rv > last and g == gv and p == plural
                    and (namespace is None or ns == namespace)]
                if not batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return
                    self._cond.wait(remaining)
                    continue
            for rv, typ, obj in batch:
                last = max(last, rv)
                yield {"type": typ, "object": obj}

    def get(self, gv, plural, namespace, name) -> dict | None:
        with self._lock:
            obj = self._objs.get(self._key(gv, plural, namespace, name))
            return json.loads(json.dumps(obj)) if obj else None

    def create(self, gv, plural, namespace, obj) -> dict:
        with self._lock:
            name = obj["metadata"]["name"]
            key = self._key(gv, plural, namespace, name)
            if key in self._objs:
                raise ApiError(409, f"{plural}/{name} already exists")
            stored = json.loads(json.dumps(obj))
            stored["metadata"].setdefault("namespace", namespace)
            self._bump(stored)
            self._objs[key] = stored
            self._record("create", gv, plural, namespace, name)
            self._emit_event("ADDED", key, stored)
            return json.loads(json.dumps(stored))

    def patch(self, gv, plural, namespace, name, patch, subresource=None) -> dict:
        with self._lock:
            key = self._key(gv, plural, namespace, name)
            obj = self._objs.get(key)
            if obj is None:
                raise ApiError(404, f"{plural}/{name} not found")
            if subresource == "status":
                obj["status"] = _merge(obj.get("status", {}),
                                       patch.get("status", patch))
            else:
                merged = _merge(obj, patch)
                merged["metadata"]["name"] = name  # immutable
                # Emulate the controller-manager: a StatefulSet template
                # change restarts pods, so readiness drops until the test
                # (playing kubelet) marks the new revision ready again.
                if (plural == "statefulsets"
                        and "template" in (patch.get("spec") or {})):
                    merged.setdefault("status", {})["readyReplicas"] = 0
                self._objs[key] = obj = merged
            self._bump(obj)
            self._record(f"patch{':' + subresource if subresource else ''}",
                         gv, plural, namespace, name)
            self._emit_event("MODIFIED", key, obj)
            self._maybe_finish_delete(key)
            return json.loads(json.dumps(self._objs[key])) \
                if key in self._objs else {}

    def replace(self, gv, plural, namespace, name, obj) -> dict:
        with self._lock:
            key = self._key(gv, plural, namespace, name)
            cur = self._objs.get(key)
            if cur is None:
                raise ApiError(404, f"{plural}/{name} not found")
            # Optimistic concurrency (real apiserver semantics): a PUT
            # carrying a stale resourceVersion is a 409.  Leader election
            # depends on this — two contenders replacing the same Lease
            # must not both win.  A missing rv skips the check (legacy
            # callers).
            sent_rv = str(obj.get("metadata", {}).get("resourceVersion", "")
                          or "")
            cur_rv = str(cur.get("metadata", {}).get("resourceVersion", ""))
            if sent_rv and sent_rv != cur_rv:
                raise ApiError(
                    409, f"{plural}/{name}: resourceVersion conflict "
                         f"(sent {sent_rv}, current {cur_rv})")
            stored = json.loads(json.dumps(obj))
            stored["metadata"]["name"] = name
            stored["metadata"].setdefault("namespace", namespace)
            # PUT on the main resource keeps status (status subresource).
            if "status" in cur:
                old_tmpl = (cur.get("spec") or {}).get("template")
                stored["status"] = cur["status"]
                # Emulate the controller-manager: template change restarts
                # pods (see patch()).
                if (plural == "statefulsets"
                        and (stored.get("spec") or {}).get("template") != old_tmpl):
                    stored["status"]["readyReplicas"] = 0
            self._bump(stored)
            self._objs[key] = stored
            self._record("replace", gv, plural, namespace, name)
            self._emit_event("MODIFIED", key, stored)
            return json.loads(json.dumps(stored))

    def delete(self, gv, plural, namespace, name) -> None:
        with self._lock:
            key = self._key(gv, plural, namespace, name)
            obj = self._objs.get(key)
            if obj is None:
                return
            self._record("delete", gv, plural, namespace, name)
            if obj["metadata"].get("finalizers"):
                obj["metadata"]["deletionTimestamp"] = "now"
                self._bump(obj)
                self._emit_event("MODIFIED", key, obj)
            else:
                # Stamp the deletion's OWN resourceVersion on the event
                # object — watchers resume from the event object's rv, and
                # a stale rv would redeliver the DELETED event forever.
                self._bump(obj)
                self._emit_event("DELETED", key, obj)
                del self._objs[key]

    def _maybe_finish_delete(self, key) -> None:
        obj = self._objs.get(key)
        if (obj is not None and obj["metadata"].get("deletionTimestamp")
                and not obj["metadata"].get("finalizers")):
            self._bump(obj)
            self._emit_event("DELETED", key, obj)
            del self._objs[key]


# ---------------------------------------------------------------------------
# Fake apiserver over HTTP (the cluster-e2e tier without a cluster)
# ---------------------------------------------------------------------------


class FakeApiServer:
    """Serve a FakeKubeApi over real HTTP with apiserver-shaped REST paths.

    This is the e2e tier the reference gets from a Kind cluster
    (test/e2e/e2e_test.go): the REAL ``KubeApi`` client — URL building,
    merge-patch content types, status subresource routing, error mapping —
    exercises the same wire protocol it speaks to a production apiserver,
    against in-memory state.  Also runnable standalone for local dry runs:
    ``python -m arks_tpu.control.k8s_client --port 8001``.
    """

    def __init__(self, fake: "FakeKubeApi | None" = None,
                 host: str = "127.0.0.1", port: int = 0):
        import http.server

        self.fake = fake or FakeKubeApi()
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, code: int, payload) -> None:
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def _route(self, method: str) -> None:
                try:
                    parsed = server._parse(self.path)
                except ValueError as e:
                    return self._send(400, {"message": str(e)})
                query = urllib.parse.parse_qs(
                    urllib.parse.urlsplit(self.path).query)
                if (method == "GET" and query.get("watch", ["0"])[0] == "1"
                        and parsed[3] is None):
                    return self._stream_watch(parsed, query)
                try:
                    code, payload = server._dispatch(method, *parsed,
                                                     body=self._body()
                                                     if method in ("POST", "PATCH", "PUT")
                                                     else None)
                except ApiError as e:
                    return self._send(e.status, {"message": str(e)})
                self._send(code, payload)

            def _stream_watch(self, parsed, query) -> None:
                """apiserver watch semantics: chunked JSON lines of
                {'type', 'object'} events, held open until timeoutSeconds."""
                gv, plural, namespace, _, _ = parsed
                since = int(query.get("resourceVersion", ["0"])[0] or 0)
                timeout = float(query.get("timeoutSeconds", ["30"])[0])
                events = server.fake.watch(gv, plural, namespace,
                                           since_rv=since,
                                           timeout_s=timeout)
                # Pull the FIRST event (or the 410) before committing to a
                # 200 — the generator only validates since_rv lazily, and
                # an error after send_response would corrupt the chunk
                # stream with a second status line.
                try:
                    first = next(events, None)
                except ApiError as e:
                    return self._send(e.status, {"message": str(e)})
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()

                    def emit(ev) -> None:
                        data = json.dumps(ev).encode() + b"\n"
                        self.wfile.write(f"{len(data):x}\r\n".encode()
                                         + data + b"\r\n")
                        self.wfile.flush()

                    if first is not None:
                        emit(first)
                        for ev in events:
                            emit(ev)
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except ApiError as e:
                    # Mid-stream expiry: apiserver semantics — an ERROR
                    # event in the 200 stream, never a second status line.
                    try:
                        emit({"type": "ERROR",
                              "object": {"code": e.status,
                                         "message": str(e)}})
                        self.wfile.write(b"0\r\n\r\n")
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError):
                        pass
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_GET(self):
                self._route("GET")

            def do_POST(self):
                self._route("POST")

            def do_PATCH(self):
                self._route("PATCH")

            def do_PUT(self):
                self._route("PUT")

            def do_DELETE(self):
                self._route("DELETE")

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="fake-apiserver", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()  # release the listening socket

    # -- path + dispatch -----------------------------------------------

    @staticmethod
    def _parse(path: str):
        """/api/v1/... or /apis/<group>/<version>/... ->
        (gv, plural, namespace, name, subresource)."""
        parts = [p for p in path.split("?")[0].split("/") if p]
        if not parts:
            raise ValueError("empty path")
        if parts[0] == "api":
            if len(parts) < 2:
                raise ValueError(f"bad path {path}")
            gv, rest = parts[1], parts[2:]
        elif parts[0] == "apis":
            if len(parts) < 3:
                raise ValueError(f"bad path {path}")
            gv, rest = f"{parts[1]}/{parts[2]}", parts[3:]
        else:
            raise ValueError(f"bad path {path}")
        namespace = None
        if rest[:1] == ["namespaces"] and len(rest) >= 2:
            namespace, rest = rest[1], rest[2:]
        if not rest:
            raise ValueError(f"no resource in {path}")
        plural, rest = rest[0], rest[1:]
        name = rest[0] if rest else None
        sub = rest[1] if len(rest) > 1 else None
        return gv, plural, namespace, name, sub

    def _dispatch(self, method, gv, plural, namespace, name, sub, body):
        f = self.fake
        if method == "GET" and name is None:
            return 200, {"kind": "List", "items": f.list(gv, plural, namespace)}
        if method == "GET":
            obj = f.get(gv, plural, namespace, name)
            if obj is None:
                raise ApiError(404, f"{plural}/{name} not found")
            return 200, obj
        if method == "POST":
            if plural == "tokenreviews":
                # Nameless review resource: answered, never stored.
                tok = (body or {}).get("spec", {}).get("token", "")
                return 201, {"apiVersion": gv, "kind": "TokenReview",
                             "status": {"authenticated":
                                        f.token_review(tok)}}
            return 201, f.create(gv, plural, namespace, body)
        if method == "PATCH":
            return 200, f.patch(gv, plural, namespace, name, body,
                                subresource=sub)
        if method == "PUT":
            return 200, f.replace(gv, plural, namespace, name, body)
        if method == "DELETE":
            # A real apiserver 404s a missing object — the client's
            # delete-swallows-404 branch must see the real status code.
            if f.get(gv, plural, namespace, name) is None:
                raise ApiError(404, f"{plural}/{name} not found")
            f.delete(gv, plural, namespace, name)
            return 200, {"status": "Success"}
        raise ApiError(405, f"method {method}")


def main() -> None:
    import argparse
    import time as _time

    p = argparse.ArgumentParser(
        "arks_tpu.control.k8s_client",
        description="Standalone fake apiserver for local dry runs")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8001)
    args = p.parse_args()
    srv = FakeApiServer(host=args.host, port=args.port)
    srv.start()
    print(f"fake apiserver on {srv.url}")
    try:
        while True:
            _time.sleep(1)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
