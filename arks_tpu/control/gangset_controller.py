"""GangSet controller: converges gang workloads via a driver and publishes
group status — the role LWS/RBGS operators play for the reference
(SURVEY.md §1 external deps)."""

from __future__ import annotations

import logging

from arks_tpu.control.reconciler import Controller, Result
from arks_tpu.control.resources import GangSet
from arks_tpu.control.store import Store
from arks_tpu.control.workloads import GangDriver

log = logging.getLogger("arks_tpu.control.gangset")


class GangSetController(Controller):
    KIND = GangSet
    FINALIZER = "gangset.arks.ai/controller"
    RESYNC_S = 1.0  # liveness poll; groups can die between events

    def __init__(self, store: Store, driver: GangDriver, workers: int = 2):
        super().__init__(store, workers=workers)
        self.driver = driver

    def reconcile(self, gs: GangSet) -> Result | None:
        self.driver.ensure(gs)
        st = self.driver.status(gs)
        if st != {k: gs.status.get(k) for k in st}:
            gs.status.update(st)
            self.store.update_status(gs)
        # Keep polling: process death must flip readiness without an event.
        return Result(requeue_after=self.RESYNC_S)

    def finalize(self, gs: GangSet) -> None:
        self.driver.teardown(gs)
