"""Autoscaler: replica scaling driven by gateway request rates.

The reference defers autoscaling entirely to Kubernetes HPA over the
gateway/runtime Prometheus metrics (SURVEY.md §7 step 7); it ships no
autoscaling code.  The TPU build covers both deployment shapes:

- **K8s / live-operator**: ``deploy/hpa.yaml`` — a standard HPA over the
  gateway's ``gateway_requests_total`` rate via prometheus-adapter, scaling
  ``Application.spec.replicas`` through the CRD's scale-like semantics.
- **Local single-binary** (this module): the operator closes the loop
  natively.  ``Application.spec.autoscale``:

  .. code-block:: yaml

      autoscale:
        minReplicas: 1
        maxReplicas: 4
        targetRPMPerReplica: 120          # admitted requests/min/replica
        scaleDownStabilizationSeconds: 60 # damping, HPA-style

  Each tick reads the embedded gateway's per-endpoint admitted-request
  rate, computes ``ceil(rpm / target)`` clamped to [min, max], scales UP
  immediately and DOWN only after the demand has stayed low for the
  stabilization window (flap damping — the same asymmetry HPA defaults
  to, since a cold replica group pays model-load time).
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Callable

from arks_tpu.control.reconciler import Controller, Result
from arks_tpu.control.resources import Application

log = logging.getLogger("arks_tpu.control.autoscaler")

# rate_source(namespace, served_model_name) -> requests per minute.
RateSource = Callable[[str, str], float]


class AutoscalerController(Controller):
    KIND = Application

    def __init__(self, store, rate_source: RateSource,
                 interval_s: float = 10.0):
        super().__init__(store, workers=1)
        self.rate_source = rate_source
        self.interval_s = interval_s
        # (ns, name) -> monotonic time the demand first dropped below the
        # current replica count (scale-down stabilization clock).
        self._below_since: dict[tuple[str, str], float] = {}
        # (ns, name) -> last status written (suppress no-op status churn:
        # each write fires a watch event that wakes every Application
        # watcher, so continuous observedRPM jitter must not write).
        self._last_status: dict[tuple[str, str], dict] = {}
        self._ticker: threading.Thread | None = None

    # Periodic evaluation runs off a DEDICATED ticker, not Result requeues:
    # a self-requeue per reconcile compounds with watch-triggered reconciles
    # (our own status writes included) into an ever-growing stream of
    # delayed queue entries — measured 13x the configured rate before this
    # design.  The ticker enqueues each autoscaled app once per interval;
    # watch events still give immediate reaction to spec edits.
    def start(self) -> None:
        super().start()

        def tick() -> None:
            while self._running:
                time.sleep(self.interval_s)
                try:
                    for app in self.store.list(Application):
                        if app.spec.get("autoscale"):
                            self.queue.add(app.key)
                except Exception:
                    log.exception("autoscaler tick failed")

        self._ticker = threading.Thread(target=tick, name="autoscaler-tick",
                                        daemon=True)
        self._ticker.start()
        self._threads.append(self._ticker)

    def finalize(self, app: Application) -> None:
        self._below_since.pop(app.key, None)
        self._last_status.pop(app.key, None)

    def _demand_share(self, app: Application) -> float:
        """This app's share of the endpoint's demand.  The endpoint
        controller routes one served name across every SERVING backend —
        standalone or disaggregated — with equal default weights
        (endpoint_controller), so each backend sees total/N.  Peers are
        counted by the same serving() rule the router uses: a crash-looping
        peer takes no traffic and must not dilute this app's share."""
        from arks_tpu.control.resources import DisaggregatedApplication
        served = app.served_model_name
        total = float(self.rate_source(app.namespace, served))
        peers = 0
        for kind in (Application, DisaggregatedApplication):
            for a in self.store.list(kind, namespace=app.namespace):
                if a.served_model_name == served and a.serving():
                    peers += 1
        # A not-yet-serving SELF joins the rotation the moment it comes up,
        # so it counts toward its own divisor — otherwise a freshly created
        # peer briefly sees the whole endpoint's demand and over-scales
        # until the scale-down window corrects it.
        if not app.serving():
            peers += 1
        return total / max(peers, 1)

    def reconcile(self, app: Application) -> Result | None:
        au = app.spec.get("autoscale")
        if not au:
            self._below_since.pop(app.key, None)
            self._last_status.pop(app.key, None)
            return None
        lo = max(au.get("minReplicas", 1), 0)
        hi = max(au.get("maxReplicas", lo), lo)
        target = max(au.get("targetRPMPerReplica", 60), 1)
        rpm = self._demand_share(app)
        cur = app.spec.get("replicas", 1)
        desired = min(hi, max(lo, math.ceil(rpm / target)))

        now = time.monotonic()
        if desired > cur:
            # Scale up immediately: under-provisioning is user-visible.
            self._below_since.pop(app.key, None)
            self._scale(app, desired, rpm)
            return None
        if desired < cur:
            stab = au.get("scaleDownStabilizationSeconds", 60)
            since = self._below_since.setdefault(app.key, now)
            if now - since >= stab:
                self._scale(app, desired, rpm)
                self._below_since.pop(app.key, None)
            return None
        self._below_since.pop(app.key, None)
        status = {"observedRPM": round(rpm, 1), "desiredReplicas": desired}
        last = self._last_status.get(app.key)
        # Write only on a meaningful change (desired flip, or rpm moved by
        # >10% or >1): jitter-driven writes would storm every watcher.
        if last is None or last["desiredReplicas"] != desired or (
                abs(last["observedRPM"] - status["observedRPM"])
                > max(1.0, 0.1 * max(last["observedRPM"], 1.0))):
            app.status["autoscale"] = status
            self.store.update_status(app)
            self._last_status[app.key] = status
        return None

    def _scale(self, app: Application, desired: int, rpm: float) -> None:
        log.info("autoscale %s/%s: rpm=%.1f replicas %d -> %d",
                 app.namespace, app.name, rpm,
                 app.spec.get("replicas", 1), desired)
        app.spec["replicas"] = desired
        status = {"observedRPM": round(rpm, 1), "desiredReplicas": desired}
        app.status["autoscale"] = status
        self._last_status[app.key] = status
        # Spec write wakes the ApplicationController, which resizes the
        # GangSet; a Conflict (someone else wrote first) retries via the
        # workqueue's error backoff against the fresh object.
        self.store.update(app)
