"""Autoscaler: replica scaling driven by gateway request rates.

The reference defers autoscaling entirely to Kubernetes HPA over the
gateway/runtime Prometheus metrics (SURVEY.md §7 step 7); it ships no
autoscaling code.  The TPU build covers both deployment shapes:

- **K8s / live-operator**: ``deploy/hpa.yaml`` — a standard HPA over the
  gateway's ``gateway_requests_total`` rate via prometheus-adapter, scaling
  ``Application.spec.replicas`` through the CRD's scale-like semantics.
- **Local single-binary** (this module): the operator closes the loop
  natively.  ``Application.spec.autoscale``:

  .. code-block:: yaml

      autoscale:
        minReplicas: 1
        maxReplicas: 4
        targetRPMPerReplica: 120          # admitted requests/min/replica
        scaleDownStabilizationSeconds: 60 # damping, HPA-style

  Each tick reads the embedded gateway's per-endpoint admitted-request
  rate, computes ``ceil(rpm / target)`` clamped to [min, max], scales UP
  immediately and DOWN only after the demand has stayed low for the
  stabilization window (flap damping — the same asymmetry HPA defaults
  to, since a cold replica group pays model-load time).

**Signals mode** (the elastic control loop): when constructed with a
``signals_source``, scaling is driven by LIVE overload evidence instead
of raw RPM — the per-tier SLO burn rate and admission-queue saturation
each backend exports on ``/readiness`` (engine.slo_burn / saturation).
One replica is added when any signal crosses its high-water mark
(ARKS_ELASTIC_BURN_HI / ARKS_ELASTIC_SAT_HI) and removed when EVERY
signal sits under its low-water mark (..._LO) — hysteresis, so a signal
oscillating between the marks holds the current shape.  Actions are
rate-limited by ARKS_ELASTIC_COOLDOWN_S (scale-up FROM ZERO is exempt:
an SLO burn against zero armed replicas is exactly the situation the
cooldown must not sit out), and scale-down still honors the
stabilization window on top of the cooldown.  An optional ``actuator``
callback fires on each scaling decision so a deployment can do the
elastic work inline (re-arm a scaled-to-zero replica via
POST /v1/elastic/resize, then Router.plan_join it).
"""

from __future__ import annotations

import http.client
import json
import logging
import math
import threading
import time
from typing import Callable

from arks_tpu.control.reconciler import Controller, Result
from arks_tpu.control.resources import Application
from arks_tpu.utils import knobs

log = logging.getLogger("arks_tpu.control.autoscaler")

# rate_source(namespace, served_model_name) -> requests per minute.
RateSource = Callable[[str, str], float]
# signals_source(namespace, served_model_name) -> signal dict or None
# (no data this tick).  Keys: "burn" (max per-tier SLO burn across
# serving backends), "saturation" (max admission saturation, 0-1);
# optional "ready" / "disarmed" backend counts ride into status.
SignalsSource = Callable[[str, str], "dict | None"]


def scrape_signals(addr: str, timeout: float = 2.0) -> dict | None:
    """One backend's autoscaler signals from its /readiness: admission
    saturation, worst per-tier SLO burn, armed state.  A 503 still
    yields a row (ready=False, disarmed for scaled-to-zero replicas);
    None means unreachable."""
    host, _, port = addr.partition(":")
    try:
        conn = http.client.HTTPConnection(host, int(port or 80),
                                          timeout=timeout)
        try:
            conn.request("GET", "/readiness")
            resp = conn.getresponse()
            status = resp.status
            data = resp.read()
        finally:
            conn.close()
    except (OSError, http.client.HTTPException, ValueError):
        return None
    try:
        obj = json.loads(data)
    except (ValueError, UnicodeDecodeError):
        obj = {}
    if not isinstance(obj, dict):
        obj = {}
    if status != 200:
        reason = str((obj.get("error") or {}).get("message", ""))
        return {"ready": False, "saturation": 0.0, "burn": 0.0,
                "disarmed": "disarmed" in reason, "reason": reason}
    adm = obj.get("admission") or {}
    burns = obj.get("slo_burn") or {}
    elastic = obj.get("elastic") or {}
    return {"ready": True,
            "saturation": float(adm.get("saturation", 0.0) or 0.0),
            "burn": max((float(v) for v in burns.values()), default=0.0),
            "disarmed": not elastic.get("armed", True),
            "reason": ""}


def fleet_signals(addrs: list[str]) -> dict | None:
    """Merge scrape_signals over a backend list into one signal dict
    (the stock ``signals_source`` for address-list deployments): worst
    burn/saturation across READY backends, plus ready/disarmed counts.
    None when no backend answered at all."""
    rows = [s for s in (scrape_signals(a) for a in addrs) if s is not None]
    if not rows:
        return None
    ready = [r for r in rows if r["ready"]]
    return {"burn": max((r["burn"] for r in ready), default=0.0),
            "saturation": max((r["saturation"] for r in ready),
                              default=0.0),
            "ready": len(ready),
            "disarmed": sum(1 for r in rows if r.get("disarmed"))}


class AutoscalerController(Controller):
    KIND = Application

    def __init__(self, store, rate_source: RateSource,
                 interval_s: float = 10.0,
                 signals_source: SignalsSource | None = None,
                 actuator=None):
        super().__init__(store, workers=1)
        self.rate_source = rate_source
        self.interval_s = interval_s
        self.signals_source = signals_source
        # actuator(app, desired, signals) — inline elastic action hook
        # (re-arm + planned join); failures log, never derail reconcile.
        self.actuator = actuator
        # (ns, name) -> monotonic time of the last signals-mode scaling
        # action (the ARKS_ELASTIC_COOLDOWN_S clock).
        self._last_action: dict[tuple[str, str], float] = {}
        # (ns, name) -> monotonic time the demand first dropped below the
        # current replica count (scale-down stabilization clock).
        self._below_since: dict[tuple[str, str], float] = {}
        # (ns, name) -> last status written (suppress no-op status churn:
        # each write fires a watch event that wakes every Application
        # watcher, so continuous observedRPM jitter must not write).
        self._last_status: dict[tuple[str, str], dict] = {}
        self._ticker: threading.Thread | None = None

    # Periodic evaluation runs off a DEDICATED ticker, not Result requeues:
    # a self-requeue per reconcile compounds with watch-triggered reconciles
    # (our own status writes included) into an ever-growing stream of
    # delayed queue entries — measured 13x the configured rate before this
    # design.  The ticker enqueues each autoscaled app once per interval;
    # watch events still give immediate reaction to spec edits.
    def start(self) -> None:
        super().start()

        def tick() -> None:
            while self._running:
                time.sleep(self.interval_s)
                try:
                    for app in self.store.list(Application):
                        if app.spec.get("autoscale"):
                            self.queue.add(app.key)
                except Exception:
                    log.exception("autoscaler tick failed")

        self._ticker = threading.Thread(target=tick, name="autoscaler-tick",
                                        daemon=True)
        self._ticker.start()
        self._threads.append(self._ticker)

    def finalize(self, app: Application) -> None:
        self._below_since.pop(app.key, None)
        self._last_status.pop(app.key, None)
        self._last_action.pop(app.key, None)

    def _demand_share(self, app: Application) -> float:
        """This app's share of the endpoint's demand.  The endpoint
        controller routes one served name across every SERVING backend —
        standalone or disaggregated — with equal default weights
        (endpoint_controller), so each backend sees total/N.  Peers are
        counted by the same serving() rule the router uses: a crash-looping
        peer takes no traffic and must not dilute this app's share."""
        from arks_tpu.control.resources import DisaggregatedApplication
        served = app.served_model_name
        total = float(self.rate_source(app.namespace, served))
        peers = 0
        for kind in (Application, DisaggregatedApplication):
            for a in self.store.list(kind, namespace=app.namespace):
                if a.served_model_name == served and a.serving():
                    peers += 1
        # A not-yet-serving SELF joins the rotation the moment it comes up,
        # so it counts toward its own divisor — otherwise a freshly created
        # peer briefly sees the whole endpoint's demand and over-scales
        # until the scale-down window corrects it.
        if not app.serving():
            peers += 1
        return total / max(peers, 1)

    def reconcile(self, app: Application) -> Result | None:
        au = app.spec.get("autoscale")
        if not au:
            self._below_since.pop(app.key, None)
            self._last_status.pop(app.key, None)
            self._last_action.pop(app.key, None)
            return None
        lo = max(au.get("minReplicas", 1), 0)
        hi = max(au.get("maxReplicas", lo), lo)
        if self.signals_source is not None and au.get("signals", True):
            return self._reconcile_signals(app, au, lo, hi)
        target = max(au.get("targetRPMPerReplica", 60), 1)
        rpm = self._demand_share(app)
        cur = app.spec.get("replicas", 1)
        desired = min(hi, max(lo, math.ceil(rpm / target)))

        now = time.monotonic()
        if desired > cur:
            # Scale up immediately: under-provisioning is user-visible.
            self._below_since.pop(app.key, None)
            self._scale(app, desired, rpm)
            return None
        if desired < cur:
            stab = au.get("scaleDownStabilizationSeconds", 60)
            since = self._below_since.setdefault(app.key, now)
            if now - since >= stab:
                self._scale(app, desired, rpm)
                self._below_since.pop(app.key, None)
            return None
        self._below_since.pop(app.key, None)
        status = {"observedRPM": round(rpm, 1), "desiredReplicas": desired}
        last = self._last_status.get(app.key)
        # Write only on a meaningful change (desired flip, or rpm moved by
        # >10% or >1): jitter-driven writes would storm every watcher.
        if last is None or last["desiredReplicas"] != desired or (
                abs(last["observedRPM"] - status["observedRPM"])
                > max(1.0, 0.1 * max(last["observedRPM"], 1.0))):
            app.status["autoscale"] = status
            self.store.update_status(app)
            self._last_status[app.key] = status
        return None

    def _scale(self, app: Application, desired: int, rpm: float) -> None:
        log.info("autoscale %s/%s: rpm=%.1f replicas %d -> %d",
                 app.namespace, app.name, rpm,
                 app.spec.get("replicas", 1), desired)
        app.spec["replicas"] = desired
        status = {"observedRPM": round(rpm, 1), "desiredReplicas": desired}
        app.status["autoscale"] = status
        self._last_status[app.key] = status
        # Spec write wakes the ApplicationController, which resizes the
        # GangSet; a Conflict (someone else wrote first) retries via the
        # workqueue's error backoff against the fresh object.
        self.store.update(app)

    # ---- signals mode (elastic control loop) -------------------------

    def _reconcile_signals(self, app: Application, au: dict,
                           lo: int, hi: int) -> Result | None:
        sig = self.signals_source(app.namespace, app.served_model_name)
        if sig is None:
            # No backend answered this tick: hold shape — scaling on
            # missing evidence is how control loops flap a fleet.
            return None
        burn = float(sig.get("burn", 0.0))
        sat = float(sig.get("saturation", 0.0))
        cur = app.spec.get("replicas", 1)
        now = time.monotonic()
        cooldown = knobs.get_float("ARKS_ELASTIC_COOLDOWN_S")
        last = self._last_action.get(app.key)
        # Hysteresis: up when ANY signal crosses its high-water mark,
        # down only when EVERY signal sits under its low-water mark;
        # the band between holds the current shape.
        up = (burn >= knobs.get_float("ARKS_ELASTIC_BURN_HI")
              or sat >= knobs.get_float("ARKS_ELASTIC_SAT_HI"))
        down = (burn <= knobs.get_float("ARKS_ELASTIC_BURN_LO")
                and sat <= knobs.get_float("ARKS_ELASTIC_SAT_LO"))
        desired = cur
        reason = "steady"
        if up:
            desired, reason = min(hi, cur + 1), "signal_high"
        elif down:
            desired, reason = max(lo, cur - 1), "signal_low"
        if desired > cur:
            self._below_since.pop(app.key, None)
            # Cooldown damps action flapping — EXCEPT scale-up from
            # zero: an SLO burn against zero armed replicas is exactly
            # what the loop exists to rescue, immediately.
            if cur > 0 and last is not None and now - last < cooldown:
                self._write_signals_status(app, cur, burn, sat,
                                           "cooldown", sig)
                return None
            self._last_action[app.key] = now
            self._scale_signals(app, desired, burn, sat, reason, sig)
            return None
        if desired < cur:
            stab = au.get("scaleDownStabilizationSeconds", 60)
            since = self._below_since.setdefault(app.key, now)
            if now - since < stab or (
                    last is not None and now - last < cooldown):
                self._write_signals_status(app, cur, burn, sat,
                                           "stabilizing", sig)
                return None
            self._below_since.pop(app.key, None)
            self._last_action[app.key] = now
            self._scale_signals(app, desired, burn, sat, reason, sig)
            return None
        self._below_since.pop(app.key, None)
        self._write_signals_status(app, desired, burn, sat, reason, sig)
        return None

    def _signals_status(self, desired: int, burn: float, sat: float,
                        reason: str, sig: dict) -> dict:
        status = {"mode": "signals", "desiredReplicas": desired,
                  "burnRate": round(burn, 3), "saturation": round(sat, 3),
                  "reason": reason}
        for k in ("ready", "disarmed"):
            if k in sig:
                status[k] = sig[k]
        return status

    def _write_signals_status(self, app: Application, desired: int,
                              burn: float, sat: float, reason: str,
                              sig: dict) -> None:
        status = self._signals_status(desired, burn, sat, reason, sig)
        last = self._last_status.get(app.key)
        # Same churn guard as RPM mode: only a meaningful move writes
        # (desired/reason flip, or a signal moved past jitter).
        if last is not None and last.get("desiredReplicas") == desired \
                and last.get("reason") == reason \
                and abs(last.get("burnRate", 0.0) - status["burnRate"]) \
                <= max(0.05, 0.1 * max(last.get("burnRate", 0.0), 0.0)) \
                and abs(last.get("saturation", 0.0)
                        - status["saturation"]) <= 0.05:
            return
        app.status["autoscale"] = status
        self.store.update_status(app)
        self._last_status[app.key] = status

    def _scale_signals(self, app: Application, desired: int, burn: float,
                       sat: float, reason: str, sig: dict) -> None:
        log.info("autoscale(signals) %s/%s: burn=%.2f sat=%.2f "
                 "replicas %d -> %d (%s)", app.namespace, app.name,
                 burn, sat, app.spec.get("replicas", 1), desired, reason)
        app.spec["replicas"] = desired
        status = self._signals_status(desired, burn, sat, reason, sig)
        app.status["autoscale"] = status
        self._last_status[app.key] = status
        self.store.update(app)
        if self.actuator is not None:
            # Inline elastic action (re-arm a scaled-to-zero replica,
            # planned join) — best-effort: the spec write above already
            # converges the deployment even if this hook fails.
            try:
                self.actuator(app, desired, dict(sig))
            except Exception:
                log.exception("elastic actuator failed for %s/%s",
                              app.namespace, app.name)
