"""Resource schemas: the six Arks resource kinds, TPU-native.

Mirrors the semantics of the reference CRDs (/root/reference/api/v1/
*_types.go) — same kinds, phases, conditions, and label keys — with
TPU-specific spec fields where the reference had GPU-isms:

- Application.runtime gains ``jax`` (reference: vllm/sglang/dynamo,
  arksapplication_types.go:46-49); ``accelerator`` ("tpu-v5e-8", "cpu", ...)
  replaces nvidia.com/gpu resource requests; ``tensor_parallel`` maps to a
  real mesh axis (not a flag passthrough).
- Model storage is a local/NFS directory standing in for the PVC (same
  reserved read-only "/models" mount contract, arksapplication_types.go:52-54),
  plus an optional Orbax conversion step (BASELINE.json north star).

Resources serialize to/from plain dicts (YAML/JSON-shaped) so manifests look
and feel like the reference's CRs.
"""

from __future__ import annotations

import copy
import dataclasses
import time
from typing import Any

# Label keys (reference: api/v1/arksapplication_types.go:56-67).
LABEL_MANAGED_BY = "arks.ai/managed-by"
LABEL_APPLICATION = "arks.ai/application"
LABEL_MODEL = "arks.ai/model"
LABEL_ROLE = "arks.ai/role"
LABEL_COMPONENT = "arks.ai/component"
MANAGED_BY = "arks-tpu"

# Reserved model mount (reference: arksapplication_types.go:52-54 —
# volume "models" mounted read-only at /models in every serving pod).
RESERVED_MODELS_VOLUME = "models"
RESERVED_MODELS_PATH = "/models"

# Runtimes (reference: arksapplication_types.go:46-49 + TPU-native "jax").
RUNTIME_JAX = "jax"
RUNTIME_VLLM = "vllm"
RUNTIME_SGLANG = "sglang"
RUNTIME_DYNAMO = "dynamo"
VALID_RUNTIMES = (RUNTIME_JAX, RUNTIME_VLLM, RUNTIME_SGLANG, RUNTIME_DYNAMO)

# Application phases (reference: arksapplication_types.go:31-37).
PHASE_PENDING = "Pending"
PHASE_CHECKING = "Checking"
PHASE_LOADING = "Loading"
PHASE_CREATING = "Creating"
PHASE_RUNNING = "Running"
PHASE_FAILED = "Failed"

# Application conditions (reference: arksapplication_types.go:40-44).
COND_PRECHECK = "Precheck"
COND_LOADED = "Loaded"
COND_READY = "Ready"

# Model phases (reference: arksmodel_types.go:30-35).
MODEL_PHASE_PENDING = "Pending"
MODEL_PHASE_STORAGE_CREATING = "StorageCreating"
MODEL_PHASE_LOADING = "ModelLoading"
MODEL_PHASE_READY = "Ready"
MODEL_PHASE_FAILED = "Failed"

# Model conditions (reference: arksmodel_types.go:37-45).
COND_STORAGE_CREATED = "StorageCreated"
COND_MODEL_LOADED = "ModelLoaded"

# Rate-limit types (reference: arkstoken_types.go:28-34).
RL_RPM = "rpm"
RL_RPD = "rpd"
RL_TPM = "tpm"
RL_TPD = "tpd"
VALID_RATE_LIMITS = (RL_RPM, RL_RPD, RL_TPM, RL_TPD)

# Quota types (reference: arksquota_types.go:28-33).
QUOTA_PROMPT = "prompt"
QUOTA_RESPONSE = "response"
QUOTA_TOTAL = "total"
VALID_QUOTAS = (QUOTA_PROMPT, QUOTA_RESPONSE, QUOTA_TOTAL)


def now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


@dataclasses.dataclass
class Condition:
    type: str
    status: str            # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: str = dataclasses.field(default_factory=now_iso)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Resource:
    """Base: kind + metadata + spec + status (k8s object shape)."""

    name: str
    namespace: str = "default"
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: dict[str, str] = dataclasses.field(default_factory=dict)
    finalizers: list[str] = dataclasses.field(default_factory=list)
    owner_refs: list[tuple[str, str]] = dataclasses.field(default_factory=list)  # (kind, name)
    deletion_requested: bool = False
    resource_version: int = 0
    spec: dict[str, Any] = dataclasses.field(default_factory=dict)
    status: dict[str, Any] = dataclasses.field(default_factory=dict)

    KIND = "Resource"

    @property
    def key(self) -> tuple[str, str]:
        return (self.namespace, self.name)

    def deepcopy(self):
        return copy.deepcopy(self)

    # -- condition helpers (shared by all kinds, like the reference's
    #    meta.SetStatusCondition usage) --

    def set_condition(self, type_: str, status: bool, reason: str = "",
                      message: str = "") -> None:
        conds = self.status.setdefault("conditions", [])
        val = "True" if status else "False"
        for c in conds:
            if c["type"] == type_:
                if c["status"] != val or c.get("reason") != reason:
                    c.update(status=val, reason=reason, message=message,
                             last_transition_time=now_iso())
                return
        conds.append(Condition(type_, val, reason, message).to_dict())

    def condition(self, type_: str) -> bool:
        for c in self.status.get("conditions", []):
            if c["type"] == type_:
                return c["status"] == "True"
        return False

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": {
                "name": self.name, "namespace": self.namespace,
                "labels": dict(self.labels), "annotations": dict(self.annotations),
                "resourceVersion": self.resource_version,
            },
            "spec": copy.deepcopy(self.spec),
            "status": copy.deepcopy(self.status),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Resource":
        md = d.get("metadata", {})
        return cls(
            name=md["name"], namespace=md.get("namespace", "default"),
            labels=dict(md.get("labels", {})),
            annotations=dict(md.get("annotations", {})),
            spec=copy.deepcopy(d.get("spec", {})),
            status=copy.deepcopy(d.get("status", {})),
        )


# ---------------------------------------------------------------------------
# The six kinds + workload/infra kinds
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Model(Resource):
    """ArksModel: model artifact = storage + download source.

    spec: {model: "Qwen/Qwen2.5-7B-Instruct",
           source: {huggingface: {tokenSecretRef: ...}} | None,
           storage: {path: ..., subPath: ...} | None,
           convertOrbax: bool}
    (reference: arksmodel_types.go:83-101; nil source = pre-existing storage,
    arksmodel_controller.go:355-358)
    """

    KIND = "Model"

    @property
    def phase(self) -> str:
        return self.status.get("phase", MODEL_PHASE_PENDING)


@dataclasses.dataclass
class Application(Resource):
    """ArksApplication: standalone inference service.

    spec: {replicas: int, size: int (hosts per replica group),
           runtime: jax|vllm|sglang|dynamo, runtimeImage: str,
           model: {name: str}, servedModelName: str,
           tensorParallel: int, accelerator: str,
           runtimeCommonArgs: [str], instanceSpec: {...}}
    (reference: arksapplication_types.go:250-300)
    """

    KIND = "Application"

    @property
    def phase(self) -> str:
        return self.status.get("phase", PHASE_PENDING)

    @property
    def served_model_name(self) -> str:
        return self.spec.get("servedModelName") or self.spec.get("model", {}).get("name", "")

    def ready(self) -> bool:
        # reference readiness: Replicas == ReadyReplicas (arksendpoint_controller.go:300)
        want = self.spec.get("replicas", 1)
        return self.status.get("readyReplicas", 0) >= want and want > 0

    def serving(self) -> bool:
        """At least one replica group can take traffic.  Deliberately looser
        than ready(): during a rolling update (maxUnavailable=1) readiness
        dips below spec.replicas, and dropping the whole route then — as the
        reference's Replicas==ReadyReplicas gate does — would turn every
        rollout into an outage.  The route's address list still contains
        only Running groups (Service status sync)."""
        return self.status.get("readyReplicas", 0) >= 1


@dataclasses.dataclass
class DisaggregatedApplication(Resource):
    """ArksDisaggregatedApplication: prefill/decode-separated service.

    spec: {router: {replicas, port}, prefill: {replicas, size, ...},
           decode: {replicas, size, ...}, runtime, model, servedModelName}
    (reference: arksdisaggregatedapplication_types.go:103-148)
    """

    KIND = "DisaggregatedApplication"

    @property
    def phase(self) -> str:
        return self.status.get("phase", PHASE_PENDING)

    @property
    def served_model_name(self) -> str:
        return self.spec.get("servedModelName") or self.spec.get("model", {}).get("name", "")

    def ready(self) -> bool:
        # reference: router>0 & prefill & decode complete
        # (arksendpoint_controller.go:326-333)
        s = self.status
        return (s.get("router", {}).get("readyReplicas", 0) > 0
                and s.get("prefill", {}).get("readyReplicas", 0)
                >= self.spec.get("prefill", {}).get("replicas", 1)
                and s.get("decode", {}).get("readyReplicas", 0)
                >= self.spec.get("decode", {}).get("replicas", 1))

    def serving(self) -> bool:
        """One ready replica in EVERY tier can take traffic — the same
        rolling-update route survival as Application.serving(): readiness
        dips by maxUnavailable=1 during a rollout and dropping the route
        then would make every disagg rollout an outage."""
        s = self.status
        return (s.get("router", {}).get("readyReplicas", 0) >= 1
                and s.get("prefill", {}).get("readyReplicas", 0) >= 1
                and s.get("decode", {}).get("readyReplicas", 0) >= 1)


@dataclasses.dataclass
class Endpoint(Resource):
    """ArksEndpoint: model-name-keyed routing rule.

    spec: {defaultWeight: int, routeConfigs: [{backend: {host, port}, weight}],
           matchConfigs: [...]}
    status: {routes: [{backend, weight}]}
    (reference: arksendpoint_types.go:27-56)
    """

    KIND = "Endpoint"


@dataclasses.dataclass
class Token(Resource):
    """ArksToken: API token with per-endpoint QoS.

    spec: {token: str, qos: [{endpoint: {name, namespace},
           rateLimits: [{type, value}], quota: {name}}]}
    (reference: arkstoken_types.go:46-61)
    """

    KIND = "Token"


@dataclasses.dataclass
class Quota(Resource):
    """ArksQuota: cumulative token-usage budget.

    spec: {quotas: [{type: prompt|response|total, value: int}]}
    status: {quotaStatus: [{type, used, lastUpdateTime}]}
    (reference: arksquota_types.go:47-73)
    """

    KIND = "Quota"


@dataclasses.dataclass
class GangSet(Resource):
    """Gang workload (LeaderWorkerSet equivalent): replicas x size pod
    groups with leader/worker commands and all-or-nothing semantics.

    spec: {replicas, size, leader: {command, env}, worker: {command, env},
           ports: {http: 8080}, restartPolicy: "RecreateGroupOnPodRestart"}
    status: {replicas, readyReplicas, groups: [{index, phase, leaderAddr}]}
    """

    KIND = "GangSet"


@dataclasses.dataclass
class Service(Resource):
    """Service record: stable name -> backend addresses.

    spec: {selector: {...}, port: int}
    status: {addresses: ["host:port", ...]}
    (reference creates Service arks-application-<name>:8080 —
    arksapplication_controller.go:376-415)
    """

    KIND = "Service"


ALL_KINDS = [Model, Application, DisaggregatedApplication, Endpoint, Token,
             Quota, GangSet, Service]
KIND_BY_NAME = {k.KIND: k for k in ALL_KINDS}
