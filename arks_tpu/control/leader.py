"""Leader election over ``coordination.k8s.io/v1`` Leases.

The reference manager runs controller-runtime leader election with lease id
``e4ada7ad.arks.ai`` (/root/reference/cmd/main.go:198-216) so a second
operator replica idles until the holder dies.  Same protocol here:

- ONE Lease object; the holder renews ``renewTime`` every ``retry_period_s``.
- A contender takes over when the lease is unheld or ``renewTime +
  leaseDurationSeconds`` has passed, via a resourceVersion-fenced PUT —
  the apiserver's optimistic concurrency guarantees a single winner.
- Graceful shutdown RELEASES the lease (empty holderIdentity) so the
  standby takes over immediately instead of waiting out the duration.

The elector only flips a flag and fires callbacks; what "leading" means
(start/stop the reconcile machinery) belongs to the caller (LiveOperator).
"""

from __future__ import annotations

import datetime
import logging
import os
import socket
import threading
import time
import uuid

from arks_tpu.control.k8s_client import ApiError
from arks_tpu.utils.swallow import swallowed

log = logging.getLogger("arks_tpu.control.leader")

LEASE_GV = "coordination.k8s.io/v1"
# Same lease id the reference manager uses (cmd/main.go:211).
DEFAULT_LEASE_NAME = "e4ada7ad.arks.ai"


def _rfc3339(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def _parse_rfc3339(s: str | None) -> float | None:
    if not s:
        return None
    try:
        return datetime.datetime.fromisoformat(
            s.replace("Z", "+00:00")).timestamp()
    except ValueError:
        return None


def default_identity() -> str:
    """hostname_pid_uuid — the controller-runtime identity shape (unique
    per process even across restarts of the same pod)."""
    return f"{socket.gethostname()}_{os.getpid()}_{uuid.uuid4().hex[:8]}"


class LeaderElector:
    """Acquire/renew a Lease in a background thread; fire callbacks on
    leadership transitions.  ``on_stopped_leading`` fires when a held lease
    cannot be renewed (apiserver took it away or renewals kept failing past
    the lease duration) — the caller decides whether that is fatal."""

    def __init__(self, api, namespace: str = "default",
                 name: str = DEFAULT_LEASE_NAME,
                 identity: str | None = None,
                 lease_duration_s: float = 15.0,
                 retry_period_s: float = 2.0,
                 on_started_leading=None,
                 on_stopped_leading=None):
        self.api = api
        self.namespace = namespace
        self.name = name
        self.identity = identity or default_identity()
        self.lease_duration_s = lease_duration_s
        self.retry_period_s = retry_period_s
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False
        self._last_renew_ok = 0.0
        self._running = False
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()

    @property
    def is_leader(self) -> bool:
        return self._leading

    # -- protocol ------------------------------------------------------

    def _spec(self, prev: dict | None, now: float) -> dict:
        prev = prev or {}
        took_over = prev.get("holderIdentity") != self.identity
        return {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": max(int(self.lease_duration_s), 1),
            "acquireTime": _rfc3339(now) if took_over
            else prev.get("acquireTime", _rfc3339(now)),
            "renewTime": _rfc3339(now),
            "leaseTransitions": int(prev.get("leaseTransitions", 0))
            + (1 if took_over and prev.get("holderIdentity") else 0),
        }

    def try_acquire_or_renew(self) -> bool:
        """One protocol step.  Returns True iff this process holds the
        lease after the step."""
        now = time.time()
        lease = self.api.get(LEASE_GV, "leases", self.namespace, self.name)
        if lease is None:
            obj = {"apiVersion": LEASE_GV, "kind": "Lease",
                   "metadata": {"name": self.name,
                                "namespace": self.namespace},
                   "spec": self._spec(None, now)}
            try:
                self.api.create(LEASE_GV, "leases", self.namespace, obj)
            except ApiError as e:
                if e.status == 409:  # lost the creation race
                    return False
                raise
            log.info("acquired leader lease %s/%s as %s", self.namespace,
                     self.name, self.identity)
            return True

        spec = lease.get("spec", {}) or {}
        holder = spec.get("holderIdentity") or ""
        renew = _parse_rfc3339(spec.get("renewTime")
                               or spec.get("acquireTime"))
        duration = float(spec.get("leaseDurationSeconds")
                         or self.lease_duration_s)
        expired = renew is None or now > renew + duration
        if holder and holder != self.identity and not expired:
            return False  # held by a live leader

        obj = {"apiVersion": LEASE_GV, "kind": "Lease",
               "metadata": {"name": self.name, "namespace": self.namespace,
                            "resourceVersion": str(
                                lease.get("metadata", {})
                                .get("resourceVersion", ""))},
               "spec": self._spec(spec, now)}
        try:
            self.api.replace(LEASE_GV, "leases", self.namespace, self.name,
                             obj)
        except ApiError as e:
            if e.status == 409:  # another contender won this round
                return False
            raise
        if holder != self.identity:
            log.info("acquired leader lease %s/%s as %s (previous holder "
                     "%r, expired=%s)", self.namespace, self.name,
                     self.identity, holder, expired)
        return True

    def release(self) -> None:
        """Give the lease up explicitly (graceful shutdown): the standby
        takes over at its next retry instead of waiting out the duration."""
        if not self._leading:
            return
        try:
            lease = self.api.get(LEASE_GV, "leases", self.namespace,
                                 self.name)
            if lease is None or (lease.get("spec", {})
                                 .get("holderIdentity") != self.identity):
                return
            obj = {"apiVersion": LEASE_GV, "kind": "Lease",
                   "metadata": {"name": self.name,
                                "namespace": self.namespace,
                                "resourceVersion": str(
                                    lease.get("metadata", {})
                                    .get("resourceVersion", ""))},
                   "spec": {**lease.get("spec", {}), "holderIdentity": "",
                            "renewTime": None}}
            self.api.replace(LEASE_GV, "leases", self.namespace, self.name,
                             obj)
            log.info("released leader lease %s/%s", self.namespace,
                     self.name)
        except Exception:
            log.warning("lease release failed (standby will take over "
                        "after expiry)", exc_info=True)
        finally:
            self._leading = False

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="leader-elector", daemon=True)
        self._thread.start()

    def stop(self, release: bool = True) -> None:
        self._running = False
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if release:
            self.release()
        else:
            self._leading = False

    def _loop(self) -> None:
        while self._running:
            ok = False
            try:
                ok = self.try_acquire_or_renew()
                if ok:
                    self._last_renew_ok = time.time()
            except Exception:
                log.exception("leader election step failed")
            if ok and not self._leading:
                self._leading = True
                if self.on_started_leading is not None:
                    try:
                        self.on_started_leading()
                    except Exception:
                        # A callback failure must not kill the elector
                        # thread with _leading stuck True (renewals would
                        # stop while this process still claims the lease).
                        # This process failed to START leading: give the
                        # lease up so a healthy replica can.
                        log.exception("on_started_leading failed; "
                                      "releasing the lease")
                        self.release()
            elif self._leading and not ok:
                # Renewals may fail transiently (apiserver blip): leadership
                # is only LOST once the lease duration has passed without a
                # successful renewal — or another holder took the lease.
                held_elsewhere = False
                try:
                    lease = self.api.get(LEASE_GV, "leases", self.namespace,
                                         self.name)
                    holder = (lease or {}).get("spec", {}).get(
                        "holderIdentity")
                    held_elsewhere = bool(holder) and holder != self.identity
                except Exception as e:
                    # Unreadable lease ≠ lost lease: the renewal-age check
                    # below is the actual demotion trigger.
                    swallowed("leader.lease-peek", e)
                if held_elsewhere or (time.time() - self._last_renew_ok
                                      > self.lease_duration_s):
                    self._leading = False
                    log.warning("leadership lost (lease %s/%s)",
                                self.namespace, self.name)
                    if self.on_stopped_leading is not None:
                        try:
                            self.on_stopped_leading()
                        except Exception:
                            log.exception("on_stopped_leading failed")
            self._wake.wait(self.retry_period_s)
            self._wake.clear()
