"""Live-operator mode: drive a real Kubernetes apiserver.

The gitops path (arks_tpu.control.k8s_export) renders manifests once and
walks away; nothing owns, repairs, or status-syncs the objects after
``kubectl apply``.  This module is the missing half — the reference's
controller-runtime process (/root/reference/cmd/main.go:255-301) rebuilt
around this repo's existing controllers:

- ``LiveOperator`` ingests the six arks.ai CRs from the apiserver into the
  in-memory Store (spec is apiserver-authoritative), lets the UNCHANGED
  controller set reconcile them, and projects Store status back through the
  status subresource (status is controller-authoritative).  Deletion is
  finalizer-gated end to end: the bridge stamps a finalizer on ingested
  CRs, mirrors apiserver deletion into the Store (which runs teardown),
  and strips the finalizer once the Store object is gone.
- ``K8sGangDriver`` materializes GangSets as per-group StatefulSets +
  headless Services (the LWS/RBGS role — SURVEY.md §1 external deps),
  owns them (labels + revision annotations), repairs drift, sequences
  cross-group rolling updates with the same pick_rolling_restart gating
  the local drivers use, and reads group readiness back from StatefulSet
  status.

Ingest model: WATCH streams per kind with resourceVersion resume (the
reference is watch-driven controller-runtime, cmd/main.go:255-301) —
spec changes propagate at event latency with O(1) apiserver requests per
change.  A periodic full list resync stays as the level-triggered safety
net, and pure polling remains available (``use_watch=False`` or an api
without ``.watch``).
"""

from __future__ import annotations

import logging
import threading
import time

from arks_tpu.control import resources as res
from arks_tpu.control.store import Conflict, NotFound, Store
from arks_tpu.control.workloads import pick_rolling_restart
from arks_tpu.utils.swallow import swallowed

log = logging.getLogger("arks_tpu.control.live")

GV = "arks.ai/v1"
FINALIZER = "live.arks.ai/operator"
PODGROUP_FLAVORS = ("scheduling.x-k8s.io/v1alpha1",
                    "scheduling.volcano.sh/v1beta1")

# (store kind, plural, wire Kind) — names match the reference CRDs
# (/root/reference/config/crd/bases/).
KINDS = [
    (res.Model, "arksmodels", "ArksModel"),
    (res.Application, "arksapplications", "ArksApplication"),
    (res.DisaggregatedApplication, "arksdisaggregatedapplications",
     "ArksDisaggregatedApplication"),
    (res.Endpoint, "arksendpoints", "ArksEndpoint"),
    (res.Token, "arkstokens", "ArksToken"),
    (res.Quota, "arksquotas", "ArksQuota"),
]


# ---------------------------------------------------------------------------
# Gang driver over the apps/v1 API
# ---------------------------------------------------------------------------


class K8sGangDriver:
    """GangDriver that owns per-group StatefulSets on a real apiserver.

    Rendering is delegated to k8s_export.render_group_from_gangset — ONE
    pod renderer for the gitops and live paths (TPU shape mapping, models
    PVC, jax.distributed env contract, probes) so they cannot drift.
    Group naming matches the gitops renderer (``arks-<name>-<i>``), so a
    cluster can migrate from rendered manifests to the live operator: the
    operator takes ownership of the existing objects and — because the
    gitops pod spec differs slightly (no gang secret env, app-level
    container args) — converges them to its own revision via ONE sequenced
    maxUnavailable=1 rolling pass, never a simultaneous restart.

    Disaggregated router gangs use label-selector pod discovery
    (``--service-discovery``, arks_tpu.router.KubeDiscovery) — the live
    operator wires the controllers with router_discovery="kubernetes" so
    routers never depend on the operator's filesystem.
    """

    def __init__(self, api, serve_port: int = 8080,
                 sts_cache_ttl_s: float = 0.5):
        self.api = api
        self.serve_port = serve_port
        # One reconcile tick touches MANY gangsets; each used to pay its
        # own full StatefulSet list.  A short-TTL per-namespace cache
        # batches them into one list per tick (writes invalidate).
        self._sts_cache_ttl = sts_cache_ttl_s
        self._sts_cache: dict[str, tuple[float, list[dict]]] = {}
        self._sts_cache_lock = threading.Lock()

    def _list_statefulsets(self, namespace: str) -> list[dict]:
        now = time.monotonic()
        with self._sts_cache_lock:
            hit = self._sts_cache.get(namespace)
            if hit and now - hit[0] < self._sts_cache_ttl:
                return hit[1]
        items = self.api.list("apps/v1", "statefulsets", namespace)
        with self._sts_cache_lock:
            self._sts_cache[namespace] = (now, items)
        return items

    def _invalidate_sts_cache(self, namespace: str) -> None:
        with self._sts_cache_lock:
            self._sts_cache.pop(namespace, None)

    def _render(self, gs, index: int) -> tuple[dict, dict]:
        from arks_tpu.control.k8s_export import render_group_from_gangset
        return render_group_from_gangset(gs, index, self.serve_port)

    def _want_revision(self, gs) -> str:
        from arks_tpu.control.k8s_export import gangset_revision
        return gangset_revision(gs, self.serve_port)

    # -- GangDriver ----------------------------------------------------

    def _existing(self, gs) -> dict[int, dict]:
        out = {}
        for sts in self._list_statefulsets(gs.namespace):
            labels = sts["metadata"].get("labels", {})
            if labels.get("arks.ai/gangset") == gs.name:
                out[int(labels.get("arks.ai/group", -1))] = sts
        return out

    @staticmethod
    def _revision(sts: dict) -> str:
        return (sts["spec"]["template"]["metadata"].get("annotations", {})
                .get("arks.ai/revision", ""))

    @staticmethod
    def _sts_ready(sts: dict) -> bool:
        # readyReplicas >= 1, NOT >= size: /readiness is leader-only by
        # design (worker processes return 503 so Services route to the
        # leader — openai_server), so a healthy size-N gang always reports
        # exactly one ready pod.
        return sts.get("status", {}).get("readyReplicas", 0) >= 1

    _RBAC_PLURALS = {"ServiceAccount": ("v1", "serviceaccounts"),
                     "Role": ("rbac.authorization.k8s.io/v1", "roles"),
                     "RoleBinding": ("rbac.authorization.k8s.io/v1",
                                     "rolebindings")}

    def _ensure_router_rbac(self, gs) -> None:
        """Router gangs list tier pods by label selector: bootstrap the
        per-app ServiceAccount/Role/RoleBinding (create-if-absent) from
        the SAME render the gitops path uses (k8s_export.render_router_rbac
        — one source, no drift)."""
        from arks_tpu.control.k8s_export import render_router_rbac
        from arks_tpu.control.resources import LABEL_APPLICATION
        app = (gs.labels or {}).get(LABEL_APPLICATION)
        if gs.spec.get("role") != "router" or not app:
            return
        for doc in render_router_rbac(app, gs.namespace):
            gv, plural = self._RBAC_PLURALS[doc["kind"]]
            name = doc["metadata"]["name"]
            if self.api.get(gv, plural, gs.namespace, name) is None:
                self.api.create(gv, plural, gs.namespace, doc)

    def ensure(self, gs) -> None:
        existing = self._existing(gs)
        replicas = gs.spec.get("replicas", 1)
        want_rev = self._want_revision(gs)
        self._ensure_router_rbac(gs)

        # Create missing groups + headless services (and their gang
        # PodGroups, when a podGroupPolicy asks for one); adopt current ones.
        for i in range(replicas):
            sts, svc = self._render(gs, i)
            name = sts["metadata"]["name"]
            if self.api.get("v1", "services", gs.namespace, name) is None:
                self.api.create("v1", "services", gs.namespace, svc)
            # Unified unit PodGroups are one shared object: converge it on
            # group 0 only (per-group stale names still probed every group).
            self._ensure_podgroup(gs, i, name, converge_target=(i == 0))
            if i not in existing:
                self.api.create("apps/v1", "statefulsets", gs.namespace, sts)
                self._invalidate_sts_cache(gs.namespace)
        # Scale down (the group's PodGroups go with it, whatever flavor).
        for i, sts in existing.items():
            if i >= replicas:
                name = sts["metadata"]["name"]
                self.api.delete("apps/v1", "statefulsets", gs.namespace, name)
                self._invalidate_sts_cache(gs.namespace)
                self.api.delete("v1", "services", gs.namespace, name)
                for gv in PODGROUP_FLAVORS:
                    self.api.delete(gv, "podgroups", gs.namespace, name)

        # Cross-group rolling update: static manifests cannot sequence
        # per-group StatefulSets; here the same maxUnavailable=1 gating as
        # the local drivers updates ONE outdated group per reconcile.
        current = {i: s for i, s in existing.items() if i < replicas}
        hashes = {i: self._revision(s) for i, s in current.items()}
        if hashes and not all(h == want_rev for h in hashes.values()):
            ready = {i: self._sts_ready(s) for i, s in current.items()}
            cand = pick_rolling_restart(hashes, want_rev, ready)
            if cand is not None:
                log.info("gang %s/%s group %d: rolling to revision %s",
                         gs.namespace, gs.name, cand, want_rev)
                desired, _ = self._render(gs, cand)
                name = desired["metadata"]["name"]
                cur = current[cand]
                # REPLACE, not merge-patch: merge cannot remove keys (a
                # dropped nodeSelector would silently survive while the
                # revision annotation claimed the group was current).
                desired["metadata"]["resourceVersion"] = (
                    cur["metadata"].get("resourceVersion", ""))
                self.api.replace("apps/v1", "statefulsets", gs.namespace,
                                 name, desired)
                self._invalidate_sts_cache(gs.namespace)

    @staticmethod
    def _unit_name(gs) -> str | None:
        """The deterministic unit-PodGroup name this gangset WOULD use in
        unified mode — needed for cleanup even when the current spec no
        longer carries a podGroupUnit (unified -> legacy switch)."""
        unit = (gs.spec.get("podGroupUnit") or {}).get("name")
        if unit:
            return unit
        role = gs.spec.get("role")
        if role and gs.name.endswith(f"-{role}"):
            return f"arks-{gs.name[: -len(role) - 1]}"
        return None

    def _ensure_podgroup(self, gs, index: int, name: str,
                         converge_target: bool = True) -> None:
        """Converge both PodGroup flavors for one group: the rendered one
        (per-group, or the shared unit PodGroup under a podGroupUnit) is
        created or replaced on drift; stale ones — policy removed, flavor
        or LAYOUT switched (incl. unified -> legacy, probed via the
        deterministic unit name) — are deleted, but only when they actually
        exist, so steady state costs reads, not blind writes."""
        from arks_tpu.control.k8s_export import render_podgroup_from_gangset
        pg = render_podgroup_from_gangset(gs, index)
        target = pg["metadata"]["name"] if pg is not None else None
        names = [name, target] if converge_target else [name]
        if converge_target:
            names.append(self._unit_name(gs))
        for gv in PODGROUP_FLAVORS:
            for nm in dict.fromkeys(n for n in names if n):
                cur = self.api.get(gv, "podgroups", gs.namespace, nm)
                if pg is not None and gv == pg["apiVersion"] and nm == target:
                    if cur is None:
                        self.api.create(gv, "podgroups", gs.namespace, pg)
                    elif cur.get("spec") != pg["spec"]:
                        # REPLACE, not merge-patch: a dropped optional key
                        # (volcano queue/priorityClassName) must actually go
                        # away, or the spec comparison never converges and
                        # the stale key keeps steering the scheduler.  A
                        # stale minMember above the real gang/unit size
                        # would deadlock scheduling forever.
                        desired = dict(pg)
                        desired["metadata"] = {
                            **pg["metadata"],
                            "resourceVersion": cur["metadata"].get(
                                "resourceVersion", "")}
                        self.api.replace(gv, "podgroups", gs.namespace, nm,
                                         desired)
                elif cur is not None:
                    self.api.delete(gv, "podgroups", gs.namespace, nm)

    def status(self, gs) -> dict:
        existing = self._existing(gs)
        replicas = gs.spec.get("replicas", 1)
        groups = []
        for i in range(replicas):
            sts = existing.get(i)
            group = f"arks-{gs.name}-{i}"
            if sts is None:
                groups.append({"index": i, "phase": "Pending", "leaderAddr": ""})
                continue
            # Readiness is revision-INDEPENDENT: a ready-but-outdated group
            # still serves traffic, and gating readiness on the revision
            # would empty the endpoint's backend list the instant a spec
            # change lands (before any pod restarted).
            phase = "Running" if self._sts_ready(sts) else (
                "Starting" if sts.get("status", {}).get("readyReplicas", 0)
                else "Pending")
            addr = f"{group}-0.{group}.{gs.namespace}.svc:{self.serve_port}"
            groups.append({"index": i, "phase": phase,
                           "leaderAddr": addr if phase == "Running" else ""})
        ready = sum(1 for g in groups if g["phase"] == "Running")
        return {"replicas": replicas, "readyReplicas": ready, "groups": groups}

    def teardown(self, gs) -> None:
        for i, sts in self._existing(gs).items():
            name = sts["metadata"]["name"]
            self.api.delete("apps/v1", "statefulsets", gs.namespace, name)
            self._invalidate_sts_cache(gs.namespace)
            self.api.delete("v1", "services", gs.namespace, name)
            # Unconditional: a policy REMOVED from the spec must not orphan
            # PodGroups created under the old spec.
            for gv in PODGROUP_FLAVORS:
                self.api.delete(gv, "podgroups", gs.namespace, name)
        # The shared unit PodGroup (unified disaggregated layout) goes with
        # the last tier torn down; deletes are idempotent across tiers, and
        # the deterministic name covers specs that already switched layouts.
        unit = self._unit_name(gs)
        if unit:
            for gv in PODGROUP_FLAVORS:
                self.api.delete(gv, "podgroups", gs.namespace, unit)


# ---------------------------------------------------------------------------
# CR <-> Store bridge
# ---------------------------------------------------------------------------


class LiveOperator:
    """Runs the existing controller set against a real apiserver."""

    def __init__(self, api, models_root: str, interval_s: float = 1.0,
                 serve_port: int = 8080, use_watch: bool = True,
                 resync_interval_s: float | None = None,
                 leader_elector=None, exit_on_lost_lease: bool = True):
        from arks_tpu.control.manager import build_manager

        self.api = api
        self.interval_s = interval_s
        # Leader election (reference cmd/main.go:198-216): with an elector,
        # the reconcile machinery starts only on lease acquisition —
        # standby replicas ingest nothing and write nothing.  Losing a held
        # lease is fatal by default (controller-runtime semantics: caches
        # and in-flight writes are no longer trustworthy); tests pass
        # exit_on_lost_lease=False to observe the transition in-process.
        self.elector = leader_elector
        self.exit_on_lost_lease = exit_on_lost_lease
        # Watch-driven ingest (the reference is watch-driven controller-
        # runtime, cmd/main.go:255-301): spec changes propagate at event
        # latency instead of poll latency, and apiserver load per change is
        # O(1) instead of O(cluster size x poll rate).  A periodic full
        # resync (list) remains the level-triggered safety net, and poll
        # mode stays available for api objects without watch support.
        self.use_watch = use_watch and hasattr(api, "watch")
        self.resync_interval_s = (resync_interval_s
                                  if resync_interval_s is not None
                                  else max(interval_s * 30, 15.0))
        self.store = Store()
        self.driver = K8sGangDriver(api, serve_port=serve_port)
        # Live-mode routers run as cluster pods: they discover
        # prefill/decode pods themselves by label selector (a discovery
        # FILE on the operator's filesystem would be invisible to them).
        self.manager = build_manager(models_root=models_root,
                                     driver=self.driver, store=self.store,
                                     router_discovery="kubernetes")
        # Operator-process metrics (reference manager serves its own
        # controller-runtime families behind authn — cmd/main.go:157-169;
        # HealthServer exposes this registry at /metrics).
        from arks_tpu.utils import metrics as prom
        self.metrics_registry = prom.Registry()
        self._m_sync = self.metrics_registry.counter(
            "operator_sync_iterations_total",
            "Reconcile/status-projection loop iterations")
        self._m_events = self.metrics_registry.counter(
            "operator_watch_events_total", "Watch events handled, by kind")
        self._m_ingests = self.metrics_registry.counter(
            "operator_spec_ingests_total", "CR specs ingested into the store")
        self._m_projections = self.metrics_registry.counter(
            "operator_status_projections_total",
            "Status subresource patches written")
        self._m_leader = self.metrics_registry.gauge(
            "operator_is_leader", "1 when this replica holds the lease")
        self._m_leader.set(0.0)  # standbys must expose a sample too
        self._running = False
        self._started = False
        self._machinery_started = False
        self._thread: threading.Thread | None = None
        self._watchers: list[threading.Thread] = []
        # Last status we projected per (plural, ns, name) — avoids writing
        # an unchanged status every poll.
        self._projected: dict[tuple, dict] = {}
        # CRs with a deletionTimestamp whose store teardown is in flight.
        self._deleting: set[tuple] = set()
        self._deleting_lock = threading.Lock()
        # Per-CR last-ingested resourceVersion (the stale-resync fence).
        self._ingested_rv: dict[tuple, int] = {}

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._started = True
        if self.elector is None:
            self._start_machinery()
            return
        self.elector.on_started_leading = self._start_machinery
        self.elector.on_stopped_leading = self._on_lost_lease
        self.elector.start()

    def _start_machinery(self) -> None:
        """Start controllers + ingest.  With an elector this fires from the
        elector thread on lease acquisition; without one, from start()."""
        if self._machinery_started:
            return
        self._machinery_started = True
        try:
            self.manager.start()
            self._running = True
            self._thread = threading.Thread(target=self._loop,
                                            name="live-sync", daemon=True)
            self._thread.start()
            if self.use_watch:
                for kind, plural, wire_kind in KINDS:
                    t = threading.Thread(
                        target=self._watch_loop, args=(kind, plural),
                        name=f"live-watch-{plural}", daemon=True)
                    t.start()
                    self._watchers.append(t)
        except Exception:
            # Leave a clean slate: the elector releases the lease on a
            # failed start callback, and a later re-acquisition must be
            # able to try again.
            self._machinery_started = False
            self._running = False
            raise

    def _on_lost_lease(self) -> None:
        if self.exit_on_lost_lease:
            log.critical("leader lease lost; exiting so the replacement "
                         "leader reconciles from a fresh cache")
            import os
            os._exit(1)
        log.warning("leader lease lost; stopping reconcile machinery")
        self._stop_machinery()

    def _stop_machinery(self) -> None:
        if not self._machinery_started:
            return
        self._machinery_started = False
        self._running = False
        if self._thread:
            self._thread.join(timeout=10)
        self._thread = None  # a later restart's healthy window is clean
        self.manager.stop()

    @property
    def is_leader(self) -> bool:
        """True when reconciling (always, without an elector)."""
        return self._machinery_started if self.elector is None \
            else self.elector.is_leader

    @property
    def healthy(self) -> bool:
        """Liveness: a standby is healthy idling; a leader is healthy only
        while its sync thread is.  ``_thread is None`` while machinery is
        STARTING (the flag flips before manager.start() finishes and the
        thread exists) — that window is healthy, not a dead loop."""
        if not self._machinery_started:
            return True
        t = self._thread
        return t is None or t.is_alive()

    @property
    def ready(self) -> bool:
        """Readiness gates SERVICE TRAFFIC, not liveness: the operator pod
        embeds the QoS gateway, and a standby's gateway serves an EMPTY
        store (it ingests nothing until it leads) — so only the leader may
        be in the Service's endpoints.  Standbys stay alive via /healthz
        and flip ready the moment they acquire the lease."""
        return self._started and (self.elector is None
                                  or self.elector.is_leader)

    def stop(self) -> None:
        if self.elector is not None:
            # Release FIRST: the standby takes over at its next retry
            # instead of waiting out the lease duration.
            self.elector.stop(release=True)
        self._stop_machinery()
        self._started = False

    def _loop(self) -> None:
        next_resync = 0.0
        while self._running:
            self._m_sync.inc()
            self._m_leader.set(1.0 if self.is_leader else 0.0)
            try:
                if not self.use_watch or time.monotonic() >= next_resync:
                    # Full level-triggered pass (poll mode: every tick;
                    # watch mode: periodic safety net).
                    self.sync_once()
                    next_resync = time.monotonic() + self.resync_interval_s
                else:
                    # Between resyncs the apiserver work is store-driven:
                    # project changed statuses, finish in-flight deletions.
                    self._project_all()
                    self._finish_deletions()
            except Exception:
                log.exception("live sync iteration failed")
            time.sleep(self.interval_s)

    # -- watch path ----------------------------------------------------

    def _watch_loop(self, kind, plural) -> None:
        rv = 0
        while self._running:
            try:
                for ev in self.api.watch(GV, plural, since_rv=rv,
                                         timeout_s=max(self.interval_s * 5,
                                                       5.0)):
                    obj = ev.get("object") or {}
                    if ev.get("type") == "ERROR":
                        # Real apiservers deliver expiry as an ERROR event
                        # inside a 200 stream (Status code 410), not as an
                        # HTTP error — route it to the relist branch below
                        # instead of spinning on the stale resourceVersion.
                        from arks_tpu.control.k8s_client import ApiError
                        raise ApiError(int(obj.get("code", 500)),
                                       obj.get("message", "watch error"))
                    meta = obj.get("metadata", {})
                    self._handle_event(kind, plural, ev.get("type"), obj)
                    # Advance the resume point only AFTER the event is
                    # handled: a handler error reopens the watch at the old
                    # rv and replays the event (handlers are idempotent)
                    # instead of silently dropping it until resync.
                    try:
                        rv = max(rv, int(meta.get("resourceVersion", 0)))
                    except (TypeError, ValueError):
                        pass
                    if not self._running:
                        return
            except Exception as e:
                status = getattr(e, "status", None)
                if status == 410:
                    # Fell off the event window: relist from scratch.
                    rv = 0
                    try:
                        self.sync_once()
                    except Exception:
                        log.exception("post-410 resync failed")
                else:
                    log.warning("watch %s failed; retrying", plural,
                                exc_info=True)
                    time.sleep(self.interval_s)

    def _handle_event(self, kind, plural, typ: str | None, cr: dict) -> None:
        self._m_events.inc(plural=plural, type=typ or "UNKNOWN")
        meta = cr.get("metadata", {})
        ns = meta.get("namespace", "default")
        name = meta.get("name")
        if not name:
            return
        if typ == "DELETED":
            # Force-removed (finalizer bypassed): tear down the store side.
            try:
                self.store.delete(kind, name, ns)
            except NotFound:
                pass
            with self._deleting_lock:
                self._deleting.discard((kind, plural, ns, name))
            self._ingested_rv.pop((kind.KIND, ns, name), None)
            return
        if meta.get("deletionTimestamp"):
            with self._deleting_lock:
                self._deleting.add((kind, plural, ns, name))
            self._handle_cr_deletion(kind, plural, ns, name)
            return
        self._ensure_finalizer(plural, ns, name, meta)
        self._ingest(kind, cr, ns, name)

    def _project_all(self) -> None:
        for kind, plural, _ in KINDS:
            for obj in self.store.list(kind):
                try:
                    self._project_status(kind, plural, obj.namespace,
                                         obj.name)
                except Exception:
                    log.exception("status projection failed for %s/%s",
                                  plural, obj.name)

    def _finish_deletions(self) -> None:
        with self._deleting_lock:
            pending = list(self._deleting)
        for key in pending:
            kind, plural, ns, name = key
            try:
                cr = self.api.get(GV, plural, ns, name)
                if cr is None:
                    with self._deleting_lock:
                        self._deleting.discard(key)
                    continue
                self._handle_cr_deletion(kind, plural, ns, name)
            except Exception:
                log.exception("deletion finalization failed for %s/%s",
                              plural, name)

    # -- one sync pass -------------------------------------------------

    def sync_once(self) -> None:
        for kind, plural, wire_kind in KINDS:
            try:
                items = self.api.list(GV, plural)
            except Exception:
                log.exception("listing %s failed", plural)
                continue
            seen = set()
            for cr in items:
                meta = cr.get("metadata", {})
                ns = meta.get("namespace", "default")
                name = meta["name"]
                seen.add((ns, name))
                if meta.get("deletionTimestamp"):
                    self._handle_cr_deletion(kind, plural, ns, name)
                    continue
                self._ensure_finalizer(plural, ns, name, meta)
                self._ingest(kind, cr, ns, name)
                self._project_status(kind, plural, ns, name)
            # CRs force-removed from the apiserver (finalizer bypassed)
            # still tear down their store objects.
            for obj in self.manager.store.list(kind):
                if (obj.namespace, obj.name) not in seen:
                    try:
                        self.store.delete(kind, obj.name, obj.namespace)
                    except NotFound:
                        pass
                    self._ingested_rv.pop(
                        (kind.KIND, obj.namespace, obj.name), None)

    def _ensure_finalizer(self, plural, ns, name, meta) -> None:
        fins = meta.get("finalizers") or []
        if FINALIZER not in fins:
            self.api.patch(GV, plural, ns, name,
                           {"metadata": {"finalizers": fins + [FINALIZER]}})

    def _ingest(self, kind, cr: dict, ns: str, name: str) -> None:
        # resourceVersion fence: a periodic-resync LIST snapshot can be
        # staler than what a watcher thread already ingested — applying it
        # would revert the store to an old spec until the next resync.
        try:
            rv = int(cr.get("metadata", {}).get("resourceVersion", 0))
        except (TypeError, ValueError):
            rv = 0
        key = (kind.KIND, ns, name)
        if rv and rv <= self._ingested_rv.get(key, 0):
            return
        if rv:
            self._ingested_rv[key] = rv
        spec = cr.get("spec", {})
        labels = cr.get("metadata", {}).get("labels", {}) or {}
        obj = self.store.try_get(kind, name, ns)
        if obj is None:
            self.store.create(kind(name=name, namespace=ns, labels=labels,
                                   spec=spec))
            self._m_ingests.inc(kind=kind.KIND)
        elif obj.spec != spec or obj.labels != labels:
            obj.spec = spec
            obj.labels = labels
            try:
                self.store.update(obj)
                self._m_ingests.inc(kind=kind.KIND)
            except Conflict:
                pass  # next poll retries against the fresh object

    def _project_status(self, kind, plural, ns, name) -> None:
        obj = self.store.try_get(kind, name, ns)
        if obj is None or not obj.status:
            return
        key = (plural, ns, name)
        if self._projected.get(key) == obj.status:
            return
        self.api.patch(GV, plural, ns, name, {"status": obj.status},
                       subresource="status")
        self._m_projections.inc(plural=plural)
        self._projected[key] = {k: v for k, v in obj.status.items()}

    def _handle_cr_deletion(self, kind, plural, ns, name) -> None:
        obj = self.store.try_get(kind, name, ns)
        if obj is not None and not obj.deletion_requested:
            try:
                self.store.delete(kind, name, ns)
            except NotFound:
                pass
            return
        if obj is None:
            # Store teardown finished (finalizers ran) — release the CR.
            cr = self.api.get(GV, plural, ns, name)
            if cr is not None:
                fins = [f for f in cr["metadata"].get("finalizers", [])
                        if f != FINALIZER]
                self.api.patch(GV, plural, ns, name,
                               {"metadata": {"finalizers": fins}})
                self._projected.pop((plural, ns, name), None)


class HealthServer:
    """``/healthz`` + ``/readyz`` + ``/metrics`` for the operator pod — the
    endpoints the reference manager wires (/root/reference/cmd/
    main.go:157-169,320-327), probes hit the first two.  Standby replicas
    are live but NOT ready (readiness keeps the embedded gateway's Service
    pointed at the leader — a standby's gateway would serve an empty
    store); a leader whose sync thread died fails liveness so the kubelet
    restarts it.  ``/metrics`` serves the operator's own registry and is
    TokenReview-authenticated when ``metrics_auth_api`` is wired (the
    reference's WithAuthenticationAndAuthorization filter's authn half)."""

    def __init__(self, operator: "LiveOperator", host: str = "0.0.0.0",
                 port: int = 8082, metrics_auth_api=None):
        import http.server
        import json as _json
        import socketserver

        op = operator
        auth_api = metrics_auth_api

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet probes
                pass

            def _metrics(self) -> None:
                # TokenReview-gated when an auth api is wired — the authn
                # the reference manager's metrics filter runs
                # (cmd/main.go:157-169).  Probes stay unauthenticated.
                if auth_api is not None:
                    hdr = self.headers.get("Authorization") or ""
                    tok = hdr[7:].strip() if hdr.startswith("Bearer ") else ""
                    if not tok:
                        self.send_response(401)
                        self.send_header("WWW-Authenticate", "Bearer")
                        self.end_headers()
                        return
                    if not auth_api.token_review(tok):
                        self.send_response(403)
                        self.end_headers()
                        return
                # Leadership is sampled at RENDER time: the gauge must be
                # truthful on a standby (whose _loop never runs) and after
                # an in-process demotion (whose _loop stopped).
                op._m_leader.set(1.0 if op.is_leader else 0.0)
                text = op.metrics_registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/metrics":
                    return self._metrics()
                if path == "/healthz":
                    ok = op.healthy
                elif path == "/readyz":
                    ok = op.ready
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = _json.dumps({
                    "ok": ok, "leader": op.is_leader,
                    "identity": getattr(op.elector, "identity", None),
                }).encode()
                self.send_response(200 if ok else 503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._srv = Server((host, port), Handler)
        self.host, self.port = self._srv.server_address

    def start(self) -> None:
        threading.Thread(target=self._srv.serve_forever,
                         name="operator-health", daemon=True).start()

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


def main() -> None:
    import argparse

    from arks_tpu.control.k8s_client import KubeApi

    p = argparse.ArgumentParser("arks_tpu.control.live")
    p.add_argument("--models-root", default="/models")
    p.add_argument("--kube-api", default=None,
                   help="apiserver URL (default: in-cluster config)")
    p.add_argument("--kube-token-file", default=None)
    p.add_argument("--kube-ca", default=None,
                   help="CA bundle for --kube-api TLS verification")
    p.add_argument("--insecure-skip-tls-verify", action="store_true",
                   help="disable apiserver TLS verification (dev only — "
                        "the bearer token rides this connection)")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--gateway-port", type=int, default=8081,
                   help="embedded QoS gateway over the live store (0 = off) "
                        "— ArksToken/Quota/Endpoint CRs gate traffic here")
    p.add_argument("--leader-elect", action="store_true",
                   help="coordination.k8s.io Lease leader election: extra "
                        "replicas idle until the holder dies "
                        "(reference cmd/main.go:198-216)")
    p.add_argument("--leader-elect-namespace", default=None,
                   help="lease namespace (default: the pod's namespace)")
    p.add_argument("--health-port", type=int, default=8082,
                   help="/healthz + /readyz + /metrics endpoint port "
                        "(0 = off)")
    p.add_argument("--insecure-metrics", action="store_true",
                   help="serve /metrics without TokenReview authentication "
                        "(the reference manager authenticates by default)")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    if args.kube_api:
        token = None
        if args.kube_token_file:
            with open(args.kube_token_file) as f:
                token = f.read().strip()
        api = KubeApi(args.kube_api, token=token, ca_file=args.kube_ca,
                      verify=not args.insecure_skip_tls_verify)
    else:
        api = KubeApi.in_cluster()
    elector = None
    if args.leader_elect:
        from arks_tpu.control.leader import LeaderElector
        ns = args.leader_elect_namespace
        if ns is None:
            try:
                ns = KubeApi.namespace_in_cluster()
            except Exception as e:
                # Outside a pod there is no serviceaccount namespace file.
                swallowed("live.namespace-in-cluster", e)
                ns = "default"
        elector = LeaderElector(api, namespace=ns)
    op = LiveOperator(api, models_root=args.models_root,
                      interval_s=args.interval, leader_elector=elector)
    health = None
    if args.health_port:
        health = HealthServer(
            op, port=args.health_port,
            metrics_auth_api=None if args.insecure_metrics else api)
        health.start()
    op.start()
    gw = None
    if args.gateway_port:
        from arks_tpu.gateway.server import Gateway
        gw = Gateway(op.store, host="0.0.0.0", port=args.gateway_port)
        gw.start(background=True)
    log.info("live operator running (interval=%.1fs, gateway=%s)",
             args.interval, args.gateway_port or "off")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        if gw is not None:
            gw.stop()
        if health is not None:
            health.stop()
        op.stop()


if __name__ == "__main__":
    main()
