"""In-memory watchable object store — the control plane's "apiserver".

Plays the role the Kubernetes apiserver plays for the reference operator:
typed CRUD with resourceVersion bumps, per-kind watch streams, finalizers,
deletion propagation to owned objects, and a status subresource.  Backed by
plain dicts; persistence (e.g. file-backed snapshots) can be layered under
``snapshot()/restore()``.

Concurrency: a single lock; reads return deep copies so reconcilers can
mutate freely and write back (mirroring controller-runtime's cached-client
get/update pattern).
"""

from __future__ import annotations

import copy
import queue
import threading
from typing import Callable, Iterable, Type

from arks_tpu.control.resources import Resource


class Conflict(Exception):
    """resourceVersion mismatch on update (optimistic concurrency)."""


class NotFound(Exception):
    pass


class Store:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        # kind -> (namespace, name) -> Resource
        self._objects: dict[str, dict[tuple[str, str], Resource]] = {}
        self._watchers: dict[str, list["queue.Queue[tuple[str, Resource]]"]] = {}
        self._rv = 0

    # ------------------------------------------------------------------
    # CRUD
    # ------------------------------------------------------------------

    def create(self, obj: Resource) -> Resource:
        with self._lock:
            kind = obj.KIND
            objs = self._objects.setdefault(kind, {})
            if obj.key in objs:
                raise Conflict(f"{kind} {obj.key} already exists")
            self._rv += 1
            obj = obj.deepcopy()
            obj.resource_version = self._rv
            objs[obj.key] = obj
            self._notify(kind, "ADDED", obj)
            return obj.deepcopy()

    def get(self, kind: Type[Resource] | str, name: str,
            namespace: str = "default") -> Resource:
        k = kind if isinstance(kind, str) else kind.KIND
        with self._lock:
            obj = self._objects.get(k, {}).get((namespace, name))
            if obj is None:
                raise NotFound(f"{k} {namespace}/{name}")
            return obj.deepcopy()

    def try_get(self, kind, name, namespace="default") -> Resource | None:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def list(self, kind: Type[Resource] | str, namespace: str | None = None,
             selector: Callable[[Resource], bool] | None = None) -> list[Resource]:
        k = kind if isinstance(kind, str) else kind.KIND
        with self._lock:
            out = []
            for obj in self._objects.get(k, {}).values():
                if namespace is not None and obj.namespace != namespace:
                    continue
                if selector is not None and not selector(obj):
                    continue
                out.append(obj.deepcopy())
            return out

    def update(self, obj: Resource) -> Resource:
        """Full update with optimistic concurrency on resource_version."""
        with self._lock:
            kind = obj.KIND
            objs = self._objects.get(kind, {})
            cur = objs.get(obj.key)
            if cur is None:
                raise NotFound(f"{kind} {obj.key}")
            if obj.resource_version != cur.resource_version:
                raise Conflict(
                    f"{kind} {obj.key}: stale resourceVersion "
                    f"{obj.resource_version} != {cur.resource_version}")
            self._rv += 1
            new = obj.deepcopy()
            new.resource_version = self._rv
            objs[obj.key] = new
            self._notify(kind, "MODIFIED", new)
            # Finalizer-driven deletion: object goes away once marked deleted
            # and no finalizers remain.
            if new.deletion_requested and not new.finalizers:
                self._remove(new)
            return new.deepcopy()

    def update_status(self, obj: Resource) -> Resource:
        """Status-subresource update: merges status only, ignores spec edits,
        retries on conflict like the reference's RetryOnConflict patch
        (arksapplication_controller.go:1024-1038)."""
        with self._lock:
            cur = self._objects.get(obj.KIND, {}).get(obj.key)
            if cur is None:
                raise NotFound(f"{obj.KIND} {obj.key}")
            self._rv += 1
            cur.status = copy.deepcopy(obj.status)
            cur.resource_version = self._rv
            self._notify(obj.KIND, "MODIFIED", cur)
            return cur.deepcopy()

    def delete(self, kind: Type[Resource] | str, name: str,
               namespace: str = "default") -> None:
        """Request deletion: with finalizers present the object is only
        marked (controllers then clean up and strip their finalizer);
        without, it is removed and owned objects cascade."""
        k = kind if isinstance(kind, str) else kind.KIND
        with self._lock:
            obj = self._objects.get(k, {}).get((namespace, name))
            if obj is None:
                raise NotFound(f"{k} {namespace}/{name}")
            if obj.finalizers:
                if not obj.deletion_requested:
                    self._rv += 1
                    obj.deletion_requested = True
                    obj.resource_version = self._rv
                    self._notify(k, "MODIFIED", obj)
                return
            self._remove(obj)

    def _remove(self, obj: Resource) -> None:
        self._objects.get(obj.KIND, {}).pop(obj.key, None)
        self._notify(obj.KIND, "DELETED", obj)
        # Cascading delete of owned objects (ownerReference GC).
        for kind_objs in list(self._objects.values()):
            for owned in list(kind_objs.values()):
                if (obj.KIND, obj.name) in owned.owner_refs \
                        and owned.namespace == obj.namespace:
                    try:
                        self.delete(owned.KIND, owned.name, owned.namespace)
                    except NotFound:
                        pass

    def strip_finalizer(self, obj: Resource, finalizer: str) -> None:
        """Remove a finalizer (post-cleanup) and finish deletion if due."""
        with self._lock:
            cur = self._objects.get(obj.KIND, {}).get(obj.key)
            if cur is None:
                return
            if finalizer in cur.finalizers:
                cur.finalizers.remove(finalizer)
                self._rv += 1
                cur.resource_version = self._rv
                self._notify(cur.KIND, "MODIFIED", cur)
            if cur.deletion_requested and not cur.finalizers:
                self._remove(cur)

    def add_finalizer(self, obj: Resource, finalizer: str) -> Resource:
        with self._lock:
            cur = self._objects.get(obj.KIND, {}).get(obj.key)
            if cur is None:
                raise NotFound(f"{obj.KIND} {obj.key}")
            if finalizer not in cur.finalizers:
                cur.finalizers.append(finalizer)
                self._rv += 1
                cur.resource_version = self._rv
            return cur.deepcopy()

    # ------------------------------------------------------------------
    # Watch
    # ------------------------------------------------------------------

    def watch(self, kind: Type[Resource] | str,
              maxsize: int = 1024) -> "queue.Queue[tuple[str, Resource]]":
        """Subscribe to (event_type, object) for a kind.  Slow consumers drop
        oldest events — reconcilers are level-triggered, so a drop only costs
        latency, never correctness."""
        k = kind if isinstance(kind, str) else kind.KIND
        with self._lock:
            # Size the queue so the initial replay can never block while the
            # store lock is held (the consumer only gets the queue after
            # watch() returns, so a bounded q.put here would deadlock).
            existing = list(self._objects.get(k, {}).values())
            q: "queue.Queue[tuple[str, Resource]]" = queue.Queue(
                maxsize=maxsize + len(existing))
            self._watchers.setdefault(k, []).append(q)
            # Replay current state (informer-style initial LIST).
            for obj in existing:
                q.put_nowait(("ADDED", obj.deepcopy()))
        return q

    def _notify(self, kind: str, event: str, obj: Resource) -> None:
        for q in self._watchers.get(kind, []):
            item = (event, obj.deepcopy())
            try:
                q.put_nowait(item)
            except queue.Full:
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                q.put_nowait(item)
