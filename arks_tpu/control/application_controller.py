"""Application controller: the workload-layer phase machine.

Mirrors the reference ArksApplicationReconciler (/root/reference/internal/
controller/arksapplication_controller.go):

- phases Pending -> Checking -> Loading -> Creating -> Running | Failed with
  conditions Precheck / Loaded / Ready (:211-219, :1165-1190)
- precheck validates the runtime (:236-264)
- gates on the referenced Model reaching Ready (:266-296), woken by a Model
  watch fan-out (requestsForModel :1063-1088)
- generates the gang workload (generateLws/generateRBGS :509-889 — here a
  GangSet with jax serve commands) and a stable Service
  ``arks-application-<name>`` on the leader port (:376-415)
- syncs replica status back from the workload (:424-503), woken by a GangSet
  ownership watch (:146-148)

TPU-native: runtime "jax" produces the arks_tpu.server command with mesh
axes from spec.tensorParallel and the coordinator env contract instead of
Ray/NCCL bootstrap scripts (:941-1014).
"""

from __future__ import annotations

import logging
from typing import Iterable

from arks_tpu.control.reconciler import Controller, Result
from arks_tpu.control.resources import (
    COND_LOADED, COND_PRECHECK, COND_READY, LABEL_APPLICATION,
    LABEL_MANAGED_BY, LABEL_MODEL, LABEL_ROLE, MANAGED_BY, MODEL_PHASE_READY,
    PHASE_CHECKING, PHASE_CREATING, PHASE_FAILED, PHASE_LOADING,
    PHASE_PENDING, PHASE_RUNNING, RESERVED_MODELS_PATH, RUNTIME_JAX,
    VALID_RUNTIMES, Application, GangSet, Model, Service,
)
from arks_tpu.control.store import NotFound, Store
from arks_tpu.control.workloads import (default_runtime_image,
                                        gpu_runtime_command,
                                        jax_serve_command)

log = logging.getLogger("arks_tpu.control.application")


def workload_name(app: Application) -> str:
    return app.name


def service_name(app: Application) -> str:
    # reference: "arks-application-<name>" (:376-415)
    return f"arks-application-{app.name}"


class ApplicationController(Controller):
    KIND = Application
    FINALIZER = "application.arks.ai/controller"

    def __init__(self, store: Store, workers: int = 4,
                 local_platform: str | None = None):
        super().__init__(store, workers=workers)
        # Forced jax platform for locally-driven gangs (tests: "cpu").
        self.local_platform = local_platform

    def watches(self) -> Iterable:
        def apps_for_model(model) -> list[tuple[str, str]]:
            # requestsForModel fan-out (:1063-1088)
            return [a.key for a in self.store.list(
                Application, namespace=model.namespace)
                if a.spec.get("model", {}).get("name") == model.name]

        def app_for_gangset(gs) -> list[tuple[str, str]]:
            for kind, name in gs.owner_refs:
                if kind == Application.KIND:
                    return [(gs.namespace, name)]
            return []

        return [(Model, apps_for_model), (GangSet, app_for_gangset)]

    # ------------------------------------------------------------------

    def reconcile(self, app: Application) -> Result | None:
        status_before = app.deepcopy().status

        if not app.status.get("phase"):
            app.status["phase"] = PHASE_PENDING

        # --- precheck (:236-264) ---
        runtime = app.spec.get("runtime", RUNTIME_JAX)
        if runtime not in VALID_RUNTIMES:
            app.set_condition(COND_PRECHECK, False, "InvalidRuntime",
                              f"runtime {runtime!r} not in {VALID_RUNTIMES}")
            app.status["phase"] = PHASE_FAILED
            self._sync(app, status_before)
            return None
        if app.spec.get("replicas", 1) < 0 or app.spec.get("size", 1) < 1:
            app.set_condition(COND_PRECHECK, False, "InvalidSpec",
                              "replicas must be >= 0 and size >= 1")
            app.status["phase"] = PHASE_FAILED
            self._sync(app, status_before)
            return None
        # Reserved-name + pod-group validation (reference precheck :236-264
        # rejects the reserved 'models' volume; PodGroupPolicy is one-of).
        from arks_tpu.control.k8s_export import (
            validate_instance_spec, validate_pod_group_policy)
        try:
            validate_instance_spec(app.spec.get("instanceSpec"))
            validate_pod_group_policy(app.spec.get("podGroupPolicy"))
        except ValueError as e:
            app.set_condition(COND_PRECHECK, False, "InvalidSpec", str(e))
            app.status["phase"] = PHASE_FAILED
            self._sync(app, status_before)
            return None
        app.set_condition(COND_PRECHECK, True, "PrecheckPassed", "")
        if app.status["phase"] == PHASE_PENDING:
            app.status["phase"] = PHASE_CHECKING

        # --- model gate (:266-296) ---
        model_name = app.spec.get("model", {}).get("name")
        if not model_name:
            app.set_condition(COND_PRECHECK, False, "NoModel", "spec.model.name required")
            app.status["phase"] = PHASE_FAILED
            self._sync(app, status_before)
            return None
        model = self.store.try_get(Model, model_name, app.namespace)
        if model is None or model.phase != MODEL_PHASE_READY:
            app.set_condition(COND_LOADED, False, "ModelNotReady",
                              f"model {model_name} not ready")
            app.status["phase"] = PHASE_LOADING
            self._sync(app, status_before)
            return Result(requeue_after=1.0)
        app.set_condition(COND_LOADED, True, "ModelReady", "")
        if app.status["phase"] in (PHASE_CHECKING, PHASE_LOADING):
            app.status["phase"] = PHASE_CREATING

        # --- workload + service (:303-415) ---
        self._ensure_gangset(app, model)
        self._ensure_service(app)

        # --- status sync (:424-503) ---
        gs = self.store.try_get(GangSet, workload_name(app), app.namespace)
        st = gs.status if gs else {}
        app.status["replicas"] = st.get("replicas", 0)
        app.status["readyReplicas"] = st.get("readyReplicas", 0)
        want = app.spec.get("replicas", 1)
        if want > 0 and app.status["readyReplicas"] >= want:
            app.status["phase"] = PHASE_RUNNING
            app.set_condition(COND_READY, True, "AllReplicasReady", "")
        else:
            app.set_condition(COND_READY, False, "WaitingForReplicas",
                              f"{app.status['readyReplicas']}/{want} ready")
            if app.status["phase"] == PHASE_RUNNING:
                app.status["phase"] = PHASE_CREATING

        self._sync(app, status_before)
        # Keep the service address list fresh against gang churn.
        self._sync_service_addresses(app, st)
        return None

    # ------------------------------------------------------------------

    def _ensure_gangset(self, app: Application, model: Model) -> None:
        spec = self._generate_gangset_spec(app, model)
        name = workload_name(app)
        existing = self.store.try_get(GangSet, name, app.namespace)
        if existing is None:
            gs = GangSet(name=name, namespace=app.namespace,
                         labels={LABEL_MANAGED_BY: MANAGED_BY,
                                 LABEL_APPLICATION: app.name,
                                 LABEL_MODEL: model.name},
                         owner_refs=[(Application.KIND, app.name)],
                         spec=spec)
            self.store.create(gs)
        elif existing.spec != spec:
            # CreateOrPatch-style rolling update (:303-341).
            existing.spec = spec
            self.store.update(existing)

    def _generate_gangset_spec(self, app: Application, model: Model) -> dict:
        from arks_tpu.control.k8s_export import try_shape

        runtime = app.spec.get("runtime", RUNTIME_JAX)
        tp = app.spec.get("tensorParallel", 1)
        shape = try_shape(app.spec.get("accelerator"))
        # Gang size defaults to what the accelerator shape REQUIRES: a
        # multi-host slice (v5e-16 = 4 hosts) or multi-slice spec
        # (tpu-v5p-16x2 = 2 slices x 2 hosts = 4 pods) sets it; an
        # explicit spec.size wins.
        size = app.spec.get("size") or (shape.total_hosts if shape else 1)
        num_slices = shape.slices if shape else 1
        served = app.served_model_name or model.name
        common = list(app.spec.get("runtimeCommonArgs", []))
        model_path = model.status.get("path", RESERVED_MODELS_PATH)
        if runtime == RUNTIME_JAX:
            model_arg = app.spec.get("modelConfig") or model_path
            leader_cmd = jax_serve_command(
                model_arg=model_arg, served_model_name=served,
                port_token="$(PORT)", tensor_parallel=tp, size=size,
                common_args=common, model_path=model_path,
                platform=self.local_platform,
                context_parallel=app.spec.get("contextParallel", 1),
                num_slices=num_slices)
        else:
            leader_cmd = gpu_runtime_command(
                runtime, model_path, served, tp, size, common)
        return {
            "replicas": app.spec.get("replicas", 1),
            "size": size,
            "leader": {"command": leader_cmd, "env": {}},
            "worker": {"command": leader_cmd, "env": {}},
            "ports": {"http": 8080},
            "restartPolicy": "RecreateGroupOnPodRestart",
            "runtime": runtime,
            # Consumed by the K8s driver (live mode): pod image, TPU node
            # selection, and the models-PVC mount.  Local drivers ignore
            # these.  The PVC default is the SHARED "models" claim the
            # operator itself downloads into (deploy/operator.yaml) — in
            # live mode nothing provisions per-model PVCs, so engine pods
            # must mount the volume the weights actually landed on.
            "image": app.spec.get("runtimeImage") or default_runtime_image(runtime),
            "accelerator": app.spec.get("accelerator", "cpu"),
            "modelPvc": (model.spec.get("storage") or {}).get("pvc")
            or "models",
            # Pod-spec passthrough + gang scheduling, consumed by the K8s
            # renderer (reference: InstanceSpec arksapplication_types.go:
            # 80-250, PodGroupPolicy utils.go:9-26).
            **({"instanceSpec": app.spec["instanceSpec"]}
               if app.spec.get("instanceSpec") else {}),
            **({"podGroupPolicy": app.spec["podGroupPolicy"]}
               if app.spec.get("podGroupPolicy") else {}),
        }

    def _ensure_service(self, app: Application) -> None:
        name = service_name(app)
        if self.store.try_get(Service, name, app.namespace) is None:
            svc = Service(
                name=name, namespace=app.namespace,
                labels={LABEL_MANAGED_BY: MANAGED_BY,
                        LABEL_APPLICATION: app.name,
                        # prometheus-discovery selector parity (:388-391)
                        "prometheus-discovery": "true"},
                owner_refs=[(Application.KIND, app.name)],
                spec={"selector": {LABEL_APPLICATION: app.name,
                                   LABEL_ROLE: "leader"},
                      "port": 8080})
            self.store.create(svc)

    def _sync_service_addresses(self, app: Application, gang_status: dict) -> None:
        svc = self.store.try_get(Service, service_name(app), app.namespace)
        if svc is None:
            return
        addrs = [g["leaderAddr"] for g in gang_status.get("groups", [])
                 if g.get("phase") == "Running" and g.get("leaderAddr")]
        if svc.status.get("addresses") != addrs:
            svc.status["addresses"] = addrs
            self.store.update_status(svc)

    def _sync(self, app: Application, before: dict) -> None:
        if app.status != before:
            self.store.update_status(app)

    def finalize(self, app: Application) -> None:
        # Owned GangSet/Service are cascade-deleted by the store GC; the
        # GangSet finalizer tears down its processes.
        for kind, name in ((GangSet, workload_name(app)),
                           (Service, service_name(app))):
            try:
                self.store.delete(kind, name, app.namespace)
            except NotFound:
                pass
