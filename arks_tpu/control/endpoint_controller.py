"""Endpoint controller: service discovery -> routing table.

Mirrors the reference ArksEndpointReconciler (/root/reference/internal/
controller/arksendpoint_controller.go):

- finds Applications + DisaggregatedApplications whose servedModelName
  (fallback: model name) equals the endpoint's name, same namespace
  (field index :51-77, list :260-281)
- static spec.routeConfigs take priority over discovered backends (:286-290)
- only *ready* apps become backends (standalone: all replicas ready :300;
  disaggregated: router+prefill+decode complete :326-333), each weighted by
  spec.defaultWeight (:293-347)
- every route carries the {namespace, model} match the gateway injects as
  headers (:349-369)
- writes status.routes (:411-414)

Instead of emitting a Gateway-API HTTPRoute for Envoy, the routing table is
written to Endpoint.status.routes and consumed directly by the arks_tpu
gateway (arks_tpu.gateway) — same two-plane split, one less moving part.
"""

from __future__ import annotations

import logging
from typing import Iterable

from arks_tpu.control.reconciler import Controller, Result
from arks_tpu.control.resources import (
    Application, DisaggregatedApplication, Endpoint, Service,
)
from arks_tpu.control.store import Store

log = logging.getLogger("arks_tpu.control.endpoint")


class EndpointController(Controller):
    KIND = Endpoint

    def watches(self) -> Iterable:
        def endpoints_for_app(app) -> list[tuple[str, str]]:
            served = app.served_model_name
            return [(app.namespace, served)] if served else []

        def endpoints_for_service(svc) -> list[tuple[str, str]]:
            # Service address churn re-resolves routes for all endpoints in ns.
            return [e.key for e in self.store.list(Endpoint, namespace=svc.namespace)]

        return [(Application, endpoints_for_app),
                (DisaggregatedApplication, endpoints_for_app),
                (Service, endpoints_for_service)]

    def reconcile(self, ep: Endpoint) -> Result | None:
        routes: list[dict] = []

        # Static routes win (:286-290).
        for rc in ep.spec.get("routeConfigs", []):
            routes.append({
                "backend": rc["backend"],
                "weight": rc.get("weight", ep.spec.get("defaultWeight", 1)),
                "static": True,
            })

        default_weight = ep.spec.get("defaultWeight", 1)
        for app in self.store.list(Application, namespace=ep.namespace):
            # serving() (>=1 ready group), not ready() (ALL groups): during
            # a rolling update readiness dips by maxUnavailable=1 and the
            # route must survive on the remaining groups.
            if app.served_model_name != ep.name or not app.serving():
                continue
            routes.append(self._app_route(app, default_weight))
        for app in self.store.list(DisaggregatedApplication, namespace=ep.namespace):
            # serving(), not ready(), for the same rollout-survival reason.
            if app.served_model_name != ep.name or not app.serving():
                continue
            routes.append({
                "backend": {"service": f"{app.name}-router-svc",
                            "addresses": self._service_addrs(
                                f"{app.name}-router-svc", ep.namespace)},
                "weight": default_weight,
                "application": app.name,
            })

        # Route match contract: the gateway injects these as headers and the
        # router matches on them (:349-369).
        match = {"namespace": ep.namespace, "model": ep.name}
        if ep.status.get("routes") != routes or ep.status.get("match") != match:
            ep.status["routes"] = routes
            ep.status["match"] = match
            self.store.update_status(ep)
        return None

    def _app_route(self, app: Application, weight: int) -> dict:
        svc = f"arks-application-{app.name}"
        return {
            "backend": {"service": svc,
                        "addresses": self._service_addrs(svc, app.namespace)},
            "weight": weight,
            "application": app.name,
        }

    def _service_addrs(self, svc_name: str, namespace: str) -> list[str]:
        svc = self.store.try_get(Service, svc_name, namespace)
        return list(svc.status.get("addresses", [])) if svc else []
