"""Model controller: storage + download + (TPU twist) Orbax conversion.

Phase machine mirrors the reference ArksModelReconciler
(/root/reference/internal/controller/arksmodel_controller.go:143-367):
Pending -> StorageCreating -> ModelLoading -> Ready | Failed, conditions
StorageCreated / ModelLoaded / Ready, terminal phases skipped on re-entry
(:150-152), nil source = "existing storage" (:355-358).

Differences, TPU-native:
- Storage is a directory under ``models_root`` (stand-in for the PVC; the
  path layout matches generateModelPath :377-382 — ``<root>/<subPath>`` or
  ``<root>/models/<ns>/<name>``).
- The download worker is a background thread running a pluggable fetcher
  (local copy, HuggingFace snapshot) instead of a worker pod; its terminal
  state maps to the ModelLoaded condition exactly like the pod-phase mapping
  (:338-353).
- Optional ``spec.convertOrbax``: after download, write an Orbax sharded
  checkpoint next to the weights so multi-host slices load shards directly
  (BASELINE.json north star).
"""

from __future__ import annotations

import logging
import os
import shutil
import threading

from arks_tpu.control.reconciler import Controller, Result
from arks_tpu.control.resources import (
    COND_MODEL_LOADED, COND_READY, COND_STORAGE_CREATED,
    MODEL_PHASE_FAILED, MODEL_PHASE_LOADING, MODEL_PHASE_PENDING,
    MODEL_PHASE_READY, MODEL_PHASE_STORAGE_CREATING, Model,
)
from arks_tpu.control.store import Store

log = logging.getLogger("arks_tpu.control.model")


def default_fetcher(model: Model, dest: str) -> None:
    """Fetch model artifacts into ``dest``.

    source.local.path  -> copy a local directory (tests, pre-staged NFS)
    source.huggingface -> snapshot_download (needs egress; mirrors
                          /root/reference/scripts/download.py)
    """
    source = model.spec.get("source") or {}
    if "local" in source:
        src = source["local"]["path"]
        if not os.path.isdir(src):
            raise FileNotFoundError(f"local source {src} does not exist")
        shutil.copytree(src, dest, dirs_exist_ok=True)
        return
    if "huggingface" in source:
        from huggingface_hub import snapshot_download  # optional dep

        repo = model.spec.get("model") or model.name
        token = source["huggingface"].get("token")
        snapshot_download(repo_id=repo, local_dir=dest, token=token)
        return
    raise ValueError(f"unsupported model source {sorted(source)}")


class _Worker:
    def __init__(self) -> None:
        self.phase = "Running"
        self.message = ""


class ModelController(Controller):
    KIND = Model
    FINALIZER = "model.arks.ai/controller"

    def __init__(self, store: Store, models_root: str,
                 fetcher=default_fetcher, workers: int = 2):
        super().__init__(store, workers=workers)
        self.models_root = models_root
        self.fetcher = fetcher
        self._download_workers: dict[tuple, _Worker] = {}
        self._dw_lock = threading.Lock()

    # reference generateModelPath (arksmodel_controller.go:377-382)
    def model_path(self, m: Model) -> str:
        sub = (m.spec.get("storage") or {}).get("subPath")
        if sub:
            return os.path.join(self.models_root, sub)
        return os.path.join(self.models_root, "models", m.namespace, m.name)

    def reconcile(self, m: Model) -> Result | None:
        if m.phase in (MODEL_PHASE_READY, MODEL_PHASE_FAILED):
            return None  # terminal (:150-152)

        changed = False
        if not m.status.get("phase"):
            m.status["phase"] = MODEL_PHASE_PENDING
            changed = True

        # --- storage (:172-216) ---
        if not m.condition(COND_STORAGE_CREATED):
            path = self.model_path(m)
            os.makedirs(path, exist_ok=True)
            m.status["path"] = path
            m.status["phase"] = MODEL_PHASE_STORAGE_CREATING
            m.set_condition(COND_STORAGE_CREATED, True, "StorageReady", path)
            changed = True

        # --- download (:218-358) ---
        if not m.condition(COND_MODEL_LOADED):
            if not m.spec.get("source"):
                # Existing storage: nothing to download (:355-358).
                m.set_condition(COND_MODEL_LOADED, True, "ExistingStorage",
                                "no source specified; using existing storage")
                changed = True
            else:
                worker = self._ensure_worker(m)
                m.status["phase"] = MODEL_PHASE_LOADING
                if worker.phase == "Succeeded":
                    m.set_condition(COND_MODEL_LOADED, True, "Downloaded", "")
                    changed = True
                elif worker.phase == "Failed":
                    m.set_condition(COND_MODEL_LOADED, False, "DownloadFailed",
                                    worker.message)
                    m.status["phase"] = MODEL_PHASE_FAILED
                    self.store.update_status(m)
                    return None
                else:
                    self.store.update_status(m)
                    return Result(requeue_after=0.2)

        # --- ready ---
        if m.condition(COND_STORAGE_CREATED) and m.condition(COND_MODEL_LOADED):
            m.status["phase"] = MODEL_PHASE_READY
            m.set_condition(COND_READY, True, "ModelReady", "")
            changed = True

        if changed:
            self.store.update_status(m)
        return None

    def _ensure_worker(self, m: Model) -> _Worker:
        with self._dw_lock:
            worker = self._download_workers.get(m.key)
            if worker is not None:
                return worker
            worker = _Worker()
            self._download_workers[m.key] = worker

        def run():
            dest = self.model_path(m)
            try:
                self.fetcher(m, dest)
                if m.spec.get("convertOrbax"):
                    self._convert_orbax(m, dest)
                worker.phase = "Succeeded"
            except Exception as e:
                log.exception("model %s/%s download failed", m.namespace, m.name)
                worker.phase = "Failed"
                worker.message = str(e)  # termination-message analogue (:338-353)
            self.queue.add(m.key)

        threading.Thread(target=run, name=f"download-{m.name}", daemon=True).start()
        return worker

    def _convert_orbax(self, m: Model, dest: str) -> None:
        from arks_tpu.models.config import ModelConfig
        from arks_tpu.models.weights import convert_hf_to_orbax

        cfg = ModelConfig.from_hf_config(dest, name=m.name)
        convert_hf_to_orbax(cfg, dest)

    def finalize(self, m: Model) -> None:
        with self._dw_lock:
            self._download_workers.pop(m.key, None)
        # Storage retention mirrors PVC semantics: data outlives the CR
        # unless explicitly reclaimed.
        if (m.spec.get("storage") or {}).get("reclaim") == "Delete":
            path = m.status.get("path")
            if path and os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
