"""Model download worker: ``python -m arks_tpu.control.download``.

Env-driven like the reference's scripts/download.py (MODEL_NAME, MODEL_PATH,
HF_TOKEN; exit code -> Job status), with the same bounded-retry behavior
(3 attempts, 10s backoff, fatal-HTTP short-circuit — download.py:44-73).
TPU twist (BASELINE.json north star): after download, optionally convert to
an Orbax sharded checkpoint (ARKS_CONVERT_ORBAX=1) so multi-host slices load
only their own shards.
"""

from __future__ import annotations

import logging
import os
import sys
import time

from arks_tpu.utils import knobs

log = logging.getLogger("arks_tpu.download")

RETRIES = 3
BACKOFF_S = 10


def fetch(repo: str, dest: str, token: str | None) -> None:
    from huggingface_hub import snapshot_download
    from huggingface_hub.errors import (
        GatedRepoError, RepositoryNotFoundError,
    )

    last: Exception | None = None
    for attempt in range(1, RETRIES + 1):
        try:
            snapshot_download(repo_id=repo, local_dir=dest, token=token)
            return
        except (GatedRepoError, RepositoryNotFoundError):
            raise  # fatal: retrying can't help (reference download.py:58-66)
        except Exception as e:  # transient (network, 5xx)
            last = e
            log.warning("download attempt %d/%d failed: %s", attempt,
                        RETRIES, e, exc_info=True)
            if attempt < RETRIES:
                time.sleep(BACKOFF_S)
    raise RuntimeError(f"download failed after {RETRIES} attempts: {last}")


def convert_orbax(dest: str) -> None:
    from arks_tpu.models.config import ModelConfig
    from arks_tpu.models.weights import convert_hf_to_orbax

    cfg_path = os.path.join(dest, "config.json")
    if not os.path.isfile(cfg_path):
        log.warning("no config.json under %s; skipping Orbax conversion", dest)
        return
    cfg = ModelConfig.from_hf_config(dest, name=os.path.basename(dest))
    path = convert_hf_to_orbax(cfg, dest)
    log.info("Orbax checkpoint at %s", path)


def main() -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    repo = os.environ.get("MODEL_NAME")
    dest = os.environ.get("MODEL_PATH")
    if not repo or not dest:
        log.error("MODEL_NAME and MODEL_PATH are required")
        return 2
    token = os.environ.get("HF_TOKEN") or None
    os.makedirs(dest, exist_ok=True)
    try:
        fetch(repo, dest, token)
    except Exception as e:
        log.exception("model download failed: %s", e)
        return 1
    if knobs.get_bool("ARKS_CONVERT_ORBAX"):
        try:
            convert_orbax(dest)
        except Exception as e:
            # Conversion is an optimization; raw safetensors still serve.
            log.warning("Orbax conversion failed (serving falls back to "
                        "safetensors): %s", e, exc_info=True)
    log.info("model %s ready at %s", repo, dest)
    return 0


if __name__ == "__main__":
    sys.exit(main())
