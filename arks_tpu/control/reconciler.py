"""Reconciler framework: level-triggered controllers over the Store.

The controller-runtime pattern the reference is built on (watch -> workqueue
-> Reconcile(key) -> requeue), reduced to its essentials: per-controller
worker threads pull dedup'd keys from a queue fed by watch streams; a
reconcile returns an optional requeue delay; errors requeue with backoff.
"""

from __future__ import annotations

import heapq
import logging
import queue
import threading
import time
from typing import Callable, Iterable, Type

from arks_tpu.control.resources import Resource
from arks_tpu.control.store import Store

log = logging.getLogger("arks_tpu.control")


class Result:
    def __init__(self, requeue_after: float | None = None):
        self.requeue_after = requeue_after


class WorkQueue:
    """Dedup'd delay-capable work queue (a tiny workqueue.RateLimiting)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: set = set()
        self._ready: list = []
        self._delayed: list[tuple[float, object]] = []  # heap (when, key)
        self._shutdown = False

    def add(self, key, delay: float = 0.0) -> None:
        with self._cond:
            if delay > 0:
                heapq.heappush(self._delayed, (time.monotonic() + delay, key))
            elif key not in self._pending:
                self._pending.add(key)
                self._ready.append(key)
            self._cond.notify()

    def get(self, timeout: float = 0.2):
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                now = time.monotonic()
                while self._delayed and self._delayed[0][0] <= now:
                    _, key = heapq.heappop(self._delayed)
                    if key not in self._pending:
                        self._pending.add(key)
                        self._ready.append(key)
                if self._ready:
                    key = self._ready.pop(0)
                    self._pending.discard(key)
                    return key
                if self._shutdown or now >= deadline:
                    return None
                wait = deadline - now
                if self._delayed:
                    wait = min(wait, self._delayed[0][0] - now)
                self._cond.wait(max(wait, 0.001))

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()


class Controller:
    """Base controller: watches kinds, reconciles keys (namespace, name).

    Subclasses set ``KIND`` (primary kind) and override ``reconcile(obj)``;
    secondary watches map events to primary keys via ``watches()`` —
    the reference's Owns()/Watches() with handler mappers
    (e.g. arksapplication_controller.go:123-150).
    """

    KIND: Type[Resource] = Resource
    FINALIZER = ""
    ERROR_BACKOFF = 0.5

    def __init__(self, store: Store, workers: int = 1, name: str | None = None):
        self.store = store
        self.queue = WorkQueue()
        self.name = name or type(self).__name__
        self._workers = workers
        self._threads: list[threading.Thread] = []
        self._running = False

    # -- wiring --------------------------------------------------------

    def watches(self) -> Iterable[tuple[Type[Resource], Callable[[Resource], Iterable[tuple[str, str]]]]]:
        """Secondary (kind, mapper) pairs: mapper(event obj) -> primary keys."""
        return []

    def start(self) -> None:
        self._running = True

        def pump(kind, mapper):
            q = self.store.watch(kind)
            while self._running:
                try:
                    event, obj = q.get(timeout=0.2)
                except queue.Empty:
                    continue
                for key in mapper(obj):
                    self.queue.add(key)

        primary_pump = threading.Thread(
            target=pump, args=(self.KIND, lambda o: [o.key]),
            name=f"{self.name}-watch", daemon=True)
        primary_pump.start()
        self._threads.append(primary_pump)
        for kind, mapper in self.watches():
            t = threading.Thread(target=pump, args=(kind, mapper),
                                 name=f"{self.name}-watch-{kind.KIND}", daemon=True)
            t.start()
            self._threads.append(t)
        for i in range(self._workers):
            t = threading.Thread(target=self._work, name=f"{self.name}-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        self.queue.shutdown()
        for t in self._threads:
            t.join(timeout=5)

    def kick(self, name: str, namespace: str = "default") -> None:
        """Enqueue an immediate reconcile of one primary object, outside
        any watch event.  The elastic control loop uses this to force an
        autoscaler evaluation the moment fresh overload evidence lands
        (an SLO-burn spike mid-tick) instead of waiting out the ticker
        interval; the workqueue's dedup makes redundant kicks free."""
        self.queue.add((namespace, name))

    # -- loop ----------------------------------------------------------

    def _work(self) -> None:
        while self._running:
            key = self.queue.get(timeout=0.2)
            if key is None:
                continue
            ns, name = key
            try:
                obj = self.store.try_get(self.KIND, name, ns)
                if obj is None:
                    continue
                if obj.deletion_requested:
                    self.finalize(obj)
                    if self.FINALIZER:
                        self.store.strip_finalizer(obj, self.FINALIZER)
                    continue
                if self.FINALIZER and self.FINALIZER not in obj.finalizers:
                    obj = self.store.add_finalizer(obj, self.FINALIZER)
                result = self.reconcile(obj)
                if result is not None and result.requeue_after:
                    self.queue.add(key, delay=result.requeue_after)
            except Exception:
                log.exception("%s: reconcile %s/%s failed", self.name, ns, name)
                self.queue.add(key, delay=self.ERROR_BACKOFF)

    # -- to override ---------------------------------------------------

    def reconcile(self, obj: Resource) -> Result | None:
        raise NotImplementedError

    def finalize(self, obj: Resource) -> None:
        """Cleanup on deletion (before the finalizer is stripped)."""


class Manager:
    """Holds the store + controllers; mirrors cmd/main.go's manager setup."""

    def __init__(self, store: Store | None = None):
        self.store = store or Store()
        self.controllers: list[Controller] = []

    def add(self, controller: Controller) -> Controller:
        self.controllers.append(controller)
        return controller

    def start(self) -> None:
        for c in self.controllers:
            c.start()

    def stop(self) -> None:
        for c in self.controllers:
            c.stop()

    def wait_idle(self, timeout: float = 30.0, settle: float = 0.3) -> bool:
        """Test helper: wait until all workqueues drain and stay drained."""
        deadline = time.monotonic() + timeout
        idle_since = None
        while time.monotonic() < deadline:
            busy = any(c.queue._ready or c.queue._pending for c in self.controllers)
            if busy:
                idle_since = None
            elif idle_since is None:
                idle_since = time.monotonic()
            elif time.monotonic() - idle_since >= settle:
                return True
            time.sleep(0.02)
        return False
