"""DisaggregatedApplication controller: prefill/decode-separated serving.

Mirrors the reference ArksDisaggregatedApplicationReconciler
(/root/reference/internal/controller/
arksdisaggregatedapplication_controller.go):

- same phase machine as the standalone controller (:208-216 precheck,
  Pending -> Checking -> Loading -> Creating -> Running | Failed)
- three workloads per app: router + prefill gang + decode gang
  (legacy-mode layout ``<name>-prefill`` / ``<name>-decode`` + router
  deployment :284-391; the router Service is ``<name>-router-svc`` :739-770)
- per-component status {replicas, readyReplicas} synced back (:393-497)

TPU-native differences:
- runtime is the arks_tpu jax server with ``--disaggregation-mode
  prefill|decode`` (flag parity with the reference's SGLang commands
  :1672-1724) and ``python -m arks_tpu.router`` instead of sglang_router
- service discovery: instead of the reference router's k8s label-selector
  pod watch (:1630-1670), the controller maintains a discovery JSON file
  (locally a tmp file; on k8s a ConfigMap volume) listing ready
  prefill/decode addresses; the router hot-reloads it on mtime change.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import tempfile
from typing import Iterable

from arks_tpu.control.reconciler import Controller, Result
from arks_tpu.control.resources import (
    COND_LOADED, COND_PRECHECK, COND_READY, LABEL_APPLICATION,
    LABEL_MANAGED_BY, LABEL_MODEL, LABEL_ROLE, MANAGED_BY, MODEL_PHASE_READY,
    PHASE_CHECKING, PHASE_CREATING, PHASE_FAILED, PHASE_LOADING,
    PHASE_PENDING, PHASE_RUNNING, RESERVED_MODELS_PATH, RUNTIME_JAX,
    DisaggregatedApplication, GangSet, Model, Service,
)
from arks_tpu.control.store import NotFound, Store
from arks_tpu.control.workloads import default_runtime_image, jax_serve_command

log = logging.getLogger("arks_tpu.control.disaggregated")

COMPONENTS = ("router", "prefill", "decode")


def component_name(app: DisaggregatedApplication, component: str) -> str:
    # reference naming: <name>-prefill / <name>-decode (:284-391)
    return f"{app.name}-{component}"


def router_service_name(app: DisaggregatedApplication) -> str:
    # reference: <name>-router-svc (:739-770)
    return f"{app.name}-router-svc"


class DisaggregatedApplicationController(Controller):
    KIND = DisaggregatedApplication
    FINALIZER = "disaggregatedapplication.arks.ai/controller"

    def __init__(self, store: Store, workers: int = 4,
                 local_platform: str | None = None,
                 discovery_dir: str | None = None,
                 router_discovery: str = "file"):
        super().__init__(store, workers=workers)
        self.local_platform = local_platform
        if router_discovery not in ("file", "kubernetes"):
            raise ValueError(f"router_discovery={router_discovery!r}")
        # "file": the operator maintains a discovery JSON on a filesystem
        # it shares with the router (local single-binary mode).
        # "kubernetes": routers discover prefill/decode pods themselves by
        # label selector (the reference's --service-discovery; REQUIRED in
        # live-operator mode, where routers run as cluster pods with no
        # shared filesystem).
        self.router_discovery = router_discovery
        self.discovery_dir = discovery_dir or os.path.join(
            tempfile.gettempdir(), "arks-tpu-discovery")
        os.makedirs(self.discovery_dir, exist_ok=True)

    def watches(self) -> Iterable:
        def apps_for_model(model) -> list[tuple[str, str]]:
            return [a.key for a in self.store.list(
                DisaggregatedApplication, namespace=model.namespace)
                if a.spec.get("model", {}).get("name") == model.name]

        def app_for_gangset(gs) -> list[tuple[str, str]]:
            for kind, name in gs.owner_refs:
                if kind == DisaggregatedApplication.KIND:
                    return [(gs.namespace, name)]
            return []

        return [(Model, apps_for_model), (GangSet, app_for_gangset)]

    # ------------------------------------------------------------------

    def reconcile(self, app: DisaggregatedApplication) -> Result | None:
        status_before = app.deepcopy().status

        if not app.status.get("phase"):
            app.status["phase"] = PHASE_PENDING

        # --- precheck: only the jax runtime supports native PD separation
        # (the reference only supports sglang there, :208-216). ---
        runtime = app.spec.get("runtime", RUNTIME_JAX)
        if runtime != RUNTIME_JAX:
            app.set_condition(COND_PRECHECK, False, "InvalidRuntime",
                              f"disaggregated serving requires runtime "
                              f"{RUNTIME_JAX!r}, got {runtime!r}")
            app.status["phase"] = PHASE_FAILED
            self._sync(app, status_before)
            return None
        from arks_tpu.control.k8s_export import (
            validate_dapp_mode, validate_instance_spec,
            validate_pod_group_policy)
        try:
            validate_dapp_mode(app.spec.get("mode", "legacy"))
            validate_pod_group_policy(app.spec.get("podGroupPolicy"))
            for section in ("prefill", "decode", "router"):
                validate_instance_spec(
                    (app.spec.get(section) or {}).get("instanceSpec"))
        except ValueError as e:
            app.set_condition(COND_PRECHECK, False, "InvalidSpec", str(e))
            app.status["phase"] = PHASE_FAILED
            self._sync(app, status_before)
            return None
        app.set_condition(COND_PRECHECK, True, "PrecheckPassed", "")
        if app.status["phase"] == PHASE_PENDING:
            app.status["phase"] = PHASE_CHECKING

        # --- model gate ---
        model_name = app.spec.get("model", {}).get("name")
        if not model_name:
            app.set_condition(COND_PRECHECK, False, "NoModel",
                              "spec.model.name required")
            app.status["phase"] = PHASE_FAILED
            self._sync(app, status_before)
            return None
        model = self.store.try_get(Model, model_name, app.namespace)
        if model is None or model.phase != MODEL_PHASE_READY:
            app.set_condition(COND_LOADED, False, "ModelNotReady",
                              f"model {model_name} not ready")
            app.status["phase"] = PHASE_LOADING
            self._sync(app, status_before)
            return Result(requeue_after=1.0)
        app.set_condition(COND_LOADED, True, "ModelReady", "")
        if app.status["phase"] in (PHASE_CHECKING, PHASE_LOADING):
            app.status["phase"] = PHASE_CREATING

        # --- workloads: prefill + decode gangs, then router ---
        statuses: dict[str, dict] = {}
        for component in ("prefill", "decode"):
            self._ensure_gangset(
                app, model, component,
                self._worker_spec(app, model, component))
            gs = self.store.try_get(
                GangSet, component_name(app, component), app.namespace)
            statuses[component] = gs.status if gs else {}

        # Discovery file BEFORE the router so it starts with addresses.
        self._write_discovery(app, statuses)
        self._ensure_gangset(app, model, "router", self._router_spec(app))
        gs = self.store.try_get(GangSet, component_name(app, "router"),
                                app.namespace)
        statuses["router"] = gs.status if gs else {}

        self._ensure_router_service(app)

        # --- status sync (:393-497) ---
        for component in COMPONENTS:
            st = statuses[component]
            app.status[component] = {
                "replicas": st.get("replicas", 0),
                "readyReplicas": st.get("readyReplicas", 0),
            }
        if app.ready():
            app.status["phase"] = PHASE_RUNNING
            app.set_condition(COND_READY, True, "AllComponentsReady", "")
        else:
            waiting = ", ".join(
                f"{c}={app.status[c]['readyReplicas']}/"
                f"{app.spec.get(c, {}).get('replicas', 1)}"
                for c in COMPONENTS)
            app.set_condition(COND_READY, False, "WaitingForComponents", waiting)
            if app.status["phase"] == PHASE_RUNNING:
                app.status["phase"] = PHASE_CREATING

        self._sync(app, status_before)
        self._sync_router_addresses(app, statuses["router"])
        return None

    # ------------------------------------------------------------------
    # Spec generation
    # ------------------------------------------------------------------

    def _worker_spec(self, app: DisaggregatedApplication, model: Model,
                     component: str) -> dict:
        from arks_tpu.control.k8s_export import try_shape

        ws = app.spec.get(component, {})
        tp = ws.get("tensorParallel", app.spec.get("tensorParallel", 1))
        # Same shape derivation as the Application path: a multi-host /
        # multi-slice accelerator sizes the tier's gang (explicit size
        # wins) — the live and gitops renderings must agree.
        shape = try_shape(ws.get("accelerator", app.spec.get("accelerator")))
        size = ws.get("size") or (shape.total_hosts if shape else 1)
        served = app.served_model_name or model.name
        common = list(ws.get("runtimeCommonArgs",
                             app.spec.get("runtimeCommonArgs", [])))
        common += ["--disaggregation-mode", component]
        model_path = model.status.get("path", RESERVED_MODELS_PATH)
        model_arg = app.spec.get("modelConfig") or model_path
        cmd = jax_serve_command(
            model_arg=model_arg, served_model_name=served,
            port_token="$(PORT)", tensor_parallel=tp, size=size,
            common_args=common, model_path=model_path,
            platform=self.local_platform,
            # Ring-attention prefill for long prompts — most useful on the
            # prefill tier (decode replicates over the seq axis).
            context_parallel=ws.get("contextParallel",
                                    app.spec.get("contextParallel", 1)),
            num_slices=shape.slices if shape else 1)
        return {
            "replicas": ws.get("replicas", 1),
            "size": size,
            "leader": {"command": cmd, "env": {}},
            "worker": {"command": cmd, "env": {}},
            "ports": {"http": 8080},
            "restartPolicy": "RecreateGroupOnPodRestart",
            "runtime": RUNTIME_JAX,
            "role": component,
            # K8s-driver (live mode) fields — see application_controller.
            "image": ws.get("runtimeImage")
            or app.spec.get("runtimeImage")
            or default_runtime_image(RUNTIME_JAX),
            "accelerator": ws.get("accelerator",
                                  app.spec.get("accelerator", "cpu")),
            "modelPvc": (model.spec.get("storage") or {}).get("pvc")
            or "models",  # shared operator claim (see application_controller)
            **({"instanceSpec": ws["instanceSpec"]}
               if ws.get("instanceSpec") else {}),
            **({"podGroupPolicy": app.spec["podGroupPolicy"]}
               if app.spec.get("podGroupPolicy") else {}),
            **({"podGroupUnit": unit}
               if (unit := self._pod_group_unit(app)) else {}),
        }

    def _router_spec(self, app: DisaggregatedApplication) -> dict:
        rs = app.spec.get("router", {})
        served = app.served_model_name or app.spec.get("model", {}).get("name", "")
        if self.router_discovery == "kubernetes":
            discovery_args = ["--service-discovery",
                              "--namespace", app.namespace,
                              "--application", app.name,
                              "--backend-port", "8080"]
        else:
            discovery_args = ["--discovery-file", self._discovery_path(app)]
        cmd = [sys.executable, "-m", "arks_tpu.router",
               "--port", "$(PORT)",
               "--served-model-name", served,
               *discovery_args,
               # RouterArgs passthrough (reference:
               # arksdisaggregatedapplication_types.go:69-84).
               *[str(a) for a in rs.get("routerArgs", [])]]
        return {
            "replicas": rs.get("replicas", 1),
            "size": 1,
            "leader": {"command": cmd, "env": {}},
            "worker": {"command": cmd, "env": {}},
            "ports": {"http": 8080},
            "restartPolicy": "RecreateGroupOnPodRestart",
            "runtime": "router",
            "role": "router",
            "image": rs.get("runtimeImage")
            or app.spec.get("runtimeImage")
            or default_runtime_image(RUNTIME_JAX),
            "accelerator": "cpu",
            **({"instanceSpec": rs["instanceSpec"]}
               if rs.get("instanceSpec") else {}),
            # Unified layout: the router (scheduler role) joins the unit
            # PodGroup too (reference unified RBGS :1316-1320).
            **({"podGroupPolicy": app.spec["podGroupPolicy"],
                "podGroupUnit": unit}
               if (unit := self._pod_group_unit(app)) else {}),
        }

    def _pod_group_unit(self, app: DisaggregatedApplication) -> dict | None:
        """Unified layout: ONE PodGroup spans every router/prefill/decode
        pod (minMember = the whole PD unit), so a unit schedules atomically
        — the GangSet carries it for the K8s driver."""
        if (app.spec.get("mode", "legacy") != "unified"
                or not app.spec.get("podGroupPolicy")):
            return None
        from arks_tpu.control.k8s_export import _shape
        total = (app.spec.get("router") or {}).get("replicas", 1)
        for tier in ("prefill", "decode"):
            ws = {**app.spec, **(app.spec.get(tier) or {})}
            total += ws.get("replicas", 1) * _shape(
                ws.get("accelerator", "cpu")).total_hosts
        return {"name": f"arks-{app.name}", "minMember": total}

    def _ensure_gangset(self, app: DisaggregatedApplication, model: Model,
                        component: str, spec: dict) -> None:
        name = component_name(app, component)
        existing = self.store.try_get(GangSet, name, app.namespace)
        if existing is None:
            gs = GangSet(name=name, namespace=app.namespace,
                         labels={LABEL_MANAGED_BY: MANAGED_BY,
                                 LABEL_APPLICATION: app.name,
                                 LABEL_MODEL: model.name if model else "",
                                 LABEL_ROLE: component},
                         owner_refs=[(DisaggregatedApplication.KIND, app.name)],
                         spec=spec)
            self.store.create(gs)
        elif existing.spec != spec:
            existing.spec = spec
            self.store.update(existing)

    # ------------------------------------------------------------------
    # Discovery + service
    # ------------------------------------------------------------------

    def _discovery_path(self, app: DisaggregatedApplication) -> str:
        return os.path.join(self.discovery_dir,
                            f"{app.namespace}-{app.name}.json")

    def _write_discovery(self, app: DisaggregatedApplication,
                         statuses: dict[str, dict]) -> None:
        data = {}
        for component in ("prefill", "decode"):
            data[component] = [
                g["leaderAddr"] for g in
                statuses.get(component, {}).get("groups", [])
                if g.get("phase") == "Running" and g.get("leaderAddr")]
        path = self._discovery_path(app)
        try:
            with open(path) as f:
                if json.load(f) == data:
                    return  # unchanged; don't bump mtime
        except (OSError, ValueError, json.JSONDecodeError):
            pass
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)

    def _ensure_router_service(self, app: DisaggregatedApplication) -> None:
        name = router_service_name(app)
        if self.store.try_get(Service, name, app.namespace) is None:
            svc = Service(
                name=name, namespace=app.namespace,
                labels={LABEL_MANAGED_BY: MANAGED_BY,
                        LABEL_APPLICATION: app.name,
                        "prometheus-discovery": "true"},
                owner_refs=[(DisaggregatedApplication.KIND, app.name)],
                spec={"selector": {LABEL_APPLICATION: app.name,
                                   LABEL_ROLE: "router"},
                      "port": 8080})
            self.store.create(svc)

    def _sync_router_addresses(self, app: DisaggregatedApplication,
                               router_status: dict) -> None:
        svc = self.store.try_get(Service, router_service_name(app),
                                 app.namespace)
        if svc is None:
            return
        addrs = [g["leaderAddr"] for g in router_status.get("groups", [])
                 if g.get("phase") == "Running" and g.get("leaderAddr")]
        if svc.status.get("addresses") != addrs:
            svc.status["addresses"] = addrs
            self.store.update_status(svc)

    def _sync(self, app: DisaggregatedApplication, before: dict) -> None:
        if app.status != before:
            self.store.update_status(app)

    def finalize(self, app: DisaggregatedApplication) -> None:
        for component in COMPONENTS:
            try:
                self.store.delete(GangSet, component_name(app, component),
                                  app.namespace)
            except NotFound:
                pass
        try:
            self.store.delete(Service, router_service_name(app), app.namespace)
        except NotFound:
            pass
        try:
            os.remove(self._discovery_path(app))
        except OSError:
            pass
