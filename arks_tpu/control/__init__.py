from arks_tpu.control.resources import (
    Application, DisaggregatedApplication, Endpoint, Model, Quota, Token,
)
from arks_tpu.control.store import Store

__all__ = ["Application", "DisaggregatedApplication", "Endpoint", "Model",
           "Quota", "Token", "Store"]
