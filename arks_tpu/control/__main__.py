"""Operator entrypoint: ``python -m arks_tpu.control [flags]``.

The single-binary analogue of the reference's two deployments (operator
cmd/main.go + gateway cmd/gateway/main.go): starts the controller set over a
store, optionally the QoS gateway, and applies manifests — so

    python -m arks_tpu.control --manifests examples/quickstart/quickstart.yaml

is the ``kubectl apply -f examples/quickstart`` of the local/single-node
deployment mode.  Manifests are YAML documents with the same kind/metadata/
spec shape as the reference CRs.
"""

from __future__ import annotations

import argparse
import logging
import signal
import time

log = logging.getLogger("arks_tpu.operator")


def apply_manifests(store, path: str) -> list:
    import yaml

    from arks_tpu.control.resources import KIND_BY_NAME
    from arks_tpu.control.store import Conflict

    applied = []
    with open(path) as f:
        for doc in yaml.safe_load_all(f):
            if not doc:
                continue
            kind = doc.get("kind")
            cls = KIND_BY_NAME.get(kind)
            if cls is None:
                raise ValueError(f"unknown kind {kind!r} in {path}")
            obj = cls.from_dict(doc)
            try:
                store.create(obj)
            except Conflict:
                cur = store.get(cls, obj.name, obj.namespace)
                cur.spec = obj.spec
                store.update(cur)
            applied.append(obj)
            log.info("applied %s %s/%s", kind, obj.namespace, obj.name)
    return applied


def main() -> None:
    p = argparse.ArgumentParser("arks_tpu.control")
    p.add_argument("--models-root", default="/tmp/arks-tpu/models")
    p.add_argument("--manifests", action="append", default=[])
    p.add_argument("--gateway-port", type=int, default=8081)
    p.add_argument("--no-gateway", action="store_true")
    p.add_argument("--local-platform", default=None,
                   help="force jax platform for spawned engines (cpu for demos)")
    args = p.parse_args()

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    from arks_tpu.control.manager import build_manager
    from arks_tpu.control.store import Store
    from arks_tpu.gateway.server import Gateway

    store = Store()
    gateway = None if args.no_gateway else Gateway(store, port=args.gateway_port)
    # The embedded gateway's admitted-request rates drive the native
    # autoscaler (Application.spec.autoscale) — K8s deployments use
    # deploy/hpa.yaml over the same metric instead.
    mgr = build_manager(models_root=args.models_root, store=store,
                        local_platform=args.local_platform,
                        rate_source=gateway.rate.rpm if gateway else None)
    mgr.start()
    if gateway is not None:
        gateway.start(background=True)
        log.info("gateway on :%d", gateway.port)
    for path in args.manifests:
        apply_manifests(mgr.store, path)

    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.5)
    finally:
        log.info("shutting down")
        if gateway:
            gateway.stop()
        mgr.stop()


if __name__ == "__main__":
    main()
