"""Kubernetes manifest rendering: arks resources -> GKE TPU YAML.

The reference deploys by reconciling CRDs into LWS/RBGS/Deployments inside a
live cluster (internal/controller/).  The TPU build has two deployment
modes:

- **Local/single-node** (arks_tpu.control.__main__): controllers drive real
  subprocesses — the demo and test path.
- **Kubernetes** (this module): the same resources render to plain K8s
  manifests for GKE TPU node pools — gitops-style (`render` then
  `kubectl apply`), so no LWS/RBGS operator dependency is needed:

  * Model      -> PVC + one-shot download Job (arksmodel_controller.go:172-354
                  semantics: storage then loader, /models contract)
  * Application-> per-replica StatefulSet (gang of ``size`` hosts,
                  ``podManagementPolicy: Parallel``, headless Service for the
                  leader DNS — the LWS leader/worker contract rendered onto
                  native objects) + a front Service
                  ``arks-application-<name>`` on :8080
                  (arksapplication_controller.go:376-415).  The front Service
                  selects ALL gang pods; the engine's /readiness gates
                  traffic to process 0, so multi-host workers receive none.
  * DisaggregatedApplication -> prefill + decode gangs (same shape, with
                  --disaggregation-mode) + per-tier Services + a router
                  Deployment (arksdisaggregatedapplication_controller.go
                  legacy-mode analogue)
  * Endpoint   -> Gateway-API HTTPRoute with the {namespace, model} header
                  matches the gateway injects (arksendpoint_controller.go:
                  349-369)

TPU topology: ``spec.accelerator`` (e.g. "tpu-v5e-8") resolves to the GKE
nodeSelector pair (gke-tpu-accelerator, gke-tpu-topology), hosts per slice,
and chips per host.  Multi-host slices get the JAX rendezvous env contract
(ARKS_COORDINATOR_ADDRESS / ARKS_NUM_PROCESSES / ARKS_PROCESS_ID — the
LWS_LEADER_ADDRESS/LWS_GROUP_SIZE/LWS_WORKER_INDEX translation, reference
controller :560-569), with the worker index taken from the pod ordinal
label (apps.kubernetes.io/pod-index).
"""

from __future__ import annotations

import copy
import dataclasses
import json

from arks_tpu.control.resources import (
    Application, DisaggregatedApplication, Endpoint, LABEL_APPLICATION,
    LABEL_COMPONENT, LABEL_MANAGED_BY, MANAGED_BY, Model,
    RESERVED_MODELS_PATH, RESERVED_MODELS_VOLUME,
)


@dataclasses.dataclass(frozen=True)
class TpuTopology:
    accelerator: str      # GKE gke-tpu-accelerator label
    topology: str         # GKE gke-tpu-topology label
    hosts: int            # pods per slice (gang size = hosts * slices)
    chips_per_host: int
    slices: int = 1       # multi-slice: ICI slices joined over DCN

    @property
    def total_hosts(self) -> int:
        return self.hosts * self.slices


# Common GKE TPU shapes (accelerator spec string -> node pool selectors).
TPU_SHAPES: dict[str, TpuTopology] = {
    "cpu": TpuTopology("", "", 1, 0),
    "tpu-v5e-1": TpuTopology("tpu-v5-lite-podslice", "1x1", 1, 1),
    "tpu-v5e-4": TpuTopology("tpu-v5-lite-podslice", "2x2", 1, 4),
    "tpu-v5e-8": TpuTopology("tpu-v5-lite-podslice", "2x4", 1, 8),
    "tpu-v5e-16": TpuTopology("tpu-v5-lite-podslice", "4x4", 4, 4),
    "tpu-v5e-32": TpuTopology("tpu-v5-lite-podslice", "4x8", 8, 4),
    "tpu-v5p-8": TpuTopology("tpu-v5p-slice", "2x2x1", 1, 4),
    "tpu-v5p-16": TpuTopology("tpu-v5p-slice", "2x2x2", 2, 4),
    "tpu-v6e-8": TpuTopology("tpu-v6e-slice", "2x4", 1, 8),
}

DEFAULT_IMAGE = "arks-tpu/engine:latest"
DEFAULT_SCRIPTS_IMAGE = "arks-tpu/engine:latest"


def _default_image(runtime: str = "jax") -> str:
    # Env escape hatches, same contract as the reference
    # (ARKS_RUNTIME_DEFAULT_*_IMAGE, arksapplication_controller.go:907-939).
    from arks_tpu.control.workloads import default_runtime_image
    return default_runtime_image(runtime)


def _scripts_image() -> str:
    from arks_tpu.control.workloads import default_scripts_image
    return default_scripts_image()

# ---------------------------------------------------------------------------
# InstanceSpec passthrough (reference: ArksInstanceSpec,
# api/v1/arksapplication_types.go:80-250 — the ~35-field pod-spec channel
# every workload-bearing CRD embeds).  Fields are grouped by where they land:
# engine container, pod spec, or pod template metadata.
# ---------------------------------------------------------------------------

# Copied verbatim onto the engine container when present.
_INSTANCE_CONTAINER_FIELDS = (
    "livenessProbe", "readinessProbe", "startupProbe", "lifecycle",
    "securityContext",
)

# Copied verbatim onto the pod spec when present.
_INSTANCE_POD_FIELDS = (
    "affinity", "tolerations", "schedulerName", "serviceAccountName",
    "priorityClassName", "priority", "terminationGracePeriodSeconds",
    "activeDeadlineSeconds", "dnsPolicy", "dnsConfig", "hostNetwork",
    "hostPID", "hostIPC", "shareProcessNamespace",
    "automountServiceAccountToken", "nodeName", "hostAliases",
    "runtimeClassName", "enableServiceLinks", "preemptionPolicy", "overhead",
    "topologySpreadConstraints", "setHostnameAsFQDN", "os", "hostUsers",
    "schedulingGates", "resourceClaims", "initContainers",
)

# Env names the renderer owns — user env may not shadow the rendezvous
# contract (a wrong ARKS_PROCESS_ID would scramble the gang).
_RESERVED_ENV = {"ARKS_COORDINATOR_ADDRESS", "ARKS_NUM_PROCESSES",
                 "ARKS_PROCESS_ID", "ARKS_GANG_SIZE", "ARKS_GANG_SECRET"}


def validate_instance_spec(inst: dict | None) -> None:
    """Reserved-name precheck (reference precheck :236-264: the 'models'
    volume / '/models' mount belong to ArksModel)."""
    if not inst:
        return
    for v in inst.get("volumes") or []:
        if v.get("name") == RESERVED_MODELS_VOLUME:
            raise ValueError(
                f"instanceSpec volume name {RESERVED_MODELS_VOLUME!r} is "
                "reserved for the model mount")
    for vm in inst.get("volumeMounts") or []:
        if vm.get("mountPath") == RESERVED_MODELS_PATH:
            raise ValueError(
                f"instanceSpec mountPath {RESERVED_MODELS_PATH!r} is "
                "reserved for the model mount")
    for e in inst.get("env") or []:
        if e.get("name") in _RESERVED_ENV:
            raise ValueError(
                f"instanceSpec env {e.get('name')!r} is reserved for the "
                "gang rendezvous contract")


def apply_instance_spec(pod_spec: dict, container: dict,
                        inst: dict | None) -> tuple[dict, dict]:
    """Merge an instanceSpec into (pod_spec, container) in place.

    Returns (extra_labels, extra_annotations) for the pod template metadata.
    Generated fields win where they are load-bearing (TPU chip requests,
    rendezvous env, models mount); user fields win for probes and
    scheduling knobs the renderer only defaults.
    """
    if not inst:
        return {}, {}
    validate_instance_spec(inst)

    if inst.get("env"):
        container["env"] = container.get("env", []) + [dict(e) for e in inst["env"]]
    if inst.get("volumeMounts"):
        container["volumeMounts"] = (container.get("volumeMounts", [])
                                     + [dict(m) for m in inst["volumeMounts"]])
    if inst.get("volumes"):
        pod_spec["volumes"] = (pod_spec.get("volumes", [])
                               + [dict(v) for v in inst["volumes"]])
    if inst.get("resources"):
        # User resources first, then re-overlay the TPU chip request — the
        # accelerator shape, not the user, owns google.com/tpu.
        merged = {k: dict(v) for k, v in inst["resources"].items()}
        for bucket, vals in (container.get("resources") or {}).items():
            merged.setdefault(bucket, {}).update(
                {k: v for k, v in vals.items() if k == "google.com/tpu"})
        container["resources"] = merged
    for f in _INSTANCE_CONTAINER_FIELDS:
        if f in inst:
            container[f] = copy.deepcopy(inst[f])
    for f in _INSTANCE_POD_FIELDS:
        if f in inst:
            pod_spec[f] = copy.deepcopy(inst[f])
    if inst.get("nodeSelector"):
        # User selector merges under the TPU selector (TPU keys win).
        pod_spec["nodeSelector"] = {**inst["nodeSelector"],
                                    **pod_spec.get("nodeSelector", {})}
    return dict(inst.get("labels") or {}), dict(inst.get("annotations") or {})


# ---------------------------------------------------------------------------
# Gang scheduling (reference: PodGroupPolicy,
# api/v1/arksdisaggregatedapplication_types.go:27-67 +
# internal/controller/utils.go:9-26).  A slice gang of ``size`` hosts is
# all-or-nothing: render a PodGroup (kube scheduler-plugins coscheduling or
# Volcano) with minMember = size and stamp the pod markers each plugin keys
# on.
# ---------------------------------------------------------------------------

PODGROUP_LABEL_COSCHED = "scheduling.x-k8s.io/pod-group"
PODGROUP_ANNOTATION_VOLCANO = "scheduling.k8s.io/group-name"

# DisaggregatedApplication layouts (reference determineBackend :269).
VALID_DAPP_MODES = ("legacy", "unified")


def validate_dapp_mode(mode: str) -> None:
    if mode not in VALID_DAPP_MODES:
        raise ValueError(
            f"spec.mode must be one of {'|'.join(VALID_DAPP_MODES)}, "
            f"got {mode!r}")


def validate_pod_group_policy(policy: dict | None) -> None:
    if not policy:
        return
    srcs = [k for k in ("kubeScheduling", "volcanoScheduling") if policy.get(k) is not None]
    if len(srcs) != 1:
        raise ValueError(
            "podGroupPolicy must set exactly one of kubeScheduling / "
            f"volcanoScheduling (got {srcs or 'neither'})")


def apply_pod_group_policy(pod_spec: dict, group: str,
                           policy: dict | None) -> tuple[dict, dict]:
    """Stamp per-pod gang markers; returns (extra_labels, extra_annotations)
    for the pod template metadata."""
    if not policy:
        return {}, {}
    validate_pod_group_policy(policy)
    if policy.get("kubeScheduling") is not None:
        return {PODGROUP_LABEL_COSCHED: group}, {}
    pod_spec["schedulerName"] = pod_spec.get("schedulerName") or "volcano"
    return {}, {PODGROUP_ANNOTATION_VOLCANO: group}


def render_podgroup(group: str, namespace: str, policy: dict | None,
                    min_member: int, labels: dict | None = None) -> dict | None:
    """The PodGroup object for one gang group (minMember = gang size)."""
    if not policy:
        return None
    validate_pod_group_policy(policy)
    if policy.get("kubeScheduling") is not None:
        ks = policy["kubeScheduling"] or {}
        return {
            "apiVersion": "scheduling.x-k8s.io/v1alpha1",
            "kind": "PodGroup",
            "metadata": _meta(group, namespace, labels),
            "spec": {
                "minMember": min_member,
                # Reference default 60s (arksdisaggregatedapplication_types.go:50-53).
                "scheduleTimeoutSeconds": ks.get("scheduleTimeoutSeconds", 60),
            },
        }
    vs = policy["volcanoScheduling"] or {}
    spec: dict = {"minMember": min_member}
    if vs.get("queue"):
        spec["queue"] = vs["queue"]
    if vs.get("priorityClassName"):
        spec["priorityClassName"] = vs["priorityClassName"]
    return {
        "apiVersion": "scheduling.volcano.sh/v1beta1",
        "kind": "PodGroup",
        "metadata": _meta(group, namespace, labels),
        "spec": spec,
    }


def _meta(name: str, namespace: str, labels: dict | None = None) -> dict:
    return {"name": name, "namespace": namespace,
            "labels": {LABEL_MANAGED_BY: MANAGED_BY, **(labels or {})}}


def try_shape(accelerator: str | None) -> TpuTopology | None:
    """``_shape``, tolerant: None for unset/cpu/unknown accelerators (the
    local drivers don't need node topology).  Controllers use this to
    derive gang size / slice count from the accelerator spec."""
    if not accelerator or accelerator == "cpu":
        return None
    try:
        return _shape(accelerator)
    except ValueError:
        return None


def _shape(accelerator: str) -> TpuTopology:
    shape = TPU_SHAPES.get(accelerator)
    if shape is not None:
        return shape
    # Multi-slice spec: "<base>x<slices>" (e.g. "tpu-v5p-16x2" = two
    # v5p-16 ICI slices joined over DCN).  Each pod stays inside one
    # slice's node pool (same per-slice selectors); the gang spans
    # hosts * slices pods and the engine builds an outermost 'slice'
    # mesh axis (--num-slices).
    base_name, _, n = accelerator.rpartition("x")
    base = TPU_SHAPES.get(base_name)
    if base is not None and n.isdigit() and int(n) >= 2:
        return dataclasses.replace(base, slices=int(n))
    raise ValueError(f"unknown accelerator {accelerator!r}; "
                     f"known: {sorted(TPU_SHAPES)} "
                     "(multi-slice: <base>x<slices>, e.g. tpu-v5p-16x2)")


def _model_storage(model: Model | None, namespace: str,
                   model_name: str) -> tuple[str, str]:
    """(pvc claim name, model path) — honoring the Model's storage overrides
    so workload mounts agree with what render_model provisions."""
    storage = (model.spec.get("storage") or {}) if model is not None else {}
    pvc = storage.get("pvc") or model_name or "models"
    sub = storage.get("subPath") or f"models/{namespace}/{model_name}"
    return pvc, f"{RESERVED_MODELS_PATH}/{sub}"


# ---------------------------------------------------------------------------
# Model -> PVC + download Job
# ---------------------------------------------------------------------------


def render_router_rbac(app_name: str, namespace: str,
                       labels: dict | None = None) -> list[dict]:
    """The disaggregated router's pod-discovery RBAC triple — ONE source
    for the gitops render and the live driver's create-if-absent bootstrap
    (reference sglang-router RBAC,
    arksdisaggregatedapplication_controller.go:530-596)."""
    name = f"arks-{app_name}-router"
    labels = labels or {LABEL_APPLICATION: app_name}
    return [
        {"apiVersion": "v1", "kind": "ServiceAccount",
         "metadata": _meta(name, namespace, labels)},
        {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "Role",
         "metadata": _meta(name, namespace, labels),
         "rules": [{"apiGroups": [""], "resources": ["pods"],
                    "verbs": ["get", "list", "watch"]}]},
        {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "RoleBinding",
         "metadata": _meta(name, namespace, labels),
         "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                     "kind": "Role", "name": name},
         "subjects": [{"kind": "ServiceAccount", "name": name,
                       "namespace": namespace}]},
    ]


def render_model(model: Model, scripts_image: str | None = None) -> list[dict]:
    if scripts_image is None:
        scripts_image = _scripts_image()
    storage = model.spec.get("storage") or {}
    pvc_name = storage.get("pvc") or model.name
    size = storage.get("size", "100Gi")
    docs = [{
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": _meta(pvc_name, model.namespace),
        "spec": {
            "accessModes": ["ReadWriteMany"],
            "resources": {"requests": {"storage": size}},
        },
    }]
    if model.spec.get("source"):
        # One-shot loader (arks-worker-<name> pod semantics; Job gives the
        # retry/backoff the reference implements by hand in download.py).
        _, model_path = _model_storage(model, model.namespace, model.name)
        env = [
            {"name": "MODEL_NAME", "value": model.spec.get("model", model.name)},
            {"name": "MODEL_PATH", "value": model_path},
        ]
        hf = model.spec.get("source", {}).get("huggingface") or {}
        if hf.get("tokenSecretRef"):
            env.append({"name": "HF_TOKEN", "valueFrom": {"secretKeyRef": {
                "name": hf["tokenSecretRef"], "key": "token"}}})
        if model.spec.get("convertOrbax", True):
            env.append({"name": "ARKS_CONVERT_ORBAX", "value": "1"})
        docs.append({
            "apiVersion": "batch/v1",
            "kind": "Job",
            "metadata": _meta(f"arks-worker-{model.name}", model.namespace),
            "spec": {
                "backoffLimit": 3,
                "template": {"spec": {
                    "restartPolicy": "Never",
                    "containers": [{
                        "name": "download",
                        "image": scripts_image,
                        "command": ["python", "-m", "arks_tpu.control.download"],
                        "env": env,
                        "volumeMounts": [{"name": RESERVED_MODELS_VOLUME,
                                          "mountPath": RESERVED_MODELS_PATH}],
                    }],
                    "volumes": [{"name": RESERVED_MODELS_VOLUME,
                                 "persistentVolumeClaim": {"claimName": pvc_name}}],
                }},
            },
        })
    return docs


# ---------------------------------------------------------------------------
# GangSet -> one group's StatefulSet + headless Service
# (consumed by the live operator's K8sGangDriver — arks_tpu.control.live)
# ---------------------------------------------------------------------------


def render_group_from_gangset(gs, index: int, port: int = 8080,
                              revision: str | None = None) -> tuple[dict, dict]:
    """Render group ``index`` of a GangSet as (StatefulSet, Service).

    The GangSet spec carries the already-compiled command (the controllers'
    jax_serve_command output), plus image/accelerator/modelPvc; this
    function owns the POD mechanics, kept consistent with the gitops
    renderer below (_engine_container): TPU shape -> nodeSelector +
    topology + google.com/tpu requests, models-PVC mount, the
    jax.distributed env contract with per-pod process index, leader-only
    readiness, and a group-independent revision annotation.
    """
    from arks_tpu.control.workloads import stable_hash

    spec = gs.spec
    shape = _shape(spec.get("accelerator", "cpu"))
    group = f"arks-{gs.name}-{index}"
    sel = {LABEL_MANAGED_BY: MANAGED_BY,
           "arks.ai/gangset": gs.name, "arks.ai/group": str(index)}
    size = spec.get("size", 1)
    cmd = [c.replace("$(PORT)", str(port)) for c in spec["leader"]["command"]]
    env = [{"name": k, "value": str(v)}
           for k, v in sorted(spec.get("leader", {}).get("env", {}).items())]
    env.append({"name": "ARKS_GANG_SIZE", "value": str(size)})
    if size > 1:
        env += [
            # jax.distributed rendezvous: pod-index label -> process id.
            {"name": "ARKS_COORDINATOR_ADDRESS",
             "value": f"$(GROUP)-0.$(GROUP):8476"},
            {"name": "ARKS_NUM_PROCESSES", "value": str(size)},
            {"name": "ARKS_PROCESS_ID", "valueFrom": {"fieldRef": {
                "fieldPath": "metadata.labels['apps.kubernetes.io/pod-index']"}}},
            # Dispatch-channel handshake secret: stable per GangSet so the
            # revision hash is stable.  In-cluster it is as visible as any
            # pod env; override via leader.env with a Secret-backed value
            # where pod-spec visibility matters.
            {"name": "ARKS_GANG_SECRET",
             "value": stable_hash((gs.namespace, gs.name, "gang-secret"))},
        ]
    if shape.slices > 1:
        # Multi-slice gang: the engine builds an outermost 'slice' mesh
        # axis over DCN (server --num-slices reads this too).
        env.append({"name": "ARKS_NUM_SLICES", "value": str(shape.slices)})
    container = {
        "name": "engine",
        "image": spec.get("image") or _default_image(),
        "command": cmd,
        "env": env,
        "ports": [{"containerPort": port, "name": "http"}],
        "readinessProbe": {
            "httpGet": {"path": "/readiness", "port": port},
            "failureThreshold": 120, "periodSeconds": 5,
        },
    }
    if shape.chips_per_host:
        container["resources"] = {
            "requests": {"google.com/tpu": str(shape.chips_per_host)},
            "limits": {"google.com/tpu": str(shape.chips_per_host)},
        }
    pod: dict = {"subdomain": "$(GROUP)", "containers": [container]}
    # Disaggregated ROUTER gangs discover tier pods from the API: bind the
    # per-app discovery ServiceAccount (created by the live driver /
    # rendered by render_disaggregated).  Part of the pod spec, so it
    # participates in the revision hash like any other pod change.
    _app = (gs.labels or {}).get(LABEL_APPLICATION)
    if spec.get("role") == "router" and _app:
        pod["serviceAccountName"] = f"arks-{_app}-router"
    pvc = spec.get("modelPvc")
    if pvc:
        container["volumeMounts"] = [{"name": RESERVED_MODELS_VOLUME,
                                      "mountPath": RESERVED_MODELS_PATH,
                                      "readOnly": True}]
        pod["volumes"] = [{"name": RESERVED_MODELS_VOLUME,
                           "persistentVolumeClaim": {"claimName": pvc,
                                                     "readOnly": True}}]
    if shape.accelerator:
        pod["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator": shape.accelerator,
            "cloud.google.com/gke-tpu-topology": shape.topology,
        }
    # InstanceSpec passthrough + gang-scheduling markers (controllers copy
    # the app's spec.instanceSpec / spec.podGroupPolicy into the GangSet).
    # A podGroupUnit (unified disaggregated layout) points every pod at the
    # shared unit-wide PodGroup instead of a per-group one.
    il, ia = apply_instance_spec(pod, container, spec.get("instanceSpec"))
    unit_name = (spec.get("podGroupUnit") or {}).get("name")
    pl, pa = apply_pod_group_policy(pod, unit_name or group,
                                    spec.get("podGroupPolicy"))
    extra_labels = {**il, **pl}
    extra_annotations = {**ia, **pa}

    # Application/component labels on the TEMPLATE (not the immutable
    # selector): the disaggregated router's label-selector pod discovery
    # (router.KubeDiscovery) finds tier pods by arks.ai/application +
    # arks.ai/component.  For DISAGG gangs (spec.role set) they join the
    # revision hash — an upgraded live operator must roll pre-existing
    # tier fleets exactly once so their pods become discoverable (without
    # labels the router would see no backends, and live-mode router
    # gangsets carry no env fallback).  Standalone gangs keep them out of
    # the hash — purely informational there, no re-roll on upgrade.
    app_label = (gs.labels or {}).get(LABEL_APPLICATION)
    role_label = (gs.labels or {}).get("arks.ai/role") or spec.get("role")
    discovery_labels = {}
    if app_label:
        discovery_labels[LABEL_APPLICATION] = app_label
    if role_label:
        discovery_labels[LABEL_COMPONENT] = role_label

    if revision is None:
        # Group-independent: hash BEFORE substituting the group name (it
        # feeds the coordinator address/subdomain; pod-group markers are
        # group-NAMED, so hash the policy input rather than the stamped
        # label value).  Specs without the new fields keep the legacy hash
        # input — an operator upgrade must not re-revision (and roll) every
        # unchanged STANDALONE gang in the fleet.
        hash_labels = discovery_labels if spec.get("role") else None
        if hash_labels:
            revision = stable_hash((pod, il, ia,
                                    spec.get("podGroupPolicy"), hash_labels))
        elif il or ia or spec.get("podGroupPolicy"):
            revision = stable_hash((pod, il, ia, spec.get("podGroupPolicy")))
        else:
            revision = stable_hash(pod)
    pod = json.loads(json.dumps(pod).replace("$(GROUP)", group))

    sts = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": _meta(group, gs.namespace, sel),
        "spec": {
            "serviceName": group,
            "replicas": size,
            "podManagementPolicy": "Parallel",
            "updateStrategy": {"type": "RollingUpdate"},
            "selector": {"matchLabels": sel},
            "template": {
                "metadata": {"labels": {**sel, **extra_labels,
                                        **discovery_labels},
                             "annotations": {"arks.ai/revision": revision,
                                             **extra_annotations}},
                "spec": pod,
            },
        },
    }
    svc = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta(group, gs.namespace, sel),
        # publishNotReadyAddresses: per-pod DNS must exist BEFORE readiness
        # — workers resolve the leader's coordinator address during
        # jax.distributed init, and the leader only readies after init
        # completes (LWS sets this for the same reason).
        "spec": {"clusterIP": "None", "selector": sel,
                 "publishNotReadyAddresses": True,
                 "ports": [{"port": port, "name": "http"}]},
    }
    return sts, svc


def gangset_revision(gs, port: int = 8080) -> str:
    """The group-independent revision a current group must carry."""
    sts, _ = render_group_from_gangset(gs, 0, port)
    return sts["spec"]["template"]["metadata"]["annotations"]["arks.ai/revision"]


def render_podgroup_from_gangset(gs, index: int) -> dict | None:
    """The gang-scheduling PodGroup for group ``index`` (None if the
    GangSet carries no podGroupPolicy).  With a podGroupUnit (unified
    disaggregated layout) every group of every tier shares ONE PodGroup
    whose minMember spans the whole PD unit — the renderings are identical
    across tiers, so each tier's driver converges the same object."""
    unit = gs.spec.get("podGroupUnit")
    if unit:
        return render_podgroup(
            unit["name"], gs.namespace, gs.spec.get("podGroupPolicy"),
            min_member=unit["minMember"],
            labels={LABEL_MANAGED_BY: MANAGED_BY,
                    "arks.ai/unit": unit["name"]})
    group = f"arks-{gs.name}-{index}"
    sel = {LABEL_MANAGED_BY: MANAGED_BY,
           "arks.ai/gangset": gs.name, "arks.ai/group": str(index)}
    return render_podgroup(group, gs.namespace, gs.spec.get("podGroupPolicy"),
                           min_member=gs.spec.get("size", 1), labels=sel)


# ---------------------------------------------------------------------------
# Gang rendering (shared by Application and DisaggregatedApplication tiers)
# ---------------------------------------------------------------------------


def _engine_container(spec: dict, served_model: str, model_path: str | None,
                      shape: TpuTopology, port: int,
                      extra_args: list[str] | None = None) -> dict:
    # Flag parity with the real entrypoint (arks_tpu/server/__main__.py).
    args = ["-m", "arks_tpu.server",
            "--model", spec.get("modelConfig") or model_path or "tiny",
            "--served-model-name", served_model,
            "--port", str(port),
            "--tensor-parallel-size", str(spec.get("tensorParallel", 1))]
    if spec.get("contextParallel", 1) > 1:
        args += ["--context-parallel-size", str(spec["contextParallel"])]
    if model_path:
        args += ["--model-path", model_path]
    args += [str(a) for a in spec.get("runtimeCommonArgs", [])]
    args += extra_args or []
    container = {
        "name": "engine",
        "image": spec.get("runtimeImage") or _default_image(spec.get("runtime", "jax")),
        "command": ["python"],
        "args": args,
        "ports": [{"containerPort": port, "name": "http"}],
        "env": [
            # JAX multi-host rendezvous (LWS env contract translated).
            {"name": "ARKS_NUM_PROCESSES", "value": str(shape.total_hosts)},
            {"name": "ARKS_PROCESS_ID", "valueFrom": {"fieldRef": {
                "fieldPath": "metadata.labels['apps.kubernetes.io/pod-index']"}}},
            *([{"name": "ARKS_NUM_SLICES", "value": str(shape.slices)}]
              if shape.slices > 1 else []),
        ],
        # /readiness is leader-only (process 0), so Services selecting the
        # whole gang still route requests to the leader exclusively.
        "readinessProbe": {
            "httpGet": {"path": "/readiness", "port": port},
            "failureThreshold": 120, "periodSeconds": 5,
        },
        "volumeMounts": [{"name": RESERVED_MODELS_VOLUME,
                          "mountPath": RESERVED_MODELS_PATH,
                          "readOnly": True}],
    }
    if shape.chips_per_host:
        container["resources"] = {
            "requests": {"google.com/tpu": str(shape.chips_per_host)},
            "limits": {"google.com/tpu": str(shape.chips_per_host)},
        }
    return container


def _render_gangs(prefix: str, namespace: str, base_labels: dict,
                  replicas: int, shape: TpuTopology, spec: dict,
                  served_model: str, model_path: str | None, pvc: str,
                  port: int, extra_args: list[str] | None = None,
                  podgroup_unit: str | None = None) -> list[dict]:
    """``podgroup_unit``: unified-mode override — pods join the named
    UNIT-wide PodGroup (rendered once by the caller) instead of per-group
    PodGroups rendered here."""
    docs: list[dict] = []
    for r in range(replicas):
        group = f"{prefix}-{r}"
        sel = {**base_labels, "arks.ai/group": group}
        coordinator = f"{group}-0.{group}.{namespace}.svc:8476"
        container = _engine_container(spec, served_model, model_path, shape,
                                      port, extra_args)
        container["env"].append(
            {"name": "ARKS_COORDINATOR_ADDRESS", "value": coordinator})
        pod_spec = {
            "subdomain": group,
            "containers": [container],
            "volumes": [{"name": RESERVED_MODELS_VOLUME,
                         "persistentVolumeClaim": {"claimName": pvc,
                                                   "readOnly": True}}],
        }
        if shape.accelerator:
            pod_spec["nodeSelector"] = {
                "cloud.google.com/gke-tpu-accelerator": shape.accelerator,
                "cloud.google.com/gke-tpu-topology": shape.topology,
            }
        # InstanceSpec passthrough + gang-scheduling markers.
        il, ia = apply_instance_spec(pod_spec, container,
                                     spec.get("instanceSpec"))
        pl, pa = apply_pod_group_policy(pod_spec, podgroup_unit or group,
                                        spec.get("podGroupPolicy"))
        extra_labels = {**il, **pl}
        extra_annotations = {**ia, **pa}
        if podgroup_unit is None:
            pg = render_podgroup(group, namespace, spec.get("podGroupPolicy"),
                                 min_member=shape.total_hosts, labels=sel)
            if pg is not None:
                docs.append(pg)
        docs.append({
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta(group, namespace, sel),
            # Pre-readiness per-pod DNS for the coordinator rendezvous
            # (see render_group_from_gangset).
            "spec": {"clusterIP": "None", "selector": sel,
                     "publishNotReadyAddresses": True,
                     "ports": [{"port": port, "name": "http"}]},
        })
        # Revision stamp over the FULL pod spec (same hash helper as the
        # gang drivers): nodeSelector/volume changes count as new revisions
        # too.  Rollout tooling and the live-operator mode compare this to
        # tell outdated groups from current ones.  Legacy hash input when no
        # instanceSpec/podGroup extras exist (upgrade stability).
        from arks_tpu.control.workloads import stable_hash
        if extra_labels or extra_annotations:
            revision = stable_hash((pod_spec, extra_labels, extra_annotations))
        else:
            revision = stable_hash(pod_spec)
        docs.append({
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": _meta(group, namespace, sel),
            "spec": {
                "serviceName": group,
                "replicas": shape.total_hosts,
                # Gang semantics: all hosts start together; a slice is
                # atomic, so any pod restart restarts the group
                # (LWS RecreateGroupOnPodRestart analogue via TPU slice
                # scheduling + shared fate of the jax coordinator).
                "podManagementPolicy": "Parallel",
                # Within a gang the explicit strategy is RollingUpdate —
                # restarting any host kills the jax coordinator, so the
                # whole gang recreates regardless of per-pod ordering.
                # CROSS-group sequencing (maxUnavailable=1 over replica
                # groups, each its own StatefulSet) cannot be expressed in
                # static manifests: gitops applies roll all groups at once;
                # the operator's reconcile mode sequences them with the
                # same pick_rolling_restart gating the local drivers use.
                "updateStrategy": {"type": "RollingUpdate"},
                "selector": {"matchLabels": sel},
                "template": {
                    "metadata": {"labels": {**sel, **extra_labels},
                                 "annotations": {"arks.ai/revision": revision,
                                                 **extra_annotations}},
                    "spec": pod_spec,
                },
            },
        })
    return docs


# ---------------------------------------------------------------------------
# Application -> StatefulSet gangs + front Service
# ---------------------------------------------------------------------------


def render_application(app: Application, model: Model | None = None,
                       port: int = 8080) -> list[dict]:
    spec = app.spec
    shape = _shape(spec.get("accelerator", "cpu"))
    model_name = spec.get("model", {}).get("name", "")
    pvc, model_path = _model_storage(model, app.namespace, model_name)
    base_labels = {LABEL_APPLICATION: app.name}
    docs = _render_gangs(
        f"arks-{app.name}", app.namespace, base_labels,
        spec.get("replicas", 1), shape, spec, app.served_model_name,
        model_path if model_name else None, pvc, port)

    # Front service (reference: arks-application-<name>:8080 with the
    # prometheus-discovery label — controller :376-415).  Selects every gang
    # pod; the leader-only /readiness probe keeps traffic on process 0.
    docs.append({
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta(f"arks-application-{app.name}", app.namespace,
                          {**base_labels, "prometheus-discovery": "true"}),
        "spec": {
            "selector": dict(base_labels),
            "ports": [{"port": port, "targetPort": port, "name": "http"}],
        },
    })
    return docs


# ---------------------------------------------------------------------------
# DisaggregatedApplication -> prefill/decode gangs + router
# ---------------------------------------------------------------------------


def render_disaggregated(dapp: DisaggregatedApplication,
                         model: Model | None = None,
                         port: int = 8080) -> list[dict]:
    """Two layouts, selected by ``spec.mode`` (reference parity:
    determineBackend, arksdisaggregatedapplication_controller.go:269 —
    legacy = two LWS + router Deployment, unified = ONE RBGS group with
    scheduler/prefill/decode roles, :1265-1326):

    - ``legacy`` (default): independent per-tier gangs; per-group
      PodGroups when a podGroupPolicy is set.
    - ``unified``: the same pods join ONE unit-wide PodGroup whose
      minMember spans every router/prefill/decode pod — the whole PD unit
      schedules atomically (a half-placed unit serves nothing: decode
      without prefill is idle, prefill without decode leaks KV).
    """
    spec = dapp.spec
    mode = spec.get("mode", "legacy")
    validate_dapp_mode(mode)
    unit = f"arks-{dapp.name}" if mode == "unified" else None
    unit_members = 0
    model_name = spec.get("model", {}).get("name", "")
    pvc, model_path = _model_storage(model, dapp.namespace, model_name)
    model_path = model_path if model_name else None
    served = dapp.served_model_name
    docs: list[dict] = []

    tiers = {}
    for tier in ("prefill", "decode"):
        tspec = dict(spec)
        tspec.update(spec.get(tier) or {})
        shape = _shape(tspec.get("accelerator", "cpu"))
        labels = {LABEL_APPLICATION: dapp.name, LABEL_COMPONENT: tier}
        unit_members += tspec.get("replicas", 1) * shape.total_hosts
        docs.extend(_render_gangs(
            f"arks-{dapp.name}-{tier}", dapp.namespace, labels,
            tspec.get("replicas", 1), shape, tspec, served, model_path, pvc,
            port, extra_args=["--disaggregation-mode", tier],
            podgroup_unit=unit))
        svc = f"arks-{dapp.name}-{tier}"
        tiers[tier] = f"{svc}.{dapp.namespace}.svc:{port}"
        docs.append({
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta(svc, dapp.namespace, labels),
            "spec": {"selector": dict(labels),
                     "ports": [{"port": port, "name": "http"}]},
        })

    router = spec.get("router") or {}
    rport = router.get("port", port)
    rlabels = {LABEL_APPLICATION: dapp.name, LABEL_COMPONENT: "router"}
    # Label-selector pod discovery needs pods get/list — bootstrap a
    # namespaced ServiceAccount/Role/RoleBinding exactly like the
    # reference's sglang-router RBAC
    # (arksdisaggregatedapplication_controller.go:530-596).  The per-tier
    # Service addresses stay as env FALLBACK for the bootstrap window
    # before the first pod list succeeds.
    sa_name = f"arks-{dapp.name}-router"
    docs.extend(render_router_rbac(dapp.name, dapp.namespace, rlabels))
    rcontainer = {
        "name": "router",
        "image": router.get("image") or _default_image(),
        "command": ["python"],
        "args": ["-m", "arks_tpu.router",
                 "--port", str(rport),
                 "--served-model-name", served,
                 "--service-discovery",
                 "--namespace", dapp.namespace,
                 "--application", dapp.name,
                 "--backend-port", str(port),
                 *[str(a) for a in router.get("routerArgs", [])]],
        "env": [
            {"name": "ARKS_PREFILL_ADDRS", "value": tiers["prefill"]},
            {"name": "ARKS_DECODE_ADDRS", "value": tiers["decode"]},
        ],
        "ports": [{"containerPort": rport, "name": "http"}],
        "readinessProbe": {
            "httpGet": {"path": "/readiness", "port": rport},
            "failureThreshold": 120, "periodSeconds": 5,
        },
    }
    rpod: dict = {"containers": [rcontainer], "serviceAccountName": sa_name}
    ril, ria = apply_instance_spec(rpod, rcontainer, router.get("instanceSpec"))
    if unit is not None:
        # The scheduler/router role joins the unit PodGroup too (reference
        # unified RBGS: scheduler is one of the three roles, :1316-1320).
        rpl, rpa = apply_pod_group_policy(rpod, unit, spec.get("podGroupPolicy"))
        ril = {**ril, **rpl}
        ria = {**ria, **rpa}
        unit_members += router.get("replicas", 1)
    docs.append({
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta(f"arks-{dapp.name}-router", dapp.namespace, rlabels),
        "spec": {
            "replicas": router.get("replicas", 1),
            "selector": {"matchLabels": rlabels},
            "template": {
                "metadata": {"labels": {**rlabels, **ril},
                             **({"annotations": ria} if ria else {})},
                "spec": rpod,
            },
        },
    })
    if unit is not None and spec.get("podGroupPolicy"):
        docs.append(render_podgroup(
            unit, dapp.namespace, spec["podGroupPolicy"],
            min_member=unit_members,
            labels={LABEL_APPLICATION: dapp.name}))
    # Router front service — the disagg app's traffic entry, named like a
    # standalone app's front service so Endpoint routing treats both alike.
    docs.append({
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta(f"arks-application-{dapp.name}", dapp.namespace,
                          {**rlabels, "prometheus-discovery": "true"}),
        "spec": {"selector": dict(rlabels),
                 "ports": [{"port": port, "targetPort": rport, "name": "http"}]},
    })
    return docs


# ---------------------------------------------------------------------------
# Endpoint -> HTTPRoute
# ---------------------------------------------------------------------------


def render_endpoint(ep: Endpoint, apps: list, gateway_name: str = "arks-eg",
                    port: int = 8080) -> list[dict]:
    backends = []
    for rc in ep.spec.get("routeConfigs", []):
        # Static routes ({backend: {service|host, port}, weight}) become
        # Gateway-API backendRefs (name/port/weight).
        be = rc.get("backend") or {}
        backends.append({
            "name": be.get("service") or be.get("host", ""),
            "port": be.get("port", port),
            "weight": rc.get("weight", ep.spec.get("defaultWeight", 1)),
        })
    for app in apps:
        # Unlike the live controller (which adds only ready apps,
        # arksendpoint_controller.go:293-347), static rendering includes
        # every matching app in the ENDPOINT'S NAMESPACE: K8s readiness
        # probes gate traffic at the Service level.
        if app.namespace == ep.namespace and app.served_model_name == ep.name:
            backends.append({
                "name": f"arks-application-{app.name}", "port": port,
                "weight": ep.spec.get("defaultWeight", 1)})
    rules = [{
        "matches": [{
            "path": {"type": "PathPrefix", "value": "/"},
            # Header matches injected by the gateway (parity with
            # arksendpoint_controller.go:349-369).
            "headers": [
                {"name": "x-arks-namespace", "value": ep.namespace},
                {"name": "x-arks-model", "value": ep.name},
            ],
        }],
        "backendRefs": backends,
    }]
    return [{
        "apiVersion": "gateway.networking.k8s.io/v1",
        "kind": "HTTPRoute",
        "metadata": _meta(ep.name, ep.namespace),
        "spec": {
            "parentRefs": [{"name": gateway_name}],
            "rules": rules,
        },
    }]


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def render_store(store) -> list[dict]:
    """Render every renderable resource in a store to K8s docs."""
    docs: list[dict] = []
    models = {(m.namespace, m.name): m for m in store.list(Model)}
    apps = store.list(Application)
    dapps = store.list(DisaggregatedApplication)

    def model_for(obj):
        name = obj.spec.get("model", {}).get("name", "")
        return models.get((obj.namespace, name))

    for m in models.values():
        docs.extend(render_model(m))
    for a in apps:
        docs.extend(render_application(a, model_for(a)))
    for d in dapps:
        docs.extend(render_disaggregated(d, model_for(d)))
    for e in store.list(Endpoint):
        docs.extend(render_endpoint(e, apps + dapps))
    return docs


def main() -> None:
    import argparse
    import sys

    import yaml

    from arks_tpu.control.__main__ import apply_manifests
    from arks_tpu.control.store import Store

    p = argparse.ArgumentParser(
        "arks_tpu.control.k8s_export",
        description="Render arks manifests to Kubernetes YAML (stdout)")
    p.add_argument("--manifests", action="append", required=True)
    args = p.parse_args()

    store = Store()
    for path in args.manifests:
        apply_manifests(store, path)
    yaml.safe_dump_all(render_store(store), sys.stdout, sort_keys=False)


if __name__ == "__main__":
    main()
