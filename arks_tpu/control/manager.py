"""Operator assembly: store + the controller set (cmd/main.go analogue)."""

from __future__ import annotations

from arks_tpu.control.application_controller import ApplicationController
from arks_tpu.control.disaggregated_controller import (
    DisaggregatedApplicationController,
)
from arks_tpu.control.endpoint_controller import EndpointController
from arks_tpu.control.gangset_controller import GangSetController
from arks_tpu.control.model_controller import ModelController, default_fetcher
from arks_tpu.control.reconciler import Manager
from arks_tpu.control.store import Store
from arks_tpu.control.workloads import GangDriver, LocalProcessDriver


def build_manager(
    models_root: str,
    driver: GangDriver | None = None,
    store: Store | None = None,
    fetcher=default_fetcher,
    local_platform: str | None = None,
    rate_source=None,
    autoscale_interval_s: float = 10.0,
    router_discovery: str = "file",
) -> Manager:
    """Wire the controller set over one store.

    Token/Quota have no controllers — by design, matching the reference where
    both reconcilers are unregistered no-ops (cmd/main.go:264-277); the
    gateway consumes those resources read-only.

    ``rate_source(namespace, served_model_name) -> rpm`` (typically the
    embedded gateway's RequestRateTracker.rpm) enables the native
    autoscaler over ``Application.spec.autoscale``.
    """
    mgr = Manager(store)
    driver = driver or LocalProcessDriver()
    mgr.add(ModelController(mgr.store, models_root, fetcher=fetcher))
    mgr.add(GangSetController(mgr.store, driver))
    mgr.add(ApplicationController(mgr.store, local_platform=local_platform))
    mgr.add(DisaggregatedApplicationController(
        mgr.store, local_platform=local_platform,
        router_discovery=router_discovery))
    mgr.add(EndpointController(mgr.store))
    if rate_source is not None:
        from arks_tpu.control.autoscaler import AutoscalerController
        mgr.add(AutoscalerController(mgr.store, rate_source,
                                     interval_s=autoscale_interval_s))
    return mgr
