"""OpenAI-compatible HTTP serving surface.

Same wire contract the reference's gateway counts on from vLLM/SGLang
runtime pods (port 8080 — /root/reference/internal/controller/
arksapplication_controller.go:631-634; usage extraction —
/root/reference/pkg/gateway/handle_response.go:113-182):

- POST /v1/chat/completions, /v1/completions (stream + non-stream; SSE
  frames ``data: {...}`` terminated by ``data: [DONE]``; when
  ``stream_options.include_usage`` is set, the final data frame carries the
  usage object and an empty choices list).
- GET /v1/models, /metrics (Prometheus, normalized runtime names),
  /healthz, /readiness.

Stdlib-only (ThreadingHTTPServer): requests are I/O-bound handoffs to the
engine thread; all device work stays on the engine thread.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from arks_tpu import slo as slo_mod
from arks_tpu import tenancy
from arks_tpu.engine import fairqueue
from arks_tpu.engine.engine import InferenceEngine
from arks_tpu.engine.tokenizer import IncrementalDetokenizer
from arks_tpu.engine.types import Request, SamplingParams
from arks_tpu.obs import logctx
from arks_tpu.obs import perfetto as perfetto_mod
from arks_tpu.obs import trace as trace_mod
from arks_tpu.utils import knobs
from arks_tpu.utils.swallow import swallowed

log = logging.getLogger("arks_tpu.server")

# SLO tier header (gateway/router forward it; arks_tpu.gateway.server
# validates it against the same ARKS_SLO_TIERS ladder).
HDR_TIER = "x-arks-tier"


def _find_stop(text: str, stop_strings: list[str], min_end: int = 0
               ) -> int | None:
    """Earliest index at which any stop string begins, else None.

    A match whose END falls at or before ``min_end`` is ignored: text
    before that boundary was generated under min_tokens and is exempt
    from stopping, but a stop straddling the boundary still counts."""
    best = None
    for s in stop_strings:
        start = 0
        while True:
            i = text.find(s, start)
            if i < 0:
                break
            if i + len(s) > min_end:
                if best is None or i < best:
                    best = i
                break
            start = i + 1
    return best


def _sampling_from_body(body: dict, tokenizer,
                        engine=None) -> tuple[SamplingParams, list[str]]:
    """Build engine sampling params; returns (params, stop_strings).

    ``stop_token_ids`` go to the engine directly.  ``stop`` strings that
    encode to a single token also become stop ids; multi-token stop strings
    are matched against streamed text by the server (which then aborts the
    engine request).  With ``engine``, logit_bias token ids are validated
    against the vocab and min_tokens' suppress set against the device
    column budget — raising ValueError (HTTP 400) instead of silently
    ignoring entries."""
    stop = body.get("stop") or []
    if isinstance(stop, str):
        stop = [stop]
    stop_ids = [int(t) for t in (body.get("stop_token_ids") or [])]
    stop_strings: list[str] = []
    for s in stop:
        ids = tokenizer.encode(s)
        if len(ids) == 1:
            stop_ids.append(ids[0])
        else:
            stop_strings.append(s)
    # logprobs: completions take an int (top-N alternatives per token,
    # 0 = chosen only); chat takes logprobs=true + top_logprobs=N.  The
    # engine param is None (off) / 0 (chosen only) / N (plus top-N).
    lp = body.get("logprobs")
    if lp is True:
        n_lp = int(body.get("top_logprobs") or 0)
    elif lp is None or lp is False:
        n_lp = None
    else:
        n_lp = int(lp)
    from arks_tpu.engine.sampler import LOGIT_BIAS_MAX, TOP_LOGPROBS_MAX
    # OpenAI logit_bias: {"token_id": bias in [-100, 100]}.  Rejected when
    # it exceeds the device column budget (silently dropping entries would
    # bias the WRONG subset).
    raw_bias = body.get("logit_bias") or {}
    if not isinstance(raw_bias, dict):
        raise ValueError("logit_bias must be an object of token_id -> bias")
    if len(raw_bias) > LOGIT_BIAS_MAX:
        raise ValueError(
            f"logit_bias supports at most {LOGIT_BIAS_MAX} entries")
    logit_bias = tuple(
        (int(t), max(-100.0, min(100.0, float(b))))
        for t, b in raw_bias.items())
    if engine is not None and logit_bias:
        vocab = engine.cfg.vocab_size
        bad = [t for t, _ in logit_bias if not 0 <= t < vocab]
        if bad:
            raise ValueError(
                f"logit_bias token ids out of range [0, {vocab}): {bad[:5]}")
    min_tokens = max(int(body.get("min_tokens", 0)), 0)
    # Guided decoding: OpenAI response_format json_object, plus the
    # vLLM-style guided_regex extra.  Compiled HERE (cached per pattern)
    # so an invalid pattern 400s before the request ever queues.
    guide = None
    rf = body.get("response_format")
    if isinstance(rf, dict) and rf.get("type"):
        rft = rf["type"]
        if rft == "json_object":
            guide = ("json", "")
        elif rft == "regex" and rf.get("regex"):
            guide = ("regex", str(rf["regex"]))
        elif rft == "json_schema":
            # OpenAI structured outputs: {"type": "json_schema",
            # "json_schema": {"name": ..., "schema": {...}}}; a bare
            # "schema" key is accepted too.  The cache key preserves the
            # body's own key order — sort_keys would reorder
            # "properties", breaking the declaration-order contract.
            wrapper = rf.get("json_schema")
            schema = (wrapper.get("schema") if isinstance(wrapper, dict)
                      else rf.get("schema"))
            if not isinstance(schema, dict):
                raise ValueError("response_format json_schema needs "
                                 "json_schema.schema")
            guide = ("json_schema", json.dumps(schema))
        elif rft != "text":
            raise ValueError(f"unknown response_format type {rft!r}")
    if body.get("guided_regex"):
        guide = ("regex", str(body["guided_regex"]))
    if isinstance(body.get("guided_json"), dict):
        # vLLM extra: guided_json carries the schema directly.
        guide = ("json_schema", json.dumps(body["guided_json"]))
    if body.get("guided_choice") is not None:
        # vLLM extra: the completion must be one of these literal strings,
        # compiled as an escaped alternation over the DFA machinery.
        # Non-string entries 400 here — coercing them (numbers, nulls)
        # would constrain to text the caller never wrote.
        choices = body["guided_choice"]
        if (not isinstance(choices, list) or not choices
                or any(not isinstance(c, str) for c in choices)):
            raise ValueError(
                "guided_choice must be a non-empty array of strings")
        guide = ("choice", json.dumps(choices))
    if guide is not None and engine is not None:
        # Syntactic check only (ValueError -> 400 on bad patterns): the
        # expensive DFA build runs on the compiler's worker pool once the
        # request is queued (engine.add_request kicks it), so a cold
        # schema never blocks this server thread for the ~seconds-scale
        # compile.  Compile-time failures (budgets exhausted with every
        # guide pinned) surface as a per-request 400 through the
        # finish_reason="error" output.
        engine.guides.validate(*guide)
    params = SamplingParams(
        max_tokens=int(body.get("max_tokens") or body.get("max_completion_tokens") or 256),
        temperature=float(body.get("temperature", 1.0)),
        top_p=float(body.get("top_p", 1.0)),
        top_k=int(body.get("top_k", 0)),
        seed=body.get("seed"),
        ignore_eos=bool(body.get("ignore_eos", False)),
        stop_token_ids=tuple(stop_ids),
        presence_penalty=float(body.get("presence_penalty", 0.0)),
        frequency_penalty=float(body.get("frequency_penalty", 0.0)),
        logprobs=None if n_lp is None else min(max(n_lp, 0), TOP_LOGPROBS_MAX),
        logit_bias=logit_bias,
        min_tokens=min_tokens,
        priority=int(body.get("priority") or 0),
        guide=guide,
    )
    if engine is not None and min_tokens:
        # Same composition the engine admits with (min_tokens_suppress_ids
        # is the single source of truth): reject oversized suppress sets
        # with a 400 here instead of a late engine-side ValueError.
        from arks_tpu.engine.sampler import SUPPRESS_MAX
        if len(engine.min_tokens_suppress_ids(params)) > SUPPRESS_MAX:
            raise ValueError(
                f"min_tokens supports at most {SUPPRESS_MAX} eos/stop "
                "token ids to suppress (silently dropping one could end "
                "the stream before the minimum)")
    return params, stop_strings


class OpenAIServer:
    def follower_wedge(self) -> str | None:
        """Non-None when a gang follower's dispatch-channel heartbeat is
        stale (hung-but-connected worker): the readiness reason string.
        ARKS_GANG_STALE_S bounds the detection window."""
        disp = getattr(self.engine, "dispatcher", None)
        if disp is None or not hasattr(disp, "follower_health"):
            return None
        h = disp.follower_health(knobs.get_float("ARKS_GANG_STALE_S"))
        if h["stale"]:
            return (f"follower heartbeat stale: {h['stale']} "
                    f"(max age {h['max_heartbeat_age_s']}s)")
        return None

    def __init__(self, engine: InferenceEngine, served_model_name: str,
                 host: str = "0.0.0.0", port: int = 8080) -> None:
        self.engine = engine
        self.served_model_name = served_model_name
        self.host, self.port = host, port
        # SLO-tier ladder: x-arks-tier maps onto params.priority here (the
        # header wins over a body "priority" — the gateway already
        # validated it, but a direct-to-pod client gets the same 400).
        self.slo = slo_mod.from_env()
        self._httpd: ThreadingHTTPServer | None = None
        self._ready = threading.Event()
        # Graceful drain (SIGTERM): readiness drops (Services/routes pull
        # this backend), new completions get 503, in-flight ones finish.
        # _active counts POST handlers between their admission check and
        # their last byte — the drain gate that closes the accept-vs-drain
        # race (engine queues alone can read idle while a handler is still
        # tokenizing, streaming tail frames, or running a detached prefill).
        self.draining = False
        self._active = 0
        self._active_lock = threading.Lock()
        self._stopped = False

    # ------------------------------------------------------------------

    def start(self, background: bool = True) -> None:
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            # Default rbufsize(-1) is fine; but the server-level accept
            # backlog must absorb connection bursts (hundreds of clients
            # reconnecting at once) — see request_queue_size below.

            def _json(self, code: int, payload: dict,
                      headers: dict | None = None) -> None:
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(data)

            def _error(self, code: int, message: str) -> None:
                self._json(code, {"error": {"message": message, "code": code}})

            def do_GET(self):
                if self.path == "/v1/models":
                    self._json(200, server._models_payload())
                elif self.path == "/metrics":
                    text = server.engine.metrics.registry.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(text)))
                    self.end_headers()
                    self.wfile.write(text)
                elif self.path in ("/healthz", "/health"):
                    self._json(200, {"status": "ok"})
                elif self.path == "/v1/traces/export":
                    # Chrome trace-event JSON of every retained trace —
                    # open at ui.perfetto.dev / chrome://tracing.
                    tracer = server.engine.trace
                    tracer.flush()
                    self._json(200, perfetto_mod.chrome_trace(
                        tracer.store.all(), tracer.phase_spans()))
                elif self.path == "/v1/traces":
                    tracer = server.engine.trace
                    tracer.flush()
                    self._json(200, {"traces": [
                        {"trace_id": t["trace_id"],
                         "request_id": t["request_id"],
                         "flags": t["flags"], "tier": t.get("tier"),
                         "spans": len(t["spans"])}
                        for t in tracer.store.all()]})
                elif self.path.startswith("/v1/traces/"):
                    # By trace id OR request id.
                    tracer = server.engine.trace
                    tracer.flush()
                    tr = tracer.store.get(self.path[len("/v1/traces/"):])
                    if tr is None:
                        self._error(404, "trace not found (expired, "
                                    "sampled out, or still in flight)")
                    else:
                        self._json(200, tr)
                elif self.path.startswith("/v1/cache/blocks/"):
                    # Fleet prefix cache: serve one raw AKV1 block to a
                    # fetching peer (host tier peeked, then disk).  404 =
                    # not resident; the peer falls back to re-prefill.
                    buf = server._block_payload(
                        self.path[len("/v1/cache/blocks/"):])
                    if buf is None:
                        self._error(404, "block not resident")
                    else:
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/octet-stream")
                        self.send_header("Content-Length", str(len(buf)))
                        self.end_headers()
                        self.wfile.write(buf)
                elif self.path == "/v1/cache/sketch":
                    # Prefix-digest sketch for cache-aware routing: a
                    # compact per-tier summary of the digest chains this
                    # backend holds (engine.cache_sketch reads host-side
                    # snapshots only — the export never touches device
                    # data, same non-blocking discipline as spills).
                    self._json(200, server._sketch_payload())
                elif self.path == "/v1/elastic/status":
                    # Elastic state snapshot (armed, current shape, last
                    # resize/rearm stats) — reachable even while the
                    # replica is disarmed/draining, unlike /readiness.
                    self._json(200, server._elastic_meta())
                elif self.path == "/readiness":
                    # Multi-host gangs: only process 0 (the leader) accepts
                    # traffic — workers participate in collectives but must
                    # stay out of Service endpoints (the K8s front Service
                    # selects the whole gang and relies on this gate).
                    if knobs.raw("ARKS_PROCESS_ID") not in ("", "0"):
                        self._error(503, "worker process (leader serves)")
                    elif server.draining:
                        self._error(503, "draining")
                    elif not server._ready.is_set():
                        self._error(503, "not ready")
                    elif getattr(server.engine, "state",
                                 "serving") != "serving":
                        # Fault recovery in progress ("recovering") or a
                        # wedged dispatch awaiting the watchdog's exit
                        # ("wedged"): pull this backend from Service
                        # endpoints; in-flight streams keep draining.
                        self._error(503, server.engine.state)
                    elif not getattr(server.engine, "armed", True):
                        # Scaled to zero: no device state exists.  The
                        # router's planned join polls this gate — the
                        # replica re-enters routing only once re-armed
                        # (and warm-up issued) flips it back to 200.
                        self._error(503, "scaled to zero (disarmed)")
                    else:
                        # Worker-wedge gate: a follower that is alive but
                        # hung (SIGSTOP, OOM-thrash) stops heartbeating on
                        # the dispatch channel — the gang must leave the
                        # Service endpoints within a bounded window, not
                        # when a collective finally times out.
                        wedged = server.follower_wedge()
                        if wedged:
                            self._error(503, wedged)
                        else:
                            # Sketch age/version metadata rides readiness
                            # so operators (and the router's monitoring)
                            # can spot a wedged/stale sketch export
                            # without scraping the sketch itself.  The
                            # admission block is the saturation signal:
                            # edges read queue depth/drain here to back
                            # off BEFORE the bounded queue starts 503ing.
                            # The admission block + per-tier SLO burn +
                            # elastic state together are the autoscaler's
                            # scrape surface (control.autoscaler.
                            # scrape_signals) — live saturation/burn
                            # drive scaling instead of raw RPM.
                            self._json(200, {"status": "ready",
                                             "sketch": server._sketch_meta(),
                                             "admission":
                                                 server.engine.saturation(),
                                             "slo_burn":
                                                 server._slo_burn(),
                                             "elastic":
                                                 server._elastic_meta()})
                else:
                    self._error(404, f"no route {self.path}")

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    return self._error(400, "invalid JSON body")
                if self.path == "/v1/profiler/start":
                    # On-demand jax.profiler window (operator tooling —
                    # exempt from the drain gate, like GET diagnostics).
                    return self._json(
                        200, server.engine.profiler.start(
                            body.get("logdir") or None))
                if self.path == "/v1/profiler/stop":
                    return self._json(200, server.engine.profiler.stop())
                if self.path == "/v1/elastic/resize":
                    # Live topology resize / scale-from-zero re-arm
                    # (operator + autoscaler actuator — exempt from the
                    # drain gate like the profiler: a resize request must
                    # land even while completions are gated).
                    return server._handle_resize(self, body)
                # Admission check and active-count increment are ATOMIC:
                # drain() waiting for _active == 0 is then guaranteed no
                # handler slips in after its last look.
                with server._active_lock:
                    if server.draining:
                        return self._error(503, "server is draining")
                    server._active += 1
                try:
                    if server.handle_post(self, body, self.path):
                        pass  # subclass route (disaggregated prefill/decode)
                    elif self.path == "/v1/chat/completions":
                        server._handle_completion(self, body, chat=True)
                    elif self.path == "/v1/completions":
                        server._handle_completion(self, body, chat=False)
                    else:
                        self._error(404, f"no route {self.path}")
                except BrokenPipeError:
                    pass
                except Exception as e:  # engine/request failure → 500
                    log.exception("request handler failure on %s",
                                  self.path)
                    try:
                        self._error(500, f"internal error: {e}")
                    except Exception as e2:
                        # Client hung up before the 500 went out.
                        swallowed("server.error-response", e2)
                finally:
                    with server._active_lock:
                        server._active -= 1

        class Server(ThreadingHTTPServer):
            # A burst of N-hundred concurrent (re)connects overflows the
            # default backlog of 5 and the kernel RSTs the overflow —
            # clients saw "connection reset by peer" under load
            # (bench_serving.py).
            request_queue_size = 512
            daemon_threads = True

        httpd = Server((self.host, self.port), Handler)
        with self._active_lock:
            self._httpd = httpd
            stopped = self._stopped
        if stopped:
            # stop()/drain() raced ahead of start() (e.g. SIGTERM between
            # installing the handler and binding the socket): entering
            # serve_forever now would hang the process unready forever.
            httpd.server_close()
            return
        self.port = httpd.server_port
        self._ready.set()
        if background:
            threading.Thread(target=httpd.serve_forever,
                             name="http", daemon=True).start()
        else:
            httpd.serve_forever()

    def stop(self) -> None:
        with self._active_lock:
            self._stopped = True
            httpd = self._httpd
        if httpd is not None:
            httpd.shutdown()

    def drain(self, timeout_s: float = 20.0) -> None:
        """Graceful shutdown: flip readiness off (routes pull this backend),
        reject new completions with 503, wait for in-flight requests to
        finish (bounded by ``timeout_s``), then stop the HTTP server.  The
        local gang driver and K8s both SIGTERM before SIGKILL — this is
        what makes rolling updates request-lossless when the grace period
        covers the longest request."""
        self.draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            # Engine idle AND no live POST handler: the handler count
            # covers the gaps the engine cannot see (tokenizing before
            # add_request, streaming tail frames to a slow client,
            # synchronous detached prefills on the prefill tier).
            with self._active_lock:
                active = self._active
            if active == 0 and self.engine.idle:
                break
            time.sleep(0.1)
        self.stop()

    def handle_post(self, h, body: dict, path: str) -> bool:
        """Subclass hook for extra POST routes; True = handled."""
        return False

    # ------------------------------------------------------------------

    def _sketch_payload(self) -> dict:
        fn = getattr(self.engine, "cache_sketch", None)
        return fn() if callable(fn) else {"enabled": False}

    def _block_payload(self, hexdigest: str) -> bytes | None:
        """One prefix block, packed for the peer-fetch wire (GET
        /v1/cache/blocks/{digest}).  The engine's export path does the
        tier lookups; the AKV1 packing (json header) happens HERE, on
        the server thread, outside the engine hot path."""
        try:
            digest = bytes.fromhex(hexdigest)
        except ValueError:
            return None
        fn = getattr(self.engine, "block_for_export", None)
        blk = fn(digest) if callable(fn) else None
        if blk is None:
            return None
        from arks_tpu.engine import kv_transfer
        return kv_transfer.pack_block(digest, self.engine.kv_epoch, blk)

    def _sketch_meta(self) -> dict:
        """Age/version metadata for /readiness (not the full sketch)."""
        p = self._sketch_payload()
        if not p.get("enabled"):
            return {"enabled": False}
        return {"enabled": True, "epoch": p.get("epoch"),
                "version": p.get("version"),
                "age_s": round(max(0.0, time.time()
                                   - float(p.get("built_unix", 0.0))), 3)}

    def _elastic_meta(self) -> dict:
        """Elastic snapshot for /readiness and /v1/elastic/status."""
        fn = getattr(self.engine, "elastic_status", None)
        return fn() if callable(fn) else {"armed": True}

    def _slo_burn(self) -> dict:
        fn = getattr(self.engine, "slo_burn", None)
        return fn() if callable(fn) else {}

    def _handle_resize(self, h, body: dict) -> None:
        """POST /v1/elastic/resize: {"tensor_parallel": N,
        "data_parallel": M, "timeout_s": T}.  Posts the resize to the
        engine's elastic state machine and waits (bounded) for it to
        drain/reshard/resume; a resize posted to a scaled-to-zero replica
        re-arms it at the requested shape (streaming scale-from-zero).
        200 = resumed at the new shape, 202 = still in flight past the
        wait budget, 409 = another resize in flight, 422 = shape refused
        (fallback matrix, docs/application-usage.md)."""
        fn = getattr(self.engine, "request_resize", None)
        if not callable(fn):
            return h._error(501, "engine has no elastic resize support")
        try:
            tp = body.get("tensor_parallel")
            dp = body.get("data_parallel")
            req = fn(tensor_parallel=None if tp is None else int(tp),
                     data_parallel=None if dp is None else int(dp))
        except (ValueError, TypeError) as e:
            return h._error(400, str(e))
        except RuntimeError as e:
            return h._error(409, str(e))
        timeout_s = float(body.get("timeout_s", 120.0))
        if not req.wait(timeout_s):
            return h._json(202, {"status": "pending",
                                 "elastic": self._elastic_meta()})
        payload = {"status": req.outcome, "seconds": req.seconds,
                   "error": str(req.error) if req.error else None,
                   "elastic": self._elastic_meta()}
        code = {"ok": 200, "rejected": 422}.get(req.outcome, 500)
        h._json(code, payload)

    def _models_payload(self) -> dict:
        data = [{
            "id": self.served_model_name, "object": "model",
            "created": int(time.time()), "owned_by": "arks-tpu",
        }]
        pool = getattr(self.engine, "pool", None)
        if pool is not None:
            # Pool residency listing: every registered model is routable by
            # its ``model`` field; the served_model_name stays the public
            # alias of the engine's primary.  The "arks" block is extra
            # metadata OpenAI clients ignore.
            primary = getattr(self.engine, "_primary_model", None)
            for row in pool.snapshot():
                if row["name"] == primary:
                    data[0]["arks"] = {
                        "state": row["state"], "pinned": row["pinned"],
                        "resident_bytes": row["resident_bytes"],
                        "cold_starts": row["cold_starts"]}
                    continue
                data.append({
                    "id": row["name"], "object": "model",
                    "created": int(time.time()), "owned_by": "arks-tpu",
                    "arks": {"state": row["state"], "pinned": row["pinned"],
                             "resident_bytes": row["resident_bytes"],
                             "cold_starts": row["cold_starts"]}})
        return {"object": "list", "data": data}

    def _prompt_ids_batch(self, body: dict, chat: bool,
                          tools: list | None = None) -> list[list[int]]:
        """One id-list per prompt. Chat is always a single prompt; completions
        accept a string, a token-id list, or a list of strings (OpenAI batch
        form -> one choice per prompt)."""
        tok = self.engine.tokenizer
        if chat:
            messages = body.get("messages") or []
            if not isinstance(messages, list) or not messages:
                raise ValueError("messages must be a non-empty list")
            return [tok.apply_chat_template(messages, tools=tools)]
        prompt = body.get("prompt", "")
        if isinstance(prompt, list):
            if all(isinstance(p, int) for p in prompt) and prompt:
                batch = [[int(t) for t in prompt]]
            elif all(isinstance(p, str) for p in prompt) and prompt:
                batch = [tok.encode(p) for p in prompt]
            else:
                raise ValueError("prompt list must be all strings or all token ids")
        else:
            batch = [tok.encode(str(prompt))]
        for ids in batch:
            if not ids:
                raise ValueError("prompt must not be empty")
        return batch

    def _handle_completion(self, h, body: dict, chat: bool) -> None:
        model = body.get("model") or self.served_model_name
        # Multi-model routing: served_model_name is the primary's public
        # alias; any other pool-registered name rides the request into the
        # engine's awaiting_model machinery.  engine_model None = primary.
        engine_model = None
        if model != self.served_model_name:
            served = getattr(self.engine, "served_models", None)
            pool_names = served() if served is not None else []
            if model not in pool_names:
                return h._error(404, f"model {model!r} not found")
            if model != pool_names[0]:
                engine_model = model
        try:
            from arks_tpu.server import tools as tools_mod
            tools = None
            tool_choice = "none"
            if chat:
                tools, tool_choice = tools_mod.validate_tools(body)
            tools_on = bool(tools) and tool_choice != "none"
            batch = self._prompt_ids_batch(body, chat,
                                           tools=tools if tools_on else None)
            params, stop_strings = _sampling_from_body(
                body, self.engine.tokenizer, self.engine)
            tier = (h.headers.get(HDR_TIER) or "").strip() or None
            if tier is not None:
                pri = self.slo.priority_of(tier) if self.slo else None
                if pri is None:
                    raise ValueError(
                        f"unknown SLO tier {tier!r} (configured: "
                        f"{', '.join(self.slo.names) or 'none'})")
                import dataclasses as _dct
                params = _dct.replace(params, priority=pri)
            tools_ctx = None
            if tools_on:
                tools_ctx = knobs.get_str("ARKS_TOOL_PARSER")
                forced = tools_mod.forced_call_guide(tools, tool_choice)
                if forced is not None:
                    if params.guide is not None:
                        raise ValueError(
                            "tool_choice required/named cannot combine "
                            "with response_format/guided_regex")
                    self.engine.guides.validate(*forced)
                    import dataclasses as _dc0
                    params = _dc0.replace(params, guide=forced)
            # OpenAI n: independent samples per prompt (choices are
            # prompt-major).  Seeded requests derive child seeds seed+j so
            # the choices differ while staying reproducible.
            n_raw = body.get("n", 1)
            if n_raw is None:
                n_raw = 1
            if isinstance(n_raw, bool) or not isinstance(n_raw, int):
                raise ValueError("n must be an integer")
            n = n_raw
            if not 1 <= n <= 16:
                raise ValueError("n must be between 1 and 16")
        except ValueError as e:
            return h._error(400, str(e))
        stream = bool(body.get("stream", False))
        if stream and (len(batch) > 1 or n > 1):
            return h._error(
                400, "streaming is not supported for batched prompts or n > 1")
        echo = bool(body.get("echo", False))
        if echo and chat:
            return h._error(400, "echo is a completions-only parameter")
        if echo and stream:
            return h._error(400, "echo is not supported with streaming")

        # Reject oversize prompts BEFORE queueing (OpenAI semantics: 400
        # context_length_exceeded — never silent truncation, which would
        # corrupt long-context results and billing).
        limit = self.engine.max_prompt_len
        for prompt_ids in batch:
            if len(prompt_ids) > limit:
                return self._context_length_error(h, len(prompt_ids), limit)

        # Routing-sketch text ledger: this is the one place that sees a
        # text prompt NEXT TO its token ids, so record the alignment the
        # tokenize-free router scoring depends on (host hashing only).
        note = getattr(self.engine, "note_prompt_text", None)
        if callable(note):
            note(body, batch[0])

        import dataclasses as _dc
        # W3C trace context: continue the gateway/router-propagated trace
        # (folding in their completed spans from the x-arks-trace-spans
        # header) or mint a fresh root for direct-to-pod clients.  Only a
        # single-choice request carries it — sibling choices would collide
        # in the trace store under one trace id; they mint engine-local ids.
        ctx = (trace_mod.TraceCtx.from_headers(h.headers)
               if self.engine.trace.enabled else None)
        single = len(batch) == 1 and n == 1
        # Tenant identity: minted by the gateway (x-arks-tenant), forwarded
        # verbatim by the router.  Direct-to-pod clients carry none — their
        # requests share the fair queue's single untenanted lane.
        tenant = (h.headers.get(tenancy.HDR_TENANT) or "").strip() or None
        # Fleet prefix cache: the router's deepest-covering-replica hint
        # (X-Arks-Peer-Hint) — the engine's peer fetch pulls warm blocks
        # from there on an admission miss (ARKS_PEER_FETCH).
        peer_hint = (h.headers.get("x-arks-peer-hint") or "").strip() or None
        reqs = []
        for prompt_ids in batch:
            for j in range(n):
                p = params
                if n > 1 and params.seed is not None:
                    p = _dc.replace(params, seed=params.seed + j)
                req = Request(request_id=f"req-{uuid.uuid4().hex[:16]}",
                              prompt_ids=list(prompt_ids), params=p,
                              model=engine_model, tenant=tenant,
                              trace=ctx if single else None,
                              peer_hint=peer_hint)
                try:
                    with logctx.bound(req.request_id,
                                      ctx.trace_id if ctx is not None else None):
                        self.engine.add_request(req)
                except fairqueue.QueueFullError as e:
                    # Overload ladder: the bounded admission queue refused
                    # this request.  Roll back the siblings already queued
                    # (a batch admits atomically or not at all) and map the
                    # scope: the GLOBAL bound means this backend is
                    # saturated (503 — router should fail over), while a
                    # per-TENANT bound is the caller's own backlog (429 —
                    # slow down; other tenants are fine).
                    for prev in reqs:
                        self.engine.abort(prev.request_id)
                    return self._queue_full_error(h, e)
                reqs.append(req)

        if len(reqs) > 1:
            self._batch_response(h, reqs, model, stop_strings, chat=chat,
                                 echo=echo, tools_ctx=tools_ctx)
        else:
            self._respond(h, reqs[0], chat, model, body, stop_strings,
                          echo=echo, tools_ctx=tools_ctx)

    def _queue_full_error(self, h, e: "fairqueue.QueueFullError") -> None:
        """Map a bounded-queue rejection to HTTP, with the backoff hints
        the edge needs: Retry-After derived from the queue's observed
        drain rate and the saturation signal so the gateway can shed
        pre-emptively instead of retry-hammering a full backend."""
        sat = self.engine.saturation()
        headers = {"Retry-After": str(e.retry_after),
                   tenancy.HDR_SATURATION: f"{sat['saturation']:.2f}"}
        if e.tenant:
            headers[tenancy.HDR_TENANT] = e.tenant
        if e.scope == "tenant":
            h._json(429, {"error": {
                "message": (f"tenant queue is full ({e.depth}/{e.limit} "
                            "queued requests for this tenant)"),
                "type": "rate_limit_error",
                "code": "tenant_queue_full",
            }}, headers=headers)
        else:
            h._json(503, {"error": {
                "message": (f"admission queue is full ({e.depth}/{e.limit} "
                            "queued requests)"),
                "type": "server_error",
                "code": "queue_full",
            }}, headers=headers)

    def _context_length_error(self, h, got: int, limit: int) -> None:
        h._json(400, {"error": {
            "message": (f"This model's maximum context length is {limit} "
                        f"tokens, but your prompt has {got} tokens."),
            "type": "invalid_request_error",
            "code": "context_length_exceeded",
        }})

    def _request_error(self, h, fin) -> None:
        """Map a finish_reason="error" engine output to HTTP.  Client-
        caused rejections (context length, bad guide) stay 400s; a request
        quarantined by fault recovery (error "engine_fault: ...") is the
        SERVER's failure — OpenAI-style 500 so clients and the gateway
        retry/alert correctly instead of blaming the request."""
        if fin.error == "context_length_exceeded":
            return self._context_length_error(
                h, fin.num_prompt_tokens, self.engine.max_prompt_len)
        if fin.error and fin.error.startswith("engine_fault"):
            return h._json(500, {"error": {
                "message": ("The server had an error while processing "
                            f"your request ({fin.error})."),
                "type": "server_error",
                "code": "engine_fault",
            }})
        if fin.error and fin.error.startswith("shed_deadline"):
            # Deadline-aware shed: the request waited so long in the
            # admission queue that its tier's TTFT budget is already
            # unmeetable — burning prefill on it would only delay work
            # that can still meet its SLO.  503 + drain-derived
            # Retry-After, same capacity semantics as queue_full.
            sat = self.engine.saturation()
            return h._json(503, {"error": {
                "message": f"request shed before prefill ({fin.error})",
                "type": "server_error",
                "code": "shed_deadline",
            }}, headers={
                "Retry-After": str(self.engine.queue_retry_after()),
                tenancy.HDR_SATURATION: f"{sat['saturation']:.2f}"})
        if fin.error and fin.error.startswith("model_pool_exhausted"):
            # Capacity, not client error: the pool can't fit the model
            # right now (pinned/in-use residents).  503 + Retry-After so
            # clients and the gateway queue-and-retry instead of failing
            # the request class permanently.
            return h._json(503, {"error": {
                "message": f"model is not loadable right now ({fin.error})",
                "type": "server_error",
                "code": "model_pool_exhausted",
            }}, headers={"Retry-After": "5"})
        if fin.error and fin.error.startswith("model_load_failed"):
            return h._json(500, {"error": {
                "message": f"model failed to load ({fin.error})",
                "type": "server_error",
                "code": "model_load_failed",
            }})
        if fin.error and fin.error.startswith("model_not_found"):
            return h._error(404, fin.error)
        return h._error(400, fin.error or "request rejected")

    def _respond(self, h, req: Request, chat: bool, model: str, body: dict,
                 stop_strings: list[str], echo: bool = False,
                 tools_ctx: str | None = None) -> None:
        """Stream-or-full dispatch tail, shared with the disaggregated path.
        ``tools_ctx`` is the tool-call parser name when the request carries
        active tools (chat only)."""
        if bool(body.get("stream", False)):
            # Peek the first engine output BEFORE committing to SSE: an
            # admission-time rejection (async guide-compile failure,
            # engine-side context check) must map to a clean HTTP 400,
            # not a text/event-stream carrying finish_reason "error".
            first = req.outputs.get()
            if first.finished and first.finish_reason == "error":
                return self._request_error(h, first)
            include_usage = bool(
                (body.get("stream_options") or {}).get("include_usage"))
            if tools_ctx is not None and chat:
                return self._stream_tools_response(
                    h, req, model, include_usage, stop_strings, tools_ctx,
                    first_out=first)
            self._stream_response(h, req, chat, model, include_usage,
                                  stop_strings, first_out=first)
        else:
            self._full_response(h, req, chat, model, stop_strings, echo=echo,
                                tools_ctx=tools_ctx)

    # ------------------------------------------------------------------

    def _collect_text(self, req: Request, stop_strings: list[str]):
        """Drain a request to completion, applying stop-string truncation to
        every chunk — including the final one and flushed tail text.
        Returns (text, finish_reason, final RequestOutput, token_ids,
        logprob entries, per-token text pieces)."""
        detok = IncrementalDetokenizer(self.engine.tokenizer)
        # Per-token text pieces come from the SAME incremental stream as the
        # response text, so stop-cut trimming and text_offset stay aligned
        # even for multi-byte BPE pieces (an isolated tok.decode([tid])
        # renders replacement chars of the wrong length).  Only paid when
        # logprobs are on — that is the only consumer of the alignment.
        track = req.params.logprobs is not None
        text = ""
        tokens: list[int] = []
        lps: list = []
        pieces: list[str] = []
        # min_tokens defers ALL stops (vLLM semantics): text generated
        # before the minimum (length ``exempt``) is exempt from stop
        # matching; _find_stop still cuts a stop straddling the boundary.
        min_tok = int(getattr(req.params, "min_tokens", 0) or 0)
        exempt = 0
        while True:
            out = req.outputs.get()
            start_len = len(tokens)
            if track:
                for j, t in enumerate(out.token_ids):
                    piece = detok.push([t])
                    text += piece
                    pieces.append(piece)
                    if stop_strings and start_len + j + 1 < min_tok:
                        exempt = len(text)
            elif stop_strings and start_len < min_tok:
                # Token-wise pushes while below min_tokens so the exemption
                # boundary lands on the exact token, not the chunk.
                for j, t in enumerate(out.token_ids):
                    text += detok.push([t])
                    if start_len + j + 1 < min_tok:
                        exempt = len(text)
            else:
                text += detok.push(out.token_ids)
            tokens.extend(out.token_ids)
            if out.logprobs:
                lps.extend(out.logprobs)
            if out.finished:
                tail = detok.flush()
                text += tail
                if track and pieces and tail:
                    # Window residue resolves after the last token; for
                    # offset/trim purposes it belongs to that token.
                    pieces[-1] += tail
            if stop_strings and len(tokens) >= min_tok:
                cut = _find_stop(text, stop_strings, min_end=exempt)
                if cut is not None:
                    text = text[:cut]
                    if not out.finished:
                        self.engine.abort(req.request_id)
                        while not out.finished:
                            out = req.outputs.get()
                    # Trim token/logprob arrays to the visible text: entries
                    # past the cut would make text_offset index out of the
                    # returned string.
                    tokens, lps, pieces = self._trim_to_text(
                        tokens, lps, pieces, cut)
                    return text, "stop", out, tokens, lps, pieces
            if out.finished:
                return text, out.finish_reason, out, tokens, lps, pieces

    def _trim_to_text(self, tokens: list[int], lps: list, pieces: list[str],
                      cut: int):
        """Keep the longest token prefix whose streamed text fits in
        ``cut`` characters (a token straddling the cut is dropped)."""
        if not pieces and tokens:
            # The logprobs-off path records no stream pieces; isolated
            # per-token decode is the best-effort fallback (lazy, stops at
            # the cut; nothing downstream consumes offsets then).
            tok = self.engine.tokenizer
            pieces = (tok.decode([t]) for t in tokens)
        keep, acc, kept = 0, 0, []
        for piece in pieces:
            if acc + len(piece) > cut:
                break
            acc += len(piece)
            keep += 1
            kept.append(piece)
        return tokens[:keep], lps[:keep], kept

    def _lp_completions_obj(self, token_ids: list[int], lps: list,
                            top_n: int, pieces: list[str] | None = None,
                            offset_base: int = 0) -> dict:
        """Legacy completions logprobs object (tokens / token_logprobs /
        top_logprobs / text_offset).  ``pieces`` (per-token text from the
        response's own incremental stream) keeps text_offset aligned with
        the returned text; alternatives in top_logprobs are hypothetical
        tokens with no stream context, so they decode in isolation.
        ``offset_base`` shifts text_offset past echoed prompt text."""
        tok = self.engine.tokenizer
        tokens, token_lps, tops, offsets = [], [], [], []
        off = offset_base
        for i, (tid, (clp, top)) in enumerate(zip(token_ids, lps)):
            s = pieces[i] if pieces is not None and i < len(pieces) \
                else tok.decode([tid])
            tokens.append(s)
            token_lps.append(clp)
            tops.append({tok.decode([j]): v for j, v in top[:top_n]})
            offsets.append(off)
            off += len(s)
        return {"tokens": tokens, "token_logprobs": token_lps,
                "top_logprobs": tops, "text_offset": offsets}

    def _lp_chat_content(self, token_ids: list[int], lps: list,
                         top_n: int, pieces: list[str] | None = None
                         ) -> list[dict]:
        """Chat logprobs.content entries ({token, logprob, bytes,
        top_logprobs})."""
        tok = self.engine.tokenizer

        def entry(tid_text: str, lp_val: float) -> dict:
            return {"token": tid_text, "logprob": lp_val,
                    "bytes": list(tid_text.encode("utf-8", "surrogatepass"))}

        out = []
        for i, (tid, (clp, top)) in enumerate(zip(token_ids, lps)):
            s = pieces[i] if pieces is not None and i < len(pieces) \
                else tok.decode([tid])
            e = entry(s, clp)
            e["top_logprobs"] = [entry(tok.decode([j]), v)
                                 for j, v in top[:top_n]]
            out.append(e)
        return out

    def _batch_response(self, h, reqs: list[Request], model: str,
                        stop_strings: list[str], chat: bool = False,
                        echo: bool = False,
                        tools_ctx: str | None = None) -> None:
        """Multi-choice responses: batched prompts and/or n > 1 (one
        engine request per choice, prompt-major indexes)."""
        choices, usage = [], {"prompt_tokens": 0, "completion_tokens": 0,
                              "total_tokens": 0}
        echo_cache: dict = {}
        for i, req in enumerate(reqs):
            text, finish_reason, fin, toks, lps, pieces = self._collect_text(
                req, stop_strings)
            if finish_reason == "error":
                # One rejected choice fails the whole batch (the OpenAI
                # response has no per-choice error channel); release the
                # siblings' slots instead of decoding for nobody.
                for r in reqs:
                    self.engine.abort(r.request_id)
                return self._request_error(h, fin)
            if chat:
                message, finish_reason = self._chat_message(
                    text, finish_reason, tools_ctx)
                choice = {"index": i, "message": message,
                          "finish_reason": finish_reason}
                if req.params.logprobs is not None and lps:
                    choice["logprobs"] = {"content": self._lp_chat_content(
                        toks, lps, req.params.logprobs, pieces)}
            else:
                prefix = ""
                if echo:
                    key = tuple(req.prompt_ids)
                    if key not in echo_cache:  # n children share one prompt
                        echo_cache[key] = self.engine.tokenizer.decode(
                            req.prompt_ids)
                    prefix = echo_cache[key]
                    text = prefix + text
                choice = {"index": i, "text": text,
                          "finish_reason": finish_reason}
                if req.params.logprobs is not None and lps:
                    choice["logprobs"] = self._lp_completions_obj(
                        toks, lps, req.params.logprobs, pieces,
                        offset_base=len(prefix))
            choices.append(choice)
            usage["prompt_tokens"] += fin.num_prompt_tokens
            usage["completion_tokens"] += fin.num_generated_tokens
        usage["total_tokens"] = usage["prompt_tokens"] + usage["completion_tokens"]
        h._json(200, {
            "id": reqs[0].request_id,
            "object": "chat.completion" if chat else "text_completion",
            "created": int(time.time()), "model": model,
            "choices": choices, "usage": usage,
        })

    def _chat_message(self, text: str, finish_reason: str,
                      tools_ctx: str | None) -> tuple[dict, str]:
        """Assistant message dict (+ effective finish_reason): with active
        tools, generated text is parsed for tool calls; a call flips the
        finish_reason to "tool_calls" (OpenAI contract — but never over a
        truncation, clients must see length limits)."""
        if tools_ctx is not None:
            from arks_tpu.server.tools import parse_tool_calls
            content, calls = parse_tool_calls(text, tools_ctx)
            if calls:
                msg = {"role": "assistant", "content": content,
                       "tool_calls": calls}
                fr = ("tool_calls" if finish_reason == "stop"
                      else finish_reason)
                return msg, fr
        return {"role": "assistant", "content": text}, finish_reason

    def _full_response(self, h, req: Request, chat: bool, model: str,
                       stop_strings: list[str], echo: bool = False,
                       tools_ctx: str | None = None) -> None:
        text, finish_reason, fin, toks, lps, pieces = self._collect_text(
            req, stop_strings)
        echo_prefix = ""
        if echo and not chat:
            # OpenAI completions echo: the prompt text precedes the
            # generated text in the same choice (non-stream only).
            echo_prefix = self.engine.tokenizer.decode(req.prompt_ids)
            text = echo_prefix + text
        if finish_reason == "error":
            # Engine-level rejection (defense for direct add_request users;
            # the HTTP path normally pre-checks) or a fault-quarantined
            # request (engine_fault -> 500).
            return self._request_error(h, fin)
        usage = {
            "prompt_tokens": fin.num_prompt_tokens,
            "completion_tokens": fin.num_generated_tokens,
            "total_tokens": fin.num_prompt_tokens + fin.num_generated_tokens,
        }
        rid = req.request_id
        n_lp = req.params.logprobs
        if chat:
            message, finish_reason = self._chat_message(text, finish_reason,
                                                        tools_ctx)
            choice = {"index": 0, "message": message,
                      "finish_reason": finish_reason}
            if n_lp is not None and lps:
                choice["logprobs"] = {
                    "content": self._lp_chat_content(toks, lps, n_lp, pieces)}
            payload = {
                "id": rid, "object": "chat.completion", "created": int(time.time()),
                "model": model, "choices": [choice], "usage": usage,
            }
        else:
            choice = {"index": 0, "text": text,
                      "finish_reason": finish_reason}
            if n_lp is not None and lps:
                choice["logprobs"] = self._lp_completions_obj(
                    toks, lps, n_lp, pieces,
                    offset_base=len(echo_prefix))
            payload = {
                "id": rid, "object": "text_completion", "created": int(time.time()),
                "model": model, "choices": [choice], "usage": usage,
            }
        h._json(200, payload)

    def _stream_tools_response(self, h, req: Request, model: str,
                               include_usage: bool, stop_strings: list[str],
                               parser: str, first_out=None) -> None:
        """Chat streaming with active tools: content streams normally until
        a tool-call marker appears; from there the text buffers and is
        emitted as ``delta.tool_calls`` when the stream ends (each call's
        arguments arrive in one delta — permitted by the protocol, and the
        only faithful option when calls must parse as complete JSON).
        Stop strings are applied over the full text, like the non-stream
        path (the stream runs fully buffered when any are set), including
        the min_tokens exemption."""
        from arks_tpu.server.tools import (TOOL_OPEN, call_spans,
                                           parse_tool_calls)
        h.send_response(200)
        h.send_header("Content-Type", "text/event-stream")
        h.send_header("Cache-Control", "no-cache")
        h.send_header("Transfer-Encoding", "chunked")
        h.end_headers()

        def send_frame(obj) -> None:
            data = b"data: " + (obj if isinstance(obj, bytes)
                                else json.dumps(obj).encode()) + b"\n\n"
            h.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            h.wfile.flush()

        rid = req.request_id
        created = int(time.time())

        def chunk(delta: dict | None, finish: str | None = None,
                  usage: dict | None = None,
                  empty_choices: bool = False) -> dict:
            choices = [] if empty_choices else [
                {"index": 0, "delta": delta or {}, "finish_reason": finish}]
            payload = {"id": rid, "object": "chat.completion.chunk",
                       "created": created, "model": model,
                       "choices": choices}
            if usage is not None:
                payload["usage"] = usage
            return payload

        detok = IncrementalDetokenizer(self.engine.tokenizer)
        text = ""
        emitted = 0
        buffering = bool(stop_strings)
        hold = len(TOOL_OPEN) - 1
        fin = None
        min_tok = int(getattr(req.params, "min_tokens", 0) or 0)
        ntok = 0
        exempt = 0
        try:
            send_frame(chunk({"role": "assistant"}))
            while True:
                out = first_out if first_out is not None \
                    else req.outputs.get()
                first_out = None  # _respond peeked the first output
                prev_ntok = ntok
                ntok += len(out.token_ids)
                if stop_strings and prev_ntok < min_tok:
                    # Token-wise pushes below min_tokens: the stop
                    # exemption boundary must land on the exact token
                    # (same semantics as _collect_text).
                    for j, t in enumerate(out.token_ids):
                        text += detok.push([t])
                        if prev_ntok + j + 1 < min_tok:
                            exempt = len(text)
                else:
                    text += detok.push(out.token_ids)
                if out.finished:
                    text += detok.flush()
                    fin = out
                if not buffering:
                    m = text.find(TOOL_OPEN)
                    if m >= 0:
                        if m > emitted:
                            send_frame(chunk({"content": text[emitted:m]}))
                            emitted = m
                        buffering = True
                    elif (parser in ("auto", "llama3")
                          and text.lstrip()[:1] == "{"):
                        buffering = True  # llama3: whole message is a call
                    elif not out.finished:
                        # Hold back a window so a straddling marker isn't
                        # half-emitted as content.
                        safe = len(text) - hold
                        if safe > emitted:
                            send_frame(chunk({"content": text[emitted:safe]}))
                            emitted = safe
                if out.finished:
                    break
            finish = fin.finish_reason
            if stop_strings and ntok >= min_tok:
                cut = _find_stop(text, stop_strings, min_end=exempt)
                if cut is not None:
                    text = text[:cut]
                    finish = "stop"
            content, calls = parse_tool_calls(text, parser)
            if calls:
                # Leftover content in RAW coordinates: everything outside
                # the call spans and past what was already streamed
                # (parse_tool_calls' stripped content doesn't line up
                # with the emitted offset).
                pos = emitted
                rest_parts = []
                for s, e in call_spans(text, parser):
                    if s > pos:
                        rest_parts.append(text[pos:s])
                    pos = max(pos, e)
                if pos < len(text):
                    rest_parts.append(text[pos:])
                rest = "".join(rest_parts)
                if rest:
                    send_frame(chunk({"content": rest}))
                for idx, call in enumerate(calls):
                    send_frame(chunk({"tool_calls": [{
                        "index": idx, "id": call["id"], "type": "function",
                        "function": dict(call["function"])}]}))
                if finish == "stop":
                    finish = "tool_calls"
            elif len(text) > emitted:
                send_frame(chunk({"content": text[emitted:]}))
            send_frame(chunk(None, finish=finish))
            if include_usage:
                send_frame(chunk(None, usage={
                    "prompt_tokens": fin.num_prompt_tokens,
                    "completion_tokens": fin.num_generated_tokens,
                    "total_tokens": (fin.num_prompt_tokens
                                     + fin.num_generated_tokens),
                }, empty_choices=True))
            send_frame(b"[DONE]")
            h.wfile.write(b"0\r\n\r\n")
            h.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            self.engine.abort(req.request_id)

    def _stream_response(self, h, req: Request, chat: bool, model: str,
                         include_usage: bool, stop_strings: list[str],
                         first_out=None) -> None:
        h.send_response(200)
        h.send_header("Content-Type", "text/event-stream")
        h.send_header("Cache-Control", "no-cache")
        h.send_header("Transfer-Encoding", "chunked")
        h.end_headers()

        def send_frame(obj) -> None:
            data = b"data: " + (obj if isinstance(obj, bytes) else json.dumps(obj).encode()) + b"\n\n"
            h.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            h.wfile.flush()

        rid = req.request_id
        created = int(time.time())
        obj = "chat.completion.chunk" if chat else "text_completion"

        n_lp = req.params.logprobs
        # Logprob entries accumulate per engine output and flush with
        # emitted frames — but never ahead of their text: entries whose
        # pieces sit in the stop-string hold-back tail stay pending (a
        # later cut may drop them), so the streamed entry set matches the
        # non-stream response exactly.
        pend_lp_toks: list[int] = []
        pend_lps: list = []
        pend_pieces: list[str] = []
        lp_flush_n: list[int | None] = [None]  # entries next frame may flush

        def lp_within(pending_text: str, boundary: int) -> int:
            """How many pending entries' text ends within the first
            ``boundary`` chars of ``pending_text``.  Pending tokens' text is
            the trailing sum(pend_pieces) chars of emitted+pending text, so
            walk from that (possibly negative) offset."""
            acc = len(pending_text) - sum(len(p) for p in pend_pieces)
            keep = 0
            for p in pend_pieces:
                if acc + len(p) > boundary:
                    break
                acc += len(p)
                keep += 1
            return keep

        def take_lp():
            if n_lp is None or not pend_lps:
                return None
            n = lp_flush_n[0]
            n = len(pend_lps) if n is None else min(n, len(pend_lps))
            if n <= 0:
                return None
            toks_, lps_, pieces_ = (pend_lp_toks[:n], pend_lps[:n],
                                    pend_pieces[:n])
            del pend_lp_toks[:n]
            del pend_lps[:n]
            del pend_pieces[:n]
            if chat:
                return {"content": self._lp_chat_content(
                    toks_, lps_, n_lp, pieces_)}
            return self._lp_completions_obj(toks_, lps_, n_lp, pieces_)

        def chunk(delta_text: str | None, finish: str | None = None, role: str | None = None,
                  usage: dict | None = None, empty_choices: bool = False) -> dict:
            if empty_choices:
                choices = []
            elif chat:
                delta: dict = {}
                if role:
                    delta["role"] = role
                if delta_text:
                    delta["content"] = delta_text
                choices = [{"index": 0, "delta": delta, "finish_reason": finish}]
            else:
                choices = [{"index": 0, "text": delta_text or "", "finish_reason": finish}]
            if choices and (delta_text or finish):
                lp_obj = take_lp()
                if lp_obj is not None:
                    choices[0]["logprobs"] = lp_obj
            payload = {"id": rid, "object": obj, "created": created,
                       "model": model, "choices": choices}
            if usage is not None:
                payload["usage"] = usage
            return payload

        detok = IncrementalDetokenizer(self.engine.tokenizer)
        fin = None
        # Text already emitted to the client; used for stop-string matching
        # across chunk boundaries (a stop string can straddle two deltas).
        pending = ""
        hold = max((len(s) for s in stop_strings), default=1) - 1
        # min_tokens defers ALL stops (vLLM semantics); ``exempt`` is the
        # pending-relative boundary below which text is exempt from
        # stop-string matching (_find_stop still cuts a stop whose end
        # crosses the boundary).
        min_tok = int(getattr(req.params, "min_tokens", 0) or 0)
        ntok = 0
        exempt = 0
        try:
            if chat:
                send_frame(chunk(None, role="assistant"))
            while True:
                out = first_out if first_out is not None \
                    else req.outputs.get()
                first_out = None  # _respond peeked the first output
                prev_ntok = ntok
                ntok += len(out.token_ids)
                if n_lp is not None:
                    # Per-token pushes through the same stream keep logprob
                    # entries aligned with real text boundaries (see
                    # _collect_text); chunk-wise push stays the no-logprobs
                    # hot path.
                    for j, t in enumerate(out.token_ids):
                        piece = detok.push([t])
                        pending += piece
                        if out.logprobs:
                            pend_pieces.append(piece)
                        if stop_strings and prev_ntok + j + 1 < min_tok:
                            exempt = len(pending)
                    if out.logprobs:
                        pend_lp_toks.extend(out.token_ids)
                        pend_lps.extend(out.logprobs)
                elif stop_strings and prev_ntok < min_tok:
                    # Token-wise pushes while below min_tokens so the
                    # stop-exemption boundary lands on the exact token.
                    for j, t in enumerate(out.token_ids):
                        pending += detok.push([t])
                        if prev_ntok + j + 1 < min_tok:
                            exempt = len(pending)
                else:
                    pending += detok.push(out.token_ids)
                if out.finished:
                    # Flush window residue BEFORE the stop check: the tail
                    # can complete a stop string, and the non-stream path
                    # (_collect_text) cuts it — paths must agree.
                    tail = detok.flush()
                    pending += tail
                    if pend_pieces and tail:
                        pend_pieces[-1] += tail
                if stop_strings and ntok >= min_tok:
                    cut = _find_stop(pending, stop_strings, min_end=exempt)
                    if cut is not None:
                        # Drop only the logprob entries whose text falls
                        # PAST the cut; kept entries flush with the cut
                        # frame (or the stop frame when the cut text is
                        # empty).
                        keep = lp_within(pending, cut)
                        del pend_lp_toks[keep:]
                        del pend_lps[keep:]
                        del pend_pieces[keep:]
                        if pending[:cut]:
                            send_frame(chunk(pending[:cut]))
                        self.engine.abort(req.request_id)
                        while not out.finished:
                            out = req.outputs.get()
                        fin = out
                        send_frame(chunk(None, finish="stop"))
                        break
                if out.finished:
                    if pending:
                        send_frame(chunk(pending))
                    send_frame(chunk(None, finish=out.finish_reason))
                    fin = out
                    break
                # Hold back enough tail to catch a straddling stop string.
                safe = len(pending) - hold
                if safe > 0:
                    # Flush only logprob entries whose text is fully inside
                    # the emitted prefix; entries in the hold-back tail wait
                    # (a later stop cut may drop them).
                    lp_flush_n[0] = lp_within(pending, safe)
                    send_frame(chunk(pending[:safe]))
                    lp_flush_n[0] = None
                    pending = pending[safe:]
                    exempt = max(0, exempt - safe)
            if include_usage and fin is not None:
                usage = {
                    "prompt_tokens": fin.num_prompt_tokens,
                    "completion_tokens": fin.num_generated_tokens,
                    "total_tokens": fin.num_prompt_tokens + fin.num_generated_tokens,
                }
                send_frame(chunk(None, usage=usage, empty_choices=True))
            send_frame(b"[DONE]")
            h.wfile.write(b"0\r\n\r\n")
            h.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            # Client went away: release the slot instead of decoding to
            # max_tokens for nobody.
            self.engine.abort(req.request_id)
