"""OpenAI tool calling for /v1/chat/completions.

Reference parity: the vLLM/SGLang runtimes the reference launches
(``internal/controller/arksapplication_controller.go:941-1014``) accept
``tools``/``tool_choice`` and extract ``tool_calls`` from generated text.
Same shape here: tools render into the prompt through the chat template,
and the model's output is parsed back into structured calls.

Two wire formats cover the supported model families:
  - "hermes": ``<tool_call>{"name": ..., "arguments": {...}}</tool_call>``
    blocks (Qwen2.5, Hermes, and most chat templates with native tool
    support emit this).
  - "llama3": the whole message is one JSON object
    ``{"name": ..., "parameters": {...}}`` (Llama-3.1 json tool calling).
``parse_tool_calls`` auto-detects unless the server pins a parser.

Forced calls (``tool_choice: "required"`` or a named function) compile to
a guided-decoding regex over the hermes format — the DFA makes the model
EMIT a syntactically valid call; no retry loops.
"""

from __future__ import annotations

import json
import re
import uuid

TOOL_OPEN = "<tool_call>"
TOOL_CLOSE = "</tool_call>"

# Function names are interpolated into the forced-call regex AND into the
# JSON the DFA makes the model emit: anything beyond this set (quotes,
# braces, backslashes, whitespace...) would corrupt the grammar into a DFA
# whose forced output parse_tool_calls cannot parse back.  OpenAI's own
# contract is the same alphabet.
_FN_NAME_RE = re.compile(r"[A-Za-z0-9_.-]+")


def validate_tools(body: dict) -> tuple[list | None, object]:
    """Returns (tools, tool_choice) validated, or raises ValueError.
    tool_choice: "auto" | "none" | "required" | {"type": "function",
    "function": {"name": ...}}."""
    tools = body.get("tools")
    if tools is None:
        return None, "none"
    if not isinstance(tools, list) or not tools:
        raise ValueError("tools must be a non-empty list")
    for t in tools:
        if not isinstance(t, dict) or t.get("type") != "function":
            raise ValueError('each tool must have type "function"')
        fn = t.get("function") or {}
        if not isinstance(fn.get("name"), str) or not fn["name"]:
            raise ValueError("each tool function needs a name")
        if not _FN_NAME_RE.fullmatch(fn["name"]):
            raise ValueError(
                f"tool function name {fn['name']!r} must match "
                "[A-Za-z0-9_.-]+ (other characters would corrupt the "
                "forced-call grammar)")
    choice = body.get("tool_choice", "auto")
    if isinstance(choice, str):
        if choice not in ("auto", "none", "required"):
            raise ValueError(f"unknown tool_choice {choice!r}")
    elif isinstance(choice, dict):
        name = (choice.get("function") or {}).get("name")
        if not name:
            raise ValueError("tool_choice object needs function.name")
        known = {t["function"]["name"] for t in tools}
        if name not in known:
            raise ValueError(f"tool_choice names unknown function {name!r}")
    else:
        raise ValueError("tool_choice must be a string or an object")
    return tools, choice


def _re_escape(s: str) -> str:
    """Escape for the engine's byte-regex dialect (ASCII metacharacters)."""
    return re.sub(r"([\\.^$|?*+()\[\]{}])", r"\\\1", s)


# A FLAT JSON object (string keys; string/number/bool/null values, no
# nesting or escapes) — the argument shape the forced-call DFA holds the
# model to.  Always parseable, so a forced call can never fail extraction;
# nested argument objects need tool_choice "auto" (model-formatted).
_JSTR = r'"[^"\\\x00-\x1f]*"'
_JVAL = f"({_JSTR}|-?[0-9]+(\\.[0-9]+)?|true|false|null)"
_FLAT_OBJ = (r"\{ ?(" + _JSTR + ": ?" + _JVAL
             + r"(, ?" + _JSTR + ": ?" + _JVAL + r")*)? ?\}")


def forced_call_guide(tools: list, choice) -> tuple[str, str] | None:
    """Guide spec forcing a hermes-format call, for tool_choice
    "required" (any listed function) or a named function.  The wrapper,
    the name, and a flat-JSON argument object are all DFA-enforced, so
    the emitted call is parseable by construction."""
    if choice == "required":
        names = [t["function"]["name"] for t in tools]
    elif isinstance(choice, dict):
        names = [choice["function"]["name"]]
    else:
        return None
    name_alt = "(" + "|".join(_re_escape(n) for n in names) + ")"
    pat = (_re_escape(TOOL_OPEN) + r"\n?" + r'\{"name": ?"' + name_alt
           + r'", ?"arguments": ?' + _FLAT_OBJ + r'\}' + r"\n?"
           + _re_escape(TOOL_CLOSE))
    return ("regex", pat)


def tools_system_text(tools: list) -> str:
    """Textual tool declaration for templates without native tools
    support (hermes convention, which the parser round-trips)."""
    decls = "\n".join(json.dumps(t["function"], ensure_ascii=False)
                      for t in tools)
    return (
        "You have access to the following functions. To call one, reply "
        "with a <tool_call>{\"name\": <function-name>, \"arguments\": "
        "<args-json-object>}</tool_call> block.\n<tools>\n" + decls
        + "\n</tools>")


def parse_tool_calls(text: str, parser: str = "auto"
                     ) -> tuple[str | None, list[dict]]:
    """(content, tool_calls) from generated text.  content is None when
    the message is nothing but calls (OpenAI convention); tool_calls is []
    when no call was found."""
    if parser in ("auto", "hermes") and TOOL_OPEN in text:
        calls = []
        content_parts = []
        pos = 0
        while True:
            i = text.find(TOOL_OPEN, pos)
            if i < 0:
                content_parts.append(text[pos:])
                break
            content_parts.append(text[:i] if pos == 0 else text[pos:i])
            j = text.find(TOOL_CLOSE, i)
            body = text[i + len(TOOL_OPEN): j if j >= 0 else len(text)]
            call = _parse_one(body)
            if call is not None:
                calls.append(call)
            else:
                content_parts.append(text[i: (j + len(TOOL_CLOSE))
                                          if j >= 0 else len(text)])
            if j < 0:
                break
            pos = j + len(TOOL_CLOSE)
        if calls:
            content = "".join(content_parts).strip()
            return (content or None), calls
    if parser in ("auto", "llama3"):
        stripped = text.strip()
        if stripped.startswith("{") and stripped.endswith("}"):
            call = _parse_one(stripped)
            if call is not None:
                return None, [call]
    return text, []


def call_spans(text: str, parser: str = "auto") -> list[tuple[int, int]]:
    """[start, end) RAW-text spans of recognized tool-call blocks — the
    regions parse_tool_calls removes from content.  Streaming uses these
    to emit leftover content in raw coordinates (parse_tool_calls returns
    STRIPPED content, whose offsets do not line up with what was already
    streamed)."""
    spans: list[tuple[int, int]] = []
    if parser in ("auto", "hermes") and TOOL_OPEN in text:
        pos = 0
        while True:
            i = text.find(TOOL_OPEN, pos)
            if i < 0:
                break
            j = text.find(TOOL_CLOSE, i)
            end = (j + len(TOOL_CLOSE)) if j >= 0 else len(text)
            body = text[i + len(TOOL_OPEN): j if j >= 0 else len(text)]
            if _parse_one(body) is not None:
                spans.append((i, end))
            if j < 0:
                break
            pos = end
        if spans:
            return spans
    if parser in ("auto", "llama3"):
        stripped = text.strip()
        if (stripped.startswith("{") and stripped.endswith("}")
                and _parse_one(stripped) is not None):
            return [(0, len(text))]
    return spans


def _parse_one(body: str) -> dict | None:
    try:
        obj = json.loads(body.strip())
    except json.JSONDecodeError:
        return None
    if not isinstance(obj, dict) or not isinstance(obj.get("name"), str):
        return None
    args = obj.get("arguments", obj.get("parameters", {}))
    if not isinstance(args, (dict, list, str, int, float, bool)):
        return None
    return {
        "id": f"call_{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {
            "name": obj["name"],
            # OpenAI wire format: arguments is a JSON STRING.
            "arguments": (args if isinstance(args, str)
                          else json.dumps(args, ensure_ascii=False)),
        },
    }
