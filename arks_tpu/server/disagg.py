"""Prefill/decode-disaggregated serving servers.

The reference delegates PD separation to SGLang (it only generates
``--disaggregation-mode prefill|decode`` command lines and a router
deployment — /root/reference/internal/controller/
arksdisaggregatedapplication_controller.go:1630-1724).  Here both sides are
native:

- **PrefillServer**: tokenizes the OpenAI request, runs detached prefill
  (compute-bound, MXU-heavy), returns the first token + KV in the
  ``kv_transfer`` wire format.
- **DecodeServer**: an OpenAIServer that additionally accepts
  ``POST /v1/disagg/*``: it *pulls* the KV from the prefill server named in
  the ``X-Arks-Prefill-Addr`` header, inserts it into its own continuous
  batch, and streams the completion.  Pull-based transfer means the KV moves
  prefill→decode directly (one hop), with the router only coordinating —
  the same topology SGLang's disaggregation uses.

Sampling-key continuity: the prefill side samples the first token from
PRNGKey(seed); the decode side reconstructs fold_in(PRNGKey(seed), 1), so a
disaggregated run is bit-identical to a single-engine run with that seed.
"""

from __future__ import annotations

import http.client
import json
import logging
import uuid

from arks_tpu.engine import kv_transfer
from arks_tpu.engine.engine import InferenceEngine
from arks_tpu.engine.types import PrefilledState, Request
from arks_tpu.server.openai_server import (
    OpenAIServer, _sampling_from_body,
)

log = logging.getLogger("arks_tpu.disagg")

PREFILL_PATH = "/v1/prefill"
HDR_PREFILL_ADDR = "X-Arks-Prefill-Addr"


class PrefillServer(OpenAIServer):
    """Serves POST /v1/prefill; the engine never starts its decode loop.

    Inherits the OpenAI server's plumbing (health/metrics/models) but
    replaces completions with the prefill API.  Regular completion endpoints
    answer 501 to catch misrouted traffic loudly.
    """

    def _handle_completion(self, h, body: dict, chat: bool) -> None:
        h._error(501, "this is a prefill-only server; use /v1/prefill")

    def handle_post(self, h, body: dict, path: str) -> bool:
        if path != PREFILL_PATH:
            return False
        chat = bool(body.get("_chat", False))
        try:
            batch = self._prompt_ids_batch(body, chat)
        except ValueError as e:
            h._error(400, str(e))
            return True
        if len(batch) > 1:
            h._error(400, "disaggregated serving takes one prompt per request")
            return True
        try:
            params, _ = _sampling_from_body(body, self.engine.tokenizer,
                                            self.engine)
        except ValueError as e:
            h._error(400, str(e))
            return True
        if (body.get("n") or 1) != 1:
            h._error(400, "disaggregated serving does not support n > 1")
            return True
        from arks_tpu.engine.engine import ContextLengthExceededError
        from arks_tpu.engine.guides import GuideError
        try:
            pf = self.engine.prefill_detached(batch[0], params)
        except ContextLengthExceededError as e:
            h._json(400, {"error": {"message": str(e),
                                    "type": "invalid_request_error",
                                    "code": "context_length_exceeded"}})
            return True
        except GuideError as e:
            # Guide compile failure on the prefill tier (budget exhausted
            # with every guide pinned, etc.) — a request fault, not a 500.
            h._error(400, str(e))
            return True
        meta = {"first_token": pf.first_token, "num_prompt": pf.num_prompt,
                "seed": pf.seed}
        if pf.prompt_ids:
            # The decode side keys the transferred KV by chain digest
            # (device prefix index + host spill tier) — digests need the
            # prompt ids, which only this side has.
            meta["prompt_ids"] = [int(t) for t in pf.prompt_ids]
        if pf.guide_row:
            # Guided decoding: the post-first-token DFA state, relative to
            # the guide's start row (the decode side rebases onto its own
            # guide table).
            meta["guide_row"] = pf.guide_row
        if pf.first_lp is not None:
            # First-token logprob data rides the JSON meta (floats + ids);
            # the decode side serves the rest of the logprob stream itself.
            meta["first_lp"] = pf.first_lp
        payload = kv_transfer.pack(meta, [pf.k, pf.v])
        h.send_response(200)
        h.send_header("Content-Type", "application/octet-stream")
        h.send_header("Content-Length", str(len(payload)))
        h.end_headers()
        h.wfile.write(payload)
        return True


class DecodeServer(OpenAIServer):
    """OpenAIServer + /v1/disagg/* routes for router-coordinated requests."""

    def handle_post(self, h, body: dict, path: str) -> bool:
        if path == "/v1/disagg/chat/completions":
            self._handle_disagg(h, body, chat=True)
            return True
        if path == "/v1/disagg/completions":
            self._handle_disagg(h, body, chat=False)
            return True
        return False

    def _handle_disagg(self, h, body: dict, chat: bool) -> None:
        prefill_addr = h.headers.get(HDR_PREFILL_ADDR, "")
        if not prefill_addr:
            return h._error(400, f"missing {HDR_PREFILL_ADDR} header")
        model = body.get("model") or self.served_model_name
        if model != self.served_model_name:
            return h._error(404, f"model {model!r} not found")

        from arks_tpu.engine.engine import ContextLengthExceededError
        try:
            meta, (k, v) = self._pull_kv(prefill_addr, body, chat)
        except ContextLengthExceededError as e:
            # Client input error, not a backend fault: a 502 here would make
            # routers/gateways retry an unservable request.
            return h._json(400, {"error": {"message": str(e),
                                           "type": "invalid_request_error",
                                           "code": "context_length_exceeded"}})
        except Exception as e:
            log.warning("prefill pull from %s failed", prefill_addr,
                        exc_info=True)
            return h._error(502, f"prefill pull failed: {e}")

        try:
            params, stop_strings = _sampling_from_body(
                body, self.engine.tokenizer, self.engine)
        except ValueError as e:
            return h._error(400, str(e))
        if (body.get("n") or 1) != 1:
            return h._error(400,
                            "disaggregated serving does not support n > 1")
        if body.get("echo"):
            return h._error(400,
                            "disaggregated serving does not support echo")
        # JSON round-trips the logprob entry as nested lists; restore the
        # engine's (chosen, [(id, lp), ...]) tuple shape.
        first_lp = meta.get("first_lp")
        if first_lp is not None:
            first_lp = (float(first_lp[0]),
                        [(int(i), float(lp)) for i, lp in first_lp[1]])
        req = Request(
            request_id=f"req-{uuid.uuid4().hex[:16]}",
            prompt_ids=[], params=params,
            prefilled=PrefilledState(
                first_token=int(meta["first_token"]),
                num_prompt=int(meta["num_prompt"]),
                seed=int(meta["seed"]), k=k, v=v, first_lp=first_lp,
                guide_row=int(meta.get("guide_row", 0)),
                prompt_ids=[int(t)
                            for t in meta.get("prompt_ids") or []]))
        self.engine.add_request(req)
        self._respond(h, req, chat, model, body, stop_strings)

    def _pull_kv(self, addr: str, body: dict, chat: bool):
        host, _, port = addr.partition(":")
        conn = http.client.HTTPConnection(host, int(port or 80), timeout=300)
        try:
            payload = dict(body)
            payload["_chat"] = chat
            conn.request("POST", PREFILL_PATH, body=json.dumps(payload).encode(),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                if resp.status == 400:
                    try:
                        err = json.loads(data).get("error") or {}
                    except (ValueError, json.JSONDecodeError):
                        err = {}
                    if err.get("code") == "context_length_exceeded":
                        from arks_tpu.engine.engine import ContextLengthExceededError
                        raise ContextLengthExceededError(
                            err.get("message") or "context length exceeded")
                raise RuntimeError(f"prefill {addr} -> {resp.status}: "
                                   f"{data[:200]!r}")
            return kv_transfer.unpack(data)
        finally:
            conn.close()
