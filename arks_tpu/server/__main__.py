"""Serving-pod entrypoint: ``python -m arks_tpu.server [flags]``.

This is the TPU-native runtime command the workload controller generates —
the analogue of the vLLM/SGLang command lines the reference operator writes
(/root/reference/internal/controller/arksapplication_controller.go:941-1014).

Multi-host rendezvous contract (the LWS env-var contract translated to JAX
distributed init — reference controller :560-569):
  ARKS_COORDINATOR_ADDRESS  leader pod address ("host:port")
  ARKS_PROCESS_ID           worker index (0 = leader)
  ARKS_NUM_PROCESSES        gang size
When set, jax.distributed.initialize() is called before anything touches the
backend; collectives then run over ICI within a slice and DCN across slices.
"""

from __future__ import annotations

import argparse
import logging
import os

from arks_tpu.utils import knobs

log = logging.getLogger("arks_tpu.server")


def main() -> None:
    p = argparse.ArgumentParser("arks_tpu.server")
    p.add_argument("--model", required=True, help="model config name (arks_tpu.models) "
                   "or path to a model dir with config.json")
    p.add_argument("--model-path", default=None, help="weights/tokenizer dir (optional; "
                   "random init without it)")
    p.add_argument("--served-model-name", default=None)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--tensor-parallel-size", "--tp", type=int, default=None, dest="tp")
    p.add_argument("--data-parallel-size", "--dp", type=int, default=1, dest="dp")
    p.add_argument("--context-parallel-size", "--cp", type=int, default=1,
                   dest="cp",
                   help="shard prefill T over a 'seq' mesh axis with ring "
                        "attention (long-context prefill; best on the "
                        "disaggregated prefill tier — decode replicates "
                        "across this axis)")
    p.add_argument("--pipeline-parallel-size", "--pp", type=int, default=1,
                   dest="pp",
                   help="shard layers (and their KV) over a 'stage' mesh "
                        "axis with a microbatched decode pipeline — HBM "
                        "capacity scaling for models beyond one chip; "
                        "exclusive with tp/dp/cp in one engine")
    p.add_argument("--num-slices", type=int, default=None,
                   help="multi-slice serving: an outermost 'slice' mesh "
                        "axis spanning ICI slices joined over DCN (v5p "
                        "multi-slice) — batch/dp shards across slices, tp "
                        "psums stay slice-local (parallel.mesh."
                        "make_multislice_mesh)")
    p.add_argument("--num-slots", type=int, default=8)
    p.add_argument("--max-model-len", type=int, default=1024)
    p.add_argument("--steps-per-dispatch", type=int, default=4)
    p.add_argument("--dtype", default=None)
    p.add_argument("--kv-cache-dtype", default="auto",
                   choices=("auto", "bf16", "int8", "int4"),
                   help="int8 halves KV HBM traffic and doubles cache capacity; "
                        "int4 packs token pairs per byte (paged layout only, "
                        "dequant fused on the page stream)")
    p.add_argument("--weight-dtype", default="bf16",
                   choices=("bf16", "int8", "int4"),
                   help="weight-only quantization: int8 (w8a16, per-channel "
                        "scales) fits 7B-class models on one 16GB chip and "
                        "halves decode weight reads; int4 (w4a16, groupwise "
                        "scales) halves them again — 13B-class single-chip, "
                        "or more HBM left for KV pages")
    p.add_argument("--kv-layout", default="auto",
                   choices=("auto", "slot", "paged"),
                   help="device KV layout: paged = block-table pool with "
                        "on-device prefix sharing (TPU default); slot = "
                        "contiguous per-slot cache (dp)")
    p.add_argument("--prefix-cache-mb", type=int, default=256,
                   help="host-RAM budget for prefix KV reuse (0 disables)")
    p.add_argument("--draft-model", default=None,
                   help="speculative decoding: draft model config name or "
                        "dir (must share the target tokenizer); greedy "
                        "requests emit identical tokens, several per "
                        "dispatch")
    p.add_argument("--draft-model-path", default=None,
                   help="draft weights dir (random init without it)")
    p.add_argument("--draft-len", type=int, default=4,
                   help="tokens per speculative dispatch (draft proposes "
                        "draft-len - 1, target verifies all in one pass)")
    p.add_argument("--extra-model", action="append", default=None,
                   metavar="NAME[=PATH]",
                   help="register an additional model with the shared "
                        "weight pool (repeatable): NAME is a config name "
                        "or a model dir, =PATH an optional weights dir. "
                        "Requests route by their 'model' field; the engine "
                        "streams the weights in and switches at drained "
                        "boundaries (single-host only)")
    p.add_argument("--model-pool-hbm-mb", type=int, default=None,
                   help="HBM budget for pooled model weights in MiB "
                        "(ARKS_MODEL_POOL_HBM_MB; 0/unset = unlimited). "
                        "LRU-evicts idle unpinned models; the primary and "
                        "draft are pinned")
    p.add_argument("--drain-timeout", type=float,
                   default=knobs.get_float("ARKS_DRAIN_TIMEOUT"),
                   help="SIGTERM grace: finish in-flight requests up to "
                        "this many seconds before exiting (rolling updates "
                        "become request-lossless when it covers the longest "
                        "request; launchers set the ARKS_DRAIN_TIMEOUT env "
                        "default to fit their own kill escalation windows)")
    p.add_argument("--dispatch-deadline", type=float, default=None,
                   help="watchdog deadline in seconds for a wedged device "
                        "dispatch: past it the engine flips readiness, "
                        "dumps in-flight diagnostics, and exits 70 so the "
                        "pod restarts (sets ARKS_DISPATCH_DEADLINE_S; "
                        "0/unset disables; must exceed the worst in-step "
                        "jit compile — see docs/runbook.md)")
    p.add_argument("--fault-retries", type=int, default=None,
                   help="per-request fault retry budget before a culprit "
                        "request fails alone with an engine_fault 500 "
                        "(sets ARKS_FAULT_RETRIES; default 1)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default=None, help="force a jax platform (cpu for tests)")
    p.add_argument("--disaggregation-mode", choices=("prefill", "decode"),
                   default=None, dest="disagg",
                   help="PD-separated serving role (reference flag parity: "
                        "arksdisaggregatedapplication_controller.go:1672-1724)")
    args = p.parse_args()

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    # Fault-tolerance knobs travel by env (the engine and its watchdog
    # read them at start); explicit flags win over inherited env.
    if args.dispatch_deadline is not None:
        knobs.push("ARKS_DISPATCH_DEADLINE_S", str(args.dispatch_deadline))
    if args.fault_retries is not None:
        knobs.push("ARKS_FAULT_RETRIES", str(args.fault_retries))

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    coord = knobs.get_str("ARKS_COORDINATOR_ADDRESS")
    if coord:
        pid = knobs.get_int("ARKS_PROCESS_ID")
        nproc = knobs.get_int("ARKS_NUM_PROCESSES")
        log.info("multi-host init: coordinator=%s process=%d/%d", coord, pid, nproc)
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid)

    from arks_tpu.engine.engine import EngineConfig, InferenceEngine
    from arks_tpu.engine.tokenizer import load_tokenizer
    from arks_tpu.models import get_config
    from arks_tpu.models.config import ModelConfig
    from arks_tpu.server.openai_server import OpenAIServer

    if os.path.isdir(args.model):
        cfg = ModelConfig.from_hf_config(args.model, name=os.path.basename(args.model))
        model_path = args.model_path or args.model
    else:
        cfg = get_config(args.model)
        model_path = args.model_path

    n_dev = len(jax.devices())
    # The k8s renderer passes the slice count by env (ARKS_NUM_SLICES);
    # an explicit --num-slices flag wins — including an explicit 1 (the
    # unset default is None, so forcing single-slice in a multi-slice pod
    # is expressible).
    if args.num_slices is None:
        args.num_slices = knobs.get_int("ARKS_NUM_SLICES")
    if (args.dp < 1 or args.cp < 1 or args.pp < 1 or args.num_slices < 1
            or (args.tp is not None and args.tp < 1)):
        raise SystemExit("parallel-size flags must be >= 1")
    if args.pp > 1:
        tp = args.tp or 1  # pp is exclusive with tp; don't auto-fill tp
    else:
        tp = args.tp or max(
            n_dev // (args.dp * args.cp * args.num_slices), 1)
    want = tp * args.dp * args.cp * args.pp * args.num_slices
    if want > n_dev:
        raise SystemExit(
            f"requested tp={tp} x dp={args.dp} x cp={args.cp} "
            f"x pp={args.pp} needs {want} devices but only "
            f"{n_dev} are visible")
    nproc = knobs.get_int("ARKS_NUM_PROCESSES")
    mesh = None
    if want > 1:
        from arks_tpu.parallel.mesh import make_mesh
        if nproc > 1:
            # Multi-host: the mesh MUST span processes with equal local
            # device counts, or some processes own no shard and every
            # cross-process collective deadlocks.  Take want/nproc devices
            # from each process (jax.devices()[:want] would grab them all
            # from process 0 when a host exposes extras).
            if want % nproc:
                raise SystemExit(
                    f"tp*dp*cp={want} must be divisible by the gang size {nproc}")
            per = want // nproc
            taken: dict[int, int] = {}
            devices = []
            for d in jax.devices():
                if taken.get(d.process_index, 0) < per:
                    taken[d.process_index] = taken.get(d.process_index, 0) + 1
                    devices.append(d)
            if len(devices) < want:
                raise SystemExit(
                    f"gang of {nproc} processes exposes only {len(devices)} "
                    f"usable devices, need {want}")
        else:
            # Use exactly the devices the plan asks for; a host may expose
            # more (e.g. a forced multi-device CPU platform) than the spec
            # wants.
            devices = jax.devices()[:want]
        if args.num_slices > 1:
            from arks_tpu.parallel.mesh import make_multislice_mesh
            mesh = make_multislice_mesh(
                args.num_slices, tensor_parallel=tp, data_parallel=args.dp,
                context_parallel=args.cp, pipeline_parallel=args.pp,
                devices=devices)
        else:
            mesh = make_mesh(tensor_parallel=tp, data_parallel=args.dp,
                             context_parallel=args.cp,
                             pipeline_parallel=args.pp, devices=devices)

    params = None
    if model_path:
        from arks_tpu.models.weights import load_params
        params = load_params(cfg, model_path, mesh=mesh, dtype=args.dtype,
                             weight_dtype=args.weight_dtype)

    ecfg = EngineConfig(
        model=cfg.name, num_slots=args.num_slots, max_cache_len=args.max_model_len,
        prefill_buckets=tuple(b for b in (32, 64, 128, 256, 512, 1024, 2048, 4096)
                              if b <= args.max_model_len),
        steps_per_dispatch=args.steps_per_dispatch,
        tensor_parallel=args.tp, data_parallel=args.dp,
        context_parallel=args.cp, pipeline_parallel=args.pp,
        dtype=args.dtype, kv_cache_dtype=args.kv_cache_dtype,
        weight_dtype=args.weight_dtype, seed=args.seed,
        prefix_cache_mb=args.prefix_cache_mb,
        kv_layout=args.kv_layout,
        draft_model=args.draft_model, draft_len=args.draft_len,
    )
    # Shared weight pool: created whenever anything multi-model is in play
    # (extra models, an explicit budget, or a draft — the draft is served
    # FROM the pool rather than a second standalone load_params, so its
    # residency shows in /v1/models and counts against the budget).
    pool = None
    if args.extra_model or args.model_pool_hbm_mb is not None or args.draft_model:
        from arks_tpu.engine.model_pool import ModelPool
        pool = ModelPool(hbm_budget_mb=args.model_pool_hbm_mb)

    draft_cfg = draft_params = None
    if args.draft_model:
        if os.path.isdir(args.draft_model):
            draft_cfg = ModelConfig.from_hf_config(
                args.draft_model, name=os.path.basename(args.draft_model))
            # A weights DIR as --draft-model loads from that dir, mirroring
            # --model's behavior (random-initializing silently would make
            # the draft useless — ~0 acceptance — with no error).
            draft_path = args.draft_model_path or args.draft_model
        else:
            draft_cfg = get_config(args.draft_model)
            draft_path = args.draft_model_path
        if draft_path:
            from arks_tpu.models.weights import load_params_streaming

            def _draft_loader(dc=draft_cfg, dp=draft_path):
                return load_params_streaming(dc, dp, mesh=mesh,
                                             dtype=args.dtype)

            pool.register(draft_cfg.name, draft_cfg, model_path=draft_path,
                          loader=_draft_loader, pinned=True)
            draft_params = pool.load(draft_cfg.name)
    # Real weights without tokenizer assets = broken mount; fail fast then.
    from arks_tpu.models.weights import has_real_weights
    tokenizer = load_tokenizer(
        model_path if model_path and os.path.isdir(model_path) else None,
        strict=has_real_weights(model_path))
    engine = InferenceEngine(cfg, ecfg, tokenizer, params=params, mesh=mesh,
                             draft_params=draft_params, draft_cfg=draft_cfg,
                             pool=pool)

    served = args.served_model_name or cfg.name

    # Multi-host gang: process 0 serves HTTP and broadcasts every device
    # dispatch; the other processes mirror them so the gang's collectives
    # stay in lockstep (arks_tpu.engine.multihost).
    if coord and nproc > 1:
        import signal as _signal

        from arks_tpu.engine.multihost import (
            DispatchFollower, DispatchLeader, dispatch_address)
        dhost, dport = dispatch_address(coord)
        pid = knobs.get_int("ARKS_PROCESS_ID")
        if pid != 0:
            # The gang driver SIGTERMs every member at once; a follower
            # dying instantly would strand the leader's drain mid-
            # collective.  Followers ignore SIGTERM and exit when the
            # leader (who coordinates the drain) closes the channel.
            _signal.signal(_signal.SIGTERM, _signal.SIG_IGN)
            log.info("follower %d/%d: mirroring leader dispatches", pid, nproc)
            DispatchFollower(engine, dhost, dport).run()
            return
        engine.dispatcher = DispatchLeader("0.0.0.0", dport, nproc - 1)

    # Extra pool models (after the multihost wiring so the single-host-only
    # check in register_model sees the dispatcher).
    for spec in args.extra_model or []:
        name, _, path = spec.partition("=")
        if os.path.isdir(name):
            engine.register_model(
                ModelConfig.from_hf_config(name, name=os.path.basename(name)),
                model_path=path or name)
        else:
            engine.register_model(name, model_path=path or None)

    if args.disagg == "prefill":
        from arks_tpu.server.disagg import PrefillServer
        # No decode loop: the engine only runs detached prefills.
        server = PrefillServer(engine, served, host=args.host, port=args.port)
    elif args.disagg == "decode":
        from arks_tpu.server.disagg import DecodeServer
        engine.start()
        server = DecodeServer(engine, served, host=args.host, port=args.port)
    else:
        engine.start()
        server = OpenAIServer(engine, served, host=args.host, port=args.port)
    # Graceful drain: SIGTERM (rolling update, scale-down, kubelet stop)
    # flips readiness off, 503s new work, and lets in-flight requests
    # finish before serve_forever returns.
    import signal
    import threading

    def _on_term(signum, frame):
        log.info("SIGTERM: draining in-flight requests (up to %.0fs)",
                 args.drain_timeout)
        threading.Thread(target=server.drain, args=(args.drain_timeout,),
                         name="drain", daemon=True).start()

    signal.signal(signal.SIGTERM, _on_term)

    log.info("serving %s on %s:%d (devices=%d, mode=%s)",
             served, args.host, args.port, n_dev, args.disagg or "unified")
    server.start(background=False)
    engine.stop()
    if engine.dispatcher is not None:
        engine.dispatcher.close()  # releases followers (they exit on close)
    log.info("drained; exiting")


if __name__ == "__main__":
    main()
