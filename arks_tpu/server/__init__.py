from arks_tpu.server.openai_server import OpenAIServer

__all__ = ["OpenAIServer"]
