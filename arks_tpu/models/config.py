"""Model architecture configs for the arks-tpu serving engine.

The reference framework (scitix/arks) never touches model architecture — it
passes a HuggingFace model directory to vLLM/SGLang containers
(/root/reference/internal/controller/arksapplication_controller.go:941-1014).
Here the engine is ours, so architecture configs are first-class.  Presets
cover the model families named in BASELINE.json (Qwen2.5 at 0.5B/1.5B/7B/72B,
Llama-3-8B) plus a ``tiny`` config for CPU-mesh tests.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = False
    qkv_bias: bool = False  # Qwen2-family uses bias on q/k/v projections.
    max_position_embeddings: int = 32768
    dtype: str = "bfloat16"
    eos_token_ids: tuple[int, ...] = ()
    # Mixture-of-Experts (0 experts = dense FFN).  norm_topk_prob=True is
    # Mixtral semantics (softmax over the selected experts); False is
    # Qwen2-MoE (global softmax, selected probs used as-is).
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    shared_expert_intermediate_size: int = 0
    norm_topk_prob: bool = True
    # Per-model KV-cache dtype preference ("auto"|"bf16"|"int8"|"int4"):
    # consulted when EngineConfig.kv_cache_dtype is left at "auto" — a
    # checkpoint known to tolerate int4 KV can ship that fact with its
    # config instead of every deployment flagging it.  "auto" = no
    # preference (the engine's backend default applies).
    kv_cache_dtype: str = "auto"

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    def num_params(self) -> int:
        """Approximate parameter count (embeddings + blocks + head)."""
        e, f, v = self.hidden_size, self.intermediate_size, self.vocab_size
        attn = e * self.q_dim + 2 * e * self.kv_dim + self.q_dim * e
        if self.qkv_bias:
            attn += self.q_dim + 2 * self.kv_dim
        if self.num_experts:
            mlp = self.num_experts * 3 * e * self.moe_intermediate_size \
                + e * self.num_experts
            if self.shared_expert_intermediate_size:
                mlp += 3 * e * self.shared_expert_intermediate_size + e
        else:
            mlp = 3 * e * f
        norms = 2 * e
        blocks = self.num_layers * (attn + mlp + norms)
        head = 0 if self.tie_word_embeddings else e * v
        return v * e + blocks + e + head

    @staticmethod
    def from_hf_config(path_or_dict: str | dict[str, Any], name: str = "") -> "ModelConfig":
        """Build a config from a HuggingFace ``config.json`` (Qwen2/Llama style)."""
        if isinstance(path_or_dict, str):
            p = path_or_dict
            if os.path.isdir(p):
                p = os.path.join(p, "config.json")
            with open(p) as f:
                d = json.load(f)
        else:
            d = dict(path_or_dict)
        arch = (d.get("architectures") or [""])[0].lower()
        model_type = d.get("model_type", "")
        qkv_bias = "qwen2" in arch or model_type in ("qwen2", "qwen2_moe")
        heads = d["num_attention_heads"]
        eos = d.get("eos_token_id")
        if eos is None:
            eos = ()
        elif isinstance(eos, int):
            eos = (eos,)
        # MoE: HF calls the expert count num_local_experts (Mixtral) or
        # num_experts (Qwen2-MoE).
        num_experts = int(d.get("num_local_experts", d.get("num_experts", 0)) or 0)
        is_mixtral = "mixtral" in arch or model_type == "mixtral"
        return ModelConfig(
            name=name or model_type or "hf-model",
            vocab_size=d["vocab_size"],
            hidden_size=d["hidden_size"],
            intermediate_size=d["intermediate_size"],
            num_layers=d["num_hidden_layers"],
            num_heads=heads,
            num_kv_heads=d.get("num_key_value_heads", heads),
            head_dim=d.get("head_dim", d["hidden_size"] // heads),
            rope_theta=float(d.get("rope_theta", 10000.0)),
            rms_norm_eps=float(d.get("rms_norm_eps", 1e-6)),
            tie_word_embeddings=bool(d.get("tie_word_embeddings", False)),
            qkv_bias=qkv_bias,
            max_position_embeddings=int(d.get("max_position_embeddings", 32768)),
            eos_token_ids=tuple(eos),
            num_experts=num_experts,
            num_experts_per_tok=int(d.get("num_experts_per_tok", 0) or 0),
            moe_intermediate_size=int(
                d.get("moe_intermediate_size",
                      d["intermediate_size"] if num_experts else 0) or 0),
            shared_expert_intermediate_size=int(
                d.get("shared_expert_intermediate_size", 0) or 0),
            norm_topk_prob=bool(d.get("norm_topk_prob", is_mixtral)),
            kv_cache_dtype=str(d.get("kv_cache_dtype", "auto")),
        )


_REGISTRY: dict[str, ModelConfig] = {}


def register_config(cfg: ModelConfig) -> ModelConfig:
    _REGISTRY[cfg.name.lower()] = cfg
    return cfg


def get_config(name: str) -> ModelConfig:
    key = name.lower()
    if key in _REGISTRY:
        return _REGISTRY[key]
    raise KeyError(f"unknown model config {name!r}; known: {sorted(_REGISTRY)}")


# Tiny config for CPU-mesh tests: dims divisible by 8 so every mesh shape works.
register_config(ModelConfig(
    name="tiny", vocab_size=512, hidden_size=64, intermediate_size=128,
    num_layers=2, num_heads=8, num_kv_heads=8, head_dim=8,
    qkv_bias=True, eos_token_ids=(0,),
))
register_config(ModelConfig(
    name="tiny-gqa", vocab_size=512, hidden_size=64, intermediate_size=128,
    num_layers=2, num_heads=8, num_kv_heads=4, head_dim=8,
    qkv_bias=True, eos_token_ids=(0,),
))

# Qwen2.5 family (HF: Qwen/Qwen2.5-*-Instruct).
register_config(ModelConfig(
    name="qwen2.5-0.5b", vocab_size=151936, hidden_size=896,
    intermediate_size=4864, num_layers=24, num_heads=14, num_kv_heads=2,
    head_dim=64, rope_theta=1000000.0, tie_word_embeddings=True,
    qkv_bias=True, eos_token_ids=(151645, 151643),
))
register_config(ModelConfig(
    name="qwen2.5-1.5b", vocab_size=151936, hidden_size=1536,
    intermediate_size=8960, num_layers=28, num_heads=12, num_kv_heads=2,
    head_dim=128, rope_theta=1000000.0, tie_word_embeddings=True,
    qkv_bias=True, eos_token_ids=(151645, 151643),
))
register_config(ModelConfig(
    name="qwen2.5-7b", vocab_size=152064, hidden_size=3584,
    intermediate_size=18944, num_layers=28, num_heads=28, num_kv_heads=4,
    head_dim=128, rope_theta=1000000.0, qkv_bias=True,
    eos_token_ids=(151645, 151643),
))
register_config(ModelConfig(
    name="qwen2.5-72b", vocab_size=152064, hidden_size=8192,
    intermediate_size=29568, num_layers=80, num_heads=64, num_kv_heads=8,
    head_dim=128, rope_theta=1000000.0, qkv_bias=True,
    eos_token_ids=(151645, 151643),
))

# MoE tiny configs for CPU-mesh tests (dims divisible by 8).
register_config(ModelConfig(
    name="tiny-moe", vocab_size=512, hidden_size=64, intermediate_size=128,
    num_layers=2, num_heads=8, num_kv_heads=4, head_dim=8, qkv_bias=True,
    num_experts=8, num_experts_per_tok=2, moe_intermediate_size=96,
    shared_expert_intermediate_size=64, norm_topk_prob=False,
    eos_token_ids=(0,),
))
register_config(ModelConfig(
    name="tiny-mixtral", vocab_size=512, hidden_size=64, intermediate_size=128,
    num_layers=2, num_heads=8, num_kv_heads=4, head_dim=8,
    num_experts=4, num_experts_per_tok=2, moe_intermediate_size=96,
    norm_topk_prob=True, eos_token_ids=(0,),
))

# MoE families (HF: mistralai/Mixtral-8x7B-Instruct-v0.1, Qwen/Qwen2-57B-A14B).
register_config(ModelConfig(
    name="mixtral-8x7b", vocab_size=32000, hidden_size=4096,
    intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8,
    head_dim=128, rope_theta=1000000.0, rms_norm_eps=1e-5,
    num_experts=8, num_experts_per_tok=2, moe_intermediate_size=14336,
    norm_topk_prob=True, eos_token_ids=(2,),
))
register_config(ModelConfig(
    name="qwen2-57b-a14b", vocab_size=151936, hidden_size=3584,
    intermediate_size=18944, num_layers=28, num_heads=28, num_kv_heads=4,
    head_dim=128, rope_theta=1000000.0, qkv_bias=True,
    num_experts=64, num_experts_per_tok=8, moe_intermediate_size=2560,
    shared_expert_intermediate_size=20480, norm_topk_prob=False,
    eos_token_ids=(151645, 151643),
))

# Llama-3 family.
register_config(ModelConfig(
    name="llama3-8b", vocab_size=128256, hidden_size=4096,
    intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8,
    head_dim=128, rope_theta=500000.0, rms_norm_eps=1e-5,
    eos_token_ids=(128001, 128009),
))
register_config(ModelConfig(
    name="llama3-70b", vocab_size=128256, hidden_size=8192,
    intermediate_size=28672, num_layers=80, num_heads=64, num_kv_heads=8,
    head_dim=128, rope_theta=500000.0, rms_norm_eps=1e-5,
    eos_token_ids=(128001, 128009),
))
