"""Weight-only int8 quantization (w8a16) for serving.

Why: a 7B-class model in bf16 (~15 GB) does not fit a single v5e chip's
16 GB HBM next to its KV cache — and decode is HBM-bandwidth-bound, so
halving the bytes read per step is also the single biggest decode-throughput
lever.  Weights are stored int8 with per-output-channel float scales;
activations stay bf16.  The dequant is expressed as ``int8 -> bf16 convert
feeding the einsum`` plus a per-channel scale on the OUTPUT, so XLA fuses
the convert into the matmul's operand read and the full-width weight never
materializes in HBM.  MXU FLOPs are unchanged (bf16); only weight bytes
halve.

The reference has no quantization of its own (it forwards dtype flags to
vLLM/SGLang via runtimeCommonArgs, /root/reference/api/v1/
arksapplication_types.go:292); this module is the TPU-native counterpart.

A quantized leaf is a dict ``{"q": int8 array, "s": float32 scale}`` —
pytree-compatible, so sharding/tree-mapping compose without special cases.
Scale layout: matmul weights [.., K, N] carry s = [.., 1, N] (per output
channel); the embedding table [V, E] carries s = [V, 1] (per row — the same
orientation serves both the lookup and the tied unembed).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Weights quantized per-output-channel along reduction dim -2 ([.., K, N]).
MATMUL_KEYS = frozenset({
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head",
    "shared_gate_proj", "shared_up", "shared_down",
})
# Router logits feed a softmax over experts — tiny and precision-sensitive,
# so it stays full width, as do norms, biases and the scalar shared gate.
SKIP_KEYS = frozenset({
    "attn_norm", "mlp_norm", "final_norm", "bq", "bk", "bv", "router",
    "shared_gate",
})


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def quantize_tensor(w: jnp.ndarray, axis: int = -2) -> dict:
    """Symmetric int8 quantization with a shared scale along ``axis``."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def qeinsum(eq: str, x: jnp.ndarray, w) -> jnp.ndarray:
    """``jnp.einsum`` where ``w`` may be a quantized leaf.

    The convert int8->x.dtype fuses into the dot's operand read; the
    per-output-channel scale applies to the OUTPUT (valid because the scale
    is constant along the contraction dim), broadcasting over trailing dims.
    """
    if not is_quantized(w):
        return jnp.einsum(eq, x, w)
    y = jnp.einsum(eq, x, w["q"].astype(x.dtype))
    return y * jnp.squeeze(w["s"], axis=-2).astype(y.dtype)


def dequantize(w, dtype: jnp.dtype) -> jnp.ndarray:
    """Materialize the full-width weight (grouped-MoE ragged_dot path only —
    everywhere else use qeinsum so the dequant stays fused)."""
    if not is_quantized(w):
        return w
    return (w["q"].astype(dtype) * w["s"].astype(dtype))


def embed_lookup(embed, tokens: jnp.ndarray, dtype: jnp.dtype) -> jnp.ndarray:
    """Row gather from a possibly-quantized [V, E] table — gathers int8 rows
    and their scales, never the dequantized table."""
    if not is_quantized(embed):
        return jnp.take(embed, tokens, axis=0)
    rows = jnp.take(embed["q"], tokens, axis=0).astype(dtype)
    scales = jnp.take(embed["s"], tokens, axis=0).astype(dtype)
    return rows * scales


def unembed_logits(h: jnp.ndarray, table, tied: bool) -> jnp.ndarray:
    """[B, E] @ unembed table -> [B, V] float32, scale applied post-dot."""
    if not is_quantized(table):
        t = table.T if tied else table
        return jnp.einsum("be,ev->bv", h, t).astype(jnp.float32)
    if tied:  # table [V, E], s [V, 1]
        logits = jnp.einsum("be,ve->bv", h, table["q"].astype(h.dtype))
        return logits.astype(jnp.float32) * jnp.squeeze(table["s"], -1)
    # lm_head [E, V], s [1, V]
    logits = jnp.einsum("be,ev->bv", h, table["q"].astype(h.dtype))
    return logits.astype(jnp.float32) * jnp.squeeze(table["s"], -2)


def quantize_params(params: dict) -> dict:
    """Quantize an already-materialized transformer Params tree.

    NOTE: the caller's full-width tree stays alive while this runs, so peak
    device memory is full tree + int8 tree.  Fine for small models and
    trees already sharded across a mesh; for HBM-limited single-chip loads
    use the bounded-peak paths instead — init_params_quantized (random
    init) or weights.params_from_hf(weight_dtype='int8') (checkpoints),
    both of which quantize leaf-by-leaf as leaves are created.
    """
    out: dict = {}
    for name, leaf in params.items():
        if isinstance(leaf, dict):
            out[name] = quantize_params(leaf)
        elif name == "embed":
            out[name] = quantize_tensor(leaf, axis=-1)
        elif name in MATMUL_KEYS:
            out[name] = quantize_tensor(leaf, axis=-2)
        else:
            assert name in SKIP_KEYS, (
                f"param leaf {name!r} is in neither MATMUL_KEYS nor "
                "SKIP_KEYS — classify it so quantization coverage can't "
                "silently drift")
            out[name] = leaf
    return out


def init_params_quantized(cfg, key, dtype=jnp.bfloat16) -> dict:
    """Random-init a transformer Params tree directly in quantized form.

    Mirrors transformer.init_params' distributions (normal*0.02 weights,
    ones norms, zeros biases) but generates + quantizes each leaf inside its
    own jit, so peak device memory is the int8 tree plus ONE full-width leaf
    — a bf16 init of a 7B model (~15 GB) would not even fit the chip that
    the quantized model is for.  Used by bench.py and anywhere random
    weights of an HBM-limited model are needed.
    """
    import functools

    from arks_tpu.models import transformer as tf

    shapes = jax.eval_shape(
        functools.partial(tf.init_params, cfg, dtype=dtype), key)

    @functools.partial(jax.jit, static_argnames=("shape", "kind", "axis"))
    def gen(k, shape, kind, axis):
        if kind == "ones":
            return jnp.ones(shape, dtype)
        if kind == "zeros":
            return jnp.zeros(shape, dtype)
        w = jax.random.normal(k, shape, jnp.float32) * 0.02
        if kind == "quant":
            return quantize_tensor(w.astype(dtype), axis=axis)
        return w.astype(dtype)

    counter = [0]

    def build(subtree):
        out = {}
        for name, leaf in subtree.items():
            if isinstance(leaf, dict):
                out[name] = build(leaf)
                continue
            counter[0] += 1
            sub = jax.random.fold_in(key, counter[0])
            if name in ("attn_norm", "mlp_norm", "final_norm"):
                kind, axis = "ones", 0
            elif name in ("bq", "bk", "bv"):
                kind, axis = "zeros", 0
            elif name == "embed":
                kind, axis = "quant", -1
            elif name in MATMUL_KEYS:
                kind, axis = "quant", -2
            else:
                kind, axis = "full", 0
            out[name] = gen(sub, tuple(leaf.shape), kind, axis)
        return out

    return build(shapes)


def quantize_pspecs(specs: dict) -> dict:
    """PartitionSpec tree matching quantize_params' output structure: the
    int8 payload keeps the original spec; the scale keeps the spec with the
    reduced dim's axis dropped (scales are [.., 1, N] there)."""
    from jax.sharding import PartitionSpec as P

    out: dict = {}
    for name, leaf in specs.items():
        if isinstance(leaf, dict):
            out[name] = quantize_pspecs(leaf)
        elif name == "embed":
            out[name] = {"q": leaf, "s": P(leaf[0], None)}
        elif name in MATMUL_KEYS:
            # All matmul specs are full-rank (param_pspecs/moe_pspecs emit
            # one entry per dim), so the scale spec is the weight spec with
            # the contraction dim (always -2) replicated.
            s_entries = list(leaf)
            s_entries[-2] = None
            out[name] = {"q": leaf, "s": P(*s_entries)}
        else:
            out[name] = leaf
    return out
