"""Weight-only quantization for serving: int8 (w8a16) and int4 (w4a16).

Why: a 7B-class model in bf16 (~15 GB) does not fit a single v5e chip's
16 GB HBM next to its KV cache — and decode is HBM-bandwidth-bound, so
shrinking the bytes read per step is also the single biggest
decode-throughput lever.  Activations stay bf16 in both modes; MXU FLOPs
are unchanged.

- **int8**: per-output-channel float scales.  The dequant is expressed as
  ``int8 -> bf16 convert feeding the einsum`` plus a per-channel scale on
  the OUTPUT (valid because the scale is constant along the contraction
  dim), so XLA fuses the convert into the matmul's operand read and the
  full-width weight never materializes in HBM.
- **int4**: per-(128-row group x output channel) scales — per-channel
  int4 loses too much fidelity, groupwise is the standard recipe (GPTQ/
  AWQ-style).  Scales vary ALONG the contraction dim, so the dequant is
  an elementwise producer of the dot's weight operand (int4 -> bf16
  convert * broadcast group scale); XLA fuses elementwise producers into
  the dot read, so HBM still sees ~K*N/2 bytes + K/128*N scale bytes.
  The embedding table stays int8 in int4 mode (row-gathered, small, and
  quality-critical).

The reference has no quantization of its own (it forwards dtype flags to
vLLM/SGLang via runtimeCommonArgs, /root/reference/api/v1/
arksapplication_types.go:292); this module is the TPU-native counterpart.

A quantized leaf is a pytree-compatible dict: int8 = ``{"q": int8,
"s": f32}`` with s = [.., 1, N] for matmul weights [.., K, N] (the
embedding [V, E] carries s = [V, 1]); int4 = ``{"q": int4 [.., K, N],
"gs": f32 [.., K/G, N]}``.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from arks_tpu.utils import knobs

INT4_GROUP = 128


def _int4_group(group: int | None) -> int:
    """Resolve the int4 group size: explicit arg > ARKS_INT4_GROUP env >
    128.  Sharded deployments need the group to divide each shard of the
    contraction dim (e.g. q_dim 3584 at tp=8 -> local K 448 -> group 64);
    the env knob avoids replumbing every load path for that case."""
    if group is not None:
        return group
    return knobs.get_int("ARKS_INT4_GROUP")

# Weights quantized per-output-channel along reduction dim -2 ([.., K, N]).
MATMUL_KEYS = frozenset({
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head",
    "shared_gate_proj", "shared_up", "shared_down",
})
# Router logits feed a softmax over experts — tiny and precision-sensitive,
# so it stays full width, as do norms, biases and the scalar shared gate.
SKIP_KEYS = frozenset({
    "attn_norm", "mlp_norm", "final_norm", "bq", "bk", "bv", "router",
    "shared_gate",
})


def weight_bits(weight_dtype: str) -> int:
    """'bf16' -> 0 (no quantization), 'int8' -> 8, 'int4' -> 4 — the ONE
    mapping every weight_dtype consumer shares."""
    try:
        return {"bf16": 0, "int8": 8, "int4": 4}[weight_dtype]
    except KeyError:
        raise ValueError(f"weight_dtype={weight_dtype!r}") from None


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w and ("s" in w or "gs" in w)


def quantize_tensor(w: jnp.ndarray, axis: int = -2) -> dict:
    """Symmetric int8 quantization with a shared scale along ``axis``."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def quantize_tensor_int4(w: jnp.ndarray, group: int | None = None,
                         shards: int = 1) -> dict:
    """Symmetric int4 quantization of a matmul weight [.., K, N] with one
    scale per (``group`` reduction rows x output channel).

    ``shards``: the mesh's model-axis size.  A row-parallel leaf shards
    its contraction dim K, and group scales shard with it, so the group
    must divide K/shards (whole groups per shard).  The group clamps down
    to the largest divisor that fits — also covers small test-sized
    weights (group <= K).
    """
    w32 = w.astype(jnp.float32)
    k = w32.shape[-2]
    local = max(k // max(shards, 1), 1)
    group = min(_int4_group(group), local)
    while local % group:
        group -= 1
    if k % group:
        raise ValueError(
            f"int4 reduction dim {k} not a multiple of group {group}")
    grp = w32.reshape(*w32.shape[:-2], k // group, group, w32.shape[-1])
    amax = jnp.max(jnp.abs(grp), axis=-2, keepdims=True)  # [.., K/G, 1, N]
    s = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(grp / s), -7, 7).astype(jnp.int4)
    return {"q": q.reshape(w32.shape), "gs": jnp.squeeze(s, -2)}


def _dequant_int4(w, dtype: jnp.dtype) -> jnp.ndarray:
    q, gs = w["q"], w["gs"]
    ngroups = gs.shape[-2]
    g = q.shape[-2] // ngroups
    grp = q.astype(dtype).reshape(*q.shape[:-2], ngroups, g, q.shape[-1])
    return (grp * gs[..., :, None, :].astype(dtype)).reshape(q.shape)


def qeinsum(eq: str, x: jnp.ndarray, w) -> jnp.ndarray:
    """``jnp.einsum`` where ``w`` may be a quantized leaf.

    int8: the convert int8->x.dtype fuses into the dot's operand read; the
    per-output-channel scale applies to the OUTPUT (valid because the scale
    is constant along the contraction dim), broadcasting over trailing dims.
    int4: groupwise scales vary along the contraction dim, so the dequant
    is an elementwise producer of the weight operand (fused by XLA).
    """
    if not is_quantized(w):
        return jnp.einsum(eq, x, w)
    if "gs" in w:
        return jnp.einsum(eq, x, _dequant_int4(w, x.dtype))
    y = jnp.einsum(eq, x, w["q"].astype(x.dtype))
    return y * jnp.squeeze(w["s"], axis=-2).astype(y.dtype)


def dequantize(w, dtype: jnp.dtype) -> jnp.ndarray:
    """Materialize the full-width weight (grouped-MoE ragged_dot path only —
    everywhere else use qeinsum so the dequant stays fused)."""
    if not is_quantized(w):
        return w
    if "gs" in w:
        return _dequant_int4(w, dtype)
    return (w["q"].astype(dtype) * w["s"].astype(dtype))


def embed_lookup(embed, tokens: jnp.ndarray, dtype: jnp.dtype) -> jnp.ndarray:
    """Row gather from a possibly-quantized [V, E] table — gathers int8 rows
    and their scales, never the dequantized table."""
    if not is_quantized(embed):
        return jnp.take(embed, tokens, axis=0)
    rows = jnp.take(embed["q"], tokens, axis=0).astype(dtype)
    scales = jnp.take(embed["s"], tokens, axis=0).astype(dtype)
    return rows * scales


def unembed_logits(h: jnp.ndarray, table, tied: bool) -> jnp.ndarray:
    """[B, E] @ unembed table -> [B, V] float32, scale applied post-dot."""
    if not is_quantized(table):
        t = table.T if tied else table
        return jnp.einsum("be,ev->bv", h, t).astype(jnp.float32)
    if "gs" in table:  # int4 lm_head [E, V] (the embedding stays int8)
        return jnp.einsum("be,ev->bv", h,
                          _dequant_int4(table, h.dtype)).astype(jnp.float32)
    if tied:  # table [V, E], s [V, 1]
        logits = jnp.einsum("be,ve->bv", h, table["q"].astype(h.dtype))
        return logits.astype(jnp.float32) * jnp.squeeze(table["s"], -1)
    # lm_head [E, V], s [1, V]
    logits = jnp.einsum("be,ev->bv", h, table["q"].astype(h.dtype))
    return logits.astype(jnp.float32) * jnp.squeeze(table["s"], -2)


def quantize_params(params: dict, bits: int = 8,
                    group: int | None = None, shards: int = 1) -> dict:
    """Quantize an already-materialized transformer Params tree.

    NOTE: the caller's full-width tree stays alive while this runs, so peak
    device memory is full tree + quantized tree.  Fine for small models and
    trees already sharded across a mesh; for HBM-limited single-chip loads
    use the bounded-peak paths instead — init_params_quantized (random
    init) or weights.params_from_hf(weight_dtype='int8'|'int4')
    (checkpoints), both of which quantize leaf-by-leaf as leaves are
    created.  ``bits=4`` stores matmul weights int4 groupwise; the
    embedding stays int8 either way.
    """
    if bits not in (4, 8):
        raise ValueError(f"bits={bits}")
    out: dict = {}
    for name, leaf in params.items():
        if isinstance(leaf, dict):
            out[name] = quantize_params(leaf, bits, group, shards)
        elif name == "embed":
            out[name] = quantize_tensor(leaf, axis=-1)
        elif name in MATMUL_KEYS:
            out[name] = (quantize_tensor_int4(leaf, group, shards)
                         if bits == 4
                         else quantize_tensor(leaf, axis=-2))
        else:
            assert name in SKIP_KEYS, (
                f"param leaf {name!r} is in neither MATMUL_KEYS nor "
                "SKIP_KEYS — classify it so quantization coverage can't "
                "silently drift")
            out[name] = leaf
    return out


def init_params_quantized(cfg, key, dtype=jnp.bfloat16, bits: int = 8,
                          shards: int = 1) -> dict:
    """Random-init a transformer Params tree directly in quantized form.

    Mirrors transformer.init_params' distributions (normal*0.02 weights,
    ones norms, zeros biases) but generates + quantizes each leaf inside its
    own jit, so peak device memory is the quantized tree plus ONE
    full-width leaf — a bf16 init of a 7B model (~15 GB) would not even fit
    the chip that the quantized model is for.  Used by bench.py and
    anywhere random weights of an HBM-limited model are needed.
    ``bits=4`` = w4a16 (matmul weights int4 groupwise, embedding int8).
    """
    import functools

    from arks_tpu.models import transformer as tf

    if bits not in (4, 8):
        raise ValueError(f"bits={bits}")
    shapes = jax.eval_shape(
        functools.partial(tf.init_params, cfg, dtype=dtype), key)

    @functools.partial(jax.jit, static_argnames=("shape", "kind", "axis"))
    def gen(k, shape, kind, axis):
        if kind == "ones":
            return jnp.ones(shape, dtype)
        if kind == "zeros":
            return jnp.zeros(shape, dtype)
        w = jax.random.normal(k, shape, jnp.float32) * 0.02
        if kind == "quant":
            if bits == 4 and axis == -2:  # matmul weights; embed stays int8
                return quantize_tensor_int4(w.astype(dtype), shards=shards)
            return quantize_tensor(w.astype(dtype), axis=axis)
        return w.astype(dtype)

    counter = [0]

    def build(subtree):
        out = {}
        for name, leaf in subtree.items():
            if isinstance(leaf, dict):
                out[name] = build(leaf)
                continue
            counter[0] += 1
            sub = jax.random.fold_in(key, counter[0])
            if name in ("attn_norm", "mlp_norm", "final_norm"):
                kind, axis = "ones", 0
            elif name in ("bq", "bk", "bv"):
                kind, axis = "zeros", 0
            elif name == "embed":
                kind, axis = "quant", -1
            elif name in MATMUL_KEYS:
                kind, axis = "quant", -2
            else:
                kind, axis = "full", 0
            out[name] = gen(sub, tuple(leaf.shape), kind, axis)
        return out

    return build(shapes)


def quantize_pspecs(specs: dict, bits: int = 8) -> dict:
    """PartitionSpec tree matching quantize_params' output structure: the
    quantized payload keeps the original spec.  int8 scales keep the spec
    with the reduced dim's axis dropped (scales are [.., 1, N] there);
    int4 group scales [.., K/G, N] keep the FULL spec — the group dim
    shards exactly like the contraction dim it tiles (whole groups per
    shard, since shard sizes are multiples of the group)."""
    from jax.sharding import PartitionSpec as P

    out: dict = {}
    for name, leaf in specs.items():
        if isinstance(leaf, dict):
            out[name] = quantize_pspecs(leaf, bits)
        elif name == "embed":
            out[name] = {"q": leaf, "s": P(leaf[0], None)}
        elif name in MATMUL_KEYS:
            if bits == 4:
                out[name] = {"q": leaf, "gs": leaf}
                continue
            # All matmul specs are full-rank (param_pspecs/moe_pspecs emit
            # one entry per dim), so the scale spec is the weight spec with
            # the contraction dim (always -2) replicated.
            s_entries = list(leaf)
            s_entries[-2] = None
            out[name] = {"q": leaf, "s": P(*s_entries)}
        else:
            out[name] = leaf
    return out
