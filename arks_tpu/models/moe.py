"""Mixture-of-Experts FFN (Mixtral / Qwen2-MoE families).

The reference orchestrates MoE models only by passing their names to
vLLM/SGLang containers (no MoE code of its own); here the block is native.

TPU-first formulation:
- **Dense dispatch**: every expert's FFN runs as one batched einsum over the
  expert dim, with unselected experts zeroed by the router-weight tensor.
  Decode is HBM-bound — all expert weights are read once per step no matter
  how many tokens route to them — so compute-all costs nothing extra at
  serving batch sizes while keeping shapes static for XLA.  (A block-sparse
  Pallas dispatch for large-T prefill is a later optimization.)
- **Expert parallelism = model-axis sharding**: expert dims shard over the
  ``model`` mesh axis (each device holds E/tp experts); activations stay
  replicated across that axis between blocks, so XLA turns the final
  expert-contraction into one psum over ICI — the same Megatron pattern the
  dense MLP already uses, no all-to-all needed.
- Router math in float32 (softmax over expert logits is tiny but
  precision-sensitive).

Weight layout per layer (leading [L] from the stacked-layer convention):
  router      [L, E, X]
  w_gate/up   [L, X, E, Fm]     w_down [L, X, Fm, E]
  shared gate/up [L, E, Fs], shared down [L, Fs, E], shared_gate [L, E]
where X = num_experts, Fm = moe_intermediate_size.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Params = dict


def init_moe_params(cfg, key, dtype) -> Params:
    l, e = cfg.num_layers, cfg.hidden_size
    x, fm = cfg.num_experts, cfg.moe_intermediate_size
    keys = iter(jax.random.split(key, 8))

    def w(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    p: Params = {
        "router": w(next(keys), (l, e, x)),
        "w_gate": w(next(keys), (l, x, e, fm)),
        "w_up": w(next(keys), (l, x, e, fm)),
        "w_down": w(next(keys), (l, x, fm, e)),
    }
    if cfg.shared_expert_intermediate_size:
        fs = cfg.shared_expert_intermediate_size
        p["shared_gate_proj"] = w(next(keys), (l, e, fs))
        p["shared_up"] = w(next(keys), (l, e, fs))
        p["shared_down"] = w(next(keys), (l, fs, e))
        p["shared_gate"] = w(next(keys), (l, e))
    return p


def moe_pspecs(cfg, axis_model: str, shard_experts: bool) -> Params:
    """PartitionSpecs matching init_moe_params.  Experts shard over the model
    axis when divisible (expert parallelism); else expert weights replicate
    and only the shared expert uses tensor parallelism."""
    from jax.sharding import PartitionSpec as P
    ex = axis_model if shard_experts else None
    p: Params = {
        "router": P(None, None, None),
        "w_gate": P(None, ex, None, None),
        "w_up": P(None, ex, None, None),
        "w_down": P(None, ex, None, None),
    }
    if cfg.shared_expert_intermediate_size:
        p["shared_gate_proj"] = P(None, None, axis_model)
        p["shared_up"] = P(None, None, axis_model)
        p["shared_down"] = P(None, axis_model, None)
        p["shared_gate"] = P(None, None)
    return p


def shard_experts(cfg, tp: int) -> bool:
    return tp > 1 and cfg.num_experts % tp == 0


def router_topk(logits: jnp.ndarray, cfg) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[.., X] router logits → ([.., k] combine weights, [.., k] expert ids):
    softmax over all experts, top-k selected; renormalized when
    ``norm_topk_prob`` (Mixtral semantics — equal to softmax over the top-k
    logits).  Float32 throughout.  Shared by the dense and grouped dispatch
    paths so routing semantics can never diverge between them."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    vals, idx = jax.lax.top_k(probs, cfg.num_experts_per_tok)
    if cfg.norm_topk_prob:
        vals = vals / (jnp.sum(vals, axis=-1, keepdims=True) + 1e-9)
    return vals, idx


def router_weights(logits: jnp.ndarray, cfg) -> jnp.ndarray:
    """[.., X] router logits → [.., X] combine weights (unselected experts
    zero) — the dense-dispatch form of router_topk."""
    vals, idx = router_topk(logits, cfg)
    onehot = jax.nn.one_hot(idx, cfg.num_experts, dtype=vals.dtype)  # [.., k, X]
    return jnp.einsum("...k,...kx->...x", vals, onehot)


_GROUPED_MIN_TOKENS = 64  # below this, dense dispatch wins on dispatch cost


def moe_ffn_grouped(x: jnp.ndarray, mp: Params, cfg) -> jnp.ndarray:
    """Dropless grouped dispatch: top-k cost instead of all-expert cost.

    Flattens tokens, sorts the (token, slot) pairs by routed expert, runs the
    three expert matmuls as ``jax.lax.ragged_dot`` grouped contractions (one
    MXU pass over exactly T*k rows), and scatter-adds the weighted expert
    outputs back per token.  Numerically equivalent to the dense dispatch —
    no capacity factor, no dropped tokens — at k/X of its FLOPs (8x cheaper
    for a 64-expert top-8 model).  Used for large-T prefill and training on
    an unsharded expert dim; the dense path stays for decode (HBM-bound:
    every expert's weights are read once regardless) and for expert-parallel
    meshes, where the einsum + psum formulation lets XLA shard the expert
    dim (ragged groups can't span devices).
    """
    lead = x.shape[:-1]
    e = x.shape[-1]
    k, nx = cfg.num_experts_per_tok, cfg.num_experts
    x2 = x.reshape(-1, e)
    n = x2.shape[0]

    from arks_tpu.models.quant import dequantize

    logits = jnp.einsum("te,ex->tx", x2, mp["router"])
    vals, idx = router_topk(logits, cfg)                    # [T, k]

    flat_expert = idx.reshape(-1)                           # [T*k]
    order = jnp.argsort(flat_expert)
    token_of = order // k                                   # source token
    xs = jnp.take(x2, token_of, axis=0)                     # [T*k, E] sorted
    group_sizes = jnp.bincount(flat_expert, length=nx)

    from arks_tpu.ops.moe_kernel import grouped_ffn, moe_impl
    if moe_impl() == "pallas":
        # Block-sparse Pallas grouped matmul with the dequant FUSED:
        # int8 per-channel scales fold into the accumulator; int4 group
        # scales dequant the weight tile in-register — either way the
        # full-width expert weights never materialize in HBM (ragged_dot
        # below forces exactly that materialization).
        down = grouped_ffn(xs, jnp.take(flat_expert, order), group_sizes,
                           mp["w_gate"], mp["w_up"], mp["w_down"], x.dtype)
    else:
        # ragged_dot needs plain arrays; dequantized expert weights
        # materialize here (prefill-only path — dense/decode keeps the
        # fused dequant).
        gate = jax.lax.ragged_dot(xs, dequantize(mp["w_gate"], x.dtype),
                                  group_sizes)
        up = jax.lax.ragged_dot(xs, dequantize(mp["w_up"], x.dtype),
                                group_sizes)
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(gate.dtype) * up
        down = jax.lax.ragged_dot(act, dequantize(mp["w_down"], x.dtype),
                                  group_sizes)              # [T*k, E]

    w = jnp.take(vals.reshape(-1), order).astype(down.dtype)   # [T*k]
    out = jnp.zeros((n, e), down.dtype).at[token_of].add(down * w[:, None])

    if cfg.shared_expert_intermediate_size:
        from arks_tpu.models.quant import qeinsum
        sg = qeinsum("te,ef->tf", x2, mp["shared_gate_proj"])
        su = qeinsum("te,ef->tf", x2, mp["shared_up"])
        sact = jax.nn.silu(sg.astype(jnp.float32)).astype(sg.dtype) * su
        shared = qeinsum("tf,fe->te", sact, mp["shared_down"])
        gatev = jax.nn.sigmoid(
            jnp.einsum("te,e->t", x2, mp["shared_gate"]).astype(jnp.float32))
        out = out + shared * gatev[:, None].astype(shared.dtype)
    return out.reshape(*lead, e)


def moe_ffn(x: jnp.ndarray, mp: Params, cfg, constrain=None,
            grouped: bool | None = None) -> jnp.ndarray:
    """MoE feed-forward on [..., E] activations (works for [B, T, E] prefill
    and [B, E] decode).  ``constrain(t, expert_dim_index)`` optionally pins
    the expert dim of intermediates to the model axis.  ``grouped`` forces
    (True) or forbids (False) the dropless grouped path; None = auto (large
    unsharded token batches)."""
    if grouped is None:
        import math
        n_tokens = math.prod(x.shape[:-1])
        # x.ndim >= 3 discriminates prefill/training ([B, T, E]) from decode
        # ([B, E]): decode stays dense regardless of slot count — it is
        # HBM-bound and the sort/gather dispatch only adds overhead there.
        grouped = (constrain is None and x.ndim >= 3
                   and n_tokens >= _GROUPED_MIN_TOKENS)
    if grouped:
        return moe_ffn_grouped(x, mp, cfg)
    from arks_tpu.models.quant import qeinsum

    logits = jnp.einsum("...e,ex->...x", x, mp["router"])
    weights = router_weights(logits, cfg).astype(x.dtype)  # [.., X]

    gate = qeinsum("...e,xef->...xf", x, mp["w_gate"])
    up = qeinsum("...e,xef->...xf", x, mp["w_up"])
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(gate.dtype) * up
    if constrain is not None:
        act = constrain(act, act.ndim - 2)
    down = qeinsum("...xf,xfe->...xe", act, mp["w_down"])  # per-expert out
    out = jnp.einsum("...xe,...x->...e", down, weights)       # psum over EP

    if cfg.shared_expert_intermediate_size:
        sg = qeinsum("...e,ef->...f", x, mp["shared_gate_proj"])
        su = qeinsum("...e,ef->...f", x, mp["shared_up"])
        sact = jax.nn.silu(sg.astype(jnp.float32)).astype(sg.dtype) * su
        if constrain is not None:
            sact = constrain(sact, sact.ndim - 1)
        shared = qeinsum("...f,fe->...e", sact, mp["shared_down"])
        gatev = jax.nn.sigmoid(
            jnp.einsum("...e,e->...", x, mp["shared_gate"]).astype(jnp.float32))
        out = out + shared * gatev[..., None].astype(shared.dtype)
    return out
