from arks_tpu.models.config import ModelConfig, get_config, register_config

__all__ = ["ModelConfig", "get_config", "register_config"]
