"""Weight loading: HF safetensors -> arks params; Orbax sharded checkpoints.

Parity anchor: the reference's ArksModel controller downloads a raw HF
snapshot into a PVC (/root/reference/internal/controller/
arksmodel_controller.go:218-354, scripts/download.py).  The TPU-native twist
(BASELINE.json north star) is a conversion step that writes **Orbax** sharded
checkpoints so every host in a multi-host slice reads only its own shards;
``arks_tpu.control.model`` drives that conversion after download.

Layout conventions: all projection matrices are stored [in, out] (JAX
convention; HF/torch stores [out, in]) and per-layer weights are stacked with
a leading [L] dim for the scan-based forward pass.
"""

from __future__ import annotations

import logging
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from arks_tpu.models.config import ModelConfig
from arks_tpu.models import transformer as tf

log = logging.getLogger("arks_tpu.weights")

ORBAX_SUBDIR = "arks_orbax"


# ---------------------------------------------------------------------------
# HF safetensors -> params
# ---------------------------------------------------------------------------

def _hf_tensors(path: str) -> dict[str, np.ndarray]:
    """Load all tensors from the safetensors shards in ``path``."""
    from safetensors import safe_open

    files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    out: dict[str, np.ndarray] = {}
    for fname in files:
        with safe_open(os.path.join(path, fname), framework="np") as f:
            for key in f.keys():
                out[key] = f.get_tensor(key)
    return out


def params_from_hf(cfg: ModelConfig, path: str, dtype: Any = None) -> tf.Params:
    """Convert a HuggingFace Qwen2/Llama checkpoint directory to arks params."""
    dtype = jnp.dtype(dtype or cfg.dtype)
    t = _hf_tensors(path)
    l = cfg.num_layers

    def get(name: str, transpose: bool = False) -> np.ndarray:
        x = t[name]
        return x.T if transpose else x

    def stack(fmt: str, transpose: bool = False) -> jnp.ndarray:
        return jnp.asarray(
            np.stack([get(fmt.format(i), transpose) for i in range(l)]), dtype)

    layers: tf.Params = {
        "attn_norm": stack("model.layers.{}.input_layernorm.weight"),
        "wq": stack("model.layers.{}.self_attn.q_proj.weight", True),
        "wk": stack("model.layers.{}.self_attn.k_proj.weight", True),
        "wv": stack("model.layers.{}.self_attn.v_proj.weight", True),
        "wo": stack("model.layers.{}.self_attn.o_proj.weight", True),
        "mlp_norm": stack("model.layers.{}.post_attention_layernorm.weight"),
        "w_gate": stack("model.layers.{}.mlp.gate_proj.weight", True),
        "w_up": stack("model.layers.{}.mlp.up_proj.weight", True),
        "w_down": stack("model.layers.{}.mlp.down_proj.weight", True),
    }
    if cfg.qkv_bias:
        layers["bq"] = stack("model.layers.{}.self_attn.q_proj.bias")
        layers["bk"] = stack("model.layers.{}.self_attn.k_proj.bias")
        layers["bv"] = stack("model.layers.{}.self_attn.v_proj.bias")
    params: tf.Params = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dtype),
        "layers": layers,
        "final_norm": jnp.asarray(get("model.norm.weight"), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(get("lm_head.weight", True), dtype)
    return params


# ---------------------------------------------------------------------------
# Orbax sharded checkpoints
# ---------------------------------------------------------------------------

def orbax_path(model_path: str) -> str:
    return os.path.join(model_path, ORBAX_SUBDIR)


def save_orbax(params: tf.Params, model_path: str) -> str:
    import orbax.checkpoint as ocp

    path = orbax_path(model_path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), params, force=True)
    ckptr.wait_until_finished()
    return path


def load_orbax(cfg: ModelConfig, model_path: str, mesh=None,
               dtype: Any = None) -> tf.Params:
    """Load an Orbax checkpoint, sharded directly to the mesh when given —
    each host reads only the shards it owns (multi-host friendly)."""
    import orbax.checkpoint as ocp

    dtype = jnp.dtype(dtype or cfg.dtype)
    path = os.path.abspath(orbax_path(model_path))
    template = jax.eval_shape(
        lambda: tf.init_params(cfg, jax.random.PRNGKey(0), dtype))
    if mesh is not None:
        tp = mesh.shape.get(tf.AXIS_MODEL, 1)
        specs = tf.param_pspecs(cfg, tp)
        template = jax.tree.map(
            lambda s, spec: jax.ShapeDtypeStruct(
                s.shape, s.dtype,
                sharding=jax.sharding.NamedSharding(mesh, spec)),
            template, specs)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path, template)


def convert_hf_to_orbax(cfg: ModelConfig, model_path: str,
                        dtype: Any = None) -> str:
    """One-shot conversion after model download (the ArksModel 'Loading'
    phase extension). Idempotent: skips when the Orbax dir already exists."""
    path = orbax_path(model_path)
    if os.path.isdir(path) and os.listdir(path):
        return path
    params = params_from_hf(cfg, model_path, dtype)
    return save_orbax(params, model_path)


# ---------------------------------------------------------------------------
# Entry point used by the serving pod
# ---------------------------------------------------------------------------

def has_real_weights(model_path: str | None) -> bool:
    """True when ``load_params`` would load actual weights (Orbax or
    safetensors) rather than falling back to random init."""
    if not model_path or not os.path.isdir(model_path):
        return False
    return os.path.isdir(orbax_path(model_path)) or any(
        f.endswith(".safetensors") for f in os.listdir(model_path))


def load_params(cfg: ModelConfig, model_path: str | None, mesh=None,
                dtype: Any = None) -> tf.Params:
    """Best available weights: Orbax (sharded) > safetensors > random init."""
    dtype = jnp.dtype(dtype or cfg.dtype)
    if model_path:
        if os.path.isdir(orbax_path(model_path)):
            log.info("loading Orbax checkpoint from %s", orbax_path(model_path))
            return load_orbax(cfg, model_path, mesh, dtype)
        if os.path.isdir(model_path) and any(
                f.endswith(".safetensors") for f in os.listdir(model_path)):
            log.info("loading HF safetensors from %s", model_path)
            params = params_from_hf(cfg, model_path, dtype)
            if mesh is not None:
                params = tf.shard_params(params, cfg, mesh)
            return params
        log.warning("no weights found under %s; using random init", model_path)
    params = tf.init_params(cfg, jax.random.PRNGKey(0), dtype)
    if mesh is not None:
        params = tf.shard_params(params, cfg, mesh)
    return params
