"""Weight loading: HF safetensors -> arks params; Orbax sharded checkpoints.

Parity anchor: the reference's ArksModel controller downloads a raw HF
snapshot into a PVC (/root/reference/internal/controller/
arksmodel_controller.go:218-354, scripts/download.py).  The TPU-native twist
(BASELINE.json north star) is a conversion step that writes **Orbax** sharded
checkpoints so every host in a multi-host slice reads only its own shards;
``arks_tpu.control.model`` drives that conversion after download.

Layout conventions: all projection matrices are stored [in, out] (JAX
convention; HF/torch stores [out, in]) and per-layer weights are stacked with
a leading [L] dim for the scan-based forward pass.
"""

from __future__ import annotations

import logging
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from arks_tpu.models.config import ModelConfig
from arks_tpu.models import transformer as tf

log = logging.getLogger("arks_tpu.weights")

ORBAX_SUBDIR = "arks_orbax"


# ---------------------------------------------------------------------------
# HF safetensors -> params
# ---------------------------------------------------------------------------

def _hf_tensors(path: str) -> dict[str, np.ndarray]:
    """Load all tensors from the safetensors shards in ``path``."""
    from safetensors import safe_open

    files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    out: dict[str, np.ndarray] = {}
    for fname in files:
        with safe_open(os.path.join(path, fname), framework="np") as f:
            for key in f.keys():
                out[key] = f.get_tensor(key)
    return out


def params_from_hf(cfg: ModelConfig, path: str, dtype: Any = None) -> tf.Params:
    """Convert a HuggingFace Qwen2/Llama checkpoint directory to arks params."""
    dtype = jnp.dtype(dtype or cfg.dtype)
    t = _hf_tensors(path)
    l = cfg.num_layers

    def get(name: str, transpose: bool = False) -> np.ndarray:
        x = t[name]
        return x.T if transpose else x

    def stack(fmt: str, transpose: bool = False) -> jnp.ndarray:
        return _stack_layers(t, l, dtype, fmt, transpose)

    layers: tf.Params = {
        "attn_norm": stack("model.layers.{}.input_layernorm.weight"),
        "wq": stack("model.layers.{}.self_attn.q_proj.weight", True),
        "wk": stack("model.layers.{}.self_attn.k_proj.weight", True),
        "wv": stack("model.layers.{}.self_attn.v_proj.weight", True),
        "wo": stack("model.layers.{}.self_attn.o_proj.weight", True),
        "mlp_norm": stack("model.layers.{}.post_attention_layernorm.weight"),
    }
    if cfg.num_experts:
        layers.update(_moe_from_hf(cfg, t, dtype))
    else:
        layers.update({
            "w_gate": stack("model.layers.{}.mlp.gate_proj.weight", True),
            "w_up": stack("model.layers.{}.mlp.up_proj.weight", True),
            "w_down": stack("model.layers.{}.mlp.down_proj.weight", True),
        })
    if cfg.qkv_bias:
        layers["bq"] = stack("model.layers.{}.self_attn.q_proj.bias")
        layers["bk"] = stack("model.layers.{}.self_attn.k_proj.bias")
        layers["bv"] = stack("model.layers.{}.self_attn.v_proj.bias")
    params: tf.Params = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dtype),
        "layers": layers,
        "final_norm": jnp.asarray(get("model.norm.weight"), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(get("lm_head.weight", True), dtype)
    return params


def _stack_layers(t: dict[str, np.ndarray], l: int, dtype: Any, fmt: str,
                  transpose: bool = False) -> jnp.ndarray:
    """Stack one per-layer tensor family into the leading-[L] convention."""
    xs = [t[fmt.format(i)] for i in range(l)]
    if transpose:
        xs = [x.T for x in xs]
    return jnp.asarray(np.stack(xs), dtype)


def _moe_from_hf(cfg: ModelConfig, t: dict[str, np.ndarray],
                 dtype: Any) -> tf.Params:
    """Expert weights for Mixtral (`block_sparse_moe.experts.{e}.w1/w3/w2`)
    and Qwen2-MoE (`mlp.experts.{e}.gate_proj/up_proj/down_proj` + shared
    expert) checkpoints, stacked [L, X, ..]."""
    l, x = cfg.num_layers, cfg.num_experts
    mixtral = any(".block_sparse_moe." in k for k in t)
    if mixtral:
        base = "model.layers.{}.block_sparse_moe"
        router = base + ".gate.weight"
        gate, up, down = (base + ".experts.{}.w1.weight",
                          base + ".experts.{}.w3.weight",
                          base + ".experts.{}.w2.weight")
    else:
        base = "model.layers.{}.mlp"
        router = base + ".gate.weight"
        gate, up, down = (base + ".experts.{}.gate_proj.weight",
                          base + ".experts.{}.up_proj.weight",
                          base + ".experts.{}.down_proj.weight")

    def estack(fmt: str) -> jnp.ndarray:
        return jnp.asarray(np.stack([
            np.stack([t[fmt.format(i, e)].T for e in range(x)])
            for i in range(l)]), dtype)

    p: tf.Params = {
        "router": _stack_layers(t, l, dtype, router, True),
        "w_gate": estack(gate),
        "w_up": estack(up),
        "w_down": estack(down),
    }
    if cfg.shared_expert_intermediate_size:
        sh = "model.layers.{}.mlp.shared_expert"
        p["shared_gate_proj"] = _stack_layers(t, l, dtype, sh + ".gate_proj.weight", True)
        p["shared_up"] = _stack_layers(t, l, dtype, sh + ".up_proj.weight", True)
        p["shared_down"] = _stack_layers(t, l, dtype, sh + ".down_proj.weight", True)
        p["shared_gate"] = jnp.asarray(np.stack(
            [t["model.layers.{}.mlp.shared_expert_gate.weight".format(i)].reshape(-1)
             for i in range(l)]), dtype)
    return p


# ---------------------------------------------------------------------------
# Orbax sharded checkpoints
# ---------------------------------------------------------------------------

def orbax_path(model_path: str) -> str:
    return os.path.join(model_path, ORBAX_SUBDIR)


def save_orbax(params: tf.Params, model_path: str) -> str:
    import orbax.checkpoint as ocp

    path = orbax_path(model_path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), params, force=True)
    ckptr.wait_until_finished()
    return path


def load_orbax(cfg: ModelConfig, model_path: str, mesh=None,
               dtype: Any = None) -> tf.Params:
    """Load an Orbax checkpoint, sharded directly to the mesh when given —
    each host reads only the shards it owns (multi-host friendly)."""
    import orbax.checkpoint as ocp

    dtype = jnp.dtype(dtype or cfg.dtype)
    path = os.path.abspath(orbax_path(model_path))
    template = jax.eval_shape(
        lambda: tf.init_params(cfg, jax.random.PRNGKey(0), dtype))
    if mesh is not None:
        tp = mesh.shape.get(tf.AXIS_MODEL, 1)
        specs = tf.param_pspecs(cfg, tp)
        template = jax.tree.map(
            lambda s, spec: jax.ShapeDtypeStruct(
                s.shape, s.dtype,
                sharding=jax.sharding.NamedSharding(mesh, spec)),
            template, specs)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path, template)


def convert_hf_to_orbax(cfg: ModelConfig, model_path: str,
                        dtype: Any = None) -> str:
    """One-shot conversion after model download (the ArksModel 'Loading'
    phase extension). Idempotent: skips when the Orbax dir already exists."""
    path = orbax_path(model_path)
    if os.path.isdir(path) and os.listdir(path):
        return path
    params = params_from_hf(cfg, model_path, dtype)
    return save_orbax(params, model_path)


# ---------------------------------------------------------------------------
# Entry point used by the serving pod
# ---------------------------------------------------------------------------

def has_real_weights(model_path: str | None) -> bool:
    """True when ``load_params`` would load actual weights (Orbax or
    safetensors) rather than falling back to random init."""
    if not model_path or not os.path.isdir(model_path):
        return False
    return os.path.isdir(orbax_path(model_path)) or any(
        f.endswith(".safetensors") for f in os.listdir(model_path))


def load_params(cfg: ModelConfig, model_path: str | None, mesh=None,
                dtype: Any = None) -> tf.Params:
    """Best available weights: Orbax (sharded) > safetensors > random init."""
    dtype = jnp.dtype(dtype or cfg.dtype)
    if model_path:
        if os.path.isdir(orbax_path(model_path)):
            log.info("loading Orbax checkpoint from %s", orbax_path(model_path))
            return load_orbax(cfg, model_path, mesh, dtype)
        if os.path.isdir(model_path) and any(
                f.endswith(".safetensors") for f in os.listdir(model_path)):
            log.info("loading HF safetensors from %s", model_path)
            params = params_from_hf(cfg, model_path, dtype)
            if mesh is not None:
                params = tf.shard_params(params, cfg, mesh)
            return params
        log.warning("no weights found under %s; using random init", model_path)
    params = tf.init_params(cfg, jax.random.PRNGKey(0), dtype)
    if mesh is not None:
        params = tf.shard_params(params, cfg, mesh)
    return params
