"""Weight loading: HF safetensors -> arks params; Orbax sharded checkpoints.

Parity anchor: the reference's ArksModel controller downloads a raw HF
snapshot into a PVC (/root/reference/internal/controller/
arksmodel_controller.go:218-354, scripts/download.py).  The TPU-native twist
(BASELINE.json north star) is a conversion step that writes **Orbax** sharded
checkpoints so every host in a multi-host slice reads only its own shards;
``arks_tpu.control.model`` drives that conversion after download.

Layout conventions: all projection matrices are stored [in, out] (JAX
convention; HF/torch stores [out, in]) and per-layer weights are stacked with
a leading [L] dim for the scan-based forward pass.
"""

from __future__ import annotations

import logging
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from arks_tpu.models.config import ModelConfig
from arks_tpu.models import transformer as tf
from arks_tpu.models.quant import weight_bits as _weight_bits

log = logging.getLogger("arks_tpu.weights")

ORBAX_SUBDIR = "arks_orbax"


# ---------------------------------------------------------------------------
# HF safetensors -> params
# ---------------------------------------------------------------------------

def _hf_tensors(path: str) -> dict[str, np.ndarray]:
    """Load all tensors from the safetensors shards in ``path``."""
    from safetensors import safe_open

    files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    out: dict[str, np.ndarray] = {}
    for fname in files:
        with safe_open(os.path.join(path, fname), framework="np") as f:
            for key in f.keys():
                out[key] = f.get_tensor(key)
    return out


def params_from_hf(cfg: ModelConfig, path: str, dtype: Any = None,
                   weight_dtype: str = "bf16", shards: int = 1) -> tf.Params:
    """Convert a HuggingFace Qwen2/Llama checkpoint directory to arks params.

    Leaves are assembled on the HOST (numpy) and moved to device one at a
    time; with ``weight_dtype='int8'`` each matmul leaf is quantized on
    arrival (models.quant w8a16) so peak device memory is the int8 tree plus
    ONE full-width leaf — the only way a ~15GB bf16 7B checkpoint reaches a
    16GB chip.
    """
    dtype = jnp.dtype(dtype or cfg.dtype)
    t = _hf_tensors(path)
    l = cfg.num_layers

    def get(name: str, transpose: bool = False) -> np.ndarray:
        x = t[name]
        x = x.T if transpose else x
        return np.asarray(x, dtype)

    def stack(fmt: str, transpose: bool = False) -> np.ndarray:
        return _stack_layers(t, l, dtype, fmt, transpose)

    layers: tf.Params = {
        "attn_norm": stack("model.layers.{}.input_layernorm.weight"),
        "wq": stack("model.layers.{}.self_attn.q_proj.weight", True),
        "wk": stack("model.layers.{}.self_attn.k_proj.weight", True),
        "wv": stack("model.layers.{}.self_attn.v_proj.weight", True),
        "wo": stack("model.layers.{}.self_attn.o_proj.weight", True),
        "mlp_norm": stack("model.layers.{}.post_attention_layernorm.weight"),
    }
    if cfg.num_experts:
        layers.update(_moe_from_hf(cfg, t, dtype))
    else:
        layers.update({
            "w_gate": stack("model.layers.{}.mlp.gate_proj.weight", True),
            "w_up": stack("model.layers.{}.mlp.up_proj.weight", True),
            "w_down": stack("model.layers.{}.mlp.down_proj.weight", True),
        })
    if cfg.qkv_bias:
        layers["bq"] = stack("model.layers.{}.self_attn.q_proj.bias")
        layers["bk"] = stack("model.layers.{}.self_attn.k_proj.bias")
        layers["bv"] = stack("model.layers.{}.self_attn.v_proj.bias")
    params: tf.Params = {
        "embed": get("model.embed_tokens.weight"),
        "layers": layers,
        "final_norm": get("model.norm.weight"),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = get("lm_head.weight", True)
    return _leaves_to_device(params, _weight_bits(weight_dtype),
                             shards=shards)


def _quantize_leaf(leaf, axis: int, bits: int = 8, shards: int = 1):
    import functools

    from arks_tpu.models.quant import quantize_tensor, quantize_tensor_int4

    x = jnp.asarray(leaf)
    # donate: the full-width device copy is freed as soon as the quantized
    # outputs exist, bounding the transient to one leaf.
    if bits == 4 and axis == -2:  # matmul weights; the embedding stays int8
        fn = jax.jit(functools.partial(quantize_tensor_int4, shards=shards),
                     donate_argnums=(0,))
    else:
        fn = jax.jit(functools.partial(quantize_tensor, axis=axis),
                     donate_argnums=(0,))
    return fn(x)


def _leaves_to_device(host_params: dict, bits: int,
                      shards: int = 1) -> tf.Params:
    """Move a host-side (numpy) params tree to device leaf-by-leaf,
    quantizing matmul leaves on arrival when requested (``bits`` =
    0 = no quantization | 8 | 4).  ``shards`` = mesh model-axis size
    (int4 groups align to shards)."""
    from arks_tpu.models.quant import MATMUL_KEYS

    def walk(sub: dict) -> dict:
        out = {}
        for name, leaf in sub.items():
            if isinstance(leaf, dict):
                out[name] = walk(leaf)
            elif bits and name == "embed":
                out[name] = _quantize_leaf(leaf, -1, bits)
            elif bits and name in MATMUL_KEYS:
                out[name] = _quantize_leaf(leaf, -2, bits, shards)
            else:
                out[name] = jnp.asarray(leaf)
        return out

    return walk(host_params)


def _stack_layers(t: dict[str, np.ndarray], l: int, dtype: Any, fmt: str,
                  transpose: bool = False) -> np.ndarray:
    """Stack one per-layer tensor family into the leading-[L] convention
    (host-side; device transfer happens in _leaves_to_device)."""
    xs = [t[fmt.format(i)] for i in range(l)]
    if transpose:
        xs = [x.T for x in xs]
    return np.stack(xs).astype(dtype)


def _moe_from_hf(cfg: ModelConfig, t: dict[str, np.ndarray],
                 dtype: Any) -> tf.Params:
    """Expert weights for Mixtral (`block_sparse_moe.experts.{e}.w1/w3/w2`)
    and Qwen2-MoE (`mlp.experts.{e}.gate_proj/up_proj/down_proj` + shared
    expert) checkpoints, stacked [L, X, ..]."""
    l, x = cfg.num_layers, cfg.num_experts
    mixtral = any(".block_sparse_moe." in k for k in t)
    if mixtral:
        base = "model.layers.{}.block_sparse_moe"
        router = base + ".gate.weight"
        gate, up, down = (base + ".experts.{}.w1.weight",
                          base + ".experts.{}.w3.weight",
                          base + ".experts.{}.w2.weight")
    else:
        base = "model.layers.{}.mlp"
        router = base + ".gate.weight"
        gate, up, down = (base + ".experts.{}.gate_proj.weight",
                          base + ".experts.{}.up_proj.weight",
                          base + ".experts.{}.down_proj.weight")

    def estack(fmt: str) -> np.ndarray:
        return np.stack([
            np.stack([t[fmt.format(i, e)].T for e in range(x)])
            for i in range(l)]).astype(dtype)

    p: tf.Params = {
        "router": _stack_layers(t, l, dtype, router, True),
        "w_gate": estack(gate),
        "w_up": estack(up),
        "w_down": estack(down),
    }
    if cfg.shared_expert_intermediate_size:
        sh = "model.layers.{}.mlp.shared_expert"
        p["shared_gate_proj"] = _stack_layers(t, l, dtype, sh + ".gate_proj.weight", True)
        p["shared_up"] = _stack_layers(t, l, dtype, sh + ".up_proj.weight", True)
        p["shared_down"] = _stack_layers(t, l, dtype, sh + ".down_proj.weight", True)
        p["shared_gate"] = np.stack(
            [t["model.layers.{}.mlp.shared_expert_gate.weight".format(i)].reshape(-1)
             for i in range(l)]).astype(dtype)
    return p


# ---------------------------------------------------------------------------
# Orbax sharded checkpoints
# ---------------------------------------------------------------------------

def orbax_path(model_path: str) -> str:
    return os.path.join(model_path, ORBAX_SUBDIR)


def save_orbax(params: tf.Params, model_path: str) -> str:
    import orbax.checkpoint as ocp

    path = orbax_path(model_path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), params, force=True)
    ckptr.wait_until_finished()
    return path


def load_orbax(cfg: ModelConfig, model_path: str, mesh=None,
               dtype: Any = None, weight_dtype: str = "bf16") -> tf.Params:
    """Load an Orbax checkpoint, sharded directly to the mesh when given —
    each host reads only the shards it owns (multi-host friendly).

    With ``weight_dtype='int8'`` and no mesh, the checkpoint is restored to
    HOST memory and quantized onto the device leaf-by-leaf (bounded peak —
    the single-chip 7B path).  With a mesh, the full-width restore is
    already spread across devices, so the tree-level quantize follows it.
    """
    import orbax.checkpoint as ocp

    dtype = jnp.dtype(dtype or cfg.dtype)
    quantize = _weight_bits(weight_dtype)
    path = os.path.abspath(orbax_path(model_path))
    template = jax.eval_shape(
        lambda: tf.init_params(cfg, jax.random.PRNGKey(0), dtype))
    if mesh is not None:
        tp = mesh.shape.get(tf.AXIS_MODEL, 1)
        specs = tf.param_pspecs(cfg, tp)
        template = jax.tree.map(
            lambda s, spec: jax.ShapeDtypeStruct(
                s.shape, s.dtype,
                sharding=jax.sharding.NamedSharding(mesh, spec)),
            template, specs)
    elif quantize:
        cpu = jax.devices("cpu")[0]
        template = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(
                s.shape, s.dtype,
                sharding=jax.sharding.SingleDeviceSharding(cpu)),
            template)
    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(path, template)
    if quantize:
        shards = mesh.shape.get(tf.AXIS_MODEL, 1) if mesh is not None else 1
        if mesh is not None:
            from arks_tpu.models.quant import quantize_params
            return quantize_params(params, bits=quantize, shards=shards)
        return _leaves_to_device(
            jax.tree.map(np.asarray, params), quantize)
    return params


def _shard_put_fns(cfg: ModelConfig, template, mesh=None):
    """Per-leaf H2D placement fns (the make_shard_and_gather_fns idiom):
    one closure per param leaf that converts the host value to the leaf's
    dtype and issues a NON-BLOCKING ``jax.device_put`` — sharded onto the
    mesh when given, whole-array otherwise.  Because each put is async,
    walking the tree overlaps the host read/convert of leaf N+1 with the
    device transfer of leaf N."""
    if mesh is not None:
        tp = mesh.shape.get(tf.AXIS_MODEL, 1)
        specs = tf.param_pspecs(cfg, tp)

        def make(s, spec):
            sh = jax.sharding.NamedSharding(mesh, spec)
            return lambda x: jax.device_put(jnp.asarray(x, s.dtype), sh)

        return jax.tree.map(make, template, specs)

    def make_local(s):
        return lambda x: jax.device_put(jnp.asarray(x, s.dtype))

    return jax.tree.map(make_local, template)


def stream_params_to_device(cfg: ModelConfig, host_params, mesh=None,
                            dtype: Any = None) -> tf.Params:
    """Stream a host-resident params tree to device leaf-by-leaf with
    async H2D puts (no blocking between leaves, no tree-level barrier).
    The returned arrays are in flight; the caller's first dispatch — an
    ordinary stream op, exactly the restore mechanics — orders after them,
    so a live engine keeps issuing pipelined decode for the CURRENT model
    while the NEXT model's weights fly."""
    dtype = jnp.dtype(dtype or cfg.dtype)
    template = jax.eval_shape(
        lambda: tf.init_params(cfg, jax.random.PRNGKey(0), dtype))
    fns = _shard_put_fns(cfg, template, mesh)
    return jax.tree.map(lambda fn, x: fn(x), fns, host_params)


def reshard_plan(cfg: ModelConfig, params, mesh=None):
    """Per-leaf placement fns for a LIVE topology resize: one closure per
    current param leaf that issues a non-blocking ``jax.device_put`` onto
    the NEW mesh's sharding (or whole-array onto the default device when
    the new shape is single-chip).  Same make_shard_and_gather_fns idiom
    as ``_shard_put_fns``, but the source leaves are already on device —
    each put is a device-to-device reshard dispatch, so walking the tree
    overlaps leaf N+1's issue with leaf N's transfer and the drained
    engine never blocks the host.  Quantized trees get quantize-aware
    pspecs (the ``shard_params`` discipline)."""
    if mesh is None:
        dev = jax.devices()[0]
        return jax.tree.map(
            lambda _: (lambda x: jax.device_put(x, dev)), params)
    tp = mesh.shape.get(tf.AXIS_MODEL, 1)
    specs = tf.param_pspecs(cfg, tp)
    from arks_tpu.models.quant import is_quantized, quantize_pspecs
    wq = params["layers"].get("wq")
    if is_quantized(wq):
        specs = quantize_pspecs(specs, bits=4 if "gs" in wq else 8)

    def make(spec):
        sh = jax.sharding.NamedSharding(mesh, spec)
        return lambda x: jax.device_put(x, sh)

    return jax.tree.map(make, specs)


def reshard_params_to_mesh(cfg: ModelConfig, params, mesh=None) -> tf.Params:
    """Migrate a live params tree to a new mesh shape with per-leaf async
    ``device_put`` (the resize half of ``stream_params_to_device``): the
    returned arrays are in flight and the first dispatch at the new shape
    orders after them."""
    fns = reshard_plan(cfg, params, mesh)
    return jax.tree.map(lambda fn, x: fn(x), fns, params)


def load_orbax_streaming(cfg: ModelConfig, model_path: str, mesh=None,
                         dtype: Any = None,
                         weight_dtype: str = "bf16") -> tf.Params:
    """Shard-streaming Orbax load for live model switches: restore the
    checkpoint to HOST memory, then scatter it to device with per-leaf
    async puts (``stream_params_to_device``).  Unlike ``load_orbax`` —
    which restores directly into device shardings and synchronizes the
    restore — every device-facing op here is an async stream dispatch, so
    it is safe to run from the model-pool loader thread while the engine
    keeps full pipeline depth on the resident model.

    Quantized loads fall back to ``load_orbax`` (its bounded-peak
    leaf-quantize path is already host-staged)."""
    import orbax.checkpoint as ocp

    dtype = jnp.dtype(dtype or cfg.dtype)
    if _weight_bits(weight_dtype):
        return load_orbax(cfg, model_path, mesh, dtype, weight_dtype)
    path = os.path.abspath(orbax_path(model_path))
    template = jax.eval_shape(
        lambda: tf.init_params(cfg, jax.random.PRNGKey(0), dtype))
    cpu = jax.devices("cpu")[0]
    host_template = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(
            s.shape, s.dtype,
            sharding=jax.sharding.SingleDeviceSharding(cpu)),
        template)
    ckptr = ocp.StandardCheckpointer()
    host_params = ckptr.restore(path, host_template)
    return stream_params_to_device(
        cfg, jax.tree.map(np.asarray, host_params), mesh, dtype)


def convert_hf_to_orbax(cfg: ModelConfig, model_path: str,
                        dtype: Any = None) -> str:
    """One-shot conversion after model download (the ArksModel 'Loading'
    phase extension). Idempotent: skips when the Orbax dir already exists."""
    path = orbax_path(model_path)
    if os.path.isdir(path) and os.listdir(path):
        return path
    params = params_from_hf(cfg, model_path, dtype)
    return save_orbax(params, model_path)


# ---------------------------------------------------------------------------
# Entry point used by the serving pod
# ---------------------------------------------------------------------------

def weights_kind(model_path: str | None) -> str | None:
    """Classify what ``load_params`` would load with ONE directory scan:
    ``"orbax"`` > ``"safetensors"`` > ``None`` (random init).

    This is the model-switch hot path: ``has_real_weights`` and
    ``load_params`` both used to stat the Orbax subdir AND list the
    directory, doubling the filesystem reads per switch.  ``os.scandir``
    gives entry types from the directory read itself (no per-entry stat
    on mainstream filesystems), so classification costs one opendir."""
    if not model_path:
        return None
    kind = None
    try:
        with os.scandir(model_path) as it:
            for e in it:
                if e.name == ORBAX_SUBDIR and e.is_dir():
                    return "orbax"
                if e.name.endswith(".safetensors"):
                    kind = "safetensors"
    except (FileNotFoundError, NotADirectoryError):
        return None
    return kind


def has_real_weights(model_path: str | None) -> bool:
    """True when ``load_params`` would load actual weights (Orbax or
    safetensors) rather than falling back to random init."""
    return weights_kind(model_path) is not None


def load_params(cfg: ModelConfig, model_path: str | None, mesh=None,
                dtype: Any = None, weight_dtype: str = "bf16") -> tf.Params:
    """Best available weights: Orbax (sharded) > safetensors > random init.

    ``weight_dtype='int8'`` quantizes during load with bounded peak memory
    (see params_from_hf / load_orbax) — quantizing after a full-width load
    would OOM exactly the HBM-limited configs the flag exists for."""
    dtype = jnp.dtype(dtype or cfg.dtype)
    quantize = _weight_bits(weight_dtype)
    if model_path:
        kind = weights_kind(model_path)
        if kind == "orbax":
            log.info("loading Orbax checkpoint from %s", orbax_path(model_path))
            return load_orbax(cfg, model_path, mesh, dtype, weight_dtype)
        if kind == "safetensors":
            log.info("loading HF safetensors from %s", model_path)
            params = params_from_hf(
                cfg, model_path, dtype, weight_dtype,
                shards=mesh.shape.get(tf.AXIS_MODEL, 1)
                if mesh is not None else 1)
            if mesh is not None:
                params = tf.shard_params(params, cfg, mesh)
            return params
        log.warning("no weights found under %s; using random init", model_path)
    if quantize:
        from arks_tpu.models.quant import init_params_quantized
        params = init_params_quantized(
            cfg, jax.random.PRNGKey(0), dtype, bits=quantize,
            shards=mesh.shape.get(tf.AXIS_MODEL, 1) if mesh is not None else 1)
    else:
        params = tf.init_params(cfg, jax.random.PRNGKey(0), dtype)
    if mesh is not None:
        params = tf.shard_params(params, cfg, mesh)
    return params


def load_params_streaming(cfg: ModelConfig, model_path: str | None, mesh=None,
                          dtype: Any = None,
                          weight_dtype: str = "bf16") -> tf.Params:
    """``load_params`` for LIVE model switches: every device-facing op is
    an async stream dispatch (per-leaf puts), never a blocking restore —
    the model-pool loader thread can run this under a serving engine
    without stalling its pipelined decode.  Same weight preference order
    as ``load_params`` (Orbax > safetensors > random init), same single
    directory scan."""
    kind = weights_kind(model_path)
    if kind == "orbax":
        log.info("streaming Orbax checkpoint from %s", orbax_path(model_path))
        return load_orbax_streaming(cfg, model_path, mesh, dtype, weight_dtype)
    # params_from_hf already streams leaf-by-leaf via _leaves_to_device;
    # the random-init fallback is device-side and cheap.
    return load_params(cfg, model_path, mesh, dtype, weight_dtype)
