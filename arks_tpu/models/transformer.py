"""Functional decoder-only transformer (Qwen2 / Llama families) for serving.

Design notes (TPU-first, not a port — the reference has no model code at all;
it shells out to vLLM/SGLang containers):

- Layers are **stacked**: every per-layer weight carries a leading [L] dim and
  the forward pass is one ``lax.scan`` over layers.  One trace + one compile
  regardless of depth, and uniform sharding per leaf.
- Serving follows the slot model (JetStream-style): a decode batch of B slots,
  each slot owning a [S] stretch of KV cache.  ``prefill`` runs a prompt
  through the model producing its KV; ``insert`` drops that KV into a free
  slot; ``decode_step`` advances every slot by one token.
- Tensor parallelism is Megatron-pattern via weight PartitionSpecs over the
  ``model`` mesh axis (column-parallel qkv/gate/up, row-parallel o/down); XLA
  inserts the psums over ICI.  Batch parallelism rides the ``data`` axis.
- KV heads shard over ``model`` when divisible; otherwise KV projections and
  cache are replicated (cheap: GQA KV dims are small) — this keeps e.g.
  Qwen2.5-7B (4 KV heads) correct on an 8-way TP mesh.

Reference parity anchor: this module + arks_tpu.engine replace the runtime
containers listed in /root/reference/api/v1/arksapplication_types.go:46-49.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from arks_tpu.models.config import ModelConfig
from arks_tpu.models.quant import embed_lookup, qeinsum, unembed_logits
from arks_tpu.ops.attention import decode_update_and_attend, prefill_attention
from arks_tpu.ops.norms import rms_norm
from arks_tpu.ops.rope import apply_rope

AXIS_DATA = "data"
AXIS_MODEL = "model"

Params = dict[str, Any]


class KVCache(NamedTuple):
    """Decode KV cache: [num_layers, num_slots, num_kv_heads, max_len, head_dim].

    Head-major layout: each (slot, kv-head) sequence is a contiguous [S, D]
    stripe, so the ragged Pallas decode kernel's block reads are dense DMAs
    (arks_tpu.ops.pallas_attention).

    Quantized (int8) caches carry per-token scales
    [L, B, Hkv, S] float32; ``k_scale is None`` means full-width storage.
    """

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: jnp.ndarray | None = None
    v_scale: jnp.ndarray | None = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]


class PagedKVCache(NamedTuple):
    """Paged decode cache: pool [num_layers, num_pages, Hkv, page, head_dim].

    A page is a (layer, kv-head)-major stripe of ``page`` consecutive
    positions of ONE sequence; per-slot block tables [B, MaxP] (owned by
    the engine, passed as dispatch args) map position p of slot b to pool
    page tables[b, p // page].  Two tables pointing at one page = zero-copy
    prefix sharing (arks_tpu.ops.paged_attention).  int8 pools carry
    per-token scales [L, N, Hkv, page] float32.  int4 pools pack token
    pairs into nibble bytes along the page axis ([L, N, Hkv, page//2, D]
    int8) while the scale stripes keep full token resolution — which is
    also how int4-ness is detected (pool page rows != scale page).
    """

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: jnp.ndarray | None = None
    v_scale: jnp.ndarray | None = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page(self) -> int:
        """Tokens per page (POSITION math everywhere uses this; the int4
        pool's byte rows are page // 2)."""
        if self.k_scale is not None:
            return self.k_scale.shape[3]
        return self.k.shape[3]

    @property
    def kv_bits(self) -> int:
        if self.k_scale is None:
            return self.k.dtype.itemsize * 8
        return 4 if self.k.shape[3] != self.k_scale.shape[3] else 8


# ---------------------------------------------------------------------------
# Parameter init + sharding specs
# ---------------------------------------------------------------------------


def init_params(cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype | None = None) -> Params:
    dtype = dtype or jnp.dtype(cfg.dtype)
    l, e, f, v = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    qd, kvd = cfg.q_dim, cfg.kv_dim
    keys = iter(jax.random.split(key, 16))

    def w(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    layers: Params = {
        "attn_norm": jnp.ones((l, e), dtype),
        "wq": w(next(keys), (l, e, qd)),
        "wk": w(next(keys), (l, e, kvd)),
        "wv": w(next(keys), (l, e, kvd)),
        "wo": w(next(keys), (l, qd, e)),
        "mlp_norm": jnp.ones((l, e), dtype),
    }
    if cfg.num_experts:
        from arks_tpu.models import moe
        layers.update(moe.init_moe_params(cfg, next(keys), dtype))
    else:
        layers.update({
            "w_gate": w(next(keys), (l, e, f)),
            "w_up": w(next(keys), (l, e, f)),
            "w_down": w(next(keys), (l, f, e)),
        })
    if cfg.qkv_bias:
        layers["bq"] = jnp.zeros((l, qd), dtype)
        layers["bk"] = jnp.zeros((l, kvd), dtype)
        layers["bv"] = jnp.zeros((l, kvd), dtype)
    params: Params = {
        "embed": w(next(keys), (v, e)),
        "layers": layers,
        "final_norm": jnp.ones((e,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(next(keys), (e, v))
    return params


def shard_kv_heads(cfg: ModelConfig, tp: int) -> bool:
    return tp > 1 and cfg.num_kv_heads % tp == 0


def param_pspecs(cfg: ModelConfig, tp: int = 1) -> Params:
    """PartitionSpec pytree matching ``init_params`` (leading [L] dim on layers)."""
    kv = P(None, None, AXIS_MODEL) if shard_kv_heads(cfg, tp) else P(None, None, None)
    kvb = P(None, AXIS_MODEL) if shard_kv_heads(cfg, tp) else P(None, None)
    layers: Params = {
        "attn_norm": P(None, None),
        "wq": P(None, None, AXIS_MODEL),
        "wk": kv,
        "wv": kv,
        "wo": P(None, AXIS_MODEL, None),
        "mlp_norm": P(None, None),
    }
    if cfg.num_experts:
        from arks_tpu.models import moe
        layers.update(moe.moe_pspecs(cfg, AXIS_MODEL, moe.shard_experts(cfg, tp)))
    else:
        layers.update({
            "w_gate": P(None, None, AXIS_MODEL),
            "w_up": P(None, None, AXIS_MODEL),
            "w_down": P(None, AXIS_MODEL, None),
        })
    if cfg.qkv_bias:
        layers["bq"] = P(None, AXIS_MODEL)
        layers["bk"] = kvb
        layers["bv"] = kvb
    specs: Params = {
        "embed": P(AXIS_MODEL, None),
        "layers": layers,
        "final_norm": P(None),
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, AXIS_MODEL)
    return specs


def cache_head_dim(cfg: ModelConfig, pad_head: bool = False) -> int:
    """Stored head dim: padded up to the 128-lane tile when requested, so
    models with head_dim < 128 (qwen2.5-0.5b, tiny test configs) ride the
    compiled Pallas decode kernels instead of the XLA fallback.  Zero
    padding is EXACT: padded K lanes add 0 to every q.k score and padded V
    lanes produce output columns the caller slices off."""
    if pad_head and cfg.head_dim % 128 != 0:
        return -(-cfg.head_dim // 128) * 128
    return cfg.head_dim


def pad_heads(x: jnp.ndarray, d_store: int) -> jnp.ndarray:
    """Zero-pad the trailing head dim to the cache's stored width (ONE
    implementation — the attention ops' _pad_last)."""
    from arks_tpu.ops.attention import _pad_last
    return _pad_last(x, d_store)


def init_cache(cfg: ModelConfig, num_slots: int, max_len: int,
               dtype: jnp.dtype | None = None,
               quantized: bool = False, pad_head: bool = False) -> KVCache:
    dtype = dtype or jnp.dtype(cfg.dtype)
    shape = (cfg.num_layers, num_slots, cfg.num_kv_heads, max_len,
             cache_head_dim(cfg, pad_head))
    if quantized:
        return KVCache(
            k=jnp.zeros(shape, jnp.int8), v=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.zeros(shape[:-1], jnp.float32),
            v_scale=jnp.zeros(shape[:-1], jnp.float32))
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def batch_axis_for(mesh: "Mesh | None"):
    """The mesh axes a batch dimension shards over: ``("slice", "data")``
    on a multi-slice mesh (dp rides DCN across slices AND ICI within),
    ``"data"``/``"slice"`` when only one is populated, None otherwise.
    PartitionSpec entries accept the tuple directly."""
    if mesh is None:
        return None
    from arks_tpu.parallel.mesh import AXIS_SLICE
    axes = [a for a in (AXIS_SLICE, AXIS_DATA) if mesh.shape.get(a, 1) > 1]
    if not axes:
        return None
    return axes[0] if len(axes) == 1 else tuple(axes)


def cache_pspecs(cfg: ModelConfig, tp: int = 1, dp: int = 1,
                 quantized: bool = False, batch=None) -> KVCache:
    batch = batch if batch is not None else (AXIS_DATA if dp > 1 else None)
    heads = AXIS_MODEL if shard_kv_heads(cfg, tp) else None
    spec = P(None, batch, heads, None, None)
    sspec = P(None, batch, heads, None) if quantized else None
    return KVCache(k=spec, v=spec, k_scale=sspec, v_scale=sspec)


def init_paged_cache(cfg: ModelConfig, num_pages: int, page: int,
                     dtype: jnp.dtype | None = None,
                     quantized: bool = False,
                     pad_head: bool = False,
                     kv_bits: int = 8) -> PagedKVCache:
    dtype = dtype or jnp.dtype(cfg.dtype)
    shape = (cfg.num_layers, num_pages, cfg.num_kv_heads, page,
             cache_head_dim(cfg, pad_head))
    if quantized:
        if kv_bits not in (4, 8):
            raise ValueError(f"quantized kv_bits must be 4 or 8, got {kv_bits}")
        if kv_bits == 4 and page % 2:
            raise ValueError(f"int4 page size {page} must be even")
        rows = page // 2 if kv_bits == 4 else page
        vshape = shape[:3] + (rows, shape[4])
        return PagedKVCache(
            k=jnp.zeros(vshape, jnp.int8), v=jnp.zeros(vshape, jnp.int8),
            k_scale=jnp.zeros(shape[:-1], jnp.float32),
            v_scale=jnp.zeros(shape[:-1], jnp.float32))
    return PagedKVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def paged_cache_pspecs(cfg: ModelConfig, tp: int = 1,
                       quantized: bool = False) -> PagedKVCache:
    """Pool sharding: kv heads over ``model`` when divisible (pages are
    whole-sequence stripes, so neither N nor P can shard without breaking
    page locality)."""
    heads = AXIS_MODEL if shard_kv_heads(cfg, tp) else None
    spec = P(None, None, heads, None, None)
    sspec = P(None, None, heads, None) if quantized else None
    return PagedKVCache(k=spec, v=spec, k_scale=sspec, v_scale=sspec)


def shard_paged_cache(cache: PagedKVCache, cfg: ModelConfig,
                      mesh: Mesh) -> PagedKVCache:
    tp = mesh.shape.get(AXIS_MODEL, 1)
    specs = paged_cache_pspecs(cfg, tp, quantized=cache.quantized)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), cache, specs)


def shard_params(params: Params, cfg: ModelConfig, mesh: Mesh) -> Params:
    tp = mesh.shape.get(AXIS_MODEL, 1)
    specs = param_pspecs(cfg, tp)
    from arks_tpu.models.quant import is_quantized, quantize_pspecs
    wq = params["layers"].get("wq")
    if is_quantized(wq):
        specs = quantize_pspecs(specs, bits=4 if "gs" in wq else 8)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def shard_cache(cache: KVCache, cfg: ModelConfig, mesh: Mesh) -> KVCache:
    tp = mesh.shape.get(AXIS_MODEL, 1)
    specs = cache_pspecs(cfg, tp, quantized=cache.quantized,
                         batch=batch_axis_for(mesh))
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), cache, specs)


# ---------------------------------------------------------------------------
# Forward passes
# ---------------------------------------------------------------------------


def _constrain(x: jnp.ndarray, mesh: Mesh | None, *spec) -> jnp.ndarray:
    if mesh is None or mesh.size == 1:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def _qkv(h: jnp.ndarray, lp: Params, cfg: ModelConfig):
    q = qeinsum("...e,eq->...q", h, lp["wq"])
    k = qeinsum("...e,ek->...k", h, lp["wk"])
    v = qeinsum("...e,ek->...k", h, lp["wv"])
    if cfg.qkv_bias:
        q = q + lp["bq"]
        k = k + lp["bk"]
        v = v + lp["bv"]
    return q, k, v


def _block_qkv(h: jnp.ndarray, lp: Params, cfg: ModelConfig,
               positions: jnp.ndarray):
    """Pre-norm + qkv projection + head split + rope for a [B, T, E] block —
    shared by one-shot and chunked prefill so their math can never diverge."""
    b, t = h.shape[:2]
    x = rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
    q, k, v = _qkv(x, lp, cfg)
    q = q.reshape(b, t, cfg.num_heads, cfg.head_dim)
    k = k.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
    q = apply_rope(q, positions, cfg.rope_theta)
    k = apply_rope(k, positions, cfg.rope_theta)
    return q, k, v


def _block_tail(h: jnp.ndarray, attn: jnp.ndarray, lp: Params,
                cfg: ModelConfig, mesh: Mesh | None, batch_axis: str | None,
                seq_axis: str | None = None) -> jnp.ndarray:
    """Output projection residual + MLP residual (post-attention half of the
    block) — the other shared piece of the prefill paths."""
    h = h + qeinsum("...q,qe->...e", attn, lp["wo"])
    h = h + _mlp(h, lp, cfg, mesh, batch_axis, seq_axis)
    return h


def _mlp(h: jnp.ndarray, lp: Params, cfg: ModelConfig, mesh: Mesh | None,
         batch_axis: str | None, seq_axis: str | None = None) -> jnp.ndarray:
    x = rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)

    def _int_spec(ndim: int, sharded_dim: int) -> list:
        # Intermediate spec: keep batch and (under context parallelism) the
        # T dim sharded — a None dim means REPLICATED to the constraint, and
        # regathering T across the seq axis would undo CP exactly where the
        # wide intermediates make it matter.
        spec = [None] * ndim
        spec[0] = batch_axis
        if ndim >= 3:
            spec[1] = seq_axis
        spec[sharded_dim] = AXIS_MODEL
        return spec

    if cfg.num_experts:
        from arks_tpu.models import moe
        tp = mesh.shape.get(AXIS_MODEL, 1) if mesh is not None else 1

        def constrain(t, dim):
            # Pin the expert (or shared-F) dim of MoE intermediates to the
            # model axis so partial-expert outputs psum instead of regather.
            if not moe.shard_experts(cfg, tp) and t.ndim - dim == 2:
                return t  # expert dim replicated in this regime
            return _constrain(t, mesh, *_int_spec(t.ndim, dim))

        return moe.moe_ffn(x, lp, cfg, constrain if mesh is not None else None)
    gate = qeinsum("...e,ef->...f", x, lp["w_gate"])
    up = qeinsum("...e,ef->...f", x, lp["w_up"])
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(gate.dtype) * up
    act = _constrain(act, mesh, *_int_spec(act.ndim, act.ndim - 1))
    return qeinsum("...f,fe->...e", act, lp["w_down"])


def _unembed(h_last: jnp.ndarray, params: Params, cfg: ModelConfig,
             mesh: Mesh | None, batch_axis: str | None) -> jnp.ndarray:
    h_last = rms_norm(h_last, params["final_norm"], cfg.rms_norm_eps)
    tied = cfg.tie_word_embeddings
    table = params["embed"] if tied else params["lm_head"]
    logits = unembed_logits(h_last, table, tied)
    return _constrain(logits, mesh, batch_axis, None)


def prefill_layer(
    h: jnp.ndarray,       # [B, T, E]
    lp: Params,
    cfg: ModelConfig,
    positions: jnp.ndarray,  # [B, T]
    mesh: Mesh | None = None,
    batch_axis: str | None = None,
    seq_axis: str | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One transformer block over a full sequence. Returns (h, k, v) — the
    single layer body shared by serving prefill and the training forward
    (train discards k/v; XLA dead-code-eliminates them there).

    With ``seq_axis`` set (context parallelism), T is sharded over that mesh
    axis and attention runs as a ring (arks_tpu.parallel.ring); every other
    op in the block is pointwise over T, so XLA partitions it for free.
    """
    b, t = h.shape[:2]
    q, k, v = _block_qkv(h, lp, cfg, positions)
    if seq_axis is not None and mesh is not None and mesh.shape.get(seq_axis, 1) > 1:
        from arks_tpu.parallel.ring import ring_prefill_attention
        heads_sharded = shard_kv_heads(cfg, mesh.shape.get(AXIS_MODEL, 1)) \
            and cfg.num_heads % mesh.shape.get(AXIS_MODEL, 1) == 0
        attn = ring_prefill_attention(q, k, v, mesh, seq_axis, batch_axis,
                                      heads_sharded=heads_sharded,
                                      model_axis=AXIS_MODEL)
        attn = attn.reshape(b, t, cfg.q_dim)
        attn = _constrain(attn, mesh, batch_axis, seq_axis, AXIS_MODEL)
    else:
        attn = prefill_attention(q, k, v).reshape(b, t, cfg.q_dim)
        attn = _constrain(attn, mesh, batch_axis, None, AXIS_MODEL)
    h = _block_tail(h, attn, lp, cfg, mesh, batch_axis, seq_axis)
    return h, k, v


def prefill(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,   # [B, T] int32, padded to bucket length T
    lengths: jnp.ndarray,  # [B] int32 true lengths (<= T)
    mesh: Mesh | None = None,
    seq_axis: str | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Run full prompts. Returns (last-token logits [B, V] float32,
    k [L, B, T, Hkv, D], v [L, B, T, Hkv, D]) for cache insertion.

    ``seq_axis`` turns on context parallelism: T shards over that mesh axis
    and attention runs as a ring (long-context prefill — prompts bigger than
    one chip's budget).  Padded positions sit at the END of the sequence, so
    under the global causal mask no valid query ever attends to them."""
    b, t = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    h = embed_lookup(params["embed"], tokens,
                     params["layers"]["attn_norm"].dtype)
    h = _constrain(h, mesh, None, seq_axis, None)

    def body(h, lp):
        h, k, v = prefill_layer(h, lp, cfg, positions, mesh, None, seq_axis)
        return h, (k, v)

    h, (ks, vs) = jax.lax.scan(body, h, params["layers"])
    h_last = jnp.take_along_axis(
        h, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = _unembed(h_last, params, cfg, mesh, None)
    return logits, ks, vs


def prefill_chunk(
    params: Params,
    cfg: ModelConfig,
    cache: KVCache,
    slot: jnp.ndarray,     # () int32 — cache slot being filled
    tokens: jnp.ndarray,   # [C] int32 — chunk tokens (padded on the last chunk)
    start: jnp.ndarray,    # () int32 — global position of tokens[0]
    valid: jnp.ndarray,    # () int32 — true token count in this chunk (<= C)
    mesh: Mesh | None = None,
) -> tuple[jnp.ndarray, KVCache]:
    """One chunk of an incremental (chunked) prefill for a single slot.

    Writes the chunk's KV into the cache at [start, start+C) and attends
    each query over the full cached prefix [0, start+i] — so a long prompt
    is processed as a sequence of bounded dispatches that interleave with
    decode steps instead of one monolithic prefill that stalls every
    decoding slot.  Returns (logits [1, V] f32 for the chunk's LAST VALID
    token — only meaningful on the final chunk — and the updated cache).

    Numerically equivalent to one-shot prefill (same math, blockwise):
    chunk-boundary differences are pure fp reassociation.  Padding rows on
    the final chunk write garbage KV beyond the prompt length; every read
    path masks by position, and decode overwrites them as generation
    proceeds (same invariant as decode's slot-0 garbage writes).
    """
    c = tokens.shape[0]
    positions = (start + jnp.arange(c, dtype=jnp.int32))[None]  # [1, C]
    h = embed_lookup(params["embed"], tokens[None],
                     params["layers"]["attn_norm"].dtype)       # [1, C, E]
    quantized = cache.quantized

    def body(carry, xs):
        h, kc, vc, ksc, vsc = carry
        lp, layer = xs
        q, k, v = _block_qkv(h, lp, cfg, positions)

        # Write the chunk's KV rows (head-major cache layout).
        kt = pad_heads(jnp.swapaxes(k[0], 0, 1), kc.shape[-1])
        vt = pad_heads(jnp.swapaxes(v[0], 0, 1), kc.shape[-1])
        at = (layer, slot.astype(jnp.int32), 0, start.astype(jnp.int32), 0)
        if quantized:
            from arks_tpu.ops.pallas_attention import quantize_kv
            kq, ks = quantize_kv(kt)
            vq, vs = quantize_kv(vt)
            kc = jax.lax.dynamic_update_slice(kc, kq[None, None], at)
            vc = jax.lax.dynamic_update_slice(vc, vq[None, None], at)
            ksc = jax.lax.dynamic_update_slice(ksc, ks[None, None], at[:-1])
            vsc = jax.lax.dynamic_update_slice(vsc, vs[None, None], at[:-1])
        else:
            kc = jax.lax.dynamic_update_slice(kc, kt[None, None].astype(kc.dtype), at)
            vc = jax.lax.dynamic_update_slice(vc, vt[None, None].astype(vc.dtype), at)

        # Attend over this slot's cache prefix (chunk rows included).
        kc_l = jax.lax.dynamic_index_in_dim(kc, layer, 0, keepdims=False)
        vc_l = jax.lax.dynamic_index_in_dim(vc, layer, 0, keepdims=False)
        kc_s = jax.lax.dynamic_index_in_dim(kc_l, slot, 0, keepdims=False)
        vc_s = jax.lax.dynamic_index_in_dim(vc_l, slot, 0, keepdims=False)
        ks_s = vs_s = None
        if quantized:
            ks_s = jax.lax.dynamic_index_in_dim(
                jax.lax.dynamic_index_in_dim(ksc, layer, 0, keepdims=False),
                slot, 0, keepdims=False)
            vs_s = jax.lax.dynamic_index_in_dim(
                jax.lax.dynamic_index_in_dim(vsc, layer, 0, keepdims=False),
                slot, 0, keepdims=False)
        g = cfg.num_heads // cfg.num_kv_heads
        qg = jnp.transpose(
            q[0].reshape(c, cfg.num_kv_heads, g, cfg.head_dim), (1, 2, 0, 3))
        d_store = kc.shape[-1]
        if d_store != cfg.head_dim:
            # Lane-padded cache: pad q (prescaled so the op's 1/sqrt(stored
            # d) nets to 1/sqrt(head_dim)); the padded V columns slice off.
            qg = pad_heads(qg, d_store) * ((d_store / cfg.head_dim) ** 0.5)
        from arks_tpu.ops.attention import chunk_attention_xla
        attn = chunk_attention_xla(qg, kc_s, vc_s, start, ks_s, vs_s)
        attn = jnp.transpose(attn[..., : cfg.head_dim],
                             (2, 0, 1, 3)).reshape(1, c, cfg.q_dim)
        attn = _constrain(attn, mesh, None, None, AXIS_MODEL)
        h = _block_tail(h, attn, lp, cfg, mesh, None)
        return (h, kc, vc, ksc, vsc), None

    (h, kc, vc, ksc, vsc), _ = jax.lax.scan(
        body, (h, cache.k, cache.v, cache.k_scale, cache.v_scale),
        (params["layers"], jnp.arange(cfg.num_layers, dtype=jnp.int32)))
    h_last = jax.lax.dynamic_index_in_dim(h[0], valid - 1, 0, keepdims=True)
    logits = _unembed(h_last, params, cfg, mesh, None)
    return logits, KVCache(k=kc, v=vc, k_scale=ksc, v_scale=vsc)


def insert(cache: KVCache, k_new: jnp.ndarray, v_new: jnp.ndarray,
           slot: jnp.ndarray) -> KVCache:
    """Insert prefill KV ([L, 1, T, Hkv, D]) into decode cache at ``slot``.

    T must be <= cache max_len; entries beyond the true length are masked by
    the per-slot length at decode time and overwritten as decoding proceeds.
    Prefill emits time-major KV; the cache is head-major, so transpose here
    (once per prompt — decode never pays for it).  Quantized caches get the
    rows quantized to int8 + per-token scales here.
    """
    start = (0, slot.astype(jnp.int32), 0, 0, 0)
    k_new = pad_heads(jnp.swapaxes(k_new, 2, 3), cache.k.shape[-1])
    v_new = pad_heads(jnp.swapaxes(v_new, 2, 3), cache.v.shape[-1])
    if cache.quantized:
        from arks_tpu.ops.pallas_attention import quantize_kv
        kq, ks = quantize_kv(k_new)  # int8 [L,1,Hkv,T,D], f32 [L,1,Hkv,T]
        vq, vs = quantize_kv(v_new)
        sstart = (0, slot.astype(jnp.int32), 0, 0)
        return KVCache(
            k=jax.lax.dynamic_update_slice(cache.k, kq, start),
            v=jax.lax.dynamic_update_slice(cache.v, vq, start),
            k_scale=jax.lax.dynamic_update_slice(cache.k_scale, ks, sstart),
            v_scale=jax.lax.dynamic_update_slice(cache.v_scale, vs, sstart),
        )
    return KVCache(
        k=jax.lax.dynamic_update_slice(cache.k, k_new.astype(cache.k.dtype), start),
        v=jax.lax.dynamic_update_slice(cache.v, v_new.astype(cache.v.dtype), start),
    )


def insert_pages(cache: PagedKVCache, k_new: jnp.ndarray, v_new: jnp.ndarray,
                 pages: jnp.ndarray, n_pages: jnp.ndarray) -> PagedKVCache:
    """Insert prefill KV ([L, 1, T, Hkv, D] time-major) into the first
    ``n_pages`` pool pages listed in ``pages`` ([T/page] int32, padded).

    The paged counterpart of ``insert``: page j gets positions
    [j*page, (j+1)*page); the last valid page's tail rows beyond the true
    prompt length are garbage that every read path masks by length (same
    invariant as bucket padding in the slot cache).  Pages listed beyond
    ``n_pages`` are never touched — the engine only allocates what the
    prompt needs."""
    page = cache.page
    int4 = cache.kv_bits == 4
    rows = page // 2 if int4 else page
    kt = pad_heads(jnp.swapaxes(k_new, 2, 3), cache.k.shape[-1])
    vt = pad_heads(jnp.swapaxes(v_new, 2, 3), cache.v.shape[-1])
    quantized = cache.quantized
    if quantized:
        from arks_tpu.ops.pallas_attention import quantize_kv
        qm = 7 if int4 else 127
        kt, ks = quantize_kv(kt, qmax=qm)   # int8 + [L, 1, Hkv, T] f32
        vt, vs = quantize_kv(vt, qmax=qm)
        if int4:
            from arks_tpu.ops.paged_attention import pack_int4
            kt = pack_int4(kt, axis=3)
            vt = pack_int4(vt, axis=3)
    else:
        kt = kt.astype(cache.k.dtype)
        vt = vt.astype(cache.v.dtype)

    def body(j, c):
        kc, vc, ksc, vsc = c
        pg = pages[j]
        kb = jax.lax.dynamic_slice(
            kt, (0, 0, 0, j * rows, 0), kt.shape[:3] + (rows, kt.shape[4]))
        vb = jax.lax.dynamic_slice(
            vt, (0, 0, 0, j * rows, 0), vt.shape[:3] + (rows, vt.shape[4]))
        at = (0, pg, 0, 0, 0)
        kc = jax.lax.dynamic_update_slice(kc, kb, at)
        vc = jax.lax.dynamic_update_slice(vc, vb, at)
        if quantized:
            ksb = jax.lax.dynamic_slice(
                ks, (0, 0, 0, j * page), ks.shape[:3] + (page,))
            vsb = jax.lax.dynamic_slice(
                vs, (0, 0, 0, j * page), vs.shape[:3] + (page,))
            ksc = jax.lax.dynamic_update_slice(ksc, ksb, at[:-1])
            vsc = jax.lax.dynamic_update_slice(vsc, vsb, at[:-1])
        return (kc, vc, ksc, vsc)

    kc, vc, ksc, vsc = jax.lax.fori_loop(
        0, n_pages.astype(jnp.int32),
        body, (cache.k, cache.v, cache.k_scale, cache.v_scale))
    return PagedKVCache(k=kc, v=vc, k_scale=ksc, v_scale=vsc)


def insert_batch(cache: KVCache, k_new: jnp.ndarray, v_new: jnp.ndarray,
                 slots: jnp.ndarray) -> KVCache:
    """Insert M prompts' prefill KV ([L, M, T, Hkv, D] time-major) into M
    slots — the batched-admission counterpart of ``insert`` (M is small
    and static, so the per-slot writes unroll)."""
    m = k_new.shape[1]
    kt = pad_heads(jnp.swapaxes(k_new, 2, 3), cache.k.shape[-1])
    vt = pad_heads(jnp.swapaxes(v_new, 2, 3), cache.v.shape[-1])
    if cache.quantized:
        from arks_tpu.ops.pallas_attention import quantize_kv
        kt, ksn = quantize_kv(kt)
        vt, vsn = quantize_kv(vt)
    else:
        kt = kt.astype(cache.k.dtype)
        vt = vt.astype(cache.v.dtype)
    kc, vc, ksc, vsc = cache.k, cache.v, cache.k_scale, cache.v_scale
    for i in range(m):
        at = (0, slots[i], 0, 0, 0)
        kc = jax.lax.dynamic_update_slice(
            kc, jax.lax.dynamic_slice_in_dim(kt, i, 1, axis=1), at)
        vc = jax.lax.dynamic_update_slice(
            vc, jax.lax.dynamic_slice_in_dim(vt, i, 1, axis=1), at)
        if cache.quantized:
            ksc = jax.lax.dynamic_update_slice(
                ksc, jax.lax.dynamic_slice_in_dim(ksn, i, 1, axis=1), at[:-1])
            vsc = jax.lax.dynamic_update_slice(
                vsc, jax.lax.dynamic_slice_in_dim(vsn, i, 1, axis=1), at[:-1])
    return KVCache(k=kc, v=vc, k_scale=ksc, v_scale=vsc)


def insert_pages_batch(cache: PagedKVCache, k_new: jnp.ndarray,
                       v_new: jnp.ndarray, pages: jnp.ndarray,
                       n_pages: jnp.ndarray) -> PagedKVCache:
    """Batched ``insert_pages``: M prompts ([L, M, T, Hkv, D], T a page
    multiple) into their page lists ([M, T/page] int32, first n_pages[i]
    valid per prompt)."""
    page = cache.page
    int4 = cache.kv_bits == 4
    rows = page // 2 if int4 else page
    m = k_new.shape[1]
    kt = pad_heads(jnp.swapaxes(k_new, 2, 3), cache.k.shape[-1])
    vt = pad_heads(jnp.swapaxes(v_new, 2, 3), cache.v.shape[-1])
    quantized = cache.quantized
    if quantized:
        from arks_tpu.ops.pallas_attention import quantize_kv
        qm = 7 if int4 else 127
        kt, ksn = quantize_kv(kt, qmax=qm)
        vt, vsn = quantize_kv(vt, qmax=qm)
        if int4:
            from arks_tpu.ops.paged_attention import pack_int4
            kt = pack_int4(kt, axis=3)
            vt = pack_int4(vt, axis=3)
    else:
        kt = kt.astype(cache.k.dtype)
        vt = vt.astype(cache.v.dtype)
    kc, vc, ksc, vsc = cache.k, cache.v, cache.k_scale, cache.v_scale

    for i in range(m):
        kti = jax.lax.dynamic_slice_in_dim(kt, i, 1, axis=1)  # [L,1,Hkv,T,D]
        vti = jax.lax.dynamic_slice_in_dim(vt, i, 1, axis=1)
        if quantized:
            ksi = jax.lax.dynamic_slice_in_dim(ksn, i, 1, axis=1)
            vsi = jax.lax.dynamic_slice_in_dim(vsn, i, 1, axis=1)

        def body(j, c, i=i, kti=kti, vti=vti,
                 ksi=ksi if quantized else None,
                 vsi=vsi if quantized else None):
            kc, vc, ksc, vsc = c
            pg = pages[i, j]
            at = (0, pg, 0, 0, 0)
            kb = jax.lax.dynamic_slice(
                kti, (0, 0, 0, j * rows, 0),
                kti.shape[:3] + (rows, kti.shape[4]))
            vb = jax.lax.dynamic_slice(
                vti, (0, 0, 0, j * rows, 0),
                vti.shape[:3] + (rows, vti.shape[4]))
            kc = jax.lax.dynamic_update_slice(kc, kb, at)
            vc = jax.lax.dynamic_update_slice(vc, vb, at)
            if quantized:
                ksb = jax.lax.dynamic_slice(
                    ksi, (0, 0, 0, j * page), ksi.shape[:3] + (page,))
                vsb = jax.lax.dynamic_slice(
                    vsi, (0, 0, 0, j * page), vsi.shape[:3] + (page,))
                ksc = jax.lax.dynamic_update_slice(ksc, ksb, at[:-1])
                vsc = jax.lax.dynamic_update_slice(vsc, vsb, at[:-1])
            return (kc, vc, ksc, vsc)

        kc, vc, ksc, vsc = jax.lax.fori_loop(
            0, n_pages[i].astype(jnp.int32), body, (kc, vc, ksc, vsc))
    return PagedKVCache(k=kc, v=vc, k_scale=ksc, v_scale=vsc)


def gather_pool_pages(cache: PagedKVCache, pages: jnp.ndarray):
    """Whole pool pages as contiguous pool-NATIVE staging blocks for the
    host prefix tier's spill path: ``(k, v, k_scale, v_scale)``, each
    ``[L, G, Hkv, P, D]`` (scales ``[L, G, Hkv, P]``; None when the pool
    is not kv-quantized).  Raw pool bytes — int8 stays int8 — so a later
    scatter_pool_pages restore reproduces the device state bit-exactly."""
    from arks_tpu.ops.paged_attention import paged_pool_gather
    k = paged_pool_gather(cache.k, pages)
    v = paged_pool_gather(cache.v, pages)
    if cache.quantized:
        return (k, v, paged_pool_gather(cache.k_scale, pages),
                paged_pool_gather(cache.v_scale, pages))
    return k, v, None, None


def scatter_pool_pages(cache: PagedKVCache, k_blocks: jnp.ndarray,
                       v_blocks: jnp.ndarray, pages: jnp.ndarray,
                       n_valid: jnp.ndarray, k_scale=None,
                       v_scale=None) -> PagedKVCache:
    """Restore pool-native page blocks (the inverse of gather_pool_pages)
    into the first ``n_valid`` pages listed in ``pages`` — the host
    prefix tier's H2D scatter.  Blocks arrive already in pool layout and
    dtype (incl. kv-quantized int8 + per-token scales), so no transpose
    or re-quantization happens on device: the written pages are byte
    copies of what the original prefill wrote."""
    from arks_tpu.ops.paged_attention import paged_pool_scatter
    kc = paged_pool_scatter(cache.k, k_blocks, pages, n_valid)
    vc = paged_pool_scatter(cache.v, v_blocks, pages, n_valid)
    ksc, vsc = cache.k_scale, cache.v_scale
    if cache.quantized:
        ksc = paged_pool_scatter(ksc, k_scale, pages, n_valid)
        vsc = paged_pool_scatter(vsc, v_scale, pages, n_valid)
    return PagedKVCache(k=kc, v=vc, k_scale=ksc, v_scale=vsc)


def gather_pages(cache: PagedKVCache, tables_row: jnp.ndarray,
                 layer: jnp.ndarray):
    """One slot's cache as contiguous per-layer views: returns
    (k [Hkv, S, D], v, k_scale [Hkv, S] | None, v_scale | None) for
    ``layer``, gathered through the slot's table row ([MaxP] int32).
    Chunked prefill's per-slot attention uses this — a full read of one
    slot's layer cache, which the attention itself would do anyway."""
    from arks_tpu.ops.paged_attention import paged_gather_kv, unpack_int4

    int4 = cache.kv_bits == 4

    def per(pool, unpack=False):
        # One pool-gather implementation (paged_attention.paged_gather_kv);
        # a [1, MaxP] table row is a batch of one.  int4 pools unpack AFTER
        # the gather (only the slot's rows, never the whole pool).
        g = paged_gather_kv(pool, tables_row[None], layer)[0]
        return unpack_int4(g, axis=1) if unpack else g

    k = per(cache.k, int4)
    v = per(cache.v, int4)
    if cache.quantized:
        return k, v, per(cache.k_scale), per(cache.v_scale)
    return k, v, None, None


def prefill_chunk_paged(
    params: Params,
    cfg: ModelConfig,
    cache: PagedKVCache,
    tables_row: jnp.ndarray,  # [MaxP] int32 — the slot's block table
    tokens: jnp.ndarray,      # [C] int32 — chunk tokens (C == cache.page)
    start: jnp.ndarray,       # () int32 — global position of tokens[0]
    valid: jnp.ndarray,       # () int32 — true token count (<= C)
    mesh: Mesh | None = None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """Chunked prefill against the paged pool: chunk == page, so each chunk
    fills exactly the page ``tables_row[start / page]`` (one dynamic-slice
    write, no scatter), and attention reads the slot's pages — including
    PREFIX pages other slots share, which is how a prefix hit skips its
    recompute without any KV copy."""
    c = tokens.shape[0]
    page = cache.page
    if c != page:
        raise ValueError(f"paged chunk size {c} must equal the page size {page}")
    positions = (start + jnp.arange(c, dtype=jnp.int32))[None]  # [1, C]
    h = embed_lookup(params["embed"], tokens[None],
                     params["layers"]["attn_norm"].dtype)       # [1, C, E]
    quantized = cache.quantized
    pg = jax.lax.dynamic_index_in_dim(
        tables_row, start.astype(jnp.int32) // page, 0, keepdims=False)

    def body(carry, xs):
        h, kc, vc, ksc, vsc = carry
        lp, layer = xs
        q, k, v = _block_qkv(h, lp, cfg, positions)

        kt = pad_heads(jnp.swapaxes(k[0], 0, 1), kc.shape[-1])
        vt = pad_heads(jnp.swapaxes(v[0], 0, 1), kc.shape[-1])
        at = (layer, pg.astype(jnp.int32), 0, 0, 0)
        if quantized:
            from arks_tpu.ops.pallas_attention import quantize_kv
            int4 = kc.shape[3] != ksc.shape[3]
            qm = 7 if int4 else 127
            kq, ks = quantize_kv(kt, qmax=qm)
            vq, vs = quantize_kv(vt, qmax=qm)
            if int4:
                from arks_tpu.ops.paged_attention import pack_int4
                kq = pack_int4(kq, axis=1)
                vq = pack_int4(vq, axis=1)
            kc = jax.lax.dynamic_update_slice(kc, kq[None, None], at)
            vc = jax.lax.dynamic_update_slice(vc, vq[None, None], at)
            ksc = jax.lax.dynamic_update_slice(ksc, ks[None, None], at[:-1])
            vsc = jax.lax.dynamic_update_slice(vsc, vs[None, None], at[:-1])
        else:
            kc = jax.lax.dynamic_update_slice(kc, kt[None, None].astype(kc.dtype), at)
            vc = jax.lax.dynamic_update_slice(vc, vt[None, None].astype(vc.dtype), at)

        kc_s, vc_s, ks_s, vs_s = gather_pages(
            PagedKVCache(k=kc, v=vc, k_scale=ksc, v_scale=vsc),
            tables_row, layer)
        g = cfg.num_heads // cfg.num_kv_heads
        qg = jnp.transpose(
            q[0].reshape(c, cfg.num_kv_heads, g, cfg.head_dim), (1, 2, 0, 3))
        d_store = kc.shape[-1]
        if d_store != cfg.head_dim:
            # Lane-padded cache: pad q (prescaled so the op's 1/sqrt(stored
            # d) nets to 1/sqrt(head_dim)); the padded V columns slice off.
            qg = pad_heads(qg, d_store) * ((d_store / cfg.head_dim) ** 0.5)
        from arks_tpu.ops.attention import chunk_attention_xla
        attn = chunk_attention_xla(qg, kc_s, vc_s, start, ks_s, vs_s)
        attn = jnp.transpose(attn[..., : cfg.head_dim],
                             (2, 0, 1, 3)).reshape(1, c, cfg.q_dim)
        attn = _constrain(attn, mesh, None, None, AXIS_MODEL)
        h = _block_tail(h, attn, lp, cfg, mesh, None)
        return (h, kc, vc, ksc, vsc), None

    (h, kc, vc, ksc, vsc), _ = jax.lax.scan(
        body, (h, cache.k, cache.v, cache.k_scale, cache.v_scale),
        (params["layers"], jnp.arange(cfg.num_layers, dtype=jnp.int32)))
    h_last = jax.lax.dynamic_index_in_dim(h[0], valid - 1, 0, keepdims=True)
    logits = _unembed(h_last, params, cfg, mesh, None)
    return logits, PagedKVCache(k=kc, v=vc, k_scale=ksc, v_scale=vsc)


def mixed_step(
    params: Params,
    cfg: ModelConfig,
    cache: PagedKVCache,
    tables: jnp.ndarray,       # [B, MaxP] int32 — lane b == slot b
    tokens: jnp.ndarray,       # [T] int32 flat mixed token batch
    token_slot: jnp.ndarray,   # [T] int32 slot per token (-1 = padding)
    token_pos: jnp.ndarray,    # [T] int32 global position per token
    sample_src: jnp.ndarray,   # [B] int32 — flat index each lane samples from
    seq_q_start: jnp.ndarray,  # [B] int32 — lane's first flat-token index
    seq_q_len: jnp.ndarray,    # [B] int32 — lane's token count (0 inactive)
    seq_pos_start: jnp.ndarray,  # [B] int32 — lane's first global position
    mesh: Mesh | None = None,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """One unified mixed prefill+decode forward: a flat ``[T]`` token batch
    carrying every decoding slot's next token PLUS one or more sequences'
    prefill-chunk tokens runs the model ONCE, writing all KV rows into the
    paged pool in place (write-then-attend, causal within each chunk) and
    returning logits only at ``sample_src`` — the last valid position of
    each lane that samples this step (decode lanes, and prefill lanes that
    just finished their prompt).  Returns (logits [B, V] f32, cache).

    This is the single-dispatch continuous-batching step: it replaces the
    chunk_step × decode_loop (× bucketed admit) program family for paged
    engines, so N prefills make progress per scheduler iteration without
    stalling decode.  Padding tokens (token_slot < 0) drop their writes and
    attend nothing; their activations are garbage no sample_src points at.
    Numerically equivalent to the legacy paths (same math, blockwise — only
    fp reassociation differs across chunk boundaries)."""
    t_flat = tokens.shape[0]
    cover = tables.shape[1] * cache.page
    # RoPE positions must be real for valid tokens; padding rows only need
    # a value the cache ops drop (their write_idx is routed past coverage).
    rope_pos = jnp.minimum(token_pos, cover - 1)[None]           # [1, T]
    h = embed_lookup(params["embed"], tokens[None],
                     params["layers"]["attn_norm"].dtype)        # [1, T, E]
    kv_sharded = mesh is not None and shard_kv_heads(
        cfg, mesh.shape.get(AXIS_MODEL, 1))
    from arks_tpu.ops.attention import paged_mixed_update_and_attend

    def body(carry, xs):
        h, kc, vc, ksc, vsc = carry
        lp, layer = xs
        q, k, v = _block_qkv(h, lp, cfg, rope_pos)   # [1, T, H(.kv), D]
        attn, kc, vc, ksc, vsc = paged_mixed_update_and_attend(
            q[0], k[0], v[0], kc, vc, tables, token_slot, token_pos,
            seq_q_start, seq_q_len, seq_pos_start, layer, mesh, kv_sharded,
            model_axis=AXIS_MODEL, k_scale=ksc, v_scale=vsc)
        attn = attn.reshape(1, t_flat, cfg.q_dim)
        attn = _constrain(attn, mesh, None, None, AXIS_MODEL)
        h = _block_tail(h, attn, lp, cfg, mesh, None)
        return (h, kc, vc, ksc, vsc), None

    (h, kc, vc, ksc, vsc), _ = jax.lax.scan(
        body, (h, cache.k, cache.v, cache.k_scale, cache.v_scale),
        (params["layers"], jnp.arange(cfg.num_layers, dtype=jnp.int32)))
    h_sel = jnp.take(h[0], sample_src.astype(jnp.int32), axis=0)  # [B, E]
    logits = _unembed(h_sel, params, cfg, mesh, None)
    return logits, PagedKVCache(k=kc, v=vc, k_scale=ksc, v_scale=vsc)


def extract(cache: KVCache, slot: jnp.ndarray,
            dtype: jnp.dtype | None = None) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Read one slot's KV back out time-major ``[L, 1, S, Hkv, D]`` — the
    inverse of ``insert`` (dequantized for int8 caches; re-inserting
    round-trips exactly because quantize(dequantize(x)) reproduces the same
    int8 values and scales).  Serves the prefix cache's harvest of
    chunk-prefilled prompts, whose KV exists only inside the slotted cache.
    """
    k = jax.lax.dynamic_index_in_dim(cache.k, slot, 1, keepdims=True)
    v = jax.lax.dynamic_index_in_dim(cache.v, slot, 1, keepdims=True)
    if cache.quantized:
        ks = jax.lax.dynamic_index_in_dim(cache.k_scale, slot, 1, keepdims=True)
        vs = jax.lax.dynamic_index_in_dim(cache.v_scale, slot, 1, keepdims=True)
        out = dtype or jnp.bfloat16
        k = (k.astype(jnp.float32) * ks[..., None]).astype(out)
        v = (v.astype(jnp.float32) * vs[..., None]).astype(out)
    elif dtype is not None:
        k = k.astype(dtype)
        v = v.astype(dtype)
    return jnp.swapaxes(k, 2, 3), jnp.swapaxes(v, 2, 3)


def decode_step(
    params: Params,
    cfg: ModelConfig,
    cache: KVCache | PagedKVCache,
    tokens: jnp.ndarray,   # [B] int32 — current token per slot
    lengths: jnp.ndarray,  # [B] int32 — tokens already in cache per slot
    mesh: Mesh | None = None,
    batch_axis: str | None = None,
    tables: jnp.ndarray | None = None,  # [B, MaxP] int32 — PagedKVCache only
) -> tuple[jnp.ndarray, KVCache | PagedKVCache]:
    """Advance every slot one token. The current token's KV is written at
    position ``lengths`` (so the new valid length is lengths+1). Returns
    (logits [B, V] float32, updated cache).

    PRECONDITION (slot cache): lengths[b] < cache.max_len for every active
    slot.  At lengths == max_len the KV scatter is silently dropped (JAX
    out-of-bounds scatter semantics) and logits would be computed against
    stale cache — the engine must retire or evict a slot before it fills.
    Paged caches take ``tables`` and use lengths >= coverage as the
    inactive-slot sentinel (write dropped, nothing attended)."""
    b = tokens.shape[0]
    h = embed_lookup(params["embed"], tokens,
                     params["layers"]["attn_norm"].dtype)  # [B, E]
    h = _constrain(h, mesh, batch_axis, None)
    write_idx = lengths.astype(jnp.int32)
    kv_sharded = mesh is not None and shard_kv_heads(cfg, mesh.shape.get(AXIS_MODEL, 1))
    paged = isinstance(cache, PagedKVCache)
    if paged and tables is None:
        raise ValueError("decode_step with a PagedKVCache requires tables")
    if paged:
        # RoPE positions must be real for active slots; the sentinel value
        # (>= coverage) only matters to the cache ops, which drop it.
        cover = tables.shape[1] * cache.page
        rope_idx = jnp.minimum(write_idx, cover - 1)
    else:
        rope_idx = write_idx

    # The FULL cache rides the scan carry and each layer updates its own
    # rows in place (decode_update_and_attend).  Scanning over the cache as
    # xs/ys instead would make XLA slice + re-stack the whole cache every
    # step — ~2x the model's entire HBM traffic.
    def body(carry, xs):
        h, kc, vc, ksc, vsc = carry
        lp, layer = xs
        x = rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(x, lp, cfg)
        q = q.reshape(b, cfg.num_heads, cfg.head_dim)
        k = k.reshape(b, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(b, cfg.num_kv_heads, cfg.head_dim)
        q = apply_rope(q, rope_idx, cfg.rope_theta)
        k = apply_rope(k, rope_idx, cfg.rope_theta)
        if paged:
            from arks_tpu.ops.attention import paged_decode_update_and_attend
            attn, kc, vc, ksc, vsc = paged_decode_update_and_attend(
                q, k, v, kc, vc, tables, write_idx, layer, mesh, kv_sharded,
                model_axis=AXIS_MODEL, k_scale=ksc, v_scale=vsc)
        else:
            attn, kc, vc, ksc, vsc = decode_update_and_attend(
                q, k, v, kc, vc, write_idx, layer, mesh, batch_axis,
                kv_sharded, model_axis=AXIS_MODEL, k_scale=ksc, v_scale=vsc)
        attn = attn.reshape(b, cfg.q_dim)
        attn = _constrain(attn, mesh, batch_axis, AXIS_MODEL)
        h = h + qeinsum("bq,qe->be", attn, lp["wo"])
        h = h + _mlp(h, lp, cfg, mesh, batch_axis)
        return (h, kc, vc, ksc, vsc), None

    (h, ks, vs, kss, vss), _ = jax.lax.scan(
        body, (h, cache.k, cache.v, cache.k_scale, cache.v_scale),
        (params["layers"], jnp.arange(cfg.num_layers, dtype=jnp.int32)))
    logits = _unembed(h, params, cfg, mesh, batch_axis)
    cls = PagedKVCache if paged else KVCache
    return logits, cls(k=ks, v=vs, k_scale=kss, v_scale=vss)


class DecodeState(NamedTuple):
    """Device-resident decode state for the pipelined dispatch path: the
    arrays the NEXT decode dispatch consumes from the PREVIOUS one without
    a host round-trip (engine ARKS_PIPELINE_DEPTH).  Host mirrors lag by
    the in-flight depth; dead slots self-mask (pad token, writes dropped
    at the sentinel) until the host retires them at resolve time."""

    tokens: jnp.ndarray   # [B] i32 — last sampled token (0 for dead slots)
    lengths: jnp.ndarray  # [B] i32 — absolute lengths (only alive slots'
                          # values are meaningful; dead/free rows keep
                          # advancing harmlessly, masked by ``alive``)
    alive: jnp.ndarray    # [B] bool — device-computed liveness


def decode_state_step(
    params: Params,
    cfg: ModelConfig,
    cache: KVCache | PagedKVCache,
    tokens: jnp.ndarray,    # [B] i32
    lengths: jnp.ndarray,   # [B] i32 — true lengths for alive slots
    alive: jnp.ndarray,     # [B] bool
    sentinel: int,          # engine's write-drop length (park value)
    mesh: Mesh | None = None,
    batch_axis: str | None = None,
    tables: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, KVCache | PagedKVCache]:
    """Liveness-masked ``decode_step`` for device-state decoding: dead
    slots read/write at the engine's park sentinel, so their KV scatters
    drop and nothing is attended — identical math to a host that had
    already parked the slot's length, which is what keeps the pipelined
    token stream byte-identical to the sequential path for live slots."""
    eff = jnp.where(alive, lengths, jnp.int32(sentinel))
    return decode_step(params, cfg, cache, tokens, eff, mesh, batch_axis,
                       tables=tables)


def verify_step(
    params: Params,
    cfg: ModelConfig,
    cache: KVCache | PagedKVCache,
    tokens: jnp.ndarray,   # [B, K] int32 — K tokens per slot (t0 + drafts)
    lengths: jnp.ndarray,  # [B] int32 — tokens already in cache per slot
    mesh: Mesh | None = None,
    batch_axis: str | None = None,
    tables: jnp.ndarray | None = None,  # [B, MaxP] int32 — PagedKVCache only
) -> tuple[jnp.ndarray, KVCache | PagedKVCache]:
    """Multi-token decode: advance every slot K tokens in ONE pass.

    A general batched multi-token scorer and the REFERENCE oracle for
    speculative verify (the serving path now expresses verify blocks as
    ragged q_len=K rows of ``mixed_step`` — one dispatch per iteration
    carries decode feeds, prefill chunks, AND spec verify; the parity
    between the two is closed in tests/test_paged_attention.py).  Token k
    of slot b sits at position lengths[b]+k, its KV is written there, and
    it attends the cache prefix plus the earlier tokens of its own block
    (causal).  Returns (logits [B, K, V] f32, cache).
    Rows written for later-rejected draft tokens become garbage beyond the
    accepted length — every read path masks by position, and the next
    dispatch overwrites them (the same invariant as decode_step's padding
    writes).

    Paged caches take ``tables``; ``lengths >= coverage`` is the inactive-
    slot sentinel (block writes dropped, nothing attended), exactly as in
    ``decode_step``.  A verify block may cross a page boundary — the paged
    update routes each row through the table independently."""
    b, kk = tokens.shape
    h = embed_lookup(params["embed"], tokens,
                     params["layers"]["attn_norm"].dtype)      # [B, K, E]
    h = _constrain(h, mesh, batch_axis, None, None)
    positions = lengths[:, None] + jnp.arange(kk, dtype=jnp.int32)  # [B, K]
    kv_sharded = mesh is not None and shard_kv_heads(cfg, mesh.shape.get(AXIS_MODEL, 1))
    paged = isinstance(cache, PagedKVCache)
    if paged and tables is None:
        raise ValueError("verify_step with a PagedKVCache requires tables")
    if paged:
        # RoPE positions must be real for active slots; the sentinel value
        # (>= coverage) only matters to the cache ops, which drop it.
        cover = tables.shape[1] * cache.page
        rope_pos = jnp.minimum(positions, cover - 1)
    else:
        rope_pos = positions
    from arks_tpu.ops.attention import (
        paged_verify_update_and_attend, verify_update_and_attend)

    def body(carry, xs):
        h, kc, vc, ksc, vsc = carry
        lp, layer = xs
        q, k, v = _block_qkv(h, lp, cfg, rope_pos)   # [B, K, H(.kv), D]
        if paged:
            attn, kc, vc, ksc, vsc = paged_verify_update_and_attend(
                q, k, v, kc, vc, tables, positions, layer, mesh, kv_sharded,
                model_axis=AXIS_MODEL, k_scale=ksc, v_scale=vsc)
        else:
            attn, kc, vc, ksc, vsc = verify_update_and_attend(
                q, k, v, kc, vc, positions, lengths, layer, mesh, batch_axis,
                kv_sharded, model_axis=AXIS_MODEL, k_scale=ksc, v_scale=vsc)
        attn = attn.reshape(b, kk, cfg.q_dim)
        attn = _constrain(attn, mesh, batch_axis, None, AXIS_MODEL)
        h = _block_tail(h, attn, lp, cfg, mesh, batch_axis)
        return (h, kc, vc, ksc, vsc), None

    (h, kc, vc, ksc, vsc), _ = jax.lax.scan(
        body, (h, cache.k, cache.v, cache.k_scale, cache.v_scale),
        (params["layers"], jnp.arange(cfg.num_layers, dtype=jnp.int32)))
    # unembed_logits is 2D-shaped; fold K into the batch for the vocab dot.
    logits = _unembed(h.reshape(b * kk, -1), params, cfg, mesh,
                      batch_axis).reshape(b, kk, -1)
    cls = PagedKVCache if paged else KVCache
    return logits, cls(k=kc, v=vc, k_scale=ksc, v_scale=vsc)


# ---------------------------------------------------------------------------
# Jit wrappers
# ---------------------------------------------------------------------------


def make_prefill_fn(cfg: ModelConfig, mesh: Mesh | None = None):
    fn = functools.partial(prefill, cfg=cfg, mesh=mesh)
    return jax.jit(lambda params, tokens, lengths: fn(params, tokens=tokens, lengths=lengths))


def make_decode_fn(cfg: ModelConfig, mesh: Mesh | None = None,
                   batch_axis: str | None = None):
    fn = functools.partial(decode_step, cfg=cfg, mesh=mesh, batch_axis=batch_axis)
    return jax.jit(
        lambda params, cache, tokens, lengths: fn(params, cache=cache, tokens=tokens, lengths=lengths),
        donate_argnums=(1,),
    )


def make_insert_fn(cfg: ModelConfig, mesh: Mesh | None = None):
    del cfg, mesh
    return jax.jit(insert, donate_argnums=(0,))
