"""Tenant identity, fair-share weights, and bounded tenant metric labels.

The gateway already knows WHO a request belongs to (TokenQos carries the
namespace and username the Bearer token resolved to), but until the
tenant-fair admission work that identity died at the gateway: the engine
queue was tenant-blind, so one key's burst starved every other key in
the same SLO tier.  This module is the shared, jax-free vocabulary the
whole path speaks:

- ``HDR_TENANT`` — the ``x-arks-tenant`` header the gateway mints from
  ``TokenQos.namespace/username``, the router forwards verbatim, and the
  OpenAI server maps onto ``Request.tenant``.  Requests arriving without
  it (direct-to-pod clients, tests) fall into ``DEFAULT_TENANT`` — with
  a single tenant the weighted-fair queue degenerates to exactly the old
  tier-FIFO order, so nothing changes for untenanted deployments.
- ``ARKS_FAIR_WEIGHTS`` — ``tenant:weight`` pairs giving a tenant a
  larger (or smaller) share of each admission round; unlisted tenants
  weigh 1.  The same weights drive the engine's deficit round-robin and
  the gateway's edge shedding (most-over-share tenant rejected first).
- ``TenantLabels`` — the metric-label cardinality bound: tenant ids are
  unbounded user input (key churn mints new namespace/username pairs
  forever), so the first ``ARKS_TENANT_LABEL_MAX`` distinct tenants keep
  their own label and everyone later lands in ``OTHER_LABEL``.  Counters
  stay accurate in aggregate; dashboards stay scrapeable.

Deliberately import-light (stdlib + knobs only): the router and gateway
read this without dragging in JAX, same rule as ``arks_tpu.slo``.
"""

from __future__ import annotations

import threading

from arks_tpu.utils import knobs

HDR_TENANT = "x-arks-tenant"
# Queue-saturation signal (0.00-1.00 of ARKS_QUEUE_MAX, "inf"-safe):
# rides /readiness and shed (429/503) responses so edges can back off
# BEFORE the engine queue absorbs a flood.
HDR_SATURATION = "x-arks-saturation"

DEFAULT_TENANT = "default"
OTHER_LABEL = "other"

WEIGHTS_ENV = "ARKS_FAIR_WEIGHTS"


def tenant_id(namespace: str, username: str) -> str:
    """The canonical tenant identity: one billing principal, matching the
    rate-limit/quota key granularity the gateway already enforces."""
    return f"{namespace}/{username}"


def parse_weights(spec: str) -> dict[str, float]:
    """Parse ``tenant:weight,...``.  Raises ValueError on malformed
    entries or non-positive weights (weight 0 would starve the tenant
    forever — use quota, not fairness, to cut someone off)."""
    weights: dict[str, float] = {}
    for entry in (s for s in spec.split(",") if s.strip()):
        name, sep, val = entry.strip().rpartition(":")
        if not sep or not name:
            raise ValueError(
                f"{WEIGHTS_ENV}: bad entry {entry!r} (want tenant:weight)")
        try:
            w = float(val)
        except ValueError:
            raise ValueError(
                f"{WEIGHTS_ENV}: non-numeric weight in {entry!r}") from None
        if w <= 0:
            raise ValueError(
                f"{WEIGHTS_ENV}: weight must be > 0 in {entry!r}")
        weights[name] = w
    return weights


def weights_from_env() -> dict[str, float]:
    spec = knobs.get_str(WEIGHTS_ENV, fallback="") or ""
    return parse_weights(spec) if spec.strip() else {}


def weight_of(weights: dict[str, float], tenant: str) -> float:
    return weights.get(tenant, 1.0)


class TenantLabels:
    """First-K-tenants bounded label mapper (thread-safe).  The K slots
    go to the first K distinct tenants seen — under normal operation the
    stable, high-volume tenants — and every later arrival shares the
    ``other`` bucket, so hostile key churn cannot mint unbounded metric
    series.  ``tests/test_metrics_conformance.py`` enforces the bound."""

    def __init__(self, cap: int | None = None) -> None:
        if cap is None:
            cap = knobs.get_int("ARKS_TENANT_LABEL_MAX")
        if cap < 1:
            raise ValueError(
                f"ARKS_TENANT_LABEL_MAX={cap}: must be >= 1")
        self.cap = cap
        self._lock = threading.Lock()
        self._known: set[str] = set()

    def label(self, tenant: str | None) -> str:
        t = tenant or DEFAULT_TENANT
        with self._lock:
            if t in self._known:
                return t
            if len(self._known) < self.cap:
                self._known.add(t)
                return t
        return OTHER_LABEL
