from arks_tpu.train.sft import TrainState, make_train_step, train_init

__all__ = ["TrainState", "make_train_step", "train_init"]
