from arks_tpu.train.checkpoint import (
    make_manager, restore_train_state, save_train_state)
from arks_tpu.train.data import PackedDataset, prefetch, read_jsonl
from arks_tpu.train.sft import TrainState, make_train_step, train_init

__all__ = [
    "PackedDataset", "TrainState", "make_manager", "make_train_step",
    "prefetch", "read_jsonl", "restore_train_state", "save_train_state",
    "train_init",
]
