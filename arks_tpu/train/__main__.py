"""Runnable SFT/fine-tune trainer: data pipeline + sharded step + resume.

    python -m arks_tpu.train --model tiny --data corpus.jsonl \
        --seq-len 512 --batch-size 32 --steps 1000 \
        --ckpt-dir /tmp/run1 [--tensor-parallel 4 --data-parallel 2]

Ties the training subsystem together end to end: PackedDataset
(tokenize/pack/shard/prefetch — train/data.py), the sharded train step
(train/sft.py), and Orbax checkpoint/resume (train/checkpoint.py).
Restarting with the same --ckpt-dir resumes from the latest step,
bit-identical to an uninterrupted run (the data pipeline's deterministic
(seed, shard, epoch) streams make the replay line up).

The reference is inference-only; this is the TPU build's additive
training surface, sharing the serving model's layer body (sft.py) so the
two can never drift.
"""

from __future__ import annotations

import argparse
import logging
import os
import time

log = logging.getLogger("arks_tpu.train")


def main() -> None:
    p = argparse.ArgumentParser("arks_tpu.train")
    p.add_argument("--model", required=True,
                   help="model config name (arks_tpu.models)")
    p.add_argument("--model-path", default=None,
                   help="init from weights/tokenizer dir (default: random "
                        "init + byte tokenizer)")
    p.add_argument("--data", required=True, action="append",
                   help="jsonl file(s): {'text'} or {'prompt','completion'}")
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=8,
                   help="GLOBAL batch per step (split over data parallel)")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=1e-5)
    p.add_argument("--weight-decay", type=float, default=0.01)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tensor-parallel", "--tp", type=int, default=None,
                   dest="tp")
    p.add_argument("--data-parallel", "--dp", type=int, default=1, dest="dp")
    p.add_argument("--ckpt-dir", default=None,
                   help="checkpoint directory (enables save + resume)")
    p.add_argument("--ckpt-every", type=int, default=100)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--platform", default=None,
                   help="force a jax platform (cpu for tests)")
    args = p.parse_args()
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    import optax

    from arks_tpu.engine.tokenizer import ByteTokenizer, load_tokenizer
    from arks_tpu.models import get_config
    from arks_tpu.train.checkpoint import (
        make_manager, restore_train_state, save_train_state)
    from arks_tpu.train.data import PackedDataset, prefetch, read_jsonl
    from arks_tpu.train.sft import make_train_step, train_init

    cfg = get_config(args.model)
    tokenizer = (load_tokenizer(args.model_path)
                 if args.model_path else ByteTokenizer())

    n_dev = len(jax.devices())
    tp = args.tp or max(n_dev // args.dp, 1)
    mesh = None
    if tp * args.dp > 1:
        from arks_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(tensor_parallel=tp, data_parallel=args.dp,
                         devices=jax.devices()[: tp * args.dp])
    if args.batch_size % args.dp:
        raise SystemExit(f"--batch-size {args.batch_size} must divide by "
                         f"--data-parallel {args.dp}")
    if args.ckpt_every < 1 or args.log_every < 1:
        raise SystemExit("--ckpt-every and --log-every must be >= 1")

    optimizer = optax.adamw(args.lr, weight_decay=args.weight_decay)
    step_fn = make_train_step(cfg, optimizer, mesh)

    manager = make_manager(args.ckpt_dir) if args.ckpt_dir else None
    if args.ckpt_dir:
        # Resume fence: the fast-forward replay is only bit-identical when
        # the data-shaping arguments match the original run — a silently
        # different stream would re-train some windows and skip others.
        import json as _json
        shape = {"model": args.model, "data": sorted(args.data),
                 "seq_len": args.seq_len, "batch_size": args.batch_size,
                 "seed": args.seed}
        fence = os.path.join(args.ckpt_dir, "trainer_config.json")
        if os.path.exists(fence):
            with open(fence) as f:
                prev = _json.load(f)
            if prev != shape:
                diff = {k: (prev.get(k), shape[k]) for k in shape
                        if prev.get(k) != shape[k]}
                raise SystemExit(
                    f"--ckpt-dir {args.ckpt_dir} was written with different "
                    f"data-shaping args (stored vs given): {diff} — resume "
                    "would not replay the same stream; use a fresh dir or "
                    "the original arguments")
        else:
            os.makedirs(args.ckpt_dir, exist_ok=True)
            with open(fence, "w") as f:
                _json.dump(shape, f)
    if manager is not None and manager.latest_step() is not None:
        state = restore_train_state(manager, cfg, optimizer, mesh)
        log.info("resumed from step %d (%s)", int(state.step),
                 args.ckpt_dir)
    else:
        if args.model_path:
            from arks_tpu.models.weights import load_params
            params = load_params(cfg, args.model_path, mesh=mesh,
                                 dtype=jnp.float32)
            opt_state = optimizer.init(params)
            from arks_tpu.train.sft import TrainState
            state = TrainState(params=params, opt_state=opt_state,
                               step=jnp.zeros((), jnp.int32))
        else:
            state = train_init(cfg, jax.random.PRNGKey(args.seed),
                               optimizer, mesh)

    records = [r for path in args.data for r in read_jsonl(path)]
    # Single-process: the mesh's dp axis shards the batch on-device, so
    # the dataset itself is unsharded (multi-host would pass
    # process_index/process_count here).
    ds = PackedDataset(records, tokenizer, seq_len=args.seq_len,
                       batch_size=args.batch_size, seed=args.seed)

    start = int(state.step)
    done = start
    bpe = ds.batches_per_epoch(0)
    if bpe == 0:
        raise SystemExit(
            f"corpus too small: {args.data} packs to fewer than "
            f"--batch-size {args.batch_size} windows of --seq-len "
            f"{args.seq_len} — zero steps per epoch")
    # Resume lands mid-epoch: fast-forward the deterministic stream.
    epoch, skip = divmod(start, bpe)
    t0 = time.monotonic()
    while done < args.steps:
        it = prefetch(ds.epoch(epoch))
        for i, batch in enumerate(it):
            if i < skip:
                continue
            state, loss = step_fn(state, jnp.asarray(batch["tokens"]),
                                  jnp.asarray(batch["targets"]),
                                  jnp.asarray(batch["loss_mask"]))
            done += 1
            if done % args.log_every == 0 or done == args.steps:
                dt = time.monotonic() - t0
                toks = (done - start) * args.batch_size * args.seq_len
                log.info("step %d loss %.4f (%.0f tok/s)", done,
                         float(loss), toks / max(dt, 1e-6))
            if manager is not None and done % args.ckpt_every == 0:
                save_train_state(manager, state, wait=False)
            if done >= args.steps:
                break
        epoch += 1
        skip = 0
    if manager is not None:
        save_train_state(manager, state, wait=True)
        log.info("final checkpoint at step %d (%s)", done, args.ckpt_dir)


if __name__ == "__main__":
    main()
