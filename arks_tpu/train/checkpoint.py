"""Training checkpoint/resume: Orbax save/restore of the full TrainState.

The reference is inference-only — its ArksModel pipeline ships SERVING
checkpoints (scripts/download.py; here models/weights.py adds the Orbax
conversion).  Training is this repo's additive capability, and a trainer
without resume isn't one: this module persists the complete state (params
+ optimizer moments + step) with step-numbered retention, sharded-aware
on restore — under a mesh each host reads only the shards it owns, the
same property the serving loader has (models/weights.py:load_orbax).

Restore builds its template ABSTRACTLY (jax.eval_shape — no device
allocation; a materialized template would double peak memory at exactly
the model sizes resume matters for) and takes the checkpoint's own stored
dtype from Orbax metadata, so a bf16 run restores bf16 without the caller
restating it — resume stays bit-identical to an uninterrupted run.
"""

from __future__ import annotations

import functools
import os
from typing import Any

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from arks_tpu.models import transformer as tf
from arks_tpu.train.sft import TrainState, train_init


def make_manager(directory: str, max_to_keep: int = 3):
    """Step-numbered checkpoint directory with bounded retention."""
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))


def save_train_state(manager, state: TrainState, wait: bool = True) -> int:
    """Persist ``state`` under its own step number; returns the step.

    ``wait=False`` lets the write overlap the next training steps
    (CheckpointManager serializes with any subsequent save itself); pass
    True — the default — when durability must be certain on return."""
    import orbax.checkpoint as ocp

    step = int(state.step)
    manager.save(step, args=ocp.args.StandardSave(state))
    if wait:
        manager.wait_until_finished()
    return step


def _stored_dtype(manager, step: int):
    """The checkpoint's own parameter dtype (Orbax metadata) — restoring
    into a template of a DIFFERENT dtype would silently cast the state and
    break bit-identical resume.  None when metadata is unavailable."""
    import logging

    import orbax.checkpoint as ocp

    try:
        meta = ocp.StandardCheckpointer().metadata(
            os.path.join(manager.directory, str(step), "default"))
        tree = getattr(meta.item_metadata, "tree", meta.item_metadata)
        return jax.numpy.dtype(tree["params"]["embed"].dtype)
    except (KeyError, TypeError, AttributeError, FileNotFoundError,
            ValueError) as e:
        # Loud fallback: a silently-wrong template dtype would upcast a
        # bf16 checkpoint and break bit-identical resume — if this fires,
        # pass dtype= explicitly (Orbax metadata layout likely changed).
        logging.getLogger("arks_tpu.train.checkpoint").warning(
            "could not read checkpoint dtype metadata (%s: %s); "
            "defaulting the restore template to float32 — pass dtype= "
            "explicitly if the run used another dtype", type(e).__name__, e)
        return None


def _sharded_template(abstract: TrainState, cfg, mesh) -> TrainState:
    """Attach restore shardings to an abstract state: every params-shaped
    subtree (the params themselves, optimizer moments) shards with the
    trainer's param specs; remaining leaves (step counters, schedule
    state) restore replicated on the mesh."""
    params_treedef = jax.tree.structure(abstract.params)
    pspecs = tf.param_pspecs(cfg, mesh.shape.get(tf.AXIS_MODEL, 1))

    def with_specs(subtree):
        return jax.tree.map(
            lambda s, spec: jax.ShapeDtypeStruct(
                s.shape, s.dtype, sharding=NamedSharding(mesh, spec)),
            subtree, pspecs)

    def walk(node):
        if jax.tree.structure(node) == params_treedef:
            return with_specs(node)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, tuple):
            mapped = [walk(c) for c in node]
            return (type(node)(*mapped) if hasattr(node, "_fields")
                    else tuple(mapped))
        if isinstance(node, list):
            return [walk(c) for c in node]
        # Leaf (ShapeDtypeStruct): replicated — a committed single-device
        # sharding here would conflict with mesh-sharded params inside the
        # jitted train step.
        return jax.ShapeDtypeStruct(node.shape, node.dtype,
                                    sharding=NamedSharding(mesh, P()))

    return walk(abstract)


def restore_train_state(manager, cfg, optimizer, mesh=None,
                        dtype: Any = None, step: int | None = None
                        ) -> TrainState:
    """Restore a TrainState (latest step by default), placed directly onto
    ``mesh`` with the trainer's shardings.  The template's tree structure
    comes from an ABSTRACT ``train_init`` (zero allocation — the optimizer
    state's structure can never drift from what the optimizer builds), its
    dtype from the checkpoint's own metadata (``dtype`` overrides)."""
    import jax.numpy as jnp
    import orbax.checkpoint as ocp

    step = manager.latest_step() if step is None else step
    if step is None:
        raise FileNotFoundError(
            f"no checkpoint steps under {manager.directory}")
    tdtype = (jnp.dtype(dtype) if dtype is not None
              else _stored_dtype(manager, step) or jnp.float32)
    abstract = jax.eval_shape(functools.partial(
        train_init, cfg, jax.random.PRNGKey(0), optimizer, None, tdtype))
    if mesh is not None:
        template = _sharded_template(abstract, cfg, mesh)
    else:
        dev = jax.devices()[0]
        template = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(
                s.shape, s.dtype,
                sharding=jax.sharding.SingleDeviceSharding(dev)),
            abstract)
    return manager.restore(step, args=ocp.args.StandardRestore(template))
