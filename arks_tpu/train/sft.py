"""Minimal SFT/fine-tune step over the serving model.

The reference is inference-only; training is additive capability here, and it
doubles as the multi-chip sharding proof: one jitted step with params sharded
over (data, model), batch over data, gradient psums inserted by XLA.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from arks_tpu.models.config import ModelConfig
from arks_tpu.models import transformer as tf
from arks_tpu.ops.norms import rms_norm


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def head_loss(params, cfg: ModelConfig, h: jnp.ndarray, targets,
              loss_mask) -> jnp.ndarray:
    """Final norm + unembed + masked mean CE on hidden states [B, T, E].

    The single definition of loss semantics — the dense trainer and the
    pipeline-parallel trainer (arks_tpu.parallel.pipeline) both end here, so
    changes (z-loss, label smoothing, denominators) can't diverge."""
    h = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    table = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    logits = jnp.einsum("bte,ev->btv", h, table).astype(jnp.float32)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    denom = jnp.maximum(jnp.sum(loss_mask), 1.0)
    return jnp.sum(ce * loss_mask) / denom


def forward_hidden(params, cfg: ModelConfig, tokens: jnp.ndarray,
                   mesh: Mesh | None = None) -> jnp.ndarray:
    """Pre-final-norm hidden states [B, T, E].

    Shares the layer body with serving prefill (tf.prefill_layer) so training
    and serving can never drift apart; the per-layer K/V outputs are unused
    here and dead-code-eliminated by XLA."""
    b, t = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    h = jnp.take(params["embed"], tokens, axis=0)
    # ("slice", "data") on a multi-slice mesh: the gradient psum then spans
    # DCN once per step (the only slice-crossing collective).
    batch_axis = tf.batch_axis_for(mesh)

    def body(h, lp):
        h, _, _ = tf.prefill_layer(h, lp, cfg, positions, mesh, batch_axis)
        return h, None

    h, _ = jax.lax.scan(body, h, params["layers"])
    return h


def forward_train(params, cfg: ModelConfig, tokens: jnp.ndarray,
                  mesh: Mesh | None = None) -> jnp.ndarray:
    """Full-sequence logits [B, T, V] (float32) for loss computation."""
    h = forward_hidden(params, cfg, tokens, mesh)
    h = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    table = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    return jnp.einsum("bte,ev->btv", h, table).astype(jnp.float32)


def loss_fn(params, cfg: ModelConfig, tokens, targets, loss_mask, mesh=None):
    h = forward_hidden(params, cfg, tokens, mesh)
    return head_loss(params, cfg, h, targets, loss_mask)


def make_step_fn(loss, optimizer: optax.GradientTransformation):
    """value_and_grad + optimizer update around any (params, tokens, targets,
    loss_mask) -> scalar loss.  Shared by the dense and pipeline trainers."""
    def step(state: TrainState, tokens, targets, loss_mask):
        loss_val, grads = jax.value_and_grad(loss)(
            state.params, tokens, targets, loss_mask)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss_val
    return step


def train_init(cfg: ModelConfig, key, optimizer: optax.GradientTransformation,
               mesh: Mesh | None = None, dtype=jnp.float32) -> TrainState:
    params = tf.init_params(cfg, key, dtype)
    if mesh is not None:
        params = tf.shard_params(params, cfg, mesh)
    opt_state = optimizer.init(params)
    return TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))


def make_train_step(cfg: ModelConfig, optimizer: optax.GradientTransformation,
                    mesh: Mesh | None = None):
    step = make_step_fn(
        lambda params, tokens, targets, loss_mask: loss_fn(
            params, cfg, tokens, targets, loss_mask, mesh),
        optimizer)
    if mesh is None:
        return jax.jit(step, donate_argnums=(0,))
    data_spec = NamedSharding(mesh, P(tf.batch_axis_for(mesh), None))
    return jax.jit(step, donate_argnums=(0,),
                   in_shardings=(None, data_spec, data_spec, data_spec))
