"""Training input pipeline: tokenize, pack, shard, prefetch.

The reference is inference-only; the trainer (train/sft.py) is this
repo's additive capability and needs a real data path, not ad-hoc arrays:

- **Packing**: documents are tokenized, joined with EOS separators, and
  cut into fixed-length windows — every position trains (no padding
  waste), the standard pretraining/SFT packing.  Each window yields
  (tokens, targets, loss_mask): targets are tokens shifted left, with
  cross-document lookahead targets masked.
- **SFT masking**: records with a ``prompt``/``completion`` split mask
  the prompt positions so loss lands on completions only.
- **Sharding**: WINDOW-level round robin — every process packs the same
  shuffled stream and takes its ``shard_index``-th stripe, capped at
  ``floor(total_windows / shard_count)`` windows, so every data-parallel
  process yields EXACTLY the same number of batches per epoch.  Unequal
  per-shard batch counts would deadlock the collective train step at the
  epoch tail (one process calls one more psum than its peers).  The cost
  is that each host tokenizes the full corpus; stream-level sharding is
  a future optimization for corpora where that dominates.
- **Determinism**: a seeded shuffle over the document order — the same
  (seed, shard, epoch) always yields the same batch stream, which is
  what makes checkpoint resume (train/checkpoint.py) reproducible end to
  end.
- **Prefetch**: a background thread keeps ``depth`` batches ready so
  host tokenization overlaps device steps; iterator errors re-raise in
  the consumer, and abandoning the generator releases the worker.
"""

from __future__ import annotations

import json
import queue
import random
import threading
from typing import Iterable, Iterator

import numpy as np


def read_jsonl(path: str) -> Iterator[dict]:
    """{"text": ...} or {"prompt": ..., "completion": ...} per line."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)


class PackedDataset:
    """Tokenize + pack documents into fixed-length training windows.

    ``records`` is any iterable of dicts (``read_jsonl`` or an in-memory
    list); it is materialized once so epochs can reshuffle.
    """

    def __init__(self, records: Iterable[dict], tokenizer, seq_len: int,
                 batch_size: int, seed: int = 0,
                 shard_index: int = 0, shard_count: int = 1):
        if not (0 <= shard_index < shard_count):
            raise ValueError(
                f"shard_index={shard_index} outside shard_count={shard_count}")
        self.tokenizer = tokenizer
        self.seq_len = seq_len
        self.batch_size = batch_size
        self.seed = seed
        self.shard_index = shard_index
        self.shard_count = shard_count
        self.records = list(records)
        if not self.records:
            raise ValueError("dataset is empty")
        self.eos = (tokenizer.eos_token_ids or (0,))[0]
        # Tokenize ONCE (order-independent): epochs only reshuffle+repack,
        # so multi-epoch runs and resume fast-forward never re-pay the
        # tokenizer.
        self._docs = [self._doc_tokens(r) for r in self.records]
        self._window_cache: tuple[int, list] | None = None

    def _doc_tokens(self, rec: dict) -> tuple[list[int], list[int]]:
        """(token_ids, loss_mask) for one document, EOS-terminated."""
        if "prompt" in rec:
            p = self.tokenizer.encode(rec["prompt"])
            c = self.tokenizer.encode(rec.get("completion", ""))
            ids = p + c + [self.eos]
            # SFT: loss on completion + EOS only, never on the prompt.
            mask = [0] * len(p) + [1] * (len(c) + 1)
        else:
            ids = self.tokenizer.encode(rec.get("text", "")) + [self.eos]
            mask = [1] * len(ids)
        return ids, mask

    def _windows(self, epoch: int) -> list[tuple[list[int], list[int],
                                                 list[int]]]:
        """All (tokens, targets, loss_mask) windows of the epoch's shuffled
        stream (shard-independent — the basis every shard stripes over)."""
        # One-epoch memo: the trainer's startup batches_per_epoch() and
        # the first epoch() pack the same windows.
        if self._window_cache is not None and \
                self._window_cache[0] == epoch:
            return self._window_cache[1]
        order = list(range(len(self.records)))
        random.Random(f"{self.seed}/{epoch}").shuffle(order)
        t = self.seq_len
        buf_ids: list[int] = []
        buf_mask: list[int] = []
        out = []
        for i in order:
            ids, mask = self._docs[i]
            buf_ids.extend(ids)
            buf_mask.extend(mask)
            while len(buf_ids) > t:  # need t+1 to form targets for t
                window = buf_ids[: t + 1]
                wmask = buf_mask[: t + 1]
                del buf_ids[:t], buf_mask[:t]
                # Loss applies where the TARGET is a trainable position.
                out.append((window[:t], window[1: t + 1], wmask[1: t + 1]))
        self._window_cache = (epoch, out)
        return out

    def batches_per_epoch(self, epoch: int = 0) -> int:
        """Identical on every shard — the number of collective train steps
        each process will run for this epoch."""
        n = len(self._windows(epoch)) // self.shard_count
        return n // self.batch_size

    def epoch(self, epoch: int = 0) -> Iterator[dict[str, np.ndarray]]:
        """Yield {"tokens", "targets", "loss_mask"} batches
        ([B, T] int32 / int32 / float32), deterministically per
        (seed, shard, epoch).  Every shard yields the SAME batch count
        (windows are capped at floor(total/shard_count) per shard); the
        remainder is dropped, like the tail that doesn't fill a window —
        both reappear under another epoch's shuffle."""
        windows = self._windows(epoch)
        per_shard = len(windows) // self.shard_count
        mine = windows[self.shard_index:: self.shard_count][:per_shard]
        b = self.batch_size
        for start in range(0, per_shard - b + 1, b):
            rows = mine[start: start + b]
            yield {
                "tokens": np.asarray([r[0] for r in rows], np.int32),
                "targets": np.asarray([r[1] for r in rows], np.int32),
                "loss_mask": np.asarray([r[2] for r in rows], np.float32),
            }


def prefetch(batches: Iterator[dict], depth: int = 2) -> Iterator[dict]:
    """Run the batch iterator in a background thread, ``depth`` batches
    ahead — host tokenization/packing overlaps device train steps.

    Iterator exceptions RE-RAISE in the consumer (a crash mid-epoch must
    not masquerade as a short epoch), and closing/abandoning the
    generator unblocks and ends the worker."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    cancel = threading.Event()
    done = object()

    def _put(item) -> bool:
        while not cancel.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for b in batches:
                if not _put(b):
                    return
            _put(done)
        except BaseException as e:  # re-raised consumer-side
            _put(e)

    threading.Thread(target=worker, name="data-prefetch",
                     daemon=True).start()
    try:
        while True:
            item = q.get()
            if item is done:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        cancel.set()  # consumer gone: release a worker blocked on put
