"""Paged KV cache: block-table Pallas kernels for decode.

The reference's runtimes all serve from a paged KV cache (vLLM paged
attention / SGLang radix-tree pages — the reference only writes their
command lines, /root/reference/internal/controller/
arksapplication_controller.go:941-1014).  This is the TPU formulation:

- **Pool layout** ``[L, N_pages, Hkv, P, D]`` (+ ``[L, N, Hkv, P]`` f32
  scales for int8): a page is one (layer, kv-head)-major stripe of P
  tokens, so a page read is a dense DMA — the same property the
  slot-contiguous cache has, minus the fixed per-slot reservation.
- **Block tables** ``[B, MaxP] int32`` ride scalar prefetch (SMEM): page j
  of slot b holds positions [j*P, (j+1)*P).  Sharing = two slots' tables
  pointing at the same page (prefix reuse with ZERO copies — the
  slot-contiguous design paid a host round-trip per reuse).
- **Attention**: same flash-decoding structure as
  ``pallas_attention.ragged_decode_attention`` (groups of ``block_b``
  slots, online softmax across the page grid axis), but a group's pages
  are scattered in the pool, so KV tiles are fetched with **manual
  double-buffered async DMAs** instead of BlockSpec pipelining: while page
  j is computed, page j+1's copies are in flight.  Per-slot copies skip
  pages past that slot's length.
- **Update**: same aligned read-modify-write trick as the slot kernels,
  with the row address indirected through the table.

The XLA oracle (`paged_gather_kv` + the existing masked attention) doubles
as the CPU-test reference and the fallback for unsupported shapes.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from arks_tpu.utils import knobs


def _compiler_params(**kw):
    """Compat shim: pallas renamed TPUCompilerParams -> CompilerParams across
    jax releases; resolve whichever this jax ships."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# int4 KV pools: packed nibble pairs along the token axis
# ---------------------------------------------------------------------------
#
# An int4 pool packs token pairs (2t, 2t+1) into one int8 byte along the
# PAGE (token) axis: pool [L, N, Hkv, P//2, D] int8, low nibble = token 2t,
# high nibble = token 2t+1.  Packing along P (not D) keeps the 128-lane D
# axis dense, so every page DMA stays a full-lane stripe.  The per-token
# scale stripes keep their int8 shape [L, N, Hkv, P] — which is also how
# int4-ness is detected everywhere: pool page != scale page.  Values are
# quantized to [-7, 7] (scale = amax/7); sign restoration is two arithmetic
# shifts, fused on the page stream inside the kernels.


def is_int4_pool(k_pool: jnp.ndarray, k_scale: jnp.ndarray | None) -> bool:
    return k_scale is not None and k_pool.shape[3] != k_scale.shape[3]


def pool_page_tokens(k_pool: jnp.ndarray,
                     k_scale: jnp.ndarray | None) -> int:
    """Tokens per page — the position-arithmetic page size (2x the packed
    byte rows for int4 pools)."""
    return k_scale.shape[3] if is_int4_pool(k_pool, k_scale) \
        else k_pool.shape[3]


def pack_int4(vals: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Pack int8 values in [-7, 7] into nibble pairs along ``axis`` (its
    extent must be even): out[.., t, ..] = lo(2t) | hi(2t+1) << 4."""
    axis = axis % vals.ndim
    ns = vals.shape[:axis] + (vals.shape[axis] // 2, 2) + vals.shape[axis + 1:]
    pr = vals.reshape(ns)
    lo = jax.lax.index_in_dim(pr, 0, axis + 1, keepdims=False)
    hi = jax.lax.index_in_dim(pr, 1, axis + 1, keepdims=False)
    return jnp.bitwise_or(jnp.bitwise_and(lo, jnp.int8(15)),
                          jnp.left_shift(hi, 4)).astype(jnp.int8)


def unpack_int4(packed: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Inverse of :func:`pack_int4`: int8 nibble pairs -> int8 values in
    [-7, 7], doubling ``axis``.  Sign-extension is two arithmetic shifts."""
    axis = axis % packed.ndim
    lo = jnp.right_shift(jnp.left_shift(packed, 4), 4)
    hi = jnp.right_shift(packed, 4)
    out = jnp.stack([lo, hi], axis=axis + 1)
    shape = list(packed.shape)
    shape[axis] *= 2
    return out.reshape(shape)


def unpack_int4_pool(pool: jnp.ndarray) -> jnp.ndarray:
    """[L, N, Hkv, P//2, D] packed -> [L, N, Hkv, P, D] int8 — the XLA
    oracle's view (every int8 oracle then applies unchanged)."""
    return unpack_int4(pool, axis=3)


# ---------------------------------------------------------------------------
# Mixed-grid planning: block sizes, q padding, grid mode
# ---------------------------------------------------------------------------


def mixed_grid_mode() -> str:
    """ARKS_MIXED_GRID: 'ragged' (work-list grid, default) | 'dense' (the
    legacy (S, num_qb, max_pages) grid, kept as the byte-identity
    reference and fallback)."""
    m = (knobs.raw("ARKS_MIXED_GRID") or "ragged").lower()
    if m not in ("ragged", "dense"):
        raise ValueError(f"ARKS_MIXED_GRID={m!r} (expected ragged|dense)")
    return m


def mixed_grid_plan(qmax: int, *, hkv: int, g: int, d: int, page: int,
                    kv: str, block_q: int | None = None,
                    grid: str | None = None,
                    dma_depth: int | None = None,
                    head_group: int | None = None) -> dict:
    """Resolve the mixed kernel's static launch parameters — ONE place, so
    the kernel wrapper, the engine's grid-step counters, and bench.py can
    never disagree on what actually launches.

    block_q defaults to the autotune table entry for this shape signature
    (arks_tpu.ops.autotune, pure lookup — never sweeps here) and falls
    back to the min(qmax, 32) heuristic.  Non-divisible qmax is handled by
    PADDING the q axis to the block (qpad), not by shrinking block_q to a
    divisor — the old ``while qmax % block_q: block_q -= 1`` fallback
    degraded to tiny odd blocks (qmax=33 -> block_q=11).

    head_group is the number of KV heads each work item streams (a
    divisor of hkv; hkv = no grouping, the default).  Grouping shrinks a
    single item's KV and accumulator VMEM footprint by hkv/head_group,
    which is what lets a tuned entry raise block_q — fewer q-blocks means
    each causal page prefix is re-streamed fewer times, which is where
    the GQA bytes-moved win actually comes from.  Only the ragged grid
    understands grouping; invalid divisors fall back to hkv rather than
    raising so stale tune tables can never break a launch."""
    from arks_tpu.ops import autotune

    qmax = max(int(qmax), 1)
    tuned: dict = {}
    if block_q is None or dma_depth is None or head_group is None:
        tuned = autotune.lookup("paged_mixed", autotune.mixed_signature(
            hkv=hkv, g=g, d=d, page=page, qmax=qmax, kv=kv)) or {}
    if head_group is None:
        head_group = int(tuned.get("head_group", 0)) or hkv
    head_group = int(head_group)
    if head_group <= 0 or hkv % head_group:
        head_group = hkv
    if block_q is None:
        block_q = int(tuned.get("block_q", 0)) or min(qmax, 32)
    block_q = max(1, min(int(block_q), qmax))
    if dma_depth is None:
        dma_depth = int(tuned.get("dma_depth", 0)) or 2
    dma_depth = max(2, int(dma_depth))
    if grid is None:
        grid = mixed_grid_mode()
    qpad = -(-qmax // block_q) * block_q
    return dict(block_q=block_q, qpad=qpad, num_qb=qpad // block_q,
                dma_depth=dma_depth, grid=grid, head_group=head_group)


def build_mixed_work_list(pos_start: jnp.ndarray, q_len: jnp.ndarray, *,
                          page: int, block_q: int, num_qb: int,
                          max_pages: int, head_groups: int = 1,
                          page_lo: jnp.ndarray | None = None,
                          page_hi: jnp.ndarray | None = None):
    """Scalar-prefetch work list for the ragged mixed grid: one item per
    REAL (sequence, head_group, q_block), compacted to the front of a
    fixed-length [S*head_groups*num_qb] list (Pallas grids are static; the
    page axis is what actually scales with work).  Returns
    (seq, hg, qb, plo, pages), each int32 [S*head_groups*num_qb]:

    - real items: pages = ceil(causal kv end / page) clamped to the table
      width — that sequence's OWN page count, not the pool-wide max;
      plo is the first page the item streams (0 unless span-bounded);
    - padding items (q_len=0 lanes, blocks past a lane's q_len): pages = 0
      and (seq, hg, qb) aliased to the LAST real item, so their grid step
      re-flushes an already-written output block and computes nothing.

    head_groups replicates every (seq, q_block) item per KV head group so
    each grid step streams only its hkv/head_groups slice of the pool's
    head axis.  Item order is seq-major, then head group, then q_block —
    with head_groups=1 the (seq, qb, pages) columns are bit-for-bit the
    PR 11 layout (pinned by test_build_mixed_work_list_compaction).

    page_lo / page_hi ([S] int32, optional) bound each sequence's page
    span to [page_lo[s], min(pages, page_hi[s])) — the windowed-residency
    hook: a caller attending only the resident window clamps the span
    here and carries the online-softmax state across spans.

    Built from fixed-shape jnp ops only: the device-state pipelined
    dispatches derive q_len on device (zero-host-sync), so the list must
    be traceable — no host round trip."""
    s = q_len.shape[0]
    n = s * head_groups * num_qb
    seq = jnp.repeat(jnp.arange(s, dtype=jnp.int32), head_groups * num_qb)
    hg = jnp.tile(jnp.repeat(jnp.arange(head_groups, dtype=jnp.int32),
                             num_qb), s)
    qb = jnp.tile(jnp.arange(num_qb, dtype=jnp.int32), s * head_groups)
    qlen_i = q_len.astype(jnp.int32)[seq]
    q_lo = qb * block_q
    active = q_lo < qlen_i
    kv_end = jnp.where(
        active,
        pos_start.astype(jnp.int32)[seq] + jnp.minimum(q_lo + block_q,
                                                       qlen_i),
        0)
    pages = jnp.minimum(-(-kv_end // page), max_pages)
    if page_hi is not None:
        pages = jnp.minimum(pages, page_hi.astype(jnp.int32)[seq])
    if page_lo is not None:
        plo = jnp.where(active,
                        jnp.minimum(page_lo.astype(jnp.int32)[seq], pages),
                        0)
    else:
        plo = jnp.zeros_like(pages)
    order = jnp.argsort(jnp.logical_not(active).astype(jnp.int32),
                        stable=True)
    seq, hg, qb, plo, pages = (seq[order], hg[order], qb[order],
                               plo[order], pages[order])
    n_real = jnp.sum(active.astype(jnp.int32))
    last = jnp.maximum(n_real - 1, 0)
    pad = jnp.arange(n, dtype=jnp.int32) >= n_real
    seq = jnp.where(pad, seq[last], seq)
    hg = jnp.where(pad, hg[last], hg)
    qb = jnp.where(pad, qb[last], qb)
    plo = jnp.where(pad, 0, plo)
    pages = jnp.where(pad, 0, pages)
    return seq, hg, qb, plo, pages


# ---------------------------------------------------------------------------
# XLA oracle / fallback
# ---------------------------------------------------------------------------


def paged_gather_kv(pool: jnp.ndarray, tables: jnp.ndarray,
                    layer) -> jnp.ndarray:
    """Materialize slot-contiguous [B, Hkv, S, D] (or [B, Hkv, S] for
    scales) from the paged pool — the oracle path; the Pallas kernel never
    does this."""
    pool_l = jax.lax.dynamic_index_in_dim(pool, layer, 0, keepdims=False)
    g = jnp.take(pool_l, tables, axis=0)  # [B, MaxP, Hkv, P, ...]
    if g.ndim == 5:
        b, mp, hkv, p, d = g.shape
        return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(b, hkv, mp * p, d)
    b, mp, hkv, p = g.shape
    return jnp.transpose(g, (0, 2, 1, 3)).reshape(b, hkv, mp * p)


def paged_update_xla(k_pool, v_pool, k_scale, v_scale, k_new, v_new,
                     write_idx, tables, layer):
    """Scatter one KV row per slot through the block table (oracle path —
    lowers to a full-pool rewrite in XLA, which is why the Pallas kernel
    exists).  int4 pools (detected by pool page != scale page) get a
    nibble merge at the target byte; all position math stays in TOKEN
    units."""
    int4 = is_int4_pool(k_pool, k_scale)
    p = pool_page_tokens(k_pool, k_scale)
    n = k_pool.shape[1]
    b, hkv, d = k_new.shape
    # write_idx beyond the table's coverage = inactive slot: route the
    # scatter to an out-of-bounds page so jit drops it (the Pallas kernel
    # guards the same way) — take_along_axis would otherwise CLAMP to the
    # last page and corrupt it.
    oob = write_idx >= tables.shape[1] * p
    safe_idx = jnp.where(oob, 0, write_idx)
    page = jnp.take_along_axis(
        tables, (safe_idx // p)[:, None], axis=1)[:, 0]    # [B]
    page = jnp.where(oob, n, page)
    off = safe_idx % p
    l_idx = jnp.full((b,), layer, jnp.int32)
    h_idx = jnp.arange(hkv)[None, :]
    quantized = k_scale is not None
    if quantized:
        from arks_tpu.ops.pallas_attention import quantize_kv
        kq, ks = quantize_kv(k_new, qmax=7 if int4 else 127)
        vq, vs = quantize_kv(v_new, qmax=7 if int4 else 127)
        if int4:
            # Two parity passes: positions 2t and 2t+1 share a byte, so a
            # single scatter of whole merged bytes would let pair-mates in
            # the same dispatch clobber each other's nibble.  Within one
            # parity every target byte is unique (distinct positions).
            boff = (off // 2)[:, None]
            for parity, vals_k, vals_v in ((0, kq, vq), (1, kq, vq)):
                sel = (off % 2) == parity
                pg_sel = jnp.where(sel, page, n)[:, None]
                oldk = k_pool[l_idx[:, None], page[:, None], h_idx, boff]
                oldv = v_pool[l_idx[:, None], page[:, None], h_idx, boff]
                if parity == 0:
                    mk = (oldk & -16) | (vals_k & 15)
                    mv = (oldv & -16) | (vals_v & 15)
                else:
                    mk = (oldk & 15) | (vals_k << 4)
                    mv = (oldv & 15) | (vals_v << 4)
                k_pool = k_pool.at[l_idx[:, None], pg_sel, h_idx, boff].set(mk)
                v_pool = v_pool.at[l_idx[:, None], pg_sel, h_idx, boff].set(mv)
        else:
            k_pool = k_pool.at[l_idx[:, None], page[:, None], h_idx,
                               off[:, None]].set(kq)
            v_pool = v_pool.at[l_idx[:, None], page[:, None], h_idx,
                               off[:, None]].set(vq)
        k_scale = k_scale.at[l_idx[:, None], page[:, None], h_idx,
                             off[:, None]].set(ks)
        v_scale = v_scale.at[l_idx[:, None], page[:, None], h_idx,
                             off[:, None]].set(vs)
    else:
        k_pool = k_pool.at[l_idx[:, None], page[:, None], h_idx,
                           off[:, None]].set(k_new.astype(k_pool.dtype))
        v_pool = v_pool.at[l_idx[:, None], page[:, None], h_idx,
                           off[:, None]].set(v_new.astype(v_pool.dtype))
    return k_pool, v_pool, k_scale, v_scale


def paged_update_block_xla(k_pool, v_pool, k_scale, v_scale, k_new, v_new,
                           positions, tables, layer):
    """Scatter a K-row KV BLOCK per slot (the speculative-verify write)
    through the block table in one gather+scatter.  ``k_new``/``v_new`` are
    [B, K, Hkv, D]; row k of slot b lands at position ``positions[b, k]``
    (which may cross a page boundary mid-block).  Positions at/past the
    table's coverage are dropped — the inactive-slot sentinel, same
    out-of-bounds-page guard as ``paged_update_xla``."""
    int4 = is_int4_pool(k_pool, k_scale)
    p = pool_page_tokens(k_pool, k_scale)
    n = k_pool.shape[1]
    b, kk, hkv, d = k_new.shape
    cover = tables.shape[1] * p
    oob = positions >= cover                              # [B, K]
    safe = jnp.where(oob, 0, positions)
    page = jnp.take_along_axis(tables, safe // p, axis=1)  # [B, K]
    page = jnp.where(oob, n, page)
    off = safe % p
    l_idx = jnp.full((b, kk, hkv), layer, jnp.int32)
    pg = page[:, :, None]
    of = off[:, :, None]
    h_idx = jnp.arange(hkv)[None, None, :]
    quantized = k_scale is not None
    if quantized:
        from arks_tpu.ops.pallas_attention import quantize_kv
        kq, ksn = quantize_kv(k_new, qmax=7 if int4 else 127)
        vq, vsn = quantize_kv(v_new, qmax=7 if int4 else 127)
        if int4:
            # Same two-parity nibble merge as paged_update_xla: a verify
            # block writes consecutive positions, so pair-mates (2t, 2t+1)
            # in one dispatch target the SAME byte.
            bof = (off // 2)[:, :, None]
            for parity in (0, 1):
                sel = (off % 2) == parity
                pg_sel = jnp.where(sel, page, n)[:, :, None]
                oldk = k_pool[l_idx, pg, h_idx, bof]
                oldv = v_pool[l_idx, pg, h_idx, bof]
                if parity == 0:
                    mk = (oldk & -16) | (kq & 15)
                    mv = (oldv & -16) | (vq & 15)
                else:
                    mk = (oldk & 15) | (kq << 4)
                    mv = (oldv & 15) | (vq << 4)
                k_pool = k_pool.at[l_idx, pg_sel, h_idx, bof].set(mk)
                v_pool = v_pool.at[l_idx, pg_sel, h_idx, bof].set(mv)
        else:
            k_pool = k_pool.at[l_idx, pg, h_idx, of].set(kq)
            v_pool = v_pool.at[l_idx, pg, h_idx, of].set(vq)
        k_scale = k_scale.at[l_idx, pg, h_idx, of].set(ksn)
        v_scale = v_scale.at[l_idx, pg, h_idx, of].set(vsn)
    else:
        k_pool = k_pool.at[l_idx, pg, h_idx, of].set(
            k_new.astype(k_pool.dtype))
        v_pool = v_pool.at[l_idx, pg, h_idx, of].set(
            v_new.astype(v_pool.dtype))
    return k_pool, v_pool, k_scale, v_scale


# ---------------------------------------------------------------------------
# Paged ragged decode attention (manual double-buffered DMA)
# ---------------------------------------------------------------------------


def _paged_attn_kernel(layer_ref, glens_ref, tables_ref, slens_ref, lens_ref,
                       q_ref, kpool, vpool, *rest,
                       block_b: int, page: int, scale: float,
                       quantized: bool):
    if quantized:
        kspool, vspool, o_ref, kbuf, vbuf, ksbuf, vsbuf, m_ref, l_ref, \
            acc_ref, sem = rest
    else:
        o_ref, kbuf, vbuf, m_ref, l_ref, acc_ref, sem = rest
        kspool = vspool = ksbuf = vsbuf = None
    bi = pl.program_id(0)
    si = pl.program_id(1)
    num_pages = pl.num_programs(1)
    lyr = layer_ref[0]

    def start_copies(page_i, buf):
        # One DMA per (slot, k/v[, scales]): the group's pages are scattered
        # in the pool, so there is no single dense tile to fetch.  Copies
        # for slots already past their length are skipped — but their
        # V-side buffer rows are ZEROED: uninitialized VMEM can hold NaN
        # bits, and the flash accumulation computes p@v where masked
        # positions contribute 0 * v — 0 * NaN would poison the output.
        # (K garbage is harmless: its scores are replaced after the dot.)
        for j in range(block_b):
            b = bi * block_b + j
            skip = page_i * page >= slens_ref[b]

            @pl.when(jnp.logical_not(skip))
            def _():
                pg = tables_ref[b, page_i]
                pltpu.make_async_copy(
                    kpool.at[lyr, pg], kbuf.at[buf, j],
                    sem.at[0, buf, j]).start()
                pltpu.make_async_copy(
                    vpool.at[lyr, pg], vbuf.at[buf, j],
                    sem.at[1, buf, j]).start()
                if quantized:
                    pltpu.make_async_copy(
                        kspool.at[lyr, pg], ksbuf.at[buf, j],
                        sem.at[2, buf, j]).start()
                    pltpu.make_async_copy(
                        vspool.at[lyr, pg], vsbuf.at[buf, j],
                        sem.at[3, buf, j]).start()

            @pl.when(skip)
            def _():
                vbuf[buf, j] = jnp.zeros_like(vbuf[buf, j])
                if quantized:
                    vsbuf[buf, j] = jnp.zeros_like(vsbuf[buf, j])

    def wait_copies(page_i, buf):
        for j in range(block_b):
            b = bi * block_b + j

            @pl.when(page_i * page < slens_ref[b])
            def _():
                pltpu.make_async_copy(kpool.at[lyr, 0], kbuf.at[buf, j],
                                      sem.at[0, buf, j]).wait()
                pltpu.make_async_copy(vpool.at[lyr, 0], vbuf.at[buf, j],
                                      sem.at[1, buf, j]).wait()
                if quantized:
                    pltpu.make_async_copy(
                        kspool.at[lyr, 0], ksbuf.at[buf, j],
                        sem.at[2, buf, j]).wait()
                    pltpu.make_async_copy(
                        vspool.at[lyr, 0], vsbuf.at[buf, j],
                        sem.at[3, buf, j]).wait()

    @pl.when(si == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)
        start_copies(0, 0)

    valid = si * page < glens_ref[bi]

    # Double buffering: kick page si+1's copies before computing page si.
    @pl.when(valid & ((si + 1) * page < glens_ref[bi]))
    def _prefetch():
        start_copies(si + 1, (si + 1) % 2)

    @pl.when(valid)
    def _block():
        buf = si % 2
        wait_copies(si, buf)
        bb, hkv, g, d = q_ref.shape
        q = q_ref[:].reshape(bb * hkv, g, d)
        k = kbuf[buf].reshape(bb * hkv, page, d).astype(q.dtype)
        v = vbuf[buf].reshape(bb * hkv, page, d).astype(q.dtype)
        scores = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        scores = scores.reshape(bb, hkv, g, page)
        if quantized:
            scores = scores * ksbuf[buf].reshape(bb, hkv, 1, page)
        pos = si * page + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 3)
        lens = lens_ref[0]  # [block_b, 1]
        scores = jnp.where(pos < lens[:, None, None, :], scores, _NEG_INF)

        m_prev = m_ref[:]
        l_prev = l_ref[:]
        m_curr = jnp.max(scores, axis=3, keepdims=True)
        m_next = jnp.maximum(m_prev, jnp.broadcast_to(m_curr, m_prev.shape))
        correction = jnp.exp(m_prev - m_next)
        p = jnp.exp(scores - m_next[..., :1])
        l_curr = jnp.sum(p, axis=3, keepdims=True)
        l_next = l_prev * correction + jnp.broadcast_to(l_curr, l_prev.shape)
        if quantized:
            p = p * vsbuf[buf].reshape(bb, hkv, 1, page)
        pv = jax.lax.dot_general(
            p.astype(v.dtype).reshape(bb * hkv, g, page), v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32).reshape(bb, hkv, g, d)
        acc_ref[:] = acc_ref[:] * correction[..., :1] + pv
        m_ref[:] = m_next
        l_ref[:] = l_next

    @pl.when(si == num_pages - 1)
    def _finish():
        out = acc_ref[:] / (l_ref[..., :1] + 1e-9)
        o_ref[:] = out.astype(o_ref.dtype)


def _pick_block_b(b: int, target: int) -> int:
    best = 1
    for cand in range(1, min(b, target) + 1):
        if b % cand == 0:
            best = cand
    return best


@functools.partial(jax.jit, static_argnames=("block_b", "interpret"))
def paged_decode_attention(
    q: jnp.ndarray,        # [B, Hkv, G, D] — one query token per slot
    k_pool: jnp.ndarray,   # [L, N, Hkv, P, D] page pool
    v_pool: jnp.ndarray,
    tables: jnp.ndarray,   # [B, MaxP] int32 block tables
    lengths: jnp.ndarray,  # [B] int32 valid positions per slot
    layer,                 # int32
    k_scale: jnp.ndarray | None = None,  # [L, N, Hkv, P] f32 (int8 pools)
    v_scale: jnp.ndarray | None = None,
    block_b: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """[B, Hkv, G, D] attention over each slot's block-table pages."""
    b, hkv, g, d = q.shape
    page = k_pool.shape[3]
    max_pages = tables.shape[1]
    quantized = k_scale is not None
    if is_int4_pool(k_pool, k_scale):
        raise ValueError(
            "int4 pools route through the mixed kernel (fused nibble "
            "dequant) or the XLA oracle; the standalone decode kernel is "
            "bf16/int8 only")
    if block_b is None:
        from arks_tpu.ops import autotune
        kvd = "int8" if quantized else str(k_pool.dtype)
        tuned = autotune.lookup("paged_decode", autotune.decode_signature(
            b=b, hkv=hkv, g=g, d=d, page=page, kv=kvd)) or {}
        # Heuristic fallback (VMEM budget: double-buffered k+v page tiles
        # must fit beside the accumulators; int8 pages are half the bytes
        # of bf16) — exactly the pre-autotune behavior when no table entry
        # exists for this signature.
        block_b = int(tuned.get("block_b", 0)) or (
            16 if k_pool.dtype == jnp.int8 else 8)
    block_b = _pick_block_b(b, block_b)
    num_groups = b // block_b
    scale = 1.0 / (d ** 0.5)
    lengths = lengths.astype(jnp.int32)
    group_lens = jnp.max(lengths.reshape(num_groups, block_b), axis=1)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)

    def q_map(bi, si, *prefetch):
        del si, prefetch
        return (bi, 0, 0, 0)

    def lens_map(bi, si, *prefetch):
        del si, prefetch
        return (bi, 0, 0)

    in_specs = [
        pl.BlockSpec((1, block_b, 1), lens_map),
        pl.BlockSpec((block_b, hkv, g, d), q_map),
        pl.BlockSpec(memory_space=pl.ANY),   # k pool (manual DMA)
        pl.BlockSpec(memory_space=pl.ANY),   # v pool
    ]
    inputs = [layer_arr, group_lens, tables.astype(jnp.int32),
              lengths, lengths.reshape(num_groups, block_b)[..., None],
              q, k_pool, v_pool]
    scratch = [
        pltpu.VMEM((2, block_b, hkv, page, d), k_pool.dtype),  # kbuf
        pltpu.VMEM((2, block_b, hkv, page, d), v_pool.dtype),  # vbuf
    ]
    n_sem = 2
    if quantized:
        in_specs += [pl.BlockSpec(memory_space=pl.ANY)] * 2
        inputs += [k_scale, v_scale]
        scratch += [pltpu.VMEM((2, block_b, hkv, page), jnp.float32),
                    pltpu.VMEM((2, block_b, hkv, page), jnp.float32)]
        n_sem = 4
    scratch += [
        pltpu.VMEM((block_b, hkv, g, 128), jnp.float32),  # m
        pltpu.VMEM((block_b, hkv, g, 128), jnp.float32),  # l
        pltpu.VMEM((block_b, hkv, g, d), jnp.float32),    # acc
        pltpu.SemaphoreType.DMA((n_sem, 2, block_b)),
    ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,  # layer, group_lens, tables, slot lens
        grid=(num_groups, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_b, hkv, g, d), q_map),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(_paged_attn_kernel, block_b=block_b,
                               page=page, scale=scale, quantized=quantized)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(*inputs)


# ---------------------------------------------------------------------------
# Ragged mixed-query paged attention (prefill chunks + decode in one grid)
# ---------------------------------------------------------------------------


def _unpack_int4_tile(w: jnp.ndarray) -> jnp.ndarray:
    """In-kernel nibble dequant, fused on the page stream: an int4 page
    tile [Hkv, page//2, D] of packed pairs -> [Hkv, page, D] int8 values.
    Sign extension is two arithmetic shifts; the interleave restores token
    order (low nibble = even token, high = odd)."""
    lo = jnp.right_shift(jnp.left_shift(w, 4), 4)
    hi = jnp.right_shift(w, 4)
    hkv, p2, d = w.shape
    return jnp.stack([lo, hi], axis=2).reshape(hkv, p2 * 2, d)


def _mixed_softmax_block(q_ref, kbuf, vbuf, ksbuf, vsbuf, m_ref, l_ref,
                         acc_ref, buf, si, pos0, q_lo, *, page, scale,
                         quantized, int4):
    """One page of online-softmax accumulation — the SHARED compute body of
    the dense and ragged mixed kernels, so byte-identity between the two
    grids is structural, not coincidental."""
    _, hkv, g, bq, d = q_ref.shape
    q = q_ref[0].reshape(hkv, g * bq, d)
    kt = kbuf[buf]
    vt = vbuf[buf]
    if int4:
        kt = _unpack_int4_tile(kt)
        vt = _unpack_int4_tile(vt)
    k = kt.astype(q.dtype)                 # [Hkv, page, D]
    v = vt.astype(q.dtype)
    scores = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale   # [Hkv, G*BQ, page]
    if quantized:
        scores = scores * ksbuf[buf][:, None, :]
    # Row r of the G*BQ axis is query index r % BQ (g-major layout).
    row = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    qpos = pos0 + q_lo + row % bq
    kvpos = si * page + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 2)
    scores = jnp.where(kvpos <= qpos, scores, _NEG_INF)

    m_prev = m_ref[:]
    l_prev = l_ref[:]
    m_curr = jnp.max(scores, axis=2, keepdims=True)
    m_next = jnp.maximum(m_prev, jnp.broadcast_to(m_curr, m_prev.shape))
    correction = jnp.exp(m_prev - m_next)
    p = jnp.exp(scores - m_next[..., :1])
    l_curr = jnp.sum(p, axis=2, keepdims=True)
    l_next = l_prev * correction + jnp.broadcast_to(l_curr, l_prev.shape)
    if quantized:
        p = p * vsbuf[buf][:, None, :]
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)           # [Hkv, G*BQ, D]
    acc_ref[:] = acc_ref[:] * correction[..., :1] + pv
    m_ref[:] = m_next
    l_ref[:] = l_next


def _paged_mixed_kernel(layer_ref, tables_ref, pos_start_ref, qlen_ref,
                        q_ref, kpool, vpool, *rest,
                        page: int, block_q: int, scale: float,
                        quantized: bool, int4: bool):
    """DENSE grid: one SEQUENCE per grid row, ``block_q`` queries per
    q-block, pages on the innermost axis — (S, num_qb, max_pages) grid
    steps regardless of how much of the batch is real.  Kept as the
    byte-identity reference and ARKS_MIXED_GRID=dense fallback; the
    ragged work-list kernel below is the default.  Query i of sequence s
    sits at global position pos_start[s]+i and attends cache positions
    [0, pos_start[s]+i] (write-then-attend as everywhere).  Pages wholly
    past a q-block's causal end are masked off with pl.when."""
    if quantized:
        kspool, vspool, o_ref, kbuf, vbuf, ksbuf, vsbuf, m_ref, l_ref, \
            acc_ref, sem = rest
    else:
        o_ref, kbuf, vbuf, m_ref, l_ref, acc_ref, sem = rest
        kspool = vspool = ksbuf = vsbuf = None
    s_i = pl.program_id(0)
    qb = pl.program_id(1)
    si = pl.program_id(2)
    num_pages = pl.num_programs(2)
    lyr = layer_ref[0]
    pos0 = pos_start_ref[s_i]
    qlen = qlen_ref[s_i]
    q_lo = qb * block_q
    # KV positions this q-block can causally see end just past its last
    # VALID query; empty blocks (q_lo >= qlen) see nothing.
    kv_end = jnp.where(q_lo < qlen,
                       pos0 + jnp.minimum(q_lo + block_q, qlen), 0)

    def start_copies(page_i, buf):
        pg = tables_ref[s_i, page_i]
        pltpu.make_async_copy(kpool.at[lyr, pg], kbuf.at[buf],
                              sem.at[0, buf]).start()
        pltpu.make_async_copy(vpool.at[lyr, pg], vbuf.at[buf],
                              sem.at[1, buf]).start()
        if quantized:
            pltpu.make_async_copy(kspool.at[lyr, pg], ksbuf.at[buf],
                                  sem.at[2, buf]).start()
            pltpu.make_async_copy(vspool.at[lyr, pg], vsbuf.at[buf],
                                  sem.at[3, buf]).start()

    def wait_copies(buf):
        pltpu.make_async_copy(kpool.at[lyr, 0], kbuf.at[buf],
                              sem.at[0, buf]).wait()
        pltpu.make_async_copy(vpool.at[lyr, 0], vbuf.at[buf],
                              sem.at[1, buf]).wait()
        if quantized:
            pltpu.make_async_copy(kspool.at[lyr, 0], ksbuf.at[buf],
                                  sem.at[2, buf]).wait()
            pltpu.make_async_copy(vspool.at[lyr, 0], vsbuf.at[buf],
                                  sem.at[3, buf]).wait()

    @pl.when(si == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

        @pl.when(kv_end > 0)
        def _():
            start_copies(0, 0)

    valid = si * page < kv_end

    # Double buffering: kick page si+1's copies before computing page si.
    @pl.when(valid & ((si + 1) * page < kv_end))
    def _prefetch():
        start_copies(si + 1, (si + 1) % 2)

    @pl.when(valid)
    def _block():
        buf = si % 2
        wait_copies(buf)
        _mixed_softmax_block(q_ref, kbuf, vbuf, ksbuf, vsbuf, m_ref, l_ref,
                             acc_ref, buf, si, pos0, q_lo, page=page,
                             scale=scale, quantized=quantized, int4=int4)

    @pl.when(si == num_pages - 1)
    def _finish():
        _, hkv, g, bq, d = q_ref.shape
        out = acc_ref[:] / (l_ref[..., :1] + 1e-9)
        o_ref[:] = out.reshape(1, hkv, g, bq, d).astype(o_ref.dtype)


def _paged_mixed_ragged_kernel(layer_ref, tables_ref, pos_start_ref,
                               wl_seq_ref, wl_hg_ref, wl_qb_ref,
                               wl_plo_ref, wl_pages_ref,
                               q_ref, kpool, vpool, *rest,
                               page: int, block_q: int, scale: float,
                               quantized: bool, int4: bool, depth: int,
                               head_group: int, carry: bool,
                               emit_state: bool):
    """RAGGED work-list grid: one grid step per (sequence, head_group,
    q_block) work item, the page loop INSIDE the kernel bounded by that
    item's own causal page span [wl_plo, wl_pages).  q_len=0 lanes and
    q-blocks past a lane's q_len never become items, so grid length
    tracks real work — a 3-active-of-64-slots batch costs 3 items'
    pages, not 64*num_qb*max_pages masked steps.  Items are compacted to
    the front of the fixed-length list by :func:`build_mixed_work_list`;
    padding items carry wl_pages=0 and alias the last real item's output
    block, so their only cost is re-flushing an already-written block.

    GQA head grouping: each item DMAs only its ``head_group``-head slice
    of the pool's head axis (wl_hg picks which), so per-item KV and
    accumulator VMEM shrink by hkv/head_group — the headroom a tuned
    entry spends on a larger block_q, which is what actually cuts the
    re-streamed causal-prefix bytes.  head_group == hkv with one group
    reduces exactly to the ungrouped kernel.

    Carried state: with ``carry`` the online-softmax state (m, l, acc)
    initializes from BlockSpec'd f32 inputs instead of (-inf, 0, 0); with
    ``emit_state`` the RAW state is written out instead of the
    normalized output.  Chaining spans through f32 state is bitwise
    exact — the per-page update sequence is identical and the final
    acc/(l+eps) division happens exactly once, on the last span.

    DMAs are ``depth``-way multi-buffered (depth=2 reduces exactly to the
    dense kernel's double buffering; the accumulation order is identical
    for any depth, so tuned depths preserve byte identity)."""
    rest = list(rest)
    if quantized:
        kspool, vspool = rest[:2]
        rest = rest[2:]
    else:
        kspool = vspool = None
    if carry:
        mi_ref, li_ref, ai_ref = rest[:3]
        rest = rest[3:]
    else:
        mi_ref = li_ref = ai_ref = None
    if emit_state:
        mo_ref, lo_ref, ao_ref = rest[:3]
        o_ref = None
        rest = rest[3:]
    else:
        o_ref = rest[0]
        mo_ref = lo_ref = ao_ref = None
        rest = rest[1:]
    if quantized:
        kbuf, vbuf, ksbuf, vsbuf, m_ref, l_ref, acc_ref, sem = rest
    else:
        kbuf, vbuf, m_ref, l_ref, acc_ref, sem = rest
        ksbuf = vsbuf = None
    item = pl.program_id(0)
    lyr = layer_ref[0]
    s_i = wl_seq_ref[item]
    hg_i = wl_hg_ref[item]
    qb = wl_qb_ref[item]
    plo = wl_plo_ref[item]
    npages = wl_pages_ref[item]
    pos0 = pos_start_ref[s_i]
    q_lo = qb * block_q
    h0 = hg_i * head_group

    def start_copies(page_i, buf):
        pg = tables_ref[s_i, page_i]
        pltpu.make_async_copy(kpool.at[lyr, pg, pl.ds(h0, head_group)],
                              kbuf.at[buf], sem.at[0, buf]).start()
        pltpu.make_async_copy(vpool.at[lyr, pg, pl.ds(h0, head_group)],
                              vbuf.at[buf], sem.at[1, buf]).start()
        if quantized:
            pltpu.make_async_copy(kspool.at[lyr, pg,
                                            pl.ds(h0, head_group)],
                                  ksbuf.at[buf], sem.at[2, buf]).start()
            pltpu.make_async_copy(vspool.at[lyr, pg,
                                            pl.ds(h0, head_group)],
                                  vsbuf.at[buf], sem.at[3, buf]).start()

    def wait_copies(buf):
        pltpu.make_async_copy(kpool.at[lyr, 0, pl.ds(0, head_group)],
                              kbuf.at[buf], sem.at[0, buf]).wait()
        pltpu.make_async_copy(vpool.at[lyr, 0, pl.ds(0, head_group)],
                              vbuf.at[buf], sem.at[1, buf]).wait()
        if quantized:
            pltpu.make_async_copy(kspool.at[lyr, 0,
                                            pl.ds(0, head_group)],
                                  ksbuf.at[buf], sem.at[2, buf]).wait()
            pltpu.make_async_copy(vspool.at[lyr, 0,
                                            pl.ds(0, head_group)],
                                  vsbuf.at[buf], sem.at[3, buf]).wait()

    # Padding item (npages == 0 <= plo): compute nothing, write nothing —
    # the output window still holds the previous (aliased) item's block
    # and re-flushes it unchanged.  A carry call must still run REAL
    # items whose span is empty (all their pages fell in earlier spans:
    # plo == npages > 0) — the carried state still has to be passed
    # through / normalized into the output.
    run_gate = (npages > 0) if carry else (npages > plo)

    @pl.when(run_gate)
    def _run():
        if carry:
            m_ref[:] = mi_ref[0].reshape(m_ref.shape)
            l_ref[:] = li_ref[0].reshape(l_ref.shape)
            acc_ref[:] = ai_ref[0].reshape(acc_ref.shape)
        else:
            m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)
        for j in range(depth - 1):
            @pl.when(plo + j < npages)
            def _warm(j=j):
                start_copies(plo + j, (plo + j) % depth)

        def body(si, loop_c):
            nxt = si + depth - 1

            @pl.when(nxt < npages)
            def _prefetch():
                start_copies(nxt, nxt % depth)

            buf = si % depth
            wait_copies(buf)
            _mixed_softmax_block(q_ref, kbuf, vbuf, ksbuf, vsbuf, m_ref,
                                 l_ref, acc_ref, buf, si, pos0, q_lo,
                                 page=page, scale=scale,
                                 quantized=quantized, int4=int4)
            return loop_c

        jax.lax.fori_loop(plo, npages, body, 0)
        _, hg, g, bq, d = q_ref.shape
        if emit_state:
            mo_ref[:] = m_ref[:].reshape(1, hg, g, bq, 128)
            lo_ref[:] = l_ref[:].reshape(1, hg, g, bq, 128)
            ao_ref[:] = acc_ref[:].reshape(1, hg, g, bq, d)
        else:
            out = acc_ref[:] / (l_ref[..., :1] + 1e-9)
            o_ref[:] = out.reshape(1, hg, g, bq, d).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_q", "interpret", "grid",
                                             "dma_depth", "head_group",
                                             "emit_state"))
def _paged_mixed_call(q, k_pool, v_pool, tables, pos_start, q_len, layer,
                      k_scale, v_scale, page_lo=None, page_hi=None,
                      carry_state=None, *, block_q: int, dma_depth: int,
                      grid: str, interpret: bool, head_group: int,
                      emit_state: bool):
    """Jitted mixed-attention launch with FULLY RESOLVED statics — the
    public wrapper resolves the plan (env + autotune) per call so flipping
    ARKS_MIXED_GRID / the tune table between calls can never hit a stale
    jit cache entry keyed on unresolved defaults."""
    s, hkv, g, qmax, d = q.shape
    quantized = k_scale is not None
    int4 = is_int4_pool(k_pool, k_scale)
    page = pool_page_tokens(k_pool, k_scale)
    kv_rows = k_pool.shape[3]            # page//2 byte rows for int4 pools
    max_pages = tables.shape[1]
    carry = carry_state is not None
    if grid == "dense" and (head_group != hkv or carry or emit_state
                            or page_lo is not None or page_hi is not None):
        raise ValueError(
            "head grouping / span bounds / carried state need the ragged "
            "work-list grid (ARKS_MIXED_GRID=ragged); the dense grid is "
            "the legacy byte-identity reference only")
    n_hg = hkv // head_group
    qpad = -(-qmax // block_q) * block_q
    num_qb = qpad // block_q
    qp = q if qpad == qmax else jnp.pad(
        q, ((0, 0), (0, 0), (0, 0), (0, qpad - qmax), (0, 0)))
    scale = 1.0 / (d ** 0.5)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    tables32 = tables.astype(jnp.int32)
    pos32 = pos_start.astype(jnp.int32)
    qlen32 = q_len.astype(jnp.int32)

    def make_scratch(nbuf):
        scratch = [
            pltpu.VMEM((nbuf, head_group, kv_rows, d), k_pool.dtype),
            pltpu.VMEM((nbuf, head_group, kv_rows, d), v_pool.dtype),
        ]
        n_sem = 2
        if quantized:
            scratch += [pltpu.VMEM((nbuf, head_group, page), jnp.float32),
                        pltpu.VMEM((nbuf, head_group, page), jnp.float32)]
            n_sem = 4
        scratch += [
            pltpu.VMEM((head_group, g * block_q, 128), jnp.float32),  # m
            pltpu.VMEM((head_group, g * block_q, 128), jnp.float32),  # l
            pltpu.VMEM((head_group, g * block_q, d), jnp.float32),    # acc
            pltpu.SemaphoreType.DMA((n_sem, nbuf)),
        ]
        return scratch

    pool_specs = [pl.BlockSpec(memory_space=pl.ANY),   # k pool (manual DMA)
                  pl.BlockSpec(memory_space=pl.ANY)]   # v pool
    scale_inputs = [k_scale, v_scale] if quantized else []
    scale_specs = [pl.BlockSpec(memory_space=pl.ANY)] * 2 if quantized else []

    if grid == "dense":
        def q_map(s_i, qb, si, *prefetch):
            del si, prefetch
            return (s_i, 0, 0, qb, 0)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,  # layer, tables, pos_start, q_len
            grid=(s, num_qb, max_pages),
            in_specs=[pl.BlockSpec((1, hkv, g, block_q, d), q_map)]
            + pool_specs + scale_specs,
            out_specs=pl.BlockSpec((1, hkv, g, block_q, d), q_map),
            scratch_shapes=make_scratch(2),
        )
        inputs = [layer_arr, tables32, pos32, qlen32,
                  qp, k_pool, v_pool] + scale_inputs
        kernel = functools.partial(_paged_mixed_kernel, page=page,
                                   block_q=block_q, scale=scale,
                                   quantized=quantized, int4=int4)
        dims = ("parallel", "arbitrary", "arbitrary")
        out_shape = jax.ShapeDtypeStruct(qp.shape, q.dtype)
    else:
        wl_seq, wl_hg, wl_qb, wl_plo, wl_pages = build_mixed_work_list(
            pos32, qlen32, page=page, block_q=block_q, num_qb=num_qb,
            max_pages=max_pages, head_groups=n_hg, page_lo=page_lo,
            page_hi=page_hi)

        def q_map(i, layer_p, tables_p, pos_p, seq_p, hg_p, qb_p, plo_p,
                  pages_p):
            del layer_p, tables_p, pos_p, plo_p, pages_p
            return (seq_p[i], hg_p[i], 0, qb_p[i], 0)

        blk = dict(q=(1, head_group, g, block_q, d),
                   ml=(1, head_group, g, block_q, 128))
        carry_inputs, carry_specs = [], []
        if carry:
            # Carry arrays are qpad-sized along the q axis — exactly what
            # a previous emit_state call produced, so spans chain without
            # re-padding.
            m0, l0, a0 = carry_state
            carry_inputs = [m0, l0, a0]
            carry_specs = [pl.BlockSpec(blk["ml"], q_map),
                           pl.BlockSpec(blk["ml"], q_map),
                           pl.BlockSpec(blk["q"], q_map)]
        if emit_state:
            out_specs = (pl.BlockSpec(blk["ml"], q_map),
                         pl.BlockSpec(blk["ml"], q_map),
                         pl.BlockSpec(blk["q"], q_map))
            out_shape = (
                jax.ShapeDtypeStruct((s, hkv, g, qpad, 128), jnp.float32),
                jax.ShapeDtypeStruct((s, hkv, g, qpad, 128), jnp.float32),
                jax.ShapeDtypeStruct((s, hkv, g, qpad, d), jnp.float32))
        else:
            out_specs = pl.BlockSpec(blk["q"], q_map)
            out_shape = jax.ShapeDtypeStruct(qp.shape, q.dtype)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=8,  # layer, tables, pos_start, work list x5
            grid=(s * n_hg * num_qb,),
            in_specs=[pl.BlockSpec(blk["q"], q_map)]
            + pool_specs + scale_specs + carry_specs,
            out_specs=out_specs,
            scratch_shapes=make_scratch(dma_depth),
        )
        inputs = [layer_arr, tables32, pos32, wl_seq, wl_hg, wl_qb,
                  wl_plo, wl_pages, qp, k_pool, v_pool] \
            + scale_inputs + carry_inputs
        kernel = functools.partial(_paged_mixed_ragged_kernel, page=page,
                                   block_q=block_q, scale=scale,
                                   quantized=quantized, int4=int4,
                                   depth=dma_depth, head_group=head_group,
                                   carry=carry, emit_state=emit_state)
        # Consecutive items may alias one output block (padding re-flush),
        # so the item axis is "arbitrary", never "parallel".
        dims = ("arbitrary",)

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=_compiler_params(dimension_semantics=dims),
        interpret=interpret,
    )(*inputs)
    # Rows past q_len[s] are undefined (dense: skipped blocks; ragged:
    # never-visited items) — zero them so both grids return IDENTICAL
    # bytes everywhere, not just on the rows callers keep.
    if emit_state:
        m, l, a = out
        validp = (jnp.arange(qpad, dtype=jnp.int32)[None, :]
                  < qlen32[:, None])[:, None, None, :, None]
        return (jnp.where(validp, m, jnp.zeros_like(m)),
                jnp.where(validp, l, jnp.zeros_like(l)),
                jnp.where(validp, a, jnp.zeros_like(a)))
    if qpad != qmax:
        out = out[..., :qmax, :]
    valid = jnp.arange(qmax, dtype=jnp.int32)[None, :] < qlen32[:, None]
    return jnp.where(valid[:, None, None, :, None], out,
                     jnp.zeros_like(out))


def paged_mixed_attention(
    q: jnp.ndarray,        # [S, Hkv, G, Q, D] — Q query tokens per sequence
    k_pool: jnp.ndarray,   # [L, N, Hkv, P, D] page pool ([.., P//2, D] int4)
    v_pool: jnp.ndarray,
    tables: jnp.ndarray,   # [S, MaxP] int32 block tables
    pos_start: jnp.ndarray,  # [S] int32 — global position of query 0
    q_len: jnp.ndarray,      # [S] int32 — valid queries (0 = inactive lane)
    layer,                   # int32
    k_scale: jnp.ndarray | None = None,  # [L, N, Hkv, P] f32 (int8/int4)
    v_scale: jnp.ndarray | None = None,
    block_q: int | None = None,
    interpret: bool = False,
    grid: str | None = None,        # "ragged" | "dense" | None (env)
    dma_depth: int | None = None,
    head_group: int | None = None,  # KV heads per work item (None = tuned)
    page_lo: jnp.ndarray | None = None,   # [S] span start (pages)
    page_hi: jnp.ndarray | None = None,   # [S] span end bound (pages)
    carry_state: tuple | None = None,     # (m, l, acc) from emit_state
    emit_state: bool = False,
):
    """[S, Hkv, G, Q, D] ragged mixed attention: query i of sequence s
    attends its table pages over positions [0, pos_start[s]+i].  Rows past
    q_len[s] are zeroed — the ONE kernel serving decode lanes (q_len=1),
    prefill chunks, and spec verify rows (q_len=K) in a single dispatch.
    The plan (block_q via autotune, grid mode via ARKS_MIXED_GRID, DMA
    depth, GQA head grouping) is resolved HERE, outside jit, then passed
    as statics.

    Span-bounded calls (page_lo/page_hi + carry_state/emit_state) chain
    the online-softmax state across page ranges — the windowed-residency
    building block.  With emit_state the return is the raw f32
    (m, l, acc) triple (q axis padded to the plan's qpad) instead of the
    normalized output; feeding it back as carry_state on the next span
    and finishing with emit_state=False reproduces the single-call
    result bitwise."""
    s, hkv, g, qmax, d = q.shape
    quantized = k_scale is not None
    int4 = is_int4_pool(k_pool, k_scale)
    page = pool_page_tokens(k_pool, k_scale)
    kvd = "int4" if int4 else ("int8" if quantized else str(k_pool.dtype))
    plan = mixed_grid_plan(qmax, hkv=hkv, g=g, d=d, page=page, kv=kvd,
                           block_q=block_q, grid=grid, dma_depth=dma_depth,
                           head_group=head_group)
    return _paged_mixed_call(q, k_pool, v_pool, tables, pos_start, q_len,
                             layer, k_scale, v_scale, page_lo, page_hi,
                             carry_state,
                             block_q=plan["block_q"],
                             dma_depth=plan["dma_depth"],
                             grid=plan["grid"], interpret=interpret,
                             head_group=plan["head_group"],
                             emit_state=emit_state)


# ---------------------------------------------------------------------------
# In-place paged KV row update
# ---------------------------------------------------------------------------

_UPDATE_CHUNK = 16        # bf16 sublane tile
_UPDATE_CHUNK_INT8 = 32   # int8 sublane tile
_SCALE_CHUNK = 128        # f32 lane tile


def _paged_update_kernel(layer_ref, idx_ref, tables_ref, kn_ref, vn_ref,
                         kp_in, vp_in, kp_out, vp_out, kscr, vscr, sem,
                         *, page: int, chunk: int):
    del kp_in, vp_in
    b, hkv, _, d = kn_ref.shape
    max_pos = tables_ref.shape[1] * page
    lyr = layer_ref[0]

    def body(i, _):
        @pl.when(idx_ref[i] < max_pos)
        def _():
            _write_row(i)
        return 0

    def _write_row(i):
        idx = idx_ref[i]
        pg = tables_ref[i, idx // page]
        off = idx % page
        base = (off // chunk) * chunk
        dst_k = kp_out.at[pl.ds(lyr, 1), pl.ds(pg, 1), :, pl.ds(base, chunk)]
        dst_v = vp_out.at[pl.ds(lyr, 1), pl.ds(pg, 1), :, pl.ds(base, chunk)]
        rk = pltpu.make_async_copy(dst_k, kscr, sem.at[0])
        rv = pltpu.make_async_copy(dst_v, vscr, sem.at[1])
        rk.start()
        rv.start()
        rk.wait()
        rv.wait()
        row = jax.lax.broadcasted_iota(jnp.int32, (1, 1, hkv, chunk, d), 3)
        hit = row == (off - base)
        kscr[:] = jnp.where(hit, kn_ref[pl.ds(i, 1)][None], kscr[:])
        vscr[:] = jnp.where(hit, vn_ref[pl.ds(i, 1)][None], vscr[:])
        wk = pltpu.make_async_copy(kscr, dst_k, sem.at[0])
        wv = pltpu.make_async_copy(vscr, dst_v, sem.at[1])
        wk.start()
        wv.start()
        wk.wait()
        wv.wait()

    jax.lax.fori_loop(0, b, body, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_kv_update(
    k_pool: jnp.ndarray,   # [L, N, Hkv, P, D]
    v_pool: jnp.ndarray,
    k_new: jnp.ndarray,    # [B, Hkv, D]
    v_new: jnp.ndarray,
    write_idx: jnp.ndarray,  # [B] int32 position per slot
    tables: jnp.ndarray,     # [B, MaxP] int32
    layer,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write one KV row per slot at its table-mapped page, in place."""
    _, n, hkv, page, d = k_pool.shape
    if page % _UPDATE_CHUNK != 0:
        raise ValueError(f"page {page} must be a multiple of {_UPDATE_CHUNK}")
    kn = k_new.astype(k_pool.dtype)[:, :, None, :]
    vn = v_new.astype(v_pool.dtype)[:, :, None, :]
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)),
        scratch_shapes=[
            pltpu.VMEM((1, 1, hkv, _UPDATE_CHUNK, d), k_pool.dtype),
            pltpu.VMEM((1, 1, hkv, _UPDATE_CHUNK, d), v_pool.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    kernel = functools.partial(_paged_update_kernel, page=page,
                               chunk=_UPDATE_CHUNK)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
                   jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype)),
        # 0=layer, 1=idx, 2=tables, 3=kn, 4=vn, 5=k_pool, 6=v_pool.
        input_output_aliases={5: 0, 6: 1},
        interpret=interpret,
    )(layer_arr, write_idx.astype(jnp.int32), tables.astype(jnp.int32),
      kn, vn, k_pool, v_pool)


def _paged_update_quant_kernel(layer_ref, idx_ref, tables_ref,
                               kn_ref, vn_ref, ksn_ref, vsn_ref,
                               kp_in, vp_in, kss_in, vss_in,
                               kp_out, vp_out, kss_out, vss_out,
                               kscr, vscr, ksscr, vsscr, sem,
                               *, page: int, int4: bool):
    del kp_in, vp_in, kss_in, vss_in
    b, hkv, _, d = kn_ref.shape
    max_pos = tables_ref.shape[1] * page
    ch = _UPDATE_CHUNK_INT8
    sch = _SCALE_CHUNK
    lyr = layer_ref[0]

    def body(i, _):
        @pl.when(idx_ref[i] < max_pos)
        def _():
            _write_row(i)
        return 0

    def _write_row(i):
        idx = idx_ref[i]
        pg = tables_ref[i, idx // page]
        off = idx % page
        # int4 pools store nibble pairs: the token's BYTE row is off//2 and
        # the read-modify-write below merges one nibble.  Rows in the same
        # dispatch that share a byte (positions 2t and 2t+1 of a prefill
        # chunk) are safe: the fori loop is sequential, so the second
        # merge reads the first one's write.  All scale/position math
        # stays in token units.
        boff = off // 2 if int4 else off
        base = (boff // ch) * ch
        sbase = (off // sch) * sch
        dst_k = kp_out.at[pl.ds(lyr, 1), pl.ds(pg, 1), :, pl.ds(base, ch)]
        dst_v = vp_out.at[pl.ds(lyr, 1), pl.ds(pg, 1), :, pl.ds(base, ch)]
        dst_ks = kss_out.at[pl.ds(lyr, 1), pl.ds(pg, 1), :, pl.ds(sbase, sch)]
        dst_vs = vss_out.at[pl.ds(lyr, 1), pl.ds(pg, 1), :, pl.ds(sbase, sch)]
        copies = [pltpu.make_async_copy(dst_k, kscr, sem.at[0]),
                  pltpu.make_async_copy(dst_v, vscr, sem.at[1]),
                  pltpu.make_async_copy(dst_ks, ksscr, sem.at[2]),
                  pltpu.make_async_copy(dst_vs, vsscr, sem.at[3])]
        for c in copies:
            c.start()
        for c in copies:
            c.wait()
        row = jax.lax.broadcasted_iota(jnp.int32, (1, 1, hkv, ch, d), 3)
        hit = row == (boff - base)
        if int4:
            # Merge ONE nibble of the hit byte, int8-domain bitwise: low
            # nibble = even token (keep 0xF0), high = odd (keep 0x0F; the
            # int8 left shift wraps the value into the high nibble).
            even = (off % 2) == 0
            newk = kn_ref[pl.ds(i, 1)][None]
            newv = vn_ref[pl.ds(i, 1)][None]
            mk = jnp.where(
                even,
                jnp.bitwise_or(jnp.bitwise_and(kscr[:], jnp.int8(-16)),
                               jnp.bitwise_and(newk, jnp.int8(15))),
                jnp.bitwise_or(jnp.bitwise_and(kscr[:], jnp.int8(15)),
                               jnp.left_shift(newk, 4)))
            mv = jnp.where(
                even,
                jnp.bitwise_or(jnp.bitwise_and(vscr[:], jnp.int8(-16)),
                               jnp.bitwise_and(newv, jnp.int8(15))),
                jnp.bitwise_or(jnp.bitwise_and(vscr[:], jnp.int8(15)),
                               jnp.left_shift(newv, 4)))
            kscr[:] = jnp.where(hit, mk, kscr[:])
            vscr[:] = jnp.where(hit, mv, vscr[:])
        else:
            kscr[:] = jnp.where(hit, kn_ref[pl.ds(i, 1)][None], kscr[:])
            vscr[:] = jnp.where(hit, vn_ref[pl.ds(i, 1)][None], vscr[:])
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, 1, hkv, sch), 3)
        shit = lane == (off - sbase)
        ksn = ksn_ref[pl.ds(i, 1)].reshape(1, 1, hkv, 1)
        vsn = vsn_ref[pl.ds(i, 1)].reshape(1, 1, hkv, 1)
        ksscr[:] = jnp.where(shit, ksn, ksscr[:])
        vsscr[:] = jnp.where(shit, vsn, vsscr[:])
        back = [pltpu.make_async_copy(kscr, dst_k, sem.at[0]),
                pltpu.make_async_copy(vscr, dst_v, sem.at[1]),
                pltpu.make_async_copy(ksscr, dst_ks, sem.at[2]),
                pltpu.make_async_copy(vsscr, dst_vs, sem.at[3])]
        for c in back:
            c.start()
        for c in back:
            c.wait()

    jax.lax.fori_loop(0, b, body, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_kv_update_quant(
    k_pool: jnp.ndarray,   # [L, N, Hkv, P, D] int8
    v_pool: jnp.ndarray,
    k_scale: jnp.ndarray,  # [L, N, Hkv, P] f32
    v_scale: jnp.ndarray,
    k_new: jnp.ndarray,    # [B, Hkv, D]
    v_new: jnp.ndarray,
    write_idx: jnp.ndarray,
    tables: jnp.ndarray,
    layer,
    interpret: bool = False,
):
    """int8/int4 variant: quantize the new rows, write values + per-token
    scales in place through the table.  int4 pools (pool page rows !=
    scale page) get the fused nibble merge in the kernel."""
    from arks_tpu.ops.pallas_attention import quantize_kv

    _, n, hkv, rows, d = k_pool.shape
    page = k_scale.shape[3]
    int4 = rows != page
    if page % _SCALE_CHUNK != 0:
        raise ValueError(
            f"quantized page {page} must be a multiple of {_SCALE_CHUNK}")
    if int4 and rows % _UPDATE_CHUNK_INT8 != 0:
        raise ValueError(
            f"int4 packed page rows {rows} must be a multiple of "
            f"{_UPDATE_CHUNK_INT8}")
    kq, ks = quantize_kv(k_new, qmax=7 if int4 else 127)
    vq, vs = quantize_kv(v_new, qmax=7 if int4 else 127)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 4
        + [pl.BlockSpec(memory_space=pl.ANY)] * 4,
        out_specs=tuple([pl.BlockSpec(memory_space=pl.ANY)] * 4),
        scratch_shapes=[
            pltpu.VMEM((1, 1, hkv, _UPDATE_CHUNK_INT8, d), k_pool.dtype),
            pltpu.VMEM((1, 1, hkv, _UPDATE_CHUNK_INT8, d), v_pool.dtype),
            pltpu.VMEM((1, 1, hkv, _SCALE_CHUNK), jnp.float32),
            pltpu.VMEM((1, 1, hkv, _SCALE_CHUNK), jnp.float32),
            pltpu.SemaphoreType.DMA((4,)),
        ],
    )
    kernel = functools.partial(_paged_update_quant_kernel, page=page,
                               int4=int4)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
                   jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
                   jax.ShapeDtypeStruct(k_scale.shape, jnp.float32),
                   jax.ShapeDtypeStruct(v_scale.shape, jnp.float32)),
        # 0=layer, 1=idx, 2=tables, 3=kq, 4=vq, 5=ks, 6=vs,
        # 7=k_pool, 8=v_pool, 9=k_scale, 10=v_scale.
        input_output_aliases={7: 0, 8: 1, 9: 2, 10: 3},
        interpret=interpret,
    )(layer_arr, write_idx.astype(jnp.int32), tables.astype(jnp.int32),
      kq[:, :, None, :], vq[:, :, None, :], ks, vs,
      k_pool, v_pool, k_scale, v_scale)


# ---------------------------------------------------------------------------
# Host-tier spill/restore: whole-page pool gather / scatter
# ---------------------------------------------------------------------------
#
# The hierarchical prefix cache moves WHOLE pages between the device pool
# and host RAM: a spill gathers evicted pages into a contiguous staging
# block drained D2H with copy_to_host_async, and a restore scatters
# host-resident blocks back into freshly-allocated pool pages.  Unlike the
# per-row update kernels above, a page is already a dense (layer-major)
# stripe, so each transfer is one aligned whole-page DMA — XLA lowers
# take/dynamic_update_slice on the page axis to exactly that, and a Pallas
# formulation would buy nothing (no read-modify-write, no masking).  Both
# carry raw pool bytes (int8 + scales for quantized pools): spill->restore
# round-trips are bit-exact by construction.


def paged_pool_gather(pool: jnp.ndarray, pages: jnp.ndarray) -> jnp.ndarray:
    """Gather whole pool pages into a contiguous staging block:
    ``[L, N, Hkv, P, ...] x [G] int32 -> [L, G, Hkv, P, ...]``.  Duplicate
    page ids (host-side padding of a short spill group) are benign — the
    host drops the padded entries."""
    return jnp.take(pool, pages.astype(jnp.int32), axis=1)


def paged_pool_scatter(pool: jnp.ndarray, blocks: jnp.ndarray,
                       pages: jnp.ndarray, n_valid: jnp.ndarray) -> jnp.ndarray:
    """Write the first ``n_valid`` staged page blocks
    (``[L, G, Hkv, P, ...]``) into the pool pages listed in ``pages``
    ([G] int32, entries past n_valid ignored).  The counterpart of
    ``paged_pool_gather`` and the restore path's one device write; G is a
    fixed group size so the jitted program compiles ONCE (n_valid is the
    dynamic fill)."""

    def body(j, p):
        blk = jax.lax.dynamic_slice_in_dim(blocks, j, 1, axis=1)
        at = (0, pages[j].astype(jnp.int32)) + (0,) * (pool.ndim - 2)
        return jax.lax.dynamic_update_slice(p, blk.astype(p.dtype), at)

    return jax.lax.fori_loop(0, n_valid.astype(jnp.int32), body, pool)
