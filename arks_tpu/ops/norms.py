"""Normalization ops. Computed in float32, cast back to the input dtype —
the standard TPU recipe so bf16 activations don't lose the variance sum."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    y = x32 * (1.0 / jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32)).astype(dtype)
