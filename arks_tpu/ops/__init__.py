from arks_tpu.ops.norms import rms_norm
from arks_tpu.ops.rope import apply_rope

__all__ = ["rms_norm", "apply_rope"]
