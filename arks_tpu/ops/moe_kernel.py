"""Block-sparse grouped matmul for MoE prefill (Pallas TPU kernel).

``jax.lax.ragged_dot`` serves the grouped path today, but for quantized
(w8a16 / w4a16) experts it forces a DEQUANTIZED materialization of every
routed expert's weights before the matmul (models/moe.py) — doubling (or
4x for int4) expert weight HBM traffic exactly where MoE prefill is
weight-bound.  This kernel is the megablocks-style alternative with the
dequant FUSED: quantized weight tiles are read raw; int8 per-channel
scales fold into the f32 accumulator, int4 group scales dequant the tile
in-register before the MXU dot.

Layout contract (prepared by ``pad_groups``):
- Rows are sorted by expert and each expert's group is padded to a
  ``block_t`` multiple with zero rows, so every [block_t, K] tile belongs
  to exactly ONE expert — ``block_expert`` (scalar prefetch) maps tile row
  index -> expert id, and the weight BlockSpec indexes expert tiles
  data-dependently (same trick as the paged-attention tables).
- Zero padding rows produce zero outputs regardless of expert/scales, so
  out-of-range tiles can point at any expert.

Opt-in for now (``ARKS_MOE_KERNEL=pallas``): the ragged_dot path remains
the default until the kernel is measured on hardware (docs/roadmap.md).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from arks_tpu.utils import knobs


def _compiler_params(**kw):
    """Compat shim: pallas renamed TPUCompilerParams -> CompilerParams across
    jax releases; resolve whichever this jax ships."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def moe_impl() -> str:
    impl = knobs.get_str("ARKS_MOE_KERNEL")
    # auto currently resolves to the ragged_dot path; flips to the kernel
    # once measured faster on hardware.
    return "xla" if impl == "auto" else impl


def pad_groups(xs: jnp.ndarray, sorted_expert: jnp.ndarray,
               group_sizes: jnp.ndarray, block_t: int
               ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Scatter expert-sorted rows into block-aligned group slots.

    Returns (xs_padded [Tp, K] with zero fill, dest [T] row positions —
    also the gather map for outputs — and block_expert [Tp/block_t]).
    Tp = T + E*block_t is static (worst-case padding)."""
    t, k = xs.shape
    nx = group_sizes.shape[0]
    # Worst-case padded total, itself block-aligned (static shape).
    tp = (-(-t // block_t) + nx) * block_t
    padded_sizes = -(-group_sizes // block_t) * block_t        # [E]
    pad_starts = jnp.cumsum(padded_sizes) - padded_sizes       # exclusive
    starts = jnp.cumsum(group_sizes) - group_sizes
    dest = (pad_starts[sorted_expert]
            + (jnp.arange(t) - starts[sorted_expert])).astype(jnp.int32)
    xs_padded = jnp.zeros((tp, k), xs.dtype).at[dest].set(xs)
    # Tile -> expert: tile i (rows [i*bt, (i+1)*bt)) belongs to the expert
    # whose padded range contains it; beyond the last group any expert
    # works (all-zero rows), clamp to E-1.
    tile_starts = jnp.arange(tp // block_t, dtype=jnp.int32) * block_t
    ends = jnp.cumsum(padded_sizes)
    block_expert = jnp.minimum(
        jnp.searchsorted(ends, tile_starts, side="right"),
        nx - 1).astype(jnp.int32)
    return xs_padded, dest, block_expert


def _gm_kernel(bexp_ref, x_ref, w_ref, *rest, quantized: bool,
               group: int = 0):
    if quantized:
        ws_ref, o_ref = rest
    else:
        (o_ref,) = rest
    x = x_ref[...]
    w = w_ref[0]
    if quantized and group:
        # int4 groupwise: scales vary ALONG the contraction dim, so they
        # cannot fold into the accumulator like int8's per-channel scales
        # — dequant the tile in-register (same bf16 math as the XLA
        # producer fusion in models/quant._dequant_int4) and feed the MXU.
        gs = ws_ref[0]                                   # [K/G, bn] f32
        kk, bn = w.shape
        wdq = (w.astype(x.dtype).reshape(kk // group, group, bn)
               * gs[:, None, :].astype(x.dtype)).reshape(kk, bn)
        o_ref[...] = jax.lax.dot(
            x, wdq, preferred_element_type=jnp.float32).astype(o_ref.dtype)
        return
    acc = jax.lax.dot(x, w.astype(x.dtype),
                      preferred_element_type=jnp.float32)
    if quantized:
        acc = acc * ws_ref[0]
    o_ref[...] = acc.astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("block_t", "block_n", "interpret"))
def grouped_matmul(
    xs: jnp.ndarray,           # [Tp, K] expert-sorted, block-aligned groups
    w: jnp.ndarray,            # [E, K, N] (int8/int4 when scales given)
    block_expert: jnp.ndarray,  # [Tp/block_t] int32 tile -> expert
    w_scale: jnp.ndarray | None = None,  # int8: [E, N] per-channel scales
    w_group_scale: jnp.ndarray | None = None,  # int4: [E, K/G, N] scales
    block_t: int = 128,
    block_n: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """[Tp, N] = per-tile xs @ w[block_expert[tile]] (scales fused)."""
    tp, k = xs.shape
    nx, _, n = w.shape
    if tp % block_t:
        raise ValueError(f"rows {tp} not a multiple of block_t {block_t}")
    block_n = min(block_n, n)
    if n % block_n:
        raise ValueError(f"N {n} not a multiple of block_n {block_n}")
    group = 0
    if w_group_scale is not None:
        if w_scale is not None:
            raise ValueError("w_scale and w_group_scale are exclusive")
        group = k // w_group_scale.shape[1]
    quantized = w_scale is not None or w_group_scale is not None

    def x_map(ti, ni, bexp):
        del ni, bexp
        return (ti, 0)

    def w_map(ti, ni, bexp):
        return (bexp[ti], 0, ni)

    def ws_map(ti, ni, bexp):
        return (bexp[ti], ni)

    def o_map(ti, ni, bexp):
        del bexp
        return (ti, ni)

    def gs_map(ti, ni, bexp):
        return (bexp[ti], 0, ni)

    in_specs = [
        pl.BlockSpec((block_t, k), x_map),
        pl.BlockSpec((1, k, block_n), w_map),
    ]
    inputs = [block_expert.astype(jnp.int32), xs, w]
    if group:
        in_specs.append(pl.BlockSpec((1, k // group, block_n), gs_map))
        inputs.append(w_group_scale)
    elif quantized:
        in_specs.append(pl.BlockSpec((1, block_n), ws_map))
        inputs.append(w_scale)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(tp // block_t, n // block_n),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_t, block_n), o_map),
    )
    return pl.pallas_call(
        functools.partial(_gm_kernel, quantized=quantized, group=group),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((tp, n), xs.dtype),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(*inputs)


def grouped_ffn(xs: jnp.ndarray, sorted_expert: jnp.ndarray,
                group_sizes: jnp.ndarray, w_gate, w_up, w_down,
                act_dtype, block_t: int = 128,
                interpret: bool | None = None) -> jnp.ndarray:
    """The full gate/up/silu/down expert FFN over expert-sorted rows via
    the block-sparse kernel (int8 dequant fused when the weights carry
    scales).  Returns rows in the SAME sorted order as ``xs``."""
    from arks_tpu.models.quant import is_quantized

    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def wv(wq):
        """(raw weight, kwargs for grouped_matmul's scale argument)."""
        if is_quantized(wq):
            if "gs" in wq:    # int4 groupwise [E, K/G, N]
                return wq["q"], {"w_group_scale":
                                 wq["gs"].astype(jnp.float32)}
            s = wq["s"].astype(jnp.float32)
            if s.ndim == 3:       # [E, 1, N] per-output-channel -> [E, N]
                s = s[:, 0, :]
            return wq["q"], {"w_scale": s}
        return wq, {}

    wg, sg = wv(w_gate)
    wu, su = wv(w_up)
    wd, sd = wv(w_down)

    xs_p, dest, bexp = pad_groups(xs, sorted_expert, group_sizes, block_t)
    gate = grouped_matmul(xs_p, wg, bexp, block_t=block_t,
                          interpret=interpret, **sg)
    up = grouped_matmul(xs_p, wu, bexp, block_t=block_t,
                        interpret=interpret, **su)
    act = (jax.nn.silu(gate.astype(jnp.float32)).astype(act_dtype)
           * up.astype(act_dtype))
    down = grouped_matmul(act, wd, bexp, block_t=block_t,
                          interpret=interpret, **sd)
    return down[dest]
