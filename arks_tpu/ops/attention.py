"""Attention ops (XLA path) for prefill and decode.

The reference delegates attention entirely to vLLM/SGLang CUDA kernels inside
runtime containers (/root/reference/internal/controller/
arksapplication_controller.go:941-1014 only builds their command lines).
Here attention is ours.  This module is the pure-XLA formulation — large
batched einsums that tile onto the MXU, masks as fused elementwise selects.
A Pallas ragged/paged kernel (arks_tpu.ops.pallas_attention) can override the
decode path; this is the portable fallback and the CPU-test reference.

Conventions:
- GQA everywhere: q heads H = G * Hkv.  q is reshaped to [.., Hkv, G, ..] so
  the kv head dim lines up for a single einsum (no repeat_kv materialization).
- Inputs stay in their storage dtype (bf16 on TPU); matmuls accumulate in
  float32 via ``preferred_element_type`` — never materialize f32 casts of the
  KV cache (that would multiply decode HBM traffic by 2x).
- Softmax in float32 with max subtraction.
"""

from __future__ import annotations

import jax.numpy as jnp

_NEG_INF = -1e30


def _softmax(scores: jnp.ndarray, axis: int) -> jnp.ndarray:
    scores = scores - jnp.max(scores, axis=axis, keepdims=True)
    unnorm = jnp.exp(scores)
    return unnorm / (jnp.sum(unnorm, axis=axis, keepdims=True) + 1e-9)


def prefill_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,  # [B, T, Hkv, D]
    v: jnp.ndarray,  # [B, T, Hkv, D]
) -> jnp.ndarray:
    """Causal self-attention over a full (padded) prompt. Returns [B, T, H, D].

    Padded positions are handled by the caller: their outputs are garbage but
    never read (only the last valid token's logits are used), and their K/V
    entries are masked at decode time by the cache length.
    """
    b, t, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    qg = q.reshape(b, t, hkv, g, d)
    scale = 1.0 / (d ** 0.5)
    # [B, Hkv, G, Tq, Tk], f32 accumulation on the MXU.
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k,
                        preferred_element_type=jnp.float32) * scale
    causal = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]  # [Tq, Tk]
    scores = jnp.where(causal[None, None, None], scores, _NEG_INF)
    probs = _softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, t, h, d).astype(q.dtype)


def decode_attention(
    q: jnp.ndarray,        # [B, H, D] — one new token per slot
    k_cache: jnp.ndarray,  # [B, S, Hkv, D]
    v_cache: jnp.ndarray,  # [B, S, Hkv, D]
    lengths: jnp.ndarray,  # [B] int32 — number of valid cache entries per slot
) -> jnp.ndarray:
    """Masked attention of one query token per slot against the slot KV cache.

    Cache index s is valid iff s < lengths[b] (the caller writes the current
    token's K/V into the cache *before* calling, so lengths includes it).
    Returns [B, H, D].
    """
    b, h, d = q.shape
    s = k_cache.shape[1]
    hkv = k_cache.shape[2]
    g = h // hkv
    qg = q.reshape(b, hkv, g, d)
    scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum("bkgd,bskd->bkgs", qg, k_cache,
                        preferred_element_type=jnp.float32) * scale
    valid = jnp.arange(s)[None] < lengths[:, None]  # [B, S]
    scores = jnp.where(valid[:, None, None], scores, _NEG_INF)
    probs = _softmax(scores, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkgs,bskd->bkgd", probs, v_cache,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, h, d).astype(q.dtype)
