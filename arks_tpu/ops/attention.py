"""Attention ops for prefill and decode.

The reference delegates attention entirely to vLLM/SGLang CUDA kernels inside
runtime containers (/root/reference/internal/controller/
arksapplication_controller.go:941-1014 only builds their command lines).
Here attention is ours.  Two decode implementations behind one dispatcher:

- ``xla``: batched einsums that tile onto the MXU, masks as fused selects —
  the portable fallback and the CPU-test oracle.  Reads the full cache.
- ``pallas``: ragged flash-decoding kernel (arks_tpu.ops.pallas_attention)
  that reads only each slot's valid KV prefix — the TPU default, since
  decode is HBM-bandwidth-bound.

Conventions:
- GQA everywhere: q heads H = G * Hkv.  q is reshaped to [.., Hkv, G, ..] so
  the kv head dim lines up for a single einsum (no repeat_kv materialization).
- Decode KV cache layout is ``[B, Hkv, S, D]`` — each (slot, head) sequence
  contiguous, which is what makes ragged block reads dense stripes.
- Inputs stay in their storage dtype (bf16 on TPU); matmuls accumulate in
  float32 via ``preferred_element_type`` — never materialize f32 casts of the
  KV cache (that would multiply decode HBM traffic by 2x).
- Softmax in float32 with max subtraction.
"""

from __future__ import annotations

import logging
import os

import jax
import jax.numpy as jnp

from arks_tpu.utils import knobs

log = logging.getLogger("arks_tpu.ops.attention")

_NEG_INF = -1e30
_lane_warned: set[int] = set()


def _pad_last(x, d_store: int):
    """Zero-pad the trailing (head) dim to the cache's stored width —
    exact: padded K lanes add 0 to every q.k score, padded V lanes yield
    output columns the caller slices off."""
    if x is None or x.shape[-1] == d_store:
        return x
    width = [(0, 0)] * (x.ndim - 1) + [(0, d_store - x.shape[-1])]
    return jnp.pad(x, width)


def default_decode_impl() -> str:
    """'pallas' on real TPU, 'xla' elsewhere; override via ARKS_ATTN_IMPL."""
    impl = knobs.get_str("ARKS_ATTN_IMPL")
    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return impl


def _softmax(scores: jnp.ndarray, axis: int) -> jnp.ndarray:
    scores = scores - jnp.max(scores, axis=axis, keepdims=True)
    unnorm = jnp.exp(scores)
    return unnorm / (jnp.sum(unnorm, axis=axis, keepdims=True) + 1e-9)


def prefill_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,  # [B, T, Hkv, D]
    v: jnp.ndarray,  # [B, T, Hkv, D]
) -> jnp.ndarray:
    """Causal self-attention over a full (padded) prompt. Returns [B, T, H, D].

    Padded positions are handled by the caller: their outputs are garbage but
    never read (only the last valid token's logits are used), and their K/V
    entries are masked at decode time by the cache length.
    """
    b, t, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    qg = q.reshape(b, t, hkv, g, d)
    scale = 1.0 / (d ** 0.5)
    # [B, Hkv, G, Tq, Tk], f32 accumulation on the MXU.
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k,
                        preferred_element_type=jnp.float32) * scale
    causal = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]  # [Tq, Tk]
    scores = jnp.where(causal[None, None, None], scores, _NEG_INF)
    probs = _softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, t, h, d).astype(q.dtype)


def decode_attention_xla(
    q: jnp.ndarray,        # [B, Hkv, G, D] — one new token per slot
    k_cache: jnp.ndarray,  # [B, Hkv, S, D]
    v_cache: jnp.ndarray,  # [B, Hkv, S, D]
    lengths: jnp.ndarray,  # [B] int32 — number of valid cache entries per slot
) -> jnp.ndarray:
    """Masked attention of one query token per slot against the slot KV cache.

    Cache index s is valid iff s < lengths[b] (the caller writes the current
    token's K/V into the cache *before* calling, so lengths includes it).
    Returns [B, Hkv, G, D].
    """
    b, hkv, g, d = q.shape
    s = k_cache.shape[2]
    scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum("bkgd,bksd->bkgs", q, k_cache,
                        preferred_element_type=jnp.float32) * scale
    valid = jnp.arange(s)[None] < lengths[:, None]  # [B, S]
    scores = jnp.where(valid[:, None, None], scores, _NEG_INF)
    probs = _softmax(scores, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkgs,bksd->bkgd", probs, v_cache,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _decode_attention_xla_quant(
    q: jnp.ndarray,        # [B, Hkv, G, D]
    k_cache: jnp.ndarray,  # [B, Hkv, S, D] int8
    v_cache: jnp.ndarray,
    k_scale: jnp.ndarray,  # [B, Hkv, S] f32
    v_scale: jnp.ndarray,
    lengths: jnp.ndarray,  # [B] int32
) -> jnp.ndarray:
    """int8 oracle/fallback: per-token scales applied to scores (K) and
    probabilities (V), mirroring the Pallas kernel's folding."""
    b, hkv, g, d = q.shape
    s = k_cache.shape[2]
    scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum("bkgd,bksd->bkgs", q, k_cache.astype(q.dtype),
                        preferred_element_type=jnp.float32) * scale
    scores = scores * k_scale[:, :, None, :]
    valid = jnp.arange(s)[None] < lengths[:, None]  # [B, S]
    scores = jnp.where(valid[:, None, None], scores, _NEG_INF)
    probs = _softmax(scores, axis=-1) * v_scale[:, :, None, :]
    out = jnp.einsum("bkgs,bksd->bkgd", probs.astype(q.dtype),
                     v_cache.astype(q.dtype),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def chunk_attention_xla(
    q: jnp.ndarray,        # [Hkv, G, C, D] — a chunk of queries for ONE slot
    k_cache: jnp.ndarray,  # [Hkv, S, D] — that slot's cache (chunk KV written)
    v_cache: jnp.ndarray,
    start: jnp.ndarray,    # () int32 — global position of the chunk's first query
    k_scale: jnp.ndarray | None = None,  # [Hkv, S] f32 — int8 caches
    v_scale: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Chunked-prefill attention: C queries against the slot's cache prefix.

    Query at chunk offset i (global position start+i) attends cache entries
    [0, start+i] — earlier chunks plus the causal prefix of this one.  The
    caller writes the chunk's KV into the cache *before* attending (same
    write-then-attend contract as decode_update_and_attend).  Cache entries
    beyond start+C (stale decode writes from interleaved dispatches, final-
    chunk padding) are masked out here and overwritten before any decode
    reads them.  Returns [Hkv, G, C, D].
    """
    hkv, g, c, d = q.shape
    s = k_cache.shape[1]
    scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum("kgcd,ksd->kgcs", q, k_cache.astype(q.dtype),
                        preferred_element_type=jnp.float32) * scale
    if k_scale is not None:
        scores = scores * k_scale[:, None, None, :]
    qpos = start + jnp.arange(c)                    # [C] global positions
    valid = jnp.arange(s)[None] <= qpos[:, None]    # [C, S]
    scores = jnp.where(valid[None, None], scores, _NEG_INF)
    probs = _softmax(scores, axis=-1)
    if v_scale is not None:
        probs = probs * v_scale[:, None, None, :]
    out = jnp.einsum("kgcs,ksd->kgcd", probs.astype(q.dtype),
                     v_cache.astype(q.dtype),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def verify_update_and_attend(
    q: jnp.ndarray,        # [B, K, H, D] — K tokens per slot
    k_new: jnp.ndarray,    # [B, K, Hkv, D]
    v_new: jnp.ndarray,
    k_cache: jnp.ndarray,  # [L, B, Hkv, S, D] — FULL stacked cache
    v_cache: jnp.ndarray,
    positions: jnp.ndarray,  # [B, K] int32 — write positions per token
    lengths: jnp.ndarray,    # [B] int32 — valid prefix before this block
    layer,                   # int32
    mesh=None,
    batch_axis: str | None = None,
    kv_sharded: bool = False,
    model_axis: str = "model",
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
           jnp.ndarray | None, jnp.ndarray | None]:
    """Speculative-verify attention: write K rows per slot at ``positions``,
    then attend each query over the cache prefix plus the causal part of its
    own block (index s valid iff s <= positions[b, k], which equals
    lengths[b]+k).  Returns ([B, K, H, D], kc, vc, k_scale, v_scale).

    XLA path only: K is small (draft lengths 2-8) and the scores tensor
    [B, Hkv, G, K, S] stays modest; under a mesh the partitioner reshards
    exactly as the non-pallas decode branch does."""
    del mesh, batch_axis, kv_sharded, model_axis, lengths
    b, kk, h, d_model = q.shape
    hkv = k_cache.shape[2]
    g = h // hkv
    # Lane padding (see decode_update_and_attend): pad to the stored head
    # dim, prescale q to keep the effective 1/sqrt(d_model) scale.
    d = k_cache.shape[-1]
    if d != d_model:
        q = _pad_last(q, d) * ((d / d_model) ** 0.5)
        k_new = _pad_last(k_new, d)
        v_new = _pad_last(v_new, d)
    quantized = k_scale is not None

    kc_l = jax.lax.dynamic_index_in_dim(k_cache, layer, 0, keepdims=False)
    vc_l = jax.lax.dynamic_index_in_dim(v_cache, layer, 0, keepdims=False)
    b_idx = jnp.arange(b)[:, None, None]
    h_idx = jnp.arange(hkv)[None, :, None]
    pos = positions[:, None, :]                       # [B, 1, K]
    kt = jnp.transpose(k_new, (0, 2, 1, 3))           # [B, Hkv, K, D]
    vt = jnp.transpose(v_new, (0, 2, 1, 3))
    if quantized:
        from arks_tpu.ops.pallas_attention import quantize_kv
        ktq, ktn = quantize_kv(kt)
        vtq, vtn = quantize_kv(vt)
        kc_l = kc_l.at[b_idx, h_idx, pos].set(ktq)
        vc_l = vc_l.at[b_idx, h_idx, pos].set(vtq)
        ks_l = jax.lax.dynamic_index_in_dim(k_scale, layer, 0, keepdims=False)
        vs_l = jax.lax.dynamic_index_in_dim(v_scale, layer, 0, keepdims=False)
        ks_l = ks_l.at[b_idx, h_idx, pos].set(ktn)
        vs_l = vs_l.at[b_idx, h_idx, pos].set(vtn)
    else:
        kc_l = kc_l.at[b_idx, h_idx, pos].set(kt.astype(kc_l.dtype))
        vc_l = vc_l.at[b_idx, h_idx, pos].set(vt.astype(vc_l.dtype))

    s = kc_l.shape[2]
    scale = 1.0 / (d ** 0.5)
    qg = jnp.transpose(q.reshape(b, kk, hkv, g, d), (0, 2, 3, 1, 4))  # [B,Hkv,G,K,D]
    scores = jnp.einsum("bkgqd,bksd->bkgqs", qg, kc_l.astype(q.dtype),
                        preferred_element_type=jnp.float32) * scale
    if quantized:
        scores = scores * ks_l[:, :, None, None, :]
    valid = jnp.arange(s)[None, None] <= positions[:, :, None]  # [B, K, S]
    scores = jnp.where(valid[:, None, None], scores, _NEG_INF)
    probs = _softmax(scores, axis=-1)
    if quantized:
        probs = probs * vs_l[:, :, None, None, :]
    out = jnp.einsum("bkgqs,bksd->bkgqd", probs.astype(q.dtype),
                     vc_l.astype(q.dtype),
                     preferred_element_type=jnp.float32)
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(
        b, kk, h, d)[..., :d_model].astype(q.dtype)

    kc = jax.lax.dynamic_update_index_in_dim(k_cache, kc_l, layer, 0)
    vc = jax.lax.dynamic_update_index_in_dim(v_cache, vc_l, layer, 0)
    if quantized:
        ks = jax.lax.dynamic_update_index_in_dim(k_scale, ks_l, layer, 0)
        vs = jax.lax.dynamic_update_index_in_dim(v_scale, vs_l, layer, 0)
        return out, kc, vc, ks, vs
    return out, kc, vc, k_scale, v_scale


def paged_verify_update_and_attend(
    q: jnp.ndarray,        # [B, K, H, D] — K tokens per slot
    k_new: jnp.ndarray,    # [B, K, Hkv, D]
    v_new: jnp.ndarray,
    k_pool: jnp.ndarray,   # [L, N, Hkv, P, D] page pool
    v_pool: jnp.ndarray,
    tables: jnp.ndarray,   # [B, MaxP] int32 block tables
    positions: jnp.ndarray,  # [B, K] int32 — write positions per token
    layer,
    mesh=None,
    kv_sharded: bool = False,
    model_axis: str = "model",
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
           jnp.ndarray | None, jnp.ndarray | None]:
    """Paged speculative-verify: write the K-row block through the block
    table (a block may cross a page boundary mid-dispatch), then attend
    each query over its table pages — index s valid iff s <=
    positions[b, k].  Positions at/past the table coverage are the
    inactive-slot sentinel: writes dropped, nothing attended.

    XLA path only, like the slot-layout ``verify_update_and_attend``: K is
    small (draft lengths 2-8), so the gather + [B, Hkv, G, K, S] scores
    stay modest; under a TP mesh the partitioner splits the Hkv axis the
    same way the paged XLA decode fallback does."""
    del mesh, kv_sharded, model_axis
    from arks_tpu.ops.paged_attention import (
        is_int4_pool, pool_page_tokens, unpack_int4_pool)
    b, kk, h, d_model = q.shape
    hkv = k_pool.shape[2]
    g = h // hkv
    int4 = is_int4_pool(k_pool, k_scale)
    page = pool_page_tokens(k_pool, k_scale)
    cover = tables.shape[1] * page
    # Lane padding (see decode_update_and_attend): pad to the pool's stored
    # head dim, prescale q to keep the effective 1/sqrt(d_model) scale.
    d = k_pool.shape[-1]
    if d != d_model:
        q = _pad_last(q, d) * ((d / d_model) ** 0.5)
        k_new = _pad_last(k_new, d)
        v_new = _pad_last(v_new, d)
    quantized = k_scale is not None

    from arks_tpu.ops.paged_attention import (
        paged_gather_kv, paged_update_block_xla)
    kp, vp, ks, vs = paged_update_block_xla(
        k_pool, v_pool, k_scale, v_scale, k_new, v_new, positions, tables,
        layer)
    # int4 pools gather through the nibble unpack so the attend math below
    # sees a plain per-token int8 view (scale math is unchanged).
    kp_r = unpack_int4_pool(kp) if int4 else kp
    vp_r = unpack_int4_pool(vp) if int4 else vp
    kc = paged_gather_kv(kp_r, tables, layer)  # [B, Hkv, cover, D]
    vc = paged_gather_kv(vp_r, tables, layer)

    scale = 1.0 / (d ** 0.5)
    qg = jnp.transpose(q.reshape(b, kk, hkv, g, d),
                       (0, 2, 3, 1, 4))        # [B, Hkv, G, K, D]
    scores = jnp.einsum("bkgqd,bksd->bkgqs", qg, kc.astype(q.dtype),
                        preferred_element_type=jnp.float32) * scale
    if quantized:
        ksc = paged_gather_kv(ks, tables, layer)   # [B, Hkv, cover]
        vsc = paged_gather_kv(vs, tables, layer)
        scores = scores * ksc[:, :, None, None, :]
    valid = (jnp.arange(cover)[None, None] <= positions[:, :, None]) \
        & (positions[:, :, None] < cover)          # [B, K, S]
    scores = jnp.where(valid[:, None, None], scores, _NEG_INF)
    probs = _softmax(scores, axis=-1)
    if quantized:
        probs = probs * vsc[:, :, None, None, :]
    out = jnp.einsum("bkgqs,bksd->bkgqd", probs.astype(q.dtype),
                     vc.astype(q.dtype), preferred_element_type=jnp.float32)
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(
        b, kk, h, d)[..., :d_model].astype(q.dtype)
    return out, kp, vp, ks, vs


def paged_mixed_update_and_attend(
    q: jnp.ndarray,        # [T, H, D] — flat mixed token batch
    k_new: jnp.ndarray,    # [T, Hkv, D]
    v_new: jnp.ndarray,
    k_pool: jnp.ndarray,   # [L, N, Hkv, P, D] page pool
    v_pool: jnp.ndarray,
    tables: jnp.ndarray,   # [B, MaxP] int32 — lane b == slot b
    token_slot: jnp.ndarray,   # [T] int32 slot per token (-1 = padding)
    token_pos: jnp.ndarray,    # [T] int32 global position per token
    seq_q_start: jnp.ndarray,  # [B] int32 — lane's first flat-token index
    seq_q_len: jnp.ndarray,    # [B] int32 — lane's token count (0 inactive)
    seq_pos_start: jnp.ndarray,  # [B] int32 — lane's first global position
    layer,
    mesh=None,
    kv_sharded: bool = False,
    impl: str | None = None,
    model_axis: str = "model",
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
           jnp.ndarray | None, jnp.ndarray | None]:
    """Mixed prefill+decode attention over one flat token batch: write every
    token's KV row through its slot's block table, then attend token t
    (slot b = token_slot[t], global position p = token_pos[t]) over that
    slot's pages at positions [0, p] — causal within a prefill chunk, the
    plain decode read for q_len-1 lanes, in ONE op.  Padding tokens
    (token_slot < 0) drop their writes and attend nothing.

    The per-token view (token_slot/token_pos) drives the KV write and the
    XLA oracle; the per-lane view (seq_q_start/q_len/pos_start) drives the
    ragged Pallas kernel, which needs queries grouped by sequence.  Returns
    (out [T, H, D], k_pool, v_pool, k_scale, v_scale)."""
    from arks_tpu.ops.paged_attention import (
        is_int4_pool, pool_page_tokens, unpack_int4_pool)
    t_flat, h, d_model = q.shape
    hkv = k_pool.shape[2]
    g = h // hkv
    int4 = is_int4_pool(k_pool, k_scale)
    page = pool_page_tokens(k_pool, k_scale)
    cover = tables.shape[1] * page
    d = k_pool.shape[-1]
    if d != d_model:
        # Lane padding (see decode_update_and_attend): pad to the stored
        # head dim, prescale q to keep the effective 1/sqrt(d_model) scale.
        q = _pad_last(q, d) * ((d / d_model) ** 0.5)
        k_new = _pad_last(k_new, d)
        v_new = _pad_last(v_new, d)
    quantized = k_scale is not None
    impl = impl or default_decode_impl()
    tp_trivial = mesh is None or mesh.shape.get(model_axis, 1) == 1
    lane_ok = d % 128 == 0 or jax.default_backend() != "tpu"
    use_pallas = impl == "pallas" and (kv_sharded or tp_trivial) and lane_ok

    tables_tok = jnp.take(tables, jnp.maximum(token_slot, 0),
                          axis=0)                       # [T, MaxP]
    write_idx = jnp.where(token_slot < 0, cover, token_pos)

    if not use_pallas:
        from arks_tpu.ops.paged_attention import paged_gather_kv, paged_update_xla
        kp, vp, ks, vs = paged_update_xla(
            k_pool, v_pool, k_scale, v_scale, k_new, v_new, write_idx,
            tables_tok, layer)
        # int4 pools gather through the nibble unpack — the oracle attend
        # sees a plain per-token int8 view.
        kc = paged_gather_kv(unpack_int4_pool(kp) if int4 else kp,
                             tables_tok, layer)         # [T, Hkv, cover, D]
        vc = paged_gather_kv(unpack_int4_pool(vp) if int4 else vp,
                             tables_tok, layer)
        attend_lens = jnp.where(token_slot < 0, 0, token_pos + 1)
        if quantized:
            ksc = paged_gather_kv(ks, tables_tok, layer)
            vsc = paged_gather_kv(vs, tables_tok, layer)
            out = _decode_attention_xla_quant(
                q.reshape(t_flat, hkv, g, d), kc, vc, ksc, vsc, attend_lens)
        else:
            out = decode_attention_xla(q.reshape(t_flat, hkv, g, d), kc, vc,
                                       attend_lens)
        return out.reshape(t_flat, h, d)[..., :d_model], kp, vp, ks, vs

    from arks_tpu.ops.paged_attention import (
        paged_kv_update, paged_kv_update_quant, paged_mixed_attention,
    )
    interpret = jax.default_backend() != "tpu"
    b_lanes = seq_q_start.shape[0]
    # Widest possible per-lane query span.  +1 covers the spec_pipe batch
    # shape (EVERY lane a q_len=K block, t_flat == b_lanes * K): with one
    # lane, t_flat - b_lanes would undershoot its own block width.
    qmax = max(t_flat - b_lanes + 1, 1)

    def local(qg, kn, vn, kp, vp, ks, vs, tbl, tok_tbl, widx, q_start,
              qlen, pos0, lyr):
        if quantized:
            kp, vp, ks, vs = paged_kv_update_quant(
                kp, vp, ks, vs, kn, vn, widx, tok_tbl, lyr,
                interpret=interpret)
        else:
            kp, vp = paged_kv_update(kp, vp, kn, vn, widx, tok_tbl, lyr,
                                     interpret=interpret)
        hkv_l = qg.shape[1]
        span = q_start[:, None] + jnp.arange(qmax, dtype=jnp.int32)
        gather_idx = jnp.minimum(span, t_flat - 1)      # [B, Qmax]
        qs = jnp.take(qg, gather_idx.reshape(-1), axis=0).reshape(
            b_lanes, qmax, hkv_l, g, d)
        qs = jnp.transpose(qs, (0, 2, 3, 1, 4))         # [B,Hkv,G,Qmax,D]
        out_seq = paged_mixed_attention(qs, kp, vp, tbl, pos0, qlen, lyr,
                                        k_scale=ks, v_scale=vs,
                                        interpret=interpret)
        rows = jnp.transpose(out_seq, (0, 3, 1, 2, 4)).reshape(
            b_lanes * qmax, hkv_l, g, d)
        q_valid = jnp.arange(qmax, dtype=jnp.int32)[None] < qlen[:, None]
        scatter_idx = jnp.where(q_valid, span, t_flat)  # OOB rows dropped
        out = jnp.zeros((t_flat, hkv_l, g, d), qg.dtype).at[
            scatter_idx.reshape(-1)].set(rows)
        return out, kp, vp, ks, vs

    qg = q.reshape(t_flat, hkv, g, d)
    if mesh is None or mesh.size == 1:
        out, kp, vp, ks, vs = local(qg, k_new, v_new, k_pool, v_pool,
                                    k_scale, v_scale, tables, tables_tok,
                                    write_idx, seq_q_start, seq_q_len,
                                    seq_pos_start, layer)
        return out.reshape(t_flat, h, d)[..., :d_model], kp, vp, ks, vs

    from arks_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P
    model = model_axis if kv_sharded else None
    qspec = P(None, model, None, None)
    kvspec = P(None, model, None)
    pspec = P(None, None, model, None, None)
    sspec = P(None, None, model, None) if quantized else None
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(qspec, kvspec, kvspec, pspec, pspec, sspec, sspec,
                  P(None, None), P(None, None), P(None), P(None), P(None),
                  P(None), P()),
        out_specs=(qspec, pspec, pspec, sspec, sspec),
        check_vma=False,
    )
    out, kp, vp, ks, vs = fn(qg, k_new, v_new, k_pool, v_pool,
                             k_scale, v_scale, tables, tables_tok,
                             write_idx, seq_q_start, seq_q_len,
                             seq_pos_start, jnp.asarray(layer, jnp.int32))
    return out.reshape(t_flat, h, d)[..., :d_model], kp, vp, ks, vs


def paged_decode_update_and_attend(
    q: jnp.ndarray,        # [B, H, D]
    k_new: jnp.ndarray,    # [B, Hkv, D]
    v_new: jnp.ndarray,
    k_pool: jnp.ndarray,   # [L, N, Hkv, P, D] page pool
    v_pool: jnp.ndarray,
    tables: jnp.ndarray,   # [B, MaxP] int32 block tables
    write_idx: jnp.ndarray,  # [B] int32 (>= MaxP*P = inactive: write dropped)
    layer,
    mesh=None,
    kv_sharded: bool = False,
    impl: str | None = None,
    model_axis: str = "model",
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
           jnp.ndarray | None, jnp.ndarray | None]:
    """Paged counterpart of ``decode_update_and_attend``: the row lands in
    the slot's table-mapped page; attention reads only table pages.  A
    ``write_idx`` at/el beyond the table's coverage marks an INACTIVE slot:
    its write is dropped and it attends nothing (the engine parks freed
    slots there so their garbage dispatch rows cannot corrupt shared
    pages).

    dp meshes are not supported (tables index one global pool); the engine
    falls back to the slot-contiguous layout there.
    """
    from arks_tpu.ops.paged_attention import (
        is_int4_pool, pool_page_tokens, unpack_int4_pool)
    b, h, d_model = q.shape
    hkv = k_pool.shape[2]
    g = h // hkv
    int4 = is_int4_pool(k_pool, k_scale)
    page = pool_page_tokens(k_pool, k_scale)
    cover = tables.shape[1] * page
    # Lane padding (see the slot op): pad to the pool's stored head dim,
    # prescale q so the kernels' 1/sqrt(stored d) nets to 1/sqrt(d_model).
    d = k_pool.shape[-1]
    if d != d_model:
        q = _pad_last(q, d) * ((d / d_model) ** 0.5)
        k_new = _pad_last(k_new, d)
        v_new = _pad_last(v_new, d)
    quantized = k_scale is not None
    impl = impl or default_decode_impl()
    tp_trivial = mesh is None or mesh.shape.get(model_axis, 1) == 1
    lane_ok = d % 128 == 0 or jax.default_backend() != "tpu"
    # int4 pools have no standalone decode kernel (decode traffic rides the
    # mixed kernel's fused dequant); this dedicated-decode entry falls back
    # to the XLA oracle — see the fallback matrix in docs.
    use_pallas = (impl == "pallas" and (kv_sharded or tp_trivial)
                  and lane_ok and not int4)
    # Inactive slots attend nothing (their stale tables may point at pages
    # other slots now own — reading them is wasted bandwidth at best).
    attend_lens = jnp.where(write_idx >= cover, 0, write_idx + 1)

    if not use_pallas:
        from arks_tpu.ops.paged_attention import paged_gather_kv, paged_update_xla
        kp, vp, ks, vs = paged_update_xla(
            k_pool, v_pool, k_scale, v_scale, k_new, v_new, write_idx,
            tables, layer)
        kc = paged_gather_kv(unpack_int4_pool(kp) if int4 else kp,
                             tables, layer)
        vc = paged_gather_kv(unpack_int4_pool(vp) if int4 else vp,
                             tables, layer)
        if quantized:
            ksc = paged_gather_kv(ks, tables, layer)
            vsc = paged_gather_kv(vs, tables, layer)
            out = _decode_attention_xla_quant(
                q.reshape(b, hkv, g, d), kc, vc, ksc, vsc, attend_lens)
        else:
            out = decode_attention_xla(q.reshape(b, hkv, g, d), kc, vc,
                                       attend_lens)
        return out.reshape(b, h, d)[..., :d_model], kp, vp, ks, vs

    from arks_tpu.ops.paged_attention import (
        paged_decode_attention, paged_kv_update, paged_kv_update_quant,
    )
    interpret = jax.default_backend() != "tpu"

    def local(qg, kn, vn, kp, vp, ks, vs, tbl, widx, alens, lyr):
        if quantized:
            kp, vp, ks, vs = paged_kv_update_quant(
                kp, vp, ks, vs, kn, vn, widx, tbl, lyr, interpret=interpret)
        else:
            kp, vp = paged_kv_update(kp, vp, kn, vn, widx, tbl, lyr,
                                     interpret=interpret)
        out = paged_decode_attention(qg, kp, vp, tbl, alens, lyr,
                                     k_scale=ks, v_scale=vs,
                                     interpret=interpret)
        return out, kp, vp, ks, vs

    qg = q.reshape(b, hkv, g, d)
    if mesh is None or mesh.size == 1:
        out, kp, vp, ks, vs = local(qg, k_new, v_new, k_pool, v_pool,
                                    k_scale, v_scale, tables, write_idx,
                                    attend_lens, layer)
        return out.reshape(b, h, d)[..., :d_model], kp, vp, ks, vs

    from arks_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P
    model = model_axis if kv_sharded else None
    qspec = P(None, model, None, None)
    kvspec = P(None, model, None)
    pspec = P(None, None, model, None, None)
    sspec = P(None, None, model, None) if quantized else None
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(qspec, kvspec, kvspec, pspec, pspec, sspec, sspec,
                  P(None, None), P(None), P(None), P()),
        out_specs=(qspec, pspec, pspec, sspec, sspec),
        check_vma=False,
    )
    out, kp, vp, ks, vs = fn(qg, k_new, v_new, k_pool, v_pool,
                             k_scale, v_scale, tables, write_idx,
                             attend_lens, jnp.asarray(layer, jnp.int32))
    return out.reshape(b, h, d)[..., :d_model], kp, vp, ks, vs


def decode_update_and_attend(
    q: jnp.ndarray,        # [B, H, D] — this step's query per slot
    k_new: jnp.ndarray,    # [B, Hkv, D] — this step's KV per slot
    v_new: jnp.ndarray,
    k_cache: jnp.ndarray,  # [L, B, Hkv, S, D] — FULL stacked cache
    v_cache: jnp.ndarray,
    write_idx: jnp.ndarray,  # [B] int32 — tokens already in cache per slot
    layer,                 # int32 — layer whose rows/blocks this step touches
    mesh=None,
    batch_axis: str | None = None,
    kv_sharded: bool = False,
    impl: str | None = None,
    model_axis: str = "model",
    k_scale: jnp.ndarray | None = None,  # [L, B, Hkv, S] f32 — int8 caches
    v_scale: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
           jnp.ndarray | None, jnp.ndarray | None]:
    """Write this step's KV row at ``write_idx`` of ``layer``, then attend
    over the valid prefix (now ``write_idx + 1`` entries).  Returns
    (out [B, H, D], kc, vc, k_scale, v_scale).

    Takes the full stacked cache so the decode layer loop can carry it and
    the Pallas path (pallas_attention) can update/read it IN PLACE: both a
    row scatter and a per-layer slice/re-stack lower to whole-cache HBM
    traffic in XLA — each costs more than the rest of the model combined.

    With ``k_scale``/``v_scale`` the caches are int8 with per-token scales:
    the update quantizes this step's rows, attention dequantizes in VMEM —
    half the HBM read width where decode is bandwidth-bound.

    Under a mesh the op is embarrassingly parallel over (batch, kv-head), so
    the kernels run inside ``shard_map`` with no collectives; when kv heads
    don't divide the TP axis (replicated-KV regime) we stay on the XLA path,
    which the partitioner reshards automatically.
    """
    b, h, d_model = q.shape
    hkv = k_cache.shape[2]
    g = h // hkv
    # Lane padding: a cache stored wider than the model head dim (see
    # transformer.cache_head_dim) lets d<128 models ride the compiled
    # kernels; inputs pad up here and the output slices back down.  The
    # kernels scale scores by 1/sqrt(stored d); prescaling q by
    # sqrt(d_store/d_model) restores the true 1/sqrt(d_model).
    d = k_cache.shape[-1]
    if d != d_model:
        q = _pad_last(q, d) * ((d / d_model) ** 0.5)
        k_new = _pad_last(k_new, d)
        v_new = _pad_last(v_new, d)
    quantized = k_scale is not None
    impl = impl or default_decode_impl()
    # The kernels also serve dp-only meshes (trivial model axis): the op is
    # embarrassingly parallel over batch.  Only the replicated-KV TP regime
    # (tp > 1 not dividing Hkv) needs the XLA partitioner.
    tp_trivial = mesh is None or mesh.shape.get(model_axis, 1) == 1
    # Mosaic tiles the last (lane) dim at 128: compiled-TPU kernels require
    # a 128-multiple STORED head dim.  The engine pads the cache for d<128
    # models (ARKS_PAD_HEAD_DIM=0 disables); an unpadded narrow cache
    # falls back to the XLA path — slower per step but correct.  Interpret
    # mode has no such constraint, so CPU kernel tests still exercise the
    # Pallas path at small D.
    lane_ok = d % 128 == 0 or jax.default_backend() != "tpu"
    if impl == "pallas" and not lane_ok and d not in _lane_warned:
        _lane_warned.add(d)
        log.warning(
            "head_dim=%d is not 128-lane aligned: decode falls back to the "
            "XLA attention path on TPU (slower per step, same results)", d)
    use_pallas = impl == "pallas" and (kv_sharded or tp_trivial) and lane_ok

    if not use_pallas:
        from arks_tpu.ops.pallas_attention import quantize_kv

        kc_l = jax.lax.dynamic_index_in_dim(k_cache, layer, 0, keepdims=False)
        vc_l = jax.lax.dynamic_index_in_dim(v_cache, layer, 0, keepdims=False)
        b_idx = jnp.arange(b)[:, None]
        h_idx = jnp.arange(hkv)[None, :]
        if quantized:
            kq, ksn = quantize_kv(k_new)
            vq, vsn = quantize_kv(v_new)
            kc_l = kc_l.at[b_idx, h_idx, write_idx[:, None]].set(kq)
            vc_l = vc_l.at[b_idx, h_idx, write_idx[:, None]].set(vq)
            ks_l = jax.lax.dynamic_index_in_dim(k_scale, layer, 0, keepdims=False)
            vs_l = jax.lax.dynamic_index_in_dim(v_scale, layer, 0, keepdims=False)
            ks_l = ks_l.at[b_idx, h_idx, write_idx[:, None]].set(ksn)
            vs_l = vs_l.at[b_idx, h_idx, write_idx[:, None]].set(vsn)
            # Scales fold into the score/prob stages (same trick as the
            # Pallas kernel) — never materialize a dequantized f32 cache.
            out = _decode_attention_xla_quant(
                q.reshape(b, hkv, g, d), kc_l, vc_l, ks_l, vs_l, write_idx + 1)
            ks = jax.lax.dynamic_update_index_in_dim(k_scale, ks_l, layer, 0)
            vs = jax.lax.dynamic_update_index_in_dim(v_scale, vs_l, layer, 0)
        else:
            kc_l = kc_l.at[b_idx, h_idx, write_idx[:, None]].set(
                k_new.astype(k_cache.dtype))
            vc_l = vc_l.at[b_idx, h_idx, write_idx[:, None]].set(
                v_new.astype(v_cache.dtype))
            out = decode_attention_xla(q.reshape(b, hkv, g, d), kc_l, vc_l,
                                       write_idx + 1)
            ks, vs = k_scale, v_scale
        kc = jax.lax.dynamic_update_index_in_dim(k_cache, kc_l, layer, 0)
        vc = jax.lax.dynamic_update_index_in_dim(v_cache, vc_l, layer, 0)
        return out.reshape(b, h, d)[..., :d_model], kc, vc, ks, vs

    from arks_tpu.ops.pallas_attention import (
        kv_cache_update, kv_cache_update_quant, ragged_decode_attention,
    )
    interpret = jax.default_backend() != "tpu"
    block_s = knobs.get_int("ARKS_ATTN_BLOCK_S")
    block_b = knobs.get_int("ARKS_ATTN_BLOCK_B")

    def local(qg, kn, vn, kc, vc, ks, vs, widx, lyr):
        if quantized:
            kc, vc, ks, vs = kv_cache_update_quant(
                kc, vc, ks, vs, kn, vn, widx, lyr, interpret=interpret)
        else:
            kc, vc = kv_cache_update(kc, vc, kn, vn, widx, lyr,
                                     interpret=interpret)
        out = ragged_decode_attention(qg, kc, vc, widx + 1, lyr,
                                      k_scale=ks, v_scale=vs,
                                      block_s=block_s, block_b=block_b,
                                      interpret=interpret)
        return out, kc, vc, ks, vs

    qg = q.reshape(b, hkv, g, d)
    if mesh is None or mesh.size == 1:
        out, kc, vc, ks, vs = local(qg, k_new, v_new, k_cache, v_cache,
                                    k_scale, v_scale, write_idx, layer)
        return out.reshape(b, h, d)[..., :d_model], kc, vc, ks, vs

    from arks_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P
    model = model_axis if kv_sharded else None
    qspec = P(batch_axis, model, None, None)
    kvspec = P(batch_axis, model, None)
    cspec = P(None, batch_axis, model, None, None)
    sspec = P(None, batch_axis, model, None) if quantized else None
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(qspec, kvspec, kvspec, cspec, cspec, sspec, sspec,
                  P(batch_axis), P()),
        out_specs=(qspec, cspec, cspec, sspec, sspec),
        check_vma=False,
    )
    out, kc, vc, ks, vs = fn(qg, k_new, v_new, k_cache, v_cache,
                             k_scale, v_scale, write_idx,
                             jnp.asarray(layer, jnp.int32))
    return out.reshape(b, h, d)[..., :d_model], kc, vc, ks, vs
