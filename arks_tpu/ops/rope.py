"""Rotary position embeddings (NeoX/Llama interleaving: rotate_half).

Position-indexed on the fly (no precomputed table) so the same code path
serves prefill ([B, T] positions) and decode ([B] positions) — XLA fuses the
sin/cos into the surrounding elementwise work, which beats gathering from an
HBM-resident table for decode-sized batches.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_freqs(head_dim: int, theta: float) -> jnp.ndarray:
    """Inverse frequencies, shape [head_dim // 2], float32."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta ** exponent)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Apply rotary embedding.

    x: [..., H, D] with leading dims matching ``positions`` (e.g. x [B, T, H, D]
    with positions [B, T], or x [B, H, D] with positions [B]).
    """
    d = x.shape[-1]
    freqs = rope_freqs(d, theta)  # [D/2]
    angles = positions.astype(jnp.float32)[..., None, None] * freqs  # [..., 1, D/2]
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    x1, x2 = x[..., : d // 2], x[..., d // 2 :]
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate([x1f * cos - x2f * sin, x2f * cos + x1f * sin], axis=-1)
    return out.astype(x.dtype)
