"""Persisted kernel autotune: (block_q, block_b, page-DMA depth) per shape.

The paged attention kernels' block sizes were hardcoded heuristics
(``block_b = 16 if int8 else 8``, ``block_q = min(qmax, 32)``) — right for
the one v5e shape they were measured on, wrong elsewhere.  This module
benchmarks the candidate grid per (kernel, model shape, kv dtype,
topology) signature, persists the winner in a JSON table, and serves it
back as a pure dict lookup at kernel trace time.

Modes (``ARKS_KERNEL_TUNE``):

- ``off``    — never look anything up; kernels use their built-in
               heuristics (byte-identical to the pre-autotune behavior).
- ``cached`` — (default) use a persisted table entry when one exists,
               heuristics otherwise.  NEVER sweeps: with no table on disk
               this is exactly ``off``, so fresh deployments stay
               byte-identical until an operator opts into a sweep.
- ``sweep``  — like ``cached``, but a missing entry triggers a benchmark
               sweep at warm-up (InferenceEngine.__init__ /
               bench.py) and persists the winner.

The split between :func:`lookup` (pure dict read, allowed at kernel trace
time and on the engine issue path) and :func:`ensure` (may sweep — warm-up
only) is structural: tests/test_hotpath_guard.py asserts the scheduler's
step loop can only ever reach the lookup side.

Block sizes are resolved at TRACE time (they are static kernel args), so
a table round-trip (persist -> load -> reuse) costs zero extra compiled
program variants: the same entry always resolves to the same statics.
"""

from __future__ import annotations

import json
import logging
import os
import threading

from arks_tpu.utils import knobs

log = logging.getLogger("arks.autotune")

_MODES = ("off", "cached", "sweep")

# In-memory table: {kernel: {signature: {param: value, ...}}}.  Loaded
# from disk at most once per path; guarded so concurrent engine threads
# cannot half-read a table mid-persist.
_lock = threading.Lock()
_table: dict | None = None
_table_path: str | None = None


def mode() -> str:
    m = (knobs.raw("ARKS_KERNEL_TUNE") or "cached").lower()
    if m not in _MODES:
        raise ValueError(
            f"ARKS_KERNEL_TUNE={m!r} (expected one of {_MODES})")
    return m


def cache_path() -> str:
    """JSON table location: ``ARKS_KERNEL_TUNE_CACHE`` wins; else the model
    dir (``ARKS_MODEL_DIR``) so the table ships next to the checkpoint it
    was tuned for; else a per-user cache dir."""
    p = knobs.get_str("ARKS_KERNEL_TUNE_CACHE")
    if p:
        return p
    base = knobs.get_str("ARKS_MODEL_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "arks_tpu")
    return os.path.join(base, "kernel_tune.json")


def topology() -> str:
    """Backend x device-count signature — a table tuned on one topology
    must not silently steer another."""
    import jax
    return f"{jax.default_backend()}x{jax.device_count()}"


def mixed_signature(*, hkv: int, g: int, d: int, page: int, qmax: int,
                    kv: str) -> str:
    return f"hkv{hkv}-g{g}-d{d}-page{page}-q{qmax}-{kv}-{topology()}"


def decode_signature(*, b: int, hkv: int, g: int, d: int, page: int,
                     kv: str) -> str:
    return f"b{b}-hkv{hkv}-g{g}-d{d}-page{page}-{kv}-{topology()}"


def _load_locked() -> dict:
    """Load the table once per path (pure host file I/O — no device work,
    no blocking fetches; the hot-path guard covers this function)."""
    global _table, _table_path
    path = cache_path()
    if _table is not None and _table_path == path:
        return _table
    data: dict = {}
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            data = {}
    except (OSError, ValueError):
        data = {}
    _table, _table_path = data, path
    return data


def lookup(kernel: str, signature: str) -> dict | None:
    """Pure table read: the persisted winner for (kernel, signature), or
    None (mode=off, or no entry).  Safe at kernel trace time and on the
    engine issue path — this function can never sweep."""
    if mode() == "off":
        return None
    with _lock:
        entry = _load_locked().get(kernel, {}).get(signature)
    return dict(entry) if isinstance(entry, dict) else None


def record(kernel: str, signature: str, params: dict) -> None:
    """Persist one winner (atomic tmp+rename so a concurrent reader never
    sees a torn table)."""
    path = cache_path()
    with _lock:
        data = _load_locked()
        data.setdefault(kernel, {})[signature] = dict(params)
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError as e:  # read-only FS: keep the in-memory entry
            log.warning("autotune table not persisted to %s: %s", path, e)


def invalidate_cache() -> None:
    """Drop the in-memory table (tests / operators editing the JSON)."""
    global _table, _table_path
    with _lock:
        _table = _table_path = None


def sweep(kernel: str, signature: str, candidates: list[dict],
          bench_fn, repeats: int = 3) -> dict:
    """Time ``bench_fn(**candidate)`` for every candidate, persist and
    return the fastest.  ``bench_fn`` must block until the work is done
    (e.g. ``np.asarray`` the kernel output) — warm-up/bench context only,
    NEVER the serving step loop."""
    import time

    best, best_t = None, float("inf")
    for cand in candidates:
        try:
            bench_fn(**cand)  # compile / warm outside the timed window
            t0 = time.perf_counter()
            for _ in range(repeats):
                bench_fn(**cand)
            t = (time.perf_counter() - t0) / repeats
        except Exception as e:  # an infeasible candidate is not fatal
            log.debug("autotune candidate %s failed: %s", cand, e,
                      exc_info=True)
            continue
        log.info("autotune %s %s %s: %.3f ms", kernel, signature, cand,
                 t * 1e3)
        if t < best_t:
            best, best_t = cand, t
    if best is None:
        raise RuntimeError(
            f"autotune sweep for {kernel}/{signature}: every candidate "
            "failed")
    record(kernel, signature, best)
    return dict(best)


def ensure(kernel: str, signature: str, candidates: list[dict],
           bench_fn, repeats: int = 3) -> dict | None:
    """Mode-aware warm-up entry: cached entry if present; in ``sweep``
    mode a miss runs the sweep; otherwise None (heuristics)."""
    got = lookup(kernel, signature)
    if got is not None or mode() != "sweep":
        return got
    return sweep(kernel, signature, candidates, bench_fn, repeats=repeats)
