"""Ragged decode attention + in-place KV update as Pallas TPU kernels.

The reference delegates its attention hot loop to vLLM/SGLang CUDA kernels
(paged attention) inside runtime containers; the TPU build owns it.  This is
the TPU formulation of the same idea: decode reads **only the valid prefix**
of each slot's KV cache instead of the full masked cache, which matters
because decode is HBM-bandwidth-bound — at long contexts the KV read *is*
the step time.

Both kernels operate on the FULL stacked cache ``[L, B, Hkv, S, D]`` with the
layer index as a scalar-prefetch argument.  That shape is load-bearing: the
decode layer loop carries the whole cache and each layer touches only its
rows/blocks.  Any formulation that materializes a per-layer slice (e.g.
scanning over the cache as scan xs/ys) makes XLA re-stack the entire cache
every step — measured ~20ms/step at [28, 32, 2, 4096, 128], more than the
rest of the model combined.

Design (flash-decoding / JetStream-ragged style):
- Cache layout ``[.., Hkv, S, D]``: each (slot, kv-head)'s sequence is
  contiguous, so a KV block DMA is one dense stripe.
- Attention grid ``(B / block_b, S / block_s)``: each program owns a *group*
  of slots and ALL kv heads — decode GQA matmuls are tiny ([G, D] x
  [D, block_s]), so per-program work must be batched or grid overhead
  dominates.  Scores for the whole group ride one batched dot_general.
- Per-slot ``lengths`` (and per-group maxima) ride scalar prefetch (SMEM) so
  both the kernel body and the BlockSpec index maps see them.  KV blocks past
  a group's max length are skipped two ways: the index map pins the block
  index (Mosaic issues no DMA for a revisited block) and ``pl.when`` skips
  the compute.  The engine packs similar-length slots into adjacent groups
  to make the skip effective under mixed lengths.
- Online softmax in f32 scratch (m/l/acc) across the KV-block grid axis;
  output written once on the final block.

The attention kernel is numerically identical (up to f32 accumulation order)
to ``arks_tpu.ops.attention.decode_attention_xla``, which stays as the XLA
fallback and the CPU test oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _compiler_params(**kw):
    """Compat shim: pallas renamed TPUCompilerParams -> CompilerParams across
    jax releases; resolve whichever this jax ships."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)

_NEG_INF = -1e30


def _attn_kernel(layer_ref, glens_ref, lens_ref, q_ref, k_ref, v_ref, *rest,
                 block_b: int, block_s: int, scale: float, quantized: bool):
    del layer_ref  # consumed by the index maps
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    bi = pl.program_id(0)
    si = pl.program_id(1)
    num_blocks = pl.num_programs(1)

    @pl.when(si == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    block_start = si * block_s

    @pl.when(block_start < glens_ref[bi])
    def _block():
        bb, hkv, g, d = q_ref.shape
        # Mosaic matmul takes at most ONE batch dim: fold (slot-group, head)
        # into it for the dots; the leading-dim reshapes are layout no-ops.
        q = q_ref[:].reshape(bb * hkv, g, d)
        # int8 caches: convert WITHOUT scaling (one elementwise pass over
        # [block_s, D]); the per-token scales fold into the [G, block_s]
        # score/prob stage below, D/G times cheaper than row dequant.
        k = k_ref[0].reshape(bb * hkv, block_s, d).astype(q.dtype)
        v = v_ref[0].reshape(bb * hkv, block_s, d).astype(q.dtype)
        # [block_b*Hkv, G, block_s] — one batched MXU contraction for the
        # whole slot group.
        scores = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        scores = scores.reshape(bb, hkv, g, block_s)
        if quantized:
            # K scales: zero for never-written rows, but those are beyond
            # ``lens`` and masked to -inf right after (order matters: 0 * a
            # finite score is fine, 0 * -inf would be NaN).
            scores = scores * ks_ref[0].reshape(bb, hkv, 1, block_s)
        pos = block_start + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 3)
        lens = lens_ref[0]  # [block_b, 1]
        scores = jnp.where(pos < lens[:, None, None, :], scores, _NEG_INF)

        m_prev = m_ref[:]  # [block_b, Hkv, G, 128] lane-replicated
        l_prev = l_ref[:]
        m_curr = jnp.max(scores, axis=3, keepdims=True)
        m_next = jnp.maximum(m_prev, jnp.broadcast_to(m_curr, m_prev.shape))
        correction = jnp.exp(m_prev - m_next)
        p = jnp.exp(scores - m_next[..., :1])  # [block_b, Hkv, G, block_s]
        l_curr = jnp.sum(p, axis=3, keepdims=True)
        l_next = l_prev * correction + jnp.broadcast_to(l_curr, l_prev.shape)
        if quantized:
            # V scales fold into the probabilities (p >= 0, vs >= 0).
            p = p * vs_ref[0].reshape(bb, hkv, 1, block_s)
        # [block_b*Hkv, G, D] → [block_b, Hkv, G, D]
        pv = jax.lax.dot_general(
            p.astype(v.dtype).reshape(bb * hkv, g, block_s), v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32).reshape(bb, hkv, g, d)
        acc_ref[:] = acc_ref[:] * correction[..., :1] + pv
        m_ref[:] = m_next
        l_ref[:] = l_next

    @pl.when(si == num_blocks - 1)
    def _finish():
        # +eps keeps empty slots (length 0) finite; their output is unused.
        out = acc_ref[:] / (l_ref[..., :1] + 1e-9)
        o_ref[:] = out.astype(o_ref.dtype)


def _pick_block_b(b: int, target: int) -> int:
    best = 1
    for cand in range(1, min(b, target) + 1):
        if b % cand == 0:
            best = cand
    return best


@functools.partial(jax.jit, static_argnames=("block_s", "block_b", "interpret"))
def ragged_decode_attention(
    q: jnp.ndarray,        # [B, Hkv, G, D] — one query token per slot
    k_cache: jnp.ndarray,  # [L, B, Hkv, S, D] — full stacked cache
    v_cache: jnp.ndarray,
    lengths: jnp.ndarray,  # [B] int32 — valid KV entries per slot
    layer,                 # int32 — which layer's blocks to read
    k_scale: jnp.ndarray | None = None,  # [L, B, Hkv, S] f32 (int8 caches)
    v_scale: jnp.ndarray | None = None,
    block_s: int = 256,
    block_b: int = 16,
    interpret: bool = False,
) -> jnp.ndarray:
    """Returns [B, Hkv, G, D] attention output, reading only valid KV blocks
    of layer ``layer``.  With ``k_scale``/``v_scale`` the caches are int8
    rows dequantized in VMEM (per-token scales)."""
    b, hkv, g, d = q.shape
    s = k_cache.shape[3]
    block_s = min(block_s, s)
    if s % block_s != 0:
        raise ValueError(f"cache len {s} not divisible by block_s {block_s}")
    quantized = k_scale is not None
    block_b = _pick_block_b(b, block_b)
    num_groups = b // block_b
    num_blocks = s // block_s
    scale = 1.0 / (d ** 0.5)
    lengths = lengths.astype(jnp.int32)
    # Per-group max length: the index map's skip signal (a group's KV block is
    # read iff ANY slot in the group still needs it).
    group_lens = jnp.max(lengths.reshape(num_groups, block_b), axis=1)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)

    def q_map(bi, si, layer, glens):
        del si, layer, glens
        return (bi, 0, 0, 0)

    def lens_map(bi, si, layer, glens):
        del si, layer, glens
        return (bi, 0, 0)

    def _pin(bi, si, glens):
        # Pin out-of-range blocks to the group's LAST VALID block (the one
        # just visited): Mosaic skips the DMA for an unchanged block index,
        # so invalid KV is never read from HBM.
        last_valid = jnp.maximum(glens[bi] - 1, 0) // block_s
        valid = si * block_s < glens[bi]
        return jax.lax.select(valid, si, last_valid)

    def kv_map(bi, si, layer, glens):
        return (layer[0], bi, 0, _pin(bi, si, glens), 0)

    def scale_map(bi, si, layer, glens):
        return (layer[0], bi, 0, _pin(bi, si, glens))

    in_specs = [
        pl.BlockSpec((1, block_b, 1), lens_map),
        pl.BlockSpec((block_b, hkv, g, d), q_map),
        pl.BlockSpec((1, block_b, hkv, block_s, d), kv_map),
        pl.BlockSpec((1, block_b, hkv, block_s, d), kv_map),
    ]
    inputs = [layer_arr, group_lens,
              lengths.reshape(num_groups, block_b)[..., None], q,
              k_cache, v_cache]
    if quantized:
        in_specs += [pl.BlockSpec((1, block_b, hkv, block_s), scale_map),
                     pl.BlockSpec((1, block_b, hkv, block_s), scale_map)]
        inputs += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(num_groups, num_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_b, hkv, g, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((block_b, hkv, g, 128), jnp.float32),  # m (lane-replicated)
            pltpu.VMEM((block_b, hkv, g, 128), jnp.float32),  # l
            pltpu.VMEM((block_b, hkv, g, d), jnp.float32),    # acc
        ],
    )
    kernel = functools.partial(_attn_kernel, block_b=block_b, block_s=block_s,
                               scale=scale, quantized=quantized)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(*inputs)


# ---------------------------------------------------------------------------
# In-place KV cache row update
# ---------------------------------------------------------------------------
#
# XLA lowers the decode-step KV scatter (one [Hkv, D] row per slot at a
# data-dependent position) to a full-cache rewrite.  This kernel aliases the
# stacked cache in place and DMAs exactly the touched rows' aligned chunks:
# O(B * Hkv * D) bytes per step instead of the whole cache.

_UPDATE_CHUNK = 16  # bf16 sublane tile: DMA slices along S must be 16-aligned


def _update_kernel(layer_ref, idx_ref, kn_ref, vn_ref, kc_in, vc_in,
                   kc_out, vc_out, kscr, vscr, sem):
    del kc_in, vc_in  # aliased with the outputs; write through the out refs
    b, hkv, _, d = kn_ref.shape
    s = kc_out.shape[3]
    ch = _UPDATE_CHUNK
    lyr = layer_ref[0]

    def body(i, _):
        # Out-of-range writes (idx >= S) are dropped, matching JAX scatter
        # semantics on the XLA path — never corrupt a valid interior row.
        @pl.when(idx_ref[i] < s)
        def _():
            _write_row(i)
        return 0

    def _write_row(i):
        idx = idx_ref[i]
        base = (idx // ch) * ch
        # Read-modify-write of the aligned chunk containing row ``idx``:
        # single unaligned rows can't be DMA'd under bf16 sublane packing.
        dst_k = kc_out.at[pl.ds(lyr, 1), pl.ds(i, 1), :, pl.ds(base, ch)]
        dst_v = vc_out.at[pl.ds(lyr, 1), pl.ds(i, 1), :, pl.ds(base, ch)]
        rk = pltpu.make_async_copy(dst_k, kscr, sem.at[0])
        rv = pltpu.make_async_copy(dst_v, vscr, sem.at[1])
        rk.start()
        rv.start()
        rk.wait()
        rv.wait()
        row = jax.lax.broadcasted_iota(jnp.int32, (1, 1, hkv, ch, d), 3)
        hit = row == (idx - base)
        kscr[:] = jnp.where(hit, kn_ref[pl.ds(i, 1)][None], kscr[:])
        vscr[:] = jnp.where(hit, vn_ref[pl.ds(i, 1)][None], vscr[:])
        wk = pltpu.make_async_copy(kscr, dst_k, sem.at[0])
        wv = pltpu.make_async_copy(vscr, dst_v, sem.at[1])
        wk.start()
        wv.start()
        wk.wait()
        wv.wait()

    jax.lax.fori_loop(0, b, body, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def kv_cache_update(
    k_cache: jnp.ndarray,  # [L, B, Hkv, S, D] — full stacked cache
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,    # [B, Hkv, D]
    v_new: jnp.ndarray,
    write_idx: jnp.ndarray,  # [B] int32
    layer,                 # int32
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write one KV row per slot at ``write_idx`` of layer ``layer``, in
    place. Returns the (aliased) updated caches."""
    _, b, hkv, s, d = k_cache.shape
    if s % _UPDATE_CHUNK != 0:
        raise ValueError(f"cache len {s} must be a multiple of {_UPDATE_CHUNK}")
    kn = k_new.astype(k_cache.dtype)[:, :, None, :]  # [B, Hkv, 1, D]
    vn = v_new.astype(v_cache.dtype)[:, :, None, :]
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)),
        scratch_shapes=[
            pltpu.VMEM((1, 1, hkv, _UPDATE_CHUNK, d), k_cache.dtype),
            pltpu.VMEM((1, 1, hkv, _UPDATE_CHUNK, d), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        _update_kernel,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
                   jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype)),
        # Inputs indexed with scalar-prefetch args first: 0=layer, 1=idx,
        # 2=kn, 3=vn, 4=k_cache, 5=v_cache.
        input_output_aliases={4: 0, 5: 1},
        interpret=interpret,
    )(layer_arr, write_idx.astype(jnp.int32), kn, vn, k_cache, v_cache)


# ---------------------------------------------------------------------------
# int8 KV quantization
# ---------------------------------------------------------------------------

_SCALE_CHUNK = 128  # f32 lane tile: scale RMW slices along S are 128-aligned


def quantize_kv(x: jnp.ndarray, axis: int = -1,
                qmax: int = 127) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-token quantization: returns (q int8, scale f32) with
    the scale axis removed. ``axis`` is the reduced (feature) axis.
    ``qmax`` is the integer range: 127 for int8 pools, 7 for int4 pools
    (values in [-7, 7] so each fits a sign-extended nibble)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis)
    scale = jnp.maximum(amax / float(qmax), 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / jnp.expand_dims(scale, axis)),
                 -qmax, qmax).astype(jnp.int8)
    return q, scale


_UPDATE_CHUNK_INT8 = 32  # int8 sublane tile is (32, 128)


def _update_quant_kernel(layer_ref, idx_ref, kn_ref, vn_ref, ksn_ref, vsn_ref,
                         kc_in, vc_in, kss_in, vss_in,
                         kc_out, vc_out, kss_out, vss_out,
                         kscr, vscr, ksscr, vsscr, sem):
    del kc_in, vc_in, kss_in, vss_in  # aliased with outputs
    b, hkv, _, d = kn_ref.shape
    s = kc_out.shape[3]
    ch = _UPDATE_CHUNK_INT8
    sch = _SCALE_CHUNK
    lyr = layer_ref[0]

    def body(i, _):
        @pl.when(idx_ref[i] < s)
        def _():
            _write_row(i)
        return 0

    def _write_row(i):
        idx = idx_ref[i]
        base = (idx // ch) * ch
        sbase = (idx // sch) * sch
        dst_k = kc_out.at[pl.ds(lyr, 1), pl.ds(i, 1), :, pl.ds(base, ch)]
        dst_v = vc_out.at[pl.ds(lyr, 1), pl.ds(i, 1), :, pl.ds(base, ch)]
        dst_ks = kss_out.at[pl.ds(lyr, 1), pl.ds(i, 1), :, pl.ds(sbase, sch)]
        dst_vs = vss_out.at[pl.ds(lyr, 1), pl.ds(i, 1), :, pl.ds(sbase, sch)]
        copies = [pltpu.make_async_copy(dst_k, kscr, sem.at[0]),
                  pltpu.make_async_copy(dst_v, vscr, sem.at[1]),
                  pltpu.make_async_copy(dst_ks, ksscr, sem.at[2]),
                  pltpu.make_async_copy(dst_vs, vsscr, sem.at[3])]
        for c in copies:
            c.start()
        for c in copies:
            c.wait()
        row = jax.lax.broadcasted_iota(jnp.int32, (1, 1, hkv, ch, d), 3)
        hit = row == (idx - base)
        kscr[:] = jnp.where(hit, kn_ref[pl.ds(i, 1)][None], kscr[:])
        vscr[:] = jnp.where(hit, vn_ref[pl.ds(i, 1)][None], vscr[:])
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, 1, hkv, sch), 3)
        shit = lane == (idx - sbase)
        ksn = ksn_ref[pl.ds(i, 1)].reshape(1, 1, hkv, 1)
        vsn = vsn_ref[pl.ds(i, 1)].reshape(1, 1, hkv, 1)
        ksscr[:] = jnp.where(shit, ksn, ksscr[:])
        vsscr[:] = jnp.where(shit, vsn, vsscr[:])
        back = [pltpu.make_async_copy(kscr, dst_k, sem.at[0]),
                pltpu.make_async_copy(vscr, dst_v, sem.at[1]),
                pltpu.make_async_copy(ksscr, dst_ks, sem.at[2]),
                pltpu.make_async_copy(vsscr, dst_vs, sem.at[3])]
        for c in back:
            c.start()
        for c in back:
            c.wait()

    jax.lax.fori_loop(0, b, body, 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def kv_cache_update_quant(
    k_cache: jnp.ndarray,  # [L, B, Hkv, S, D] int8
    v_cache: jnp.ndarray,
    k_scale: jnp.ndarray,  # [L, B, Hkv, S] f32
    v_scale: jnp.ndarray,
    k_new: jnp.ndarray,    # [B, Hkv, D] (bf16/f32 — quantized here)
    v_new: jnp.ndarray,
    write_idx: jnp.ndarray,  # [B] int32
    layer,                 # int32
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Quantize this step's KV rows to int8 + per-token scale and write both
    in place. Returns (kc, vc, k_scale, v_scale), all aliased."""
    _, b, hkv, s, d = k_cache.shape
    if s % _SCALE_CHUNK != 0:
        raise ValueError(f"int8 cache len {s} must be a multiple of {_SCALE_CHUNK}")
    kq, ks = quantize_kv(k_new)  # [B, Hkv, D] int8, [B, Hkv] f32
    vq, vs = quantize_kv(v_new)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 4
        + [pl.BlockSpec(memory_space=pl.ANY)] * 4,
        out_specs=tuple([pl.BlockSpec(memory_space=pl.ANY)] * 4),
        scratch_shapes=[
            pltpu.VMEM((1, 1, hkv, _UPDATE_CHUNK_INT8, d), k_cache.dtype),
            pltpu.VMEM((1, 1, hkv, _UPDATE_CHUNK_INT8, d), v_cache.dtype),
            pltpu.VMEM((1, 1, hkv, _SCALE_CHUNK), jnp.float32),
            pltpu.VMEM((1, 1, hkv, _SCALE_CHUNK), jnp.float32),
            pltpu.SemaphoreType.DMA((4,)),
        ],
    )
    return pl.pallas_call(
        _update_quant_kernel,
        grid_spec=grid_spec,
        out_shape=(jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
                   jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
                   jax.ShapeDtypeStruct(k_scale.shape, jnp.float32),
                   jax.ShapeDtypeStruct(v_scale.shape, jnp.float32)),
        # 0=layer, 1=idx, 2=kq, 3=vq, 4=ks, 5=vs, 6=kc, 7=vc, 8=kss, 9=vss.
        input_output_aliases={6: 0, 7: 1, 8: 2, 9: 3},
        interpret=interpret,
    )(layer_arr, write_idx.astype(jnp.int32),
      kq[:, :, None, :], vq[:, :, None, :], ks, vs,
      k_cache, v_cache, k_scale, v_scale)
