"""Gateway Prometheus metrics — same families as the reference
(/root/reference/pkg/gateway/metrics/metrics.go:24-132)."""

from __future__ import annotations

from arks_tpu.utils import metrics as prom


class GatewayMetrics:
    def __init__(self, registry: prom.Registry | None = None):
        self.registry = registry or prom.Registry()
        r = self.registry
        self.requests_total = r.counter(
            "gateway_requests_total", "Requests by namespace/user/model/status")
        self.request_duration = r.histogram(
            "gateway_request_duration_seconds", "End-to-end request duration",
            buckets=[0.1, 0.25, 0.5, 1, 2.5, 5, 10, 20, 30, 60])
        self.response_process_duration = r.histogram(
            "gateway_response_process_duration_milliseconds",
            "Gateway-side processing time",
            buckets=[1, 5, 10, 25, 50, 100, 250, 500, 1000, 5000])
        self.token_usage = r.counter(
            "gateway_token_usage", "Token usage by type")
        self.token_distribution = r.histogram(
            "gateway_token_distribution", "Per-request total tokens",
            buckets=[2 ** i for i in range(0, 17)])
        self.rate_limit_hits_total = r.counter(
            "gateway_rate_limit_hits_total", "Rate-limit rejections by rule")
        self.rate_limit_tokens = r.counter(
            "gateway_rate_limit_tokens", "Tokens counted toward rate limits")
        self.quota_usage = r.gauge("gateway_quota_usage", "Quota used")
        self.quota_limit = r.gauge("gateway_quota_limit", "Quota limit")
        self.errors_total = r.counter(
            "gateway_errors_total", "Gateway errors by stage")
